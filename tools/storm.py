"""Storm suite — fleet-scale adversarial scenarios with SLO gates.

Chaos (tools/chaos.py) proves the fleet survives component DEATH;
production traffic fails uglier. This harness drives five adversarial
workloads against live components, each scored by explicit pass/fail
SLO gates that ride into the BENCH artifact
(`BENCH_r10_builder_storm.json`, `bench_host.py --storm`):

  flash_crowd      a 10x client-concurrency step against a TcpLB on a
                   single worker loop. Runs TWICE at identical load —
                   overload guard static, then adaptive
                   (docs/robustness.md): the differential gate shows
                   the adaptive controller passing the p99 SLO that the
                   static guard fails (degrade-rather-than-fail: shed
                   some with RST, serve the rest fast); on hardware
                   with headroom for both, there is nothing to
                   demonstrate and the gate passes as not-demonstrable.
  adversarial_crowd a replayed legit client mix (docs/replay.md) plus
                   an attacking herd from one address, policing ON vs
                   OFF at identical load: the legit SLO must hold and
                   the herd shed >=90% by ATTRIBUTED policing actions
                   with policing on, the differential demonstrated (or
                   honestly not-demonstrable) with it off
                   (docs/robustness.md "admission policing").
  slowloris        a half-open flood (incomplete HTTP heads) against an
                   http-splice LB pins fds/parser state; the
                   pre-handover handshake deadline must release every
                   half-open session (counted
                   vproxy_lb_shed_total{reason=halfopen}) while legit
                   traffic keeps >= 99% success.
  dns_storm        a query storm against the DNS server's packed-answer
                   cache, repeat names + NXDOMAIN misses, with a
                   mid-storm group mutation; zero failed queries.
  elephant_mice    an elephant flow (one hot 5-tuple) vs hundreds of
                   one-packet mice through the native switch flow
                   cache; the elephant must not starve the mice and
                   nothing may drop or stale-forward.
  rolling_upgrade  a 3-node cluster fleet under step-synchronized
                   classify load, every peer drained/restarted one at a
                   time; a mid-roll torn replication frame must be
                   REJECTED at the framing layer leaving last-known-good
                   serving (generation_reject observed, zero failed
                   queries), and the fleet must converge after.

`--seed` pins every probability failpoint arm
(VPROXY_TPU_FAILPOINT_SEED) plus harness payloads, and is echoed into
the artifact so a failed gate replays exactly. `--scale` shrinks the
load shape (the tier-1 `storm` smoke runs at a fraction; full scenarios
are `slow`-marked). `--only <name>` runs one scenario.

Run: env JAX_PLATFORMS=cpu python tools/storm.py [--seed N] [--scale X]
     [--only name] [--out BENCH_r10_builder_storm.json]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from vproxy_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(8)

import _fleetlib  # noqa: E402  (tools/_fleetlib.py — shared fleet helpers)

ROUND = "r10"


# ------------------------------------------------------------- SLO gates

def _gate(value, limit, op: str = "<=") -> dict:
    ok = {"<=": value <= limit, ">=": value >= limit,
          "==": value == limit}[op]
    return {"value": round(value, 4) if isinstance(value, float) else value,
            "op": op, "limit": limit, "pass": bool(ok)}


def _passed(slo: dict) -> bool:
    return all(g["pass"] for g in slo.values())


def _ctr(name: str, **labels):
    from vproxy_tpu.utils.metrics import GlobalInspection
    return GlobalInspection.get().get_counter(name, **labels)


# --------------------------------------------------------- LB scaffolding

class _LBWorld:
    """Backends + group + upstream + one TcpLB, torn down in close()."""

    def __init__(self, alias: str, n_backends: int = 2, workers: int = 1,
                 protocol: str = "tcp", overload: str = "static",
                 max_sessions: int = 0, host_hint: str = None,
                 lanes: int = -1):
        from vproxy_tpu.components.elgroup import EventLoopGroup
        from vproxy_tpu.components.servergroup import (HealthCheckConfig,
                                                       ServerGroup)
        from vproxy_tpu.components.tcplb import TcpLB
        from vproxy_tpu.components.upstream import Upstream
        from vproxy_tpu.rules.ir import HintRule
        self.backends = [_fleetlib.EchoBackend(b"%d" % i)
                         for i in range(n_backends)]
        self.elg = EventLoopGroup(f"{alias}-elg", workers)
        # hc period long: health edges play no part in these scenarios
        self.group = ServerGroup(
            f"{alias}-g", self.elg,
            HealthCheckConfig(timeout_ms=500, period_ms=200, up=1,
                              down=100), "wrr")
        for i, b in enumerate(self.backends):
            self.group.add(f"b{i}", "127.0.0.1", b.port)
        if not _fleetlib.wait_for(
                lambda: sum(1 for s in self.group.servers if s.healthy)
                == n_backends, 10):
            raise TimeoutError("storm backends never came healthy")
        self.ups = Upstream(f"{alias}-u")
        if host_hint:
            self.ups.add(self.group, annotations=HintRule(host=host_hint))
        else:
            self.ups.add(self.group)
        self.lb = TcpLB(alias, self.elg, self.elg, "127.0.0.1", 0,
                        self.ups, protocol=protocol, overload=overload,
                        max_sessions=max_sessions, lanes=lanes)
        self.lb.start()

    def close(self) -> None:
        self.lb.stop()
        self.group.close()
        for b in self.backends:
            b.close()
        self.elg.close()


# ------------------------------------------------------------ scenario 1

def scenario_flash_crowd(scale: float = 1.0, seed: int = 0,
                         log=lambda *_: None) -> dict:
    """10x client-concurrency step (8 -> 80 closed-loop clients on a
    single worker loop), static vs adaptive at IDENTICAL load. The
    differential gate is the tentpole proof: adaptive passes the p99
    SLO static fails — the AIMD ceiling holds admitted concurrency near
    the accept-latency setpoint, RST-shedding the excess cheaply, while
    static queues all 80 and Little's law sets the latency. Both rows
    measure the SUSTAINED crowd (a short unmeasured warm surge lets the
    controller reach steady state — SLOs are about the storm's body,
    not its first half-second)."""
    from vproxy_tpu.components import overload as ov
    sessions = max(80, int(1200 * scale))
    base_clients, surge_clients = 8, 80      # the 10x step
    payload = random.Random(seed or "storm").randbytes(4096)
    p99_limit_ms = 120.0
    served_floor = 0.30
    saved = (ov.FLOOR, ov.TICK_MS, ov.STALL_HI_MS, ov.ACCEPT_HI_MS)
    # storm-sized controller: small floor so the shed is visible, fast
    # ticks so the ceiling moves within the surge window, and an
    # accept-latency setpoint well under the SLO being gated
    ov.FLOOR, ov.TICK_MS = 6, 50
    ov.STALL_HI_MS, ov.ACCEPT_HI_MS = 50.0, 20.0
    rows = {}
    from vproxy_tpu.utils import sketch
    try:
        for mode in ("static", "adaptive"):
            log(f"flash_crowd: {mode} run")
            sketch.reset()  # per-mode window: the crowd must show NOW
            w = _LBWorld(f"storm-flash-{mode}", n_backends=2, workers=1,
                         overload=mode, max_sessions=4096)
            shed_ctr = _ctr("vproxy_lb_shed_total",
                            lb=f"storm-flash-{mode}", reason=mode)
            try:
                base = _fleetlib.blast(w.lb.bind_port, sessions // 6,
                                       base_clients, payload,
                                       latencies=True, timeout=15)
                # unmeasured warm surge: the controller converges
                _fleetlib.blast(w.lb.bind_port, surge_clients,
                                surge_clients, payload, retry_shed=2,
                                timeout=15)
                shed0 = shed_ctr.value()
                surge = _fleetlib.blast(w.lb.bind_port, sessions,
                                        surge_clients, payload,
                                        latencies=True, retry_shed=2,
                                        timeout=15)
                ceiling = w.lb.overload_stat().get("ceiling")
                guard = w.lb.overload_stat()
                # analytics: the flash crowd must SHOW as a heavy
                # hitter — the crowd's source in top-clients and the
                # storm LB in top-routes (utils/sketch; the loopback
                # blaster is one client address by construction)
                top_clients = sketch.top_table("clients", 5)
                top_routes = sketch.top_table("routes", 5)
            finally:
                w.close()
            attempts = max(1, sessions // surge_clients) * surge_clients
            lat = surge.get("lat_s", [])
            p99_ms = _fleetlib.percentile(lat, 99) * 1000
            crowd_seen = int(
                not sketch.enabled()  # knob off: nothing to gate
                or (bool(top_clients)
                    and top_clients[0]["key"] == "127.0.0.1"
                    and any(r["key"] == f"storm-flash-{mode}"
                            for r in top_routes)))
            slo = {
                "p99_ms": _gate(p99_ms, p99_limit_ms, "<="),
                "hard_failures": _gate(surge["fail"], 0, "=="),
                "served_rate": _gate(surge["ok"] / attempts,
                                     served_floor, ">="),
                "crowd_in_top_clients": _gate(crowd_seen, 1, "=="),
            }
            rows[mode] = {
                "mode": mode, "attempts": attempts, "ok": surge["ok"],
                "fail": surge["fail"], "shed": surge["shed"],
                "p50_ms": round(_fleetlib.percentile(lat, 50) * 1000, 2),
                "p99_ms": round(p99_ms, 2),
                "base_p99_ms": round(
                    _fleetlib.percentile(base.get("lat_s", []), 99) * 1000,
                    2),
                "final_ceiling": ceiling, "guard": guard,
                "shed_counted": shed_ctr.value() - shed0,
                "top_clients": top_clients, "top_routes": top_routes,
                "slo": slo, "pass": _passed(slo),
            }
    finally:
        ov.FLOOR, ov.TICK_MS, ov.STALL_HI_MS, ov.ACCEPT_HI_MS = saved
    # the differential: adaptive survives the load static drowns under.
    # On hardware with enough headroom that static ALSO holds every
    # gate at this scale, the crowd never saturated the loop and there
    # is no differential to demonstrate — that is capacity, not a
    # regression, so the gate passes as "demonstrated OR not
    # demonstrable here" instead of demanding the machine be slow (an
    # inverted absolute-SLO assertion would go permanently red on a
    # fast builder with zero product change). The committed artifact's
    # rows carry the actual demonstration when it happens.
    demonstrated = (not rows["static"]["slo"]["p99_ms"]["pass"]
                    and rows["adaptive"]["pass"])
    headroom = rows["static"]["pass"]
    slo = {"adaptive_passes": _gate(int(rows["adaptive"]["pass"]), 1, "=="),
           "differential": _gate(int(demonstrated or headroom), 1, "==")}
    return {"name": "flash_crowd", "rows": rows,
            "differential_demonstrated": demonstrated, "slo": slo,
            "pass": _passed(slo)}


# ------------------------------------------------------------ scenario 2

def scenario_slowloris(scale: float = 1.0, seed: int = 0,
                       log=lambda *_: None) -> dict:
    """Half-open flood: incomplete HTTP heads pin parser state until the
    pre-handover handshake deadline (VPROXY_TPU_HANDSHAKE_MS) kills and
    counts them; legit traffic must not notice."""
    from vproxy_tpu.components import tcplb as T
    half_open = max(20, int(120 * scale))
    legit_n = max(30, int(240 * scale))
    deadline_ms = 1000
    saved_hs = T.HANDSHAKE_MS
    T.HANDSHAKE_MS = deadline_ms
    alias = "storm-loris"
    w = _LBWorld(alias, n_backends=2, workers=1, protocol="http-splice",
                 host_hint="storm.example.com")
    halfopen_ctr = _ctr("vproxy_lb_shed_total", lb=alias, reason="halfopen")
    shed0 = halfopen_ctr.value()
    port = w.lb.bind_port
    head = b"GET / HTTP/1.1\r\nHost: storm.example.com\r\n\r\n"
    try:
        log(f"slowloris: {half_open} half-open + {legit_n} legit")
        flood = []
        for _ in range(half_open):
            try:
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=5)
                s.settimeout(10)
                s.sendall(b"GET / HTTP/1.1\r\nHost: storm")  # never done
                flood.append(s)
            except OSError:
                pass
        # legit traffic WHILE the flood is pinned
        lock = threading.Lock()
        stats = {"ok": 0, "fail": 0}
        lats: list = []
        ids = {b.sid for b in w.backends}

        def legit(count: int) -> None:
            for _ in range(count):
                t0 = time.monotonic()
                try:
                    c = socket.create_connection(("127.0.0.1", port),
                                                 timeout=5)
                    c.settimeout(5)
                    c.sendall(head)
                    want = 1 + len(head)  # backend id byte + head echo
                    got = b""
                    while len(got) < want:
                        d = c.recv(4096)
                        if not d:
                            raise OSError("short")
                        got += d
                    c.close()
                    ok = got[:1] in ids and got[1:] == head
                except OSError:
                    ok = False
                with lock:
                    stats["ok" if ok else "fail"] += 1
                    if ok:
                        lats.append(time.monotonic() - t0)

        clients = 6
        ts = [threading.Thread(target=legit,
                               args=(max(1, legit_n // clients),))
              for _ in range(clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # the deadline must release every half-open session (RST)
        released = 0
        release_deadline = time.monotonic() + deadline_ms / 1000.0 + 6
        for s in flood:
            s.settimeout(max(0.1, release_deadline - time.monotonic()))
            try:
                released += int(s.recv(1) == b"")
            except (ConnectionResetError, ConnectionAbortedError,
                    BrokenPipeError):
                released += 1  # RST: exactly the designed shed
            except OSError:
                pass  # still open at the deadline: NOT released
            s.close()
        _fleetlib.wait_for(lambda: w.lb.active_sessions == 0, 5)
        legit_total = stats["ok"] + stats["fail"]
        slo = {
            "legit_success": _gate(
                stats["ok"] / max(1, legit_total), 0.99, ">="),
            "halfopen_released": _gate(
                released / max(1, len(flood)), 0.99, ">="),
            "halfopen_counted": _gate(
                (halfopen_ctr.value() - shed0) / max(1, len(flood)),
                0.95, ">="),
            "sessions_drained": _gate(w.lb.active_sessions, 0, "=="),
            "legit_p99_ms": _gate(
                _fleetlib.percentile(sorted(lats), 99) * 1000, 400.0,
                "<="),
        }
        return {"name": "slowloris", "half_open": len(flood),
                "released": released,
                "halfopen_counted": halfopen_ctr.value() - shed0,
                "legit": dict(stats),
                "legit_p99_ms": round(
                    _fleetlib.percentile(sorted(lats), 99) * 1000, 2),
                "deadline_ms": deadline_ms, "slo": slo,
                "pass": _passed(slo)}
    finally:
        T.HANDSHAKE_MS = saved_hs
        w.close()


# ------------------------------------------------------------ scenario 3

def scenario_dns_storm(scale: float = 1.0, seed: int = 0,
                       log=lambda *_: None) -> dict:
    """Query storm against the packed-answer cache: repeat names (cache
    hits), NXDOMAIN misses, and a mid-storm group mutation (cache
    invalidation). Gate: ZERO failed queries — a dropped datagram is
    recovered by the client retry and counted, never lost."""
    from vproxy_tpu.components.elgroup import EventLoopGroup
    from vproxy_tpu.components.servergroup import (HealthCheckConfig,
                                                   ServerGroup)
    from vproxy_tpu.components.upstream import Upstream
    from vproxy_tpu.dns import packet as P
    from vproxy_tpu.dns.server import DNSServer
    from vproxy_tpu.rules.ir import HintRule
    n_svcs = 6
    total = max(400, int(4000 * scale))
    clients = 8
    elg = EventLoopGroup("storm-dns-elg", 1)
    groups = []
    ups = Upstream("storm-dns-u")
    try:
        for i in range(n_svcs):
            # protocol="none": always-healthy synthetic backends — the
            # storm is about the answer path, not health edges
            g = ServerGroup(f"storm-sd{i}", elg,
                            HealthCheckConfig(timeout_ms=500,
                                              period_ms=60000, up=1,
                                              down=2, protocol="none"),
                            "wrr")
            g.add(f"s{i}a", "10.9.0.1", 1000 + i)
            g.add(f"s{i}b", "10.9.0.2", 1000 + i)
            groups.append(g)
            ups.add(g, annotations=HintRule(
                host=f"svc{i}.storm.example"))
        d = DNSServer("storm-d", elg.next(), "127.0.0.1", 0, ups)
        d.start()
        log(f"dns_storm: {total} queries x {clients} clients")
        names = [f"svc{i}.storm.example." for i in range(n_svcs)]
        names += [f"nx{i}.storm.example." for i in range(2)]  # NXDOMAIN
        lock = threading.Lock()
        stats = {"ok": 0, "fail": 0, "retried": 0}
        lats: list = []

        def worker(wid: int, count: int) -> None:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.settimeout(0.5)
            rng = random.Random((seed, wid))
            for q in range(count):
                qid = (wid * 131 + q) % 65536
                name = names[rng.randrange(len(names))]
                pkt = P.Packet(id=qid, rd=True,
                               questions=[P.Question(name, P.A)]).encode()
                t0 = time.monotonic()
                got = False
                for attempt in range(3):  # client retry IS the protocol
                    try:
                        s.sendto(pkt, ("127.0.0.1", d.bind_port))
                        while True:
                            data, _ = s.recvfrom(4096)
                            resp = P.parse(data)
                            if resp.id == qid:  # stale answers skipped
                                got = True
                                break
                    except (socket.timeout, OSError):
                        with lock:
                            stats["retried"] += attempt < 2
                        continue
                    except P.DNSFormatError:
                        continue
                    break
                with lock:
                    stats["ok" if got else "fail"] += 1
                    if got:
                        lats.append(time.monotonic() - t0)
                if wid == 0 and q == count // 2:
                    # mid-storm mutation: the packed-answer cache must
                    # invalidate (group recalc bumps health_version)
                    groups[0].add("mid", "10.9.0.3", 999)
            s.close()

        ts = [threading.Thread(target=worker,
                               args=(i, max(1, total // clients)))
              for i in range(clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        slo = {
            "failed_queries": _gate(stats["fail"], 0, "=="),
            "p99_ms": _gate(
                _fleetlib.percentile(sorted(lats), 99) * 1000, 50.0,
                "<="),
            "cache_hits": _gate(d.cache_hits, 1, ">="),
        }
        return {"name": "dns_storm", "queries": stats["ok"] + stats["fail"],
                "ok": stats["ok"], "fail": stats["fail"],
                "retried": stats["retried"], "cache_hits": d.cache_hits,
                "server_drops": d.drops,
                "p50_ms": round(
                    _fleetlib.percentile(sorted(lats), 50) * 1000, 3),
                "p99_ms": round(
                    _fleetlib.percentile(sorted(lats), 99) * 1000, 3),
                "slo": slo, "pass": _passed(slo)}
    finally:
        try:
            d.stop()
        except Exception:
            pass
        for g in groups:
            g.close()
        elg.close()


# ------------------------------------------------------------ scenario 4

def scenario_elephant_mice(scale: float = 1.0, seed: int = 0,
                           log=lambda *_: None) -> dict:
    """One hot 5-tuple (the elephant, riding the C flow cache) vs
    hundreds of one-packet mice (every one a cache miss compiling
    through the python slow path) through the native switch. The
    elephant must not starve the mice, nothing may drop, and the
    forward/drop accounting must balance."""
    from vproxy_tpu.net import vtl as V
    if not (V.PROVIDER == "native" and V.flowcache_supported()):
        return {"name": "elephant_mice", "skipped": True,
                "reason": "native flow cache unavailable", "pass": None}
    from vproxy_tpu.components.secgroup import SecurityGroup
    from vproxy_tpu.net.eventloop import SelectorEventLoop
    from vproxy_tpu.utils.ip import Network, parse_ip
    from vproxy_tpu.vswitch.packets import Ethernet, Ipv4, Vxlan
    from vproxy_tpu.vswitch.switch import Switch, synthetic_mac
    from vproxy_tpu.rules.ir import RouteRule
    elephant_n = max(400, int(4000 * scale))
    mice_n = max(60, int(400 * scale))
    DST_MAC = b"\x02\xfe\x00\x00\x00\x01"
    env = {"VPROXY_TPU_FLOWCACHE": "1",
           "VPROXY_TPU_FLOWCACHE_TTL_MS": "60000"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    loop = SelectorEventLoop("storm-sw")
    loop.loop_thread()
    sw = None
    rx = tx = None
    mice_socks: list = []
    try:
        sw = Switch("storm-sw", loop, "127.0.0.1", 0,
                    bare_vxlan_access=SecurityGroup.allow_all())
        sw.start()
        n1 = sw.add_network(101, Network.parse("10.1.0.0/16"))
        n2 = sw.add_network(102, Network.parse("10.2.0.0/16"))
        gw_mac = synthetic_mac(101, parse_ip("10.1.0.1"))
        n1.ips.add(parse_ip("10.1.0.1"), gw_mac)
        n2.ips.add(parse_ip("10.2.255.254"),
                   synthetic_mac(102, parse_ip("10.2.255.254")))
        n1.add_route(RouteRule("r0", Network.parse("10.2.0.0/16"),
                               to_vni=102))
        rx = V.udp_bind("127.0.0.1", 0)
        V.set_rcvbuf(rx, 8 << 20)
        _, rx_port = V.sock_name(rx)
        sw.add_remote_switch("out", "127.0.0.1", rx_port)
        out = sw.ifaces[("remote", "out")][0]
        n2.macs.record(DST_MAC, out)
        dst = parse_ip("10.2.0.9")
        n2.arps.record(dst, DST_MAC)

        def frame(src_ip: bytes, src_tail: int, payload: bytes) -> bytes:
            ip = Ipv4(src=src_ip, dst=dst, proto=17, payload=payload,
                      ttl=64)
            eth = Ethernet(gw_mac,
                           b"\x02\xaa\x00\x00\x00" + bytes([src_tail]),
                           0x0800, b"", packet=ip)
            return Vxlan(101, eth).to_bytes()

        # payload length tells the receiver which herd a frame is from.
        # Mice are distinct FLOWS (the key includes the outer sender
        # ip:port and the inner v4 src) from a BOUNDED endpoint set — 8
        # source MACs x 64 inner IPs, uniqueness via a sender-socket
        # pool. A brand-new mac/ip per mouse would be a MAC/ARP-LEARNING
        # mutation per packet, and the generation gate — correctly —
        # invalidates every installed flow on each one; real mice are
        # new flows from known endpoints, not new endpoints.
        ele = frame(parse_ip("10.1.0.9"), 1, b"e" * 18)
        mice = [frame(parse_ip(f"10.1.1.{1 + (i // 16) % 64}"),
                      2 + (i % 8), b"m" * 26)
                for i in range(mice_n)]
        counters0 = V.flowcache_counters()
        got = {"ele": 0, "mice": 0}
        stop_rx = threading.Event()
        ele_len, mice_len = len(ele), len(mice[0])

        def drain() -> None:
            while not stop_rx.is_set():
                try:
                    if not V.wait_readable(rx, 200):
                        continue
                except OSError:
                    return
                for data, _, _ in V.recvmmsg(rx):
                    if len(data) == ele_len:
                        got["ele"] += 1
                    elif len(data) == mice_len:
                        got["mice"] += 1

        rt = threading.Thread(target=drain, daemon=True)
        rt.start()
        log(f"elephant_mice: {elephant_n} elephant + {mice_n} mice")
        tx = V.udp_socket()
        mice_socks = [V.udp_socket() for _ in range(16)]
        sent = {"ele": 0, "mice": 0}
        # pre-learn the mice endpoints (one frame per mac/ip pair):
        # after this the storm itself causes no table mutations at all
        seen = set()
        for i, m in enumerate(mice):
            key = (2 + (i % 8), 1 + (i // 16) % 64)
            if key in seen:
                continue
            seen.add(key)
            V.sendto(mice_socks[i % 16], m, "127.0.0.1", sw.bind_port)
            sent["mice"] += 1
        time.sleep(0.4)

        def send_ele() -> None:
            # a real elephant is a LONG-LIVED flow: the first packets
            # miss (python compiles the flow entry), the stream then
            # rides the C fast path. Model that: a small warm burst, a
            # beat for the install, then the flood.
            warm = min(64, elephant_n // 4)
            for i in range(elephant_n):
                try:
                    V.sendto(tx, ele, "127.0.0.1", sw.bind_port)
                    sent["ele"] += 1
                except OSError:
                    pass
                if i == warm:
                    time.sleep(0.4)  # flow-entry install window (the
                    # compile runs on the switch loop's PYTHON side and
                    # must win the GIL from this very sender)
                elif i % 64 == 0:
                    time.sleep(0.0002)  # real yield: mice + switch loop

        def send_mice() -> None:
            for i, m in enumerate(mice):
                try:
                    V.sendto(mice_socks[i % 16], m, "127.0.0.1",
                             sw.bind_port)
                    sent["mice"] += 1
                except OSError:
                    pass
                time.sleep(0.0005)  # a trickle under the elephant

        te = threading.Thread(target=send_ele)
        tm = threading.Thread(target=send_mice)
        te.start()
        tm.start()
        te.join()
        tm.join()
        deadline = time.monotonic() + 5
        while (got["ele"] + got["mice"] < sent["ele"] + sent["mice"]
               and time.monotonic() < deadline):
            time.sleep(0.05)
        stop_rx.set()
        rt.join(2)
        counters = [c - c0 for c, c0
                    in zip(V.flowcache_counters(), counters0)]
        hits, misses = counters[0], counters[1]
        drops = sum(counters[5:])
        slo = {
            "mice_delivery": _gate(
                got["mice"] / max(1, sent["mice"]), 0.99, ">="),
            "elephant_delivery": _gate(
                got["ele"] / max(1, sent["ele"]), 0.95, ">="),
            "native_drops": _gate(drops, 0, "=="),
            "cache_hit_rate": _gate(
                hits / max(1, hits + misses), 0.5, ">="),
        }
        return {"name": "elephant_mice", "sent": dict(sent),
                "received": dict(got),
                "flowcache": {"hits": hits, "misses": misses,
                              "evict": counters[2], "stale": counters[3],
                              "native_fwd": counters[4], "drops": drops},
                "slo": slo, "pass": _passed(slo)}
    finally:
        if sw is not None:
            sw.stop()
        for fd in [rx, tx] + mice_socks:
            if fd:
                try:
                    V.close(fd)
                except OSError:
                    pass
        loop.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ------------------------------------------------------------ scenario 5

def scenario_rolling_upgrade(scale: float = 1.0, seed: int = 0,
                             log=lambda *_: None) -> dict:
    """Drain/restart every peer of a 3-node fleet, one at a time, under
    continuous step-synchronized classify load; mid-roll, a torn
    replication frame forces a REJECTED generation that must leave
    last-known-good serving. Zero failed or wrong verdicts anywhere."""
    from vproxy_tpu.control.command import Command
    from vproxy_tpu.rules import oracle
    from vproxy_tpu.rules.ir import Hint
    from vproxy_tpu.utils import failpoint
    from vproxy_tpu.utils.events import FlightRecorder
    failpoint.clear()
    FlightRecorder.reset()
    G = 10
    per_node_inflight = max(20, int(120 * scale))
    HB, POLL, STEP_TO = 300, 120, 400
    wait_for = _fleetlib.wait_for
    spec = _fleetlib.cluster_spec(3)
    apps, nodes = zip(*[_fleetlib.make_node(i, spec, hb_ms=HB,
                                            poll_ms=POLL)
                        for i in range(3)])
    apps, nodes = list(apps), list(nodes)
    loops: list = [None, None, None]
    stats = {i: {"ok": 0, "bad": 0, "lost": 0} for i in range(3)}
    stop_evts = [threading.Event() for _ in range(3)]
    threads: list = [None, None, None]
    lock = threading.Lock()
    report: dict = {"name": "rolling_upgrade"}
    try:
        assert wait_for(
            lambda: all(n.membership.peers_up() == 3 for n in nodes)), \
            "membership never converged"
        Command.execute(apps[0], "add upstream u0")
        for i in range(G):
            Command.execute(
                apps[0], f"add server-group g{i} timeout 500 period 60000 "
                "up 1 down 2 annotations "
                f'{{"vproxy/hint-host":"s{i}.storm.example"}}')
            Command.execute(
                apps[0], f"add server-group g{i} to upstream u0 weight 10")
        gen0 = nodes[0].replicator.generation
        assert wait_for(lambda: all(n.replicator.generation == gen0
                                    for n in nodes)), "replication lag"
        # the oracle verdict set: mid-roll mutations only APPEND groups
        # with hints nobody queries, so these indices stay authoritative
        rules = [h.merged_rule() for h in apps[0].upstreams["u0"].handles]

        def attach(i: int) -> None:
            loops[i] = nodes[i].attach_submit(
                apps[i].upstreams["u0"]._matcher, step_ms=20,
                batch_cap=8, timeout_ms=STEP_TO)

        for i in range(3):
            attach(i)
        assert wait_for(lambda: all(
            p.stepping for n in nodes for p in n.membership.peer_list()),
            15), "fleet never stepped"

        def traffic(i: int) -> None:
            # closed loop: one in-flight query per pass, loss bounded
            rng = random.Random((seed, "roll", i))
            q = 0
            while not stop_evts[i].is_set():
                h = Hint(host=f"s{rng.randrange(G + 2)}.storm.example")
                got = {"e": threading.Event(), "idx": None}

                def cb(idx, payload, got=got):
                    got["idx"] = idx
                    got["e"].set()
                try:
                    loops[i].submit(h, cb)
                except OSError:
                    break  # node is being drained
                if not got["e"].wait(10):
                    with lock:
                        stats[i]["lost"] += 1
                else:
                    with lock:
                        key = ("ok" if got["idx"]
                               == oracle.search(rules, h) else "bad")
                        stats[i][key] += 1
                q += 1
                time.sleep(0.01)

        def start_traffic(i: int) -> None:
            stop_evts[i] = threading.Event()
            threads[i] = threading.Thread(target=traffic, args=(i,))
            threads[i].start()

        for i in range(3):
            start_traffic(i)
        time.sleep(0.6)  # mid-traffic, not before it
        mutations = [0]
        rolls = []
        for k, victim in enumerate((2, 1, 0)):
            log(f"rolling_upgrade: drain node {victim}")
            # drain: stop steering load at it, then take it down
            stop_evts[victim].set()
            threads[victim].join(30)
            threads[victim] = None
            nodes[victim].close()
            apps[victim].close()
            time.sleep(0.8)  # survivors ride the barrier-timeout degrade
            survivors = [i for i in range(3) if i != victim
                         and threads[i] is not None]
            leader = min(survivors)
            assert wait_for(lambda: nodes[leader].membership.leader_id()
                            == leader, 10), "leadership never settled"
            # mid-roll mutation; on the middle roll the frame is TORN —
            # the follower must reject it at the framing layer and keep
            # serving last-known-good until the snapshot heal
            torn = (k == 1)
            if torn:
                failpoint.arm("cluster.replicate.torn", count=1)
            mutations[0] += 1
            m = mutations[0]
            Command.execute(
                apps[leader],
                f"add server-group roll{m} timeout 500 period 60000 up 1 "
                f"down 2 annotations "
                f'{{"vproxy/hint-host":"roll{m}.storm.example"}}')
            Command.execute(
                apps[leader],
                f"add server-group roll{m} to upstream u0 weight 10")
            genm = nodes[leader].replicator.generation
            healed = wait_for(
                lambda: all(nodes[i].replicator.generation == genm
                            for i in survivors), 20)
            rolls.append({"victim": victim, "torn": torn,
                          "generation": genm, "survivors_healed": healed})
            # restart the victim: re-sync to the CURRENT generation
            apps[victim], nodes[victim] = _fleetlib.make_node(
                victim, spec, hb_ms=HB, poll_ms=POLL)
            assert wait_for(
                lambda: all(n.membership.peers_up() == 3 for n in nodes),
                20), f"node {victim} never re-joined membership"
            assert wait_for(
                lambda: "u0" in apps[victim].upstreams
                and nodes[victim].replicator.generation
                == nodes[leader].replicator.generation, 20), \
                f"node {victim} never re-synced"
            attach(victim)
            start_traffic(victim)
            time.sleep(0.4)
        for i in range(3):
            stop_evts[i].set()
        for t in threads:
            if t is not None:
                t.join(30)
        rejects = sum(1 for e in FlightRecorder.get().snapshot()
                      if e["kind"] == "generation_reject")
        gen_final = nodes[0].replicator.generation
        converged = wait_for(
            lambda: all(n.replicator.generation == gen_final
                        for n in nodes), 10)
        # a wait, not a point sample: an engine install can still be
        # in flight right after the last roll's traffic stops
        checksums_equal = wait_for(
            lambda: len({n.replicator.checksum() for n in nodes}) == 1,
            10)
        total_bad = sum(stats[i]["bad"] for i in range(3))
        total_lost = sum(stats[i]["lost"] for i in range(3))
        total_ok = sum(stats[i]["ok"] for i in range(3))
        slo = {
            "failed_queries": _gate(total_bad + total_lost, 0, "=="),
            "rejected_generation_seen": _gate(rejects, 1, ">="),
            "healed_after_reject": _gate(
                int(all(r["survivors_healed"] for r in rolls)), 1, "=="),
            "fleet_converged": _gate(
                int(converged and checksums_equal), 1, "=="),
            "min_traffic": _gate(total_ok, per_node_inflight, ">="),
        }
        report.update({
            "traffic": {str(i): dict(stats[i]) for i in range(3)},
            "rolls": rolls, "generation_rejects": rejects,
            "final_generation": gen_final, "converged": converged,
            "checksums_equal": checksums_equal, "slo": slo,
            "pass": _passed(slo)})
        return report
    finally:
        for e in stop_evts:
            e.set()
        for t in threads:
            if t is not None:
                t.join(5)
        failpoint.clear()
        _fleetlib.close_fleet(nodes, apps)


# ---------------------------------------------------------------- driver

def scenario_replay_flash_crowd(scale: float = 1.0, seed: int = 0,
                                log=lambda *_: None) -> dict:
    """Record-replay under storm rules (docs/replay.md): record a
    flash-crowd client mix through a real LB (workload capture window
    + analytics sketch, distinct loopback client addresses), then
    replay the captured model at 2x SPEED against a FRESH world via
    tools/replay.py and hold the replay to the legit-traffic SLO —
    zero hard failures (shed is the designed degrade, scored apart),
    a served-rate floor, and the p99 bound. The schedule is the
    seeded-determinism contract: two builds of the same (model, seed)
    must hash identically and the hash rides the artifact, so a
    failed gate replays exactly."""
    import replay as RP
    from vproxy_tpu.utils import sketch, workload
    from vproxy_tpu.utils.workload import WorkloadModel
    rseed = seed or 1
    n = max(60, int(240 * scale))
    served_floor, p99_limit_ms = 0.80, 500.0
    log(f"replay_flash_crowd: recording a {n}-session crowd")
    sketch.reset()
    workload.reset()
    w = _LBWorld("storm-replay-src", n_backends=2, workers=1,
                 max_sessions=4096)
    try:
        workload.capture_start()
        mix = RP.drive_zipf_mix(w.lb.bind_port, seed=rseed, n=n,
                                clients=10, alpha=1.3, keys=14,
                                pace_s=0.004)
        workload.capture_stop()
        model = WorkloadModel.fit(seed=rseed)
    finally:
        w.close()
    # same (model, seed) -> byte-identical schedule, twice over
    h_a = RP.schedule_hash(RP.build_schedule(model, rseed, speed=2.0,
                                             max_arrivals=n))
    h_b = RP.schedule_hash(RP.build_schedule(model, rseed, speed=2.0,
                                             max_arrivals=n))
    log("replay_flash_crowd: replaying at 2x against a fresh world")
    rep = RP.run_replay(model, seed=rseed, speed=2.0, max_arrivals=n,
                        n_backends=2, workers=1, max_sessions=4096,
                        served_floor=served_floor, p99_ms=p99_limit_ms)
    total = sum(rep["results"][k] for k in ("ok", "fail", "shed"))
    slo = {
        "recorded_mix_clean": _gate(mix["fail"], 0, "=="),
        "hard_failures": _gate(rep["results"]["fail"], 0, "=="),
        "served_rate": _gate(rep["results"]["ok"] / max(1, total),
                             served_floor, ">="),
        "p99_ms": _gate(rep["p99_ms"], p99_limit_ms, "<="),
        "schedule_deterministic": _gate(
            int(h_a == h_b == rep["schedule_hash"]), 1, "=="),
    }
    return {
        "name": "replay_flash_crowd",
        "recorded": {"sessions": n, "ok": mix["ok"],
                     "shed": mix["shed"], "fail": mix["fail"],
                     "true_top3": mix["true_top"][:3]},
        "model_rate_hz": model.plane_rate("accept"),
        "schedule_hash": h_a,
        "replay": {"speed": rep["speed"], "span_s": rep["span_s"],
                   "late_s": rep["late_s"], "ok": rep["results"]["ok"],
                   "shed": rep["results"]["shed"],
                   "fail": rep["results"]["fail"],
                   "p50_ms": rep["p50_ms"], "p99_ms": rep["p99_ms"]},
        "slo": slo, "pass": _passed(slo),
    }


def scenario_adversarial_crowd(scale: float = 1.0, seed: int = 0,
                               log=lambda *_: None) -> dict:
    """The policing plane's acceptance proof (docs/robustness.md
    "admission policing"): a REPLAYED legit mix (the PR-16 capture →
    schedule loop, distinct loopback client identities) runs while an
    attacking herd hammers from one address. With policing ON a
    rate-based `clients` policy — calibrated from the schedule itself
    so the hottest legit client sits at 1/3 of quota — must shed the
    herd >=90% (attributed to policing actions, receipted) while the
    legit mix holds its SLO; with policing OFF at identical load the
    differential is demonstrated (the herd eats the serving capacity
    or 3x+ the served slots), or machine-honestly reported
    not-demonstrable (the flash-crowd headroom rule)."""
    import replay as RP
    from vproxy_tpu.policing import engine as policing
    from vproxy_tpu.policing.engine import Policy, PolicingEngine
    from vproxy_tpu.utils import failpoint, sketch, workload
    from vproxy_tpu.utils.workload import WorkloadModel
    if not sketch.enabled():
        return {"name": "adversarial_crowd", "skipped": True,
                "reason": "analytics sketches disabled", "pass": None}
    rseed = seed or 1
    n = max(60, int(240 * scale))
    herd_threads = 3
    herd_cap = max(400, int(4000 * scale))  # per thread, a runaway stop
    herd_ip = "127.66.6.6"  # outside every legit identity range
    served_floor, p99_limit_ms = 0.30, 250.0
    herd_payload = b"h" * 256

    # --- record the legit mix (the PR-16 capture loop) --------------
    log(f"adversarial_crowd: recording a {n}-session legit mix")
    sketch.reset()
    workload.reset()
    w = _LBWorld("storm-adv-src", n_backends=2, workers=1,
                 max_sessions=4096)
    try:
        workload.capture_start()
        mix = RP.drive_zipf_mix(w.lb.bind_port, seed=rseed, n=n,
                                clients=10, alpha=1.3, keys=14,
                                pace_s=0.004)
        workload.capture_stop()
        model = WorkloadModel.fit(seed=rseed)
    finally:
        w.close()
    sched = RP.build_schedule(model, rseed, speed=1.0, max_arrivals=n)
    # stretch the replay to a fixed measurement window: the capture is
    # a tight loopback blast, and a quota calibrated against THAT rate
    # would sit above anything a closed-loop herd can even offer —
    # rate discrimination needs legit rates human-shaped, not
    # benchmark-shaped. `speed` only divides at dispatch, so the
    # schedule (and its hash) is still the pure (model, seed) function
    span_s = 4.0
    src_span = (sched["arrivals"][-1]["t"] if sched["arrivals"]
                else 1.0)
    sched["speed"] = max(1e-3, src_span / span_s)
    shash = RP.schedule_hash(sched)
    # calibrate the policy FROM the schedule: the hottest legit client
    # replays at a known rate, quota = 3x that — rate discrimination,
    # not identity discrimination (the herd is caught for BEHAVING
    # like a herd, legit clients keep 3x headroom by construction)
    per_src: dict = {}
    for a in sched["arrivals"]:
        per_src[a["src"]] = per_src.get(a["src"], 0) + 1
    hot_legit_rate = max(per_src.values()) / span_s
    rate = max(4.0, 3.0 * hot_legit_rate)
    burst = 2.0 * rate

    # --- determinism receipt: same schedule + same seed => the SAME
    # shed set, twice over (the policing.decision.force coin under
    # VPROXY_TPU_FAILPOINT_SEED is the replayable-evidence contract)
    def _receipt() -> str:
        eng = PolicingEngine()
        failpoint.arm("policing.decision.force", probability=0.25,
                      seed=rseed)
        try:
            for arr in sched["arrivals"]:
                eng.check("clients", arr["src"], lb="storm-adv")
        finally:
            failpoint.clear()
        return eng.shed_receipt()

    r_a, r_b = _receipt(), _receipt()

    rows = {}
    eng = policing.default()
    try:
        for knob in ("on", "off"):
            log(f"adversarial_crowd: policing {knob} run")
            sketch.reset()
            eng.set_policies([])
            eng.reset()
            policing.configure(knob == "on")
            w = _LBWorld(f"storm-adv-{knob}", n_backends=2, workers=1,
                         max_sessions=4096, lanes=2)
            try:
                eng.set_policy(Policy("crowd", "clients", rate, burst,
                                      "shed"))
                # warm: the herd must SURFACE in the sketch before the
                # tick can bucket it — detection precedes enforcement.
                # Lane-accepted warm sessions reach the python sketch on
                # the lane-0 drain cadence (~1 poll period), so WAIT for
                # the key before ticking: a tick against a not-yet-
                # drained sketch compiles an empty table AND resets the
                # tick clock, pushing the first real install a full
                # TICK_S into the measurement window
                for _ in range(16):
                    try:
                        _fleetlib.one_session(w.lb.bind_port,
                                              herd_payload, 5,
                                              src_ip=herd_ip)
                    except OSError:
                        pass
                _fleetlib.wait_for(
                    lambda: any(r["key"] == herd_ip
                                for r in sketch.top_table("clients", 0)),
                    6)
                if knob == "on":
                    policing.tick()
                    # enforcement armed = the key holds a bucket in the
                    # decision table (the tick pushed it into the C
                    # lanes synchronously via the installer hooks)
                    if not any(e["key"] == herd_ip
                               for e in eng.table_snapshot()):
                        log("adversarial_crowd: WARNING herd key not "
                            "in decision table after warm tick")
                pol0 = eng.policed_total(action="shed", dim="clients")
                herd = {"ok": 0, "shed": 0, "fail": 0, "attempts": 0}
                hlock = threading.Lock()
                stop_herd = threading.Event()

                def herd_worker() -> None:
                    for _ in range(herd_cap):
                        if stop_herd.is_set():
                            return
                        try:
                            _fleetlib.one_session(w.lb.bind_port,
                                                  herd_payload, 5,
                                                  src_ip=herd_ip)
                        except OSError as e:
                            k = ("shed" if _fleetlib._is_shed(e)
                                 else "fail")
                        else:
                            k = "ok"
                        with hlock:
                            herd[k] += 1
                            herd["attempts"] += 1

                hts = [threading.Thread(target=herd_worker)
                       for _ in range(herd_threads)]
                for t in hts:
                    t.start()
                res = RP.replay_schedule(sched, w.lb.bind_port,
                                         timeout=10)
                stop_herd.set()
                for t in hts:
                    t.join(30)
                if knob == "on":
                    # the C lane sheds fold on the lane-0 drain tick
                    _fleetlib.wait_for(
                        lambda: eng.policed_total(
                            action="shed", dim="clients") - pol0
                        >= 0.9 * herd["shed"], 3)
                policed = eng.policed_total(action="shed",
                                            dim="clients") - pol0
            finally:
                w.close()
            total = res["ok"] + res["fail"] + res["shed"]
            p99_ms = _fleetlib.percentile(res["lat_s"], 99) * 1000
            legit_slo = {
                "hard_failures": _gate(res["fail"], 0, "=="),
                "served_rate": _gate(res["ok"] / max(1, total),
                                     served_floor, ">="),
                "p99_ms": _gate(p99_ms, p99_limit_ms, "<="),
            }
            rows[knob] = {
                "policing": knob,
                "legit": {"ok": res["ok"], "fail": res["fail"],
                          "shed": res["shed"],
                          "p50_ms": round(_fleetlib.percentile(
                              res["lat_s"], 50) * 1000, 2),
                          "p99_ms": round(p99_ms, 2)},
                "herd": dict(herd), "policed_sheds": policed,
                "shed_receipt": eng.shed_receipt(),
                "legit_slo": legit_slo,
                "legit_pass": _passed(legit_slo),
            }
    finally:
        policing.configure(True)
        eng.set_policies([])
        eng.reset()
    on, off = rows["on"], rows["off"]
    herd_rej = on["herd"]["shed"] / max(1, on["herd"]["attempts"])
    # the differential, under the flash-crowd honesty rule: OFF either
    # breaks a legit gate or hands the herd 3x+ the served slots
    # (demonstrated); a machine with headroom for BOTH at this scale
    # has nothing to demonstrate and says so instead of going red
    demonstrated = ((not off["legit_pass"])
                    or off["herd"]["ok"] >= 3 * max(1, on["herd"]["ok"]))
    headroom = off["legit_pass"]
    slo = {
        "legit_slo_on": _gate(int(on["legit_pass"]), 1, "=="),
        "herd_rejected": _gate(herd_rej, 0.90, ">="),
        "herd_attributed": _gate(
            int(on["policed_sheds"] >= 0.9 * on["herd"]["shed"]), 1,
            "=="),
        "receipt_deterministic": _gate(int(r_a == r_b), 1, "=="),
        "differential": _gate(int(demonstrated or headroom), 1, "=="),
    }
    return {"name": "adversarial_crowd",
            "recorded": {"sessions": n, "ok": mix["ok"],
                         "shed": mix["shed"], "fail": mix["fail"]},
            "schedule_hash": shash,
            "policy": {"rate": round(rate, 2), "burst": round(burst, 2),
                       "hot_legit_rate": round(hot_legit_rate, 2)},
            "rows": rows,
            "determinism_receipt": r_a,
            "differential_demonstrated": demonstrated,
            "slo": slo, "pass": _passed(slo)}


SCENARIOS = {
    "flash_crowd": scenario_flash_crowd,
    "adversarial_crowd": scenario_adversarial_crowd,
    "replay_flash_crowd": scenario_replay_flash_crowd,
    "slowloris": scenario_slowloris,
    "dns_storm": scenario_dns_storm,
    "elephant_mice": scenario_elephant_mice,
    "rolling_upgrade": scenario_rolling_upgrade,
}


def run_all(seed: int = 0, scale: float = 1.0, only: str = None,
            log=lambda *_: None) -> dict:
    os.environ["VPROXY_TPU_FAILPOINT_SEED"] = str(seed)
    report = {"round": ROUND, "seed": seed, "scale": scale,
              "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
              "scenarios": {}}
    names = [only] if only else list(SCENARIOS)
    for name in names:
        log(f"=== scenario {name}")
        t0 = time.monotonic()
        try:
            out = SCENARIOS[name](scale=scale, seed=seed, log=log)
        except Exception as e:  # a crashed scenario is a FAILED gate
            out = {"name": name, "error": f"{type(e).__name__}: {e}",
                   "pass": False}
        out["elapsed_s"] = round(time.monotonic() - t0, 2)
        report["scenarios"][name] = out
        log(f"=== scenario {name}: "
            f"{'SKIP' if out.get('skipped') else 'PASS' if out.get('pass') else 'FAIL'} "
            f"({out['elapsed_s']}s)")
    ran = [s for s in report["scenarios"].values() if not s.get("skipped")]
    report["pass"] = bool(ran) and all(s.get("pass") for s in ran)
    # the shed/drop counters the scenarios exercised, straight from the
    # production /metrics surface
    from vproxy_tpu.utils.metrics import GlobalInspection
    snap = GlobalInspection.get().bench_snapshot()
    report["metrics"] = {k: v for k, v in snap.items()
                        if k.startswith(("vproxy_lb_shed_total",
                                         "vproxy_lb_overload_total",
                                         "vproxy_udp_drop_total",
                                         "vproxy_cluster_",
                                         "vproxy_trace_"))}
    # storm runs under VPROXY_TPU_TRACE_SAMPLE dump their worst traces
    # like the bench --trace stage: the slowest sampled requests of an
    # adversarial run, attribution included, right in the artifact
    from vproxy_tpu.utils import trace as TR
    if TR.enabled():
        report["slowest_traces"] = TR.slowest(8)
        report["stage_table"] = TR.stage_table()
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0,
                    help="pin failpoint RNGs + payloads; echoed into "
                    "the artifact so a failed gate replays exactly")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink/grow every scenario's load shape")
    ap.add_argument("--only", choices=sorted(SCENARIOS), default=None)
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here (the BENCH "
                    "artifact, e.g. BENCH_r10_builder_storm.json)")
    args = ap.parse_args(argv)
    report = run_all(seed=args.seed, scale=args.scale, only=args.only,
                     log=lambda m: print(f"[storm] {m}", file=sys.stderr))
    print(json.dumps(report, indent=2, default=str))
    if args.out:
        with open(args.out + ".tmp", "w") as f:
            json.dump(report, f, indent=2, default=str)
        os.replace(args.out + ".tmp", args.out)
    print(f"[storm] overall: {'PASS' if report['pass'] else 'FAIL'}",
          file=sys.stderr)
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
