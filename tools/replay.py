"""Record-replay engine — the capacity twin (docs/replay.md).

Loads a captured `WorkloadModel` (a file exported by `capture export`,
or a live `GET /workload`), synthesizes a deterministic arrival
schedule from it, and replays that schedule at Nx speed against a
candidate LB config on the `_fleetlib` fleet harness — shed-vs-fail
accounting, latency percentiles and explicit SLO gates, so "would this
config survive yesterday's traffic at twice the rate?" is a command,
not a guess.

Determinism is the seeded-failpoint idiom (`VPROXY_TPU_FAILPOINT_SEED`
family): every sampling site gets its own `random.Random(f"{seed}:
<site>")` stream, string seeds hash by VALUE in CPython, so the same
(model, seed) pair produces a byte-identical schedule in every process
— `schedule_hash` (sha256 over the canonical JSON) is echoed into the
replay report and BENCH rows, and two same-seed runs MUST agree on it.

The fidelity gate closes the loop: replayed clients bind distinct
loopback source addresses (one_session `src_ip`), so the analytics
sketch and the workload capture hooks see the synthesized traffic
exactly like real traffic; re-capturing during the replay and
comparing top-K identity plus per-plane rate shape against the source
model proves the twin is faithful, not just plausible.

Run: env JAX_PLATFORMS=cpu python tools/replay.py \
        (--model capture.json | --url http://HOST:PORT/workload) \
        [--seed N] [--speed X] [--max-arrivals N] [--fidelity] \
        [--hash-only] [--overload static|adaptive] [--out report.json]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from vproxy_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(8)

import _fleetlib  # noqa: E402  (tools/_fleetlib.py — shared fleet helpers)

# schedule caps: a replay is a bounded experiment, not a soak
MAX_ARRIVALS_DEFAULT = 400
PAYLOAD_CAP = 1 << 18          # clamp sampled connection sizes (bytes)
SYNTH_KEYS = 16                # synthetic client count when top is empty


def _gate(value, limit, op: str = "<=") -> dict:
    ok = {"<=": value <= limit, ">=": value >= limit,
          "==": value == limit}[op]
    return {"value": round(value, 4) if isinstance(value, float) else value,
            "op": op, "limit": limit, "pass": bool(ok)}


# ---------------------------------------------------------- model loading

def load_model(src: str):
    """A WorkloadModel from a file path or a live `GET /workload` URL
    (stdlib urllib only — the replay box may be anywhere)."""
    from vproxy_tpu.utils.workload import WorkloadModel
    if src.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(src, timeout=10) as r:
            return WorkloadModel.from_json(r.read().decode())
    with open(src, encoding="utf-8") as f:
        return WorkloadModel.from_json(f.read())


def client_addr_map(model) -> dict:
    """Model client key -> replayable loopback source address. Keys
    that already ARE loopback addresses (a capture taken on this
    harness) replay as themselves; foreign keys (real client IPs,
    opaque ids) get deterministic 127.0.x.y aliases by top-table rank,
    so top-K identity survives the round trip via this map."""
    out = {}
    nxt = 0
    top = model.data["popularity"].get("clients", {}).get("top", [])
    for key, _cnt, _err in top:
        if isinstance(key, str) and key.startswith("127."):
            out[key] = key
        else:
            out[key] = f"127.0.{1 + nxt // 250}.{2 + nxt % 250}"
            nxt += 1
    return out


# ------------------------------------------------------ schedule synthesis

def _weighted_keys(model, alpha: float):
    """(keys, cumulative integer weights) for popularity draws. The
    sketch top table is the head; when it is empty (fresh process) a
    synthetic Zipf(alpha) head stands in so a schedule always exists."""
    top = model.data["popularity"].get("clients", {}).get("top", [])
    pairs = [(k, int(c)) for k, c, _e in top if int(c) > 0]
    if not pairs:
        pairs = [(f"c{i:02d}", max(1, int(1e6 * (i + 1) ** -alpha)))
                 for i in range(SYNTH_KEYS)]
    keys, cum, acc = [], [], 0
    for k, w in pairs:
        acc += w
        keys.append(k)
        cum.append(acc)
    return keys, cum, acc


def build_schedule(model, seed: int, speed: float = 1.0,
                   max_arrivals: int = MAX_ARRIVALS_DEFAULT,
                   duration_s: float = 0.0, plane: str = "accept") -> dict:
    """Synthesize the deterministic replay schedule: arrival offsets
    from the plane's inter-arrival histogram, client identity from the
    Zipf popularity head, connection sizes from the bytes histogram.
    Offsets `t` are in SOURCE time (seconds); `speed` only divides at
    dispatch, so one schedule serves every replay rate. Pure function
    of (model JSON, seed) — byte-identical in every process."""
    import random

    from vproxy_tpu.utils.workload import sample_from_hist
    rng_arr = random.Random(f"{seed}:arrivals")
    rng_key = random.Random(f"{seed}:keys")
    rng_size = random.Random(f"{seed}:sizes")

    pl = model.data["planes"].get(plane, {})
    ia = pl.get("interarrival_us", {})
    ia_total = sum(ia.get("buckets") or [])
    rate = float(pl.get("rate_hz", 0.0))
    alpha = float(model.data["popularity"].get("clients", {})
                  .get("alpha", 1.0))
    keys, cum, total_w = _weighted_keys(model, alpha)
    addr_map = client_addr_map(model)
    bh = model.data["conn"].get("bytes", {})
    bh_total = sum(bh.get("buckets") or [])

    raws = []
    for _ in range(max(1, int(max_arrivals))):
        if ia_total > 0:
            raws.append(sample_from_hist(rng_arr, ia) / 1e6)
        elif rate > 0:
            raws.append(1.0 / rate)
        else:
            raws.append(0.001)
    # mean-true rescale: log2 buckets preserve SHAPE but uniform
    # within-bucket resampling biases the mean (up to ~1.5x for a
    # single-bucket mass) — scale the draws so the schedule's mean
    # inter-arrival equals the model's measured sum/count exactly,
    # which is what the fidelity rate-ratio gate holds replay to
    if ia_total > 0 and ia.get("count", 0) > 0:
        true_mean = (ia["sum"] / ia["count"]) / 1e6
        raw_mean = sum(raws) / len(raws)
        if raw_mean > 0 and true_mean > 0:
            factor = true_mean / raw_mean
            raws = [r * factor for r in raws]

    arrivals, t = [], 0.0
    import bisect
    for dt in raws:
        t += dt
        if duration_s and t > duration_s:
            break
        key = keys[bisect.bisect_right(cum, rng_key.randrange(total_w))]
        nbytes = int(sample_from_hist(rng_size, bh)) if bh_total else 2048
        arrivals.append({
            "t": round(t, 9),
            "key": key,
            "src": addr_map.get(key, "127.0.0.1"),
            "bytes": max(1, min(PAYLOAD_CAP, nbytes)),
        })
    return {"seed": int(seed), "speed": float(speed), "plane": plane,
            "arrivals": arrivals}


def schedule_hash(schedule: dict) -> str:
    """sha256 over the canonical JSON form — the determinism receipt
    two same-seed replays must agree on."""
    blob = json.dumps(schedule, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


# ------------------------------------------------------------ replay world

class ReplayWorld:
    """Backends + group + upstream + one TcpLB — the candidate config
    under replay (the storm _LBWorld shape, minus scenario extras)."""

    def __init__(self, alias: str = "replay", n_backends: int = 2,
                 workers: int = 1, overload: str = "static",
                 max_sessions: int = 0):
        from vproxy_tpu.components.elgroup import EventLoopGroup
        from vproxy_tpu.components.servergroup import (HealthCheckConfig,
                                                       ServerGroup)
        from vproxy_tpu.components.tcplb import TcpLB
        from vproxy_tpu.components.upstream import Upstream
        self.backends = [_fleetlib.EchoBackend(b"%d" % i)
                         for i in range(n_backends)]
        self.elg = EventLoopGroup(f"{alias}-elg", workers)
        self.group = ServerGroup(
            f"{alias}-g", self.elg,
            HealthCheckConfig(timeout_ms=500, period_ms=200, up=1,
                              down=100), "wrr")
        for i, b in enumerate(self.backends):
            self.group.add(f"b{i}", "127.0.0.1", b.port)
        if not _fleetlib.wait_for(
                lambda: sum(1 for s in self.group.servers if s.healthy)
                == n_backends, 10):
            raise TimeoutError("replay backends never came healthy")
        self.ups = Upstream(f"{alias}-u")
        self.ups.add(self.group)
        self.lb = TcpLB(alias, self.elg, self.elg, "127.0.0.1", 0,
                        self.ups, protocol="tcp", overload=overload,
                        max_sessions=max_sessions)
        self.lb.start()

    def close(self) -> None:
        self.lb.stop()
        self.group.close()
        for b in self.backends:
            b.close()
        self.elg.close()


def _payload(n: int) -> bytes:
    return (b"vproxy-replay---" * (n // 16 + 1))[:n]


def replay_schedule(schedule: dict, port: int, timeout: float = 10.0,
                    max_inflight: int = 64) -> dict:
    """Dispatch every arrival at its deadline (absolute offsets — a
    slow session never skews later arrivals) with shed-vs-fail
    accounting: `{"ok","fail","shed","ids","lat_s","span_s","late_s"}`.
    `speed` comes from the schedule; sessions run on daemon threads
    capped at max_inflight so an overloaded target back-pressures the
    pacer visibly (late_s) instead of silently thinning the offered
    rate."""
    speed = max(1e-9, float(schedule.get("speed", 1.0)))
    lock = threading.Lock()
    stats: dict = {"ok": 0, "fail": 0, "shed": 0, "ids": {}}
    lats: list = []
    sem = threading.BoundedSemaphore(max_inflight)
    threads = []

    def one(arr: dict) -> None:
        t0 = time.monotonic()
        try:
            sid = _fleetlib.one_session(port, _payload(arr["bytes"]),
                                        timeout, src_ip=arr["src"])
        except OSError as e:
            with lock:
                stats["shed" if getattr(e, "shed", False)
                      else "fail"] += 1
        else:
            with lock:
                stats["ok"] += 1
                stats["ids"][sid] = stats["ids"].get(sid, 0) + 1
                lats.append(time.monotonic() - t0)
        finally:
            sem.release()

    t_start = time.monotonic()
    late = 0.0
    for arr in schedule["arrivals"]:
        due = t_start + arr["t"] / speed
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        else:
            late = max(late, -delay)
        sem.acquire()
        th = threading.Thread(target=one, args=(arr,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout + 5)
    stats["span_s"] = round(time.monotonic() - t_start, 6)
    stats["late_s"] = round(late, 6)
    stats["lat_s"] = sorted(lats)
    return stats


# ----------------------------------------------------------- fidelity gate

def fidelity(source_model, recap_model, speed: float, k: int = 5,
             rate_band=(0.9, 1.1), plane: str = "accept") -> dict:
    """Compare the RE-CAPTURED replay traffic against the source model:
    top-K client identity (>= 4/5 of the source's heavy hitters must
    reappear in the replay's sketch, modulo the loopback alias map) and
    per-plane offered-rate shape (recaptured rate / source rate must
    land within rate_band of the replay speed)."""
    amap = client_addr_map(source_model)
    src_top = [kk for kk, _c, _e in
               source_model.data["popularity"].get("clients", {})
               .get("top", [])][:k]
    want = {amap.get(kk, kk) for kk in src_top}
    got = {kk for kk, _c, _e in
           recap_model.data["popularity"].get("clients", {})
           .get("top", [])}
    hits = len(want & got)
    src_rate = source_model.plane_rate(plane)
    rep_rate = recap_model.plane_rate(plane)
    ratio = rep_rate / (src_rate * speed) if src_rate > 0 else 0.0
    out = {
        "topk_want": sorted(want), "topk_hits": hits,
        "rate_source_hz": round(src_rate, 4),
        "rate_replay_hz": round(rep_rate, 4),
        "gates": {
            "topk_identity": _gate(hits, max(1, math.ceil(len(want)
                                                          * 4 / 5)), ">="),
            "rate_ratio_lo": _gate(ratio, rate_band[0], ">="),
            "rate_ratio_hi": _gate(ratio, rate_band[1], "<="),
        },
    }
    out["pass"] = all(g["pass"] for g in out["gates"].values())
    return out


# --------------------------------------------------------- capacity maths

def capacity_row(model, node_capacity_rps: float,
                 users: int = 10_000_000, peak_factor: float = 2.0) -> dict:
    """Nodes needed for a diurnal fleet: the model's mean per-client
    arrival rate (plane rate / distinct heads the sketch saw) scaled to
    `users` at `peak_factor`x diurnal peak, divided by the measured
    per-node serving capacity. Planning arithmetic from MEASURED
    numbers — both inputs ride in the row so the estimate audits."""
    top = model.data["popularity"].get("clients", {}).get("top", [])
    heads = max(1, len(top))
    per_user = model.plane_rate("accept") / heads
    demand = users * per_user * peak_factor
    nodes = (math.ceil(demand / node_capacity_rps)
             if node_capacity_rps > 0 and demand > 0 else 0)
    return {"users": users, "peak_factor": peak_factor,
            "per_user_rps": round(per_user, 6),
            "peak_demand_rps": round(demand, 2),
            "node_capacity_rps": round(node_capacity_rps, 2),
            "nodes_needed": nodes}


# ------------------------------------------------------------- full replay

def run_replay(model, seed: int = None, speed: float = 1.0,
               max_arrivals: int = MAX_ARRIVALS_DEFAULT,
               duration_s: float = 0.0, n_backends: int = 2,
               workers: int = 1, overload: str = "static",
               max_sessions: int = 0, timeout: float = 10.0,
               served_floor: float = 0.9, p99_ms: float = 500.0,
               fidelity_gate: bool = False, rate_band=(0.9, 1.1)) -> dict:
    """capture twin end-to-end: schedule -> ReplayWorld -> SLO verdicts
    (-> fidelity). With fidelity_gate the process-global sketch and
    workload windows are reset around the replay (run it in a dedicated
    process, the bench/storm idiom) so the re-capture sees ONLY the
    synthesized traffic."""
    if seed is None:
        seed = model.seed if model.seed is not None else 0
    sched = build_schedule(model, seed, speed=speed,
                           max_arrivals=max_arrivals,
                           duration_s=duration_s)
    shash = schedule_hash(sched)
    recap = None
    if fidelity_gate:
        from vproxy_tpu.utils import sketch, workload
        sketch.reset()
        workload.reset()
    world = ReplayWorld(n_backends=n_backends, workers=workers,
                        overload=overload, max_sessions=max_sessions)
    try:
        if fidelity_gate:
            from vproxy_tpu.utils import workload
            workload.capture_start()
        res = replay_schedule(sched, world.lb.bind_port, timeout=timeout)
        if fidelity_gate:
            from vproxy_tpu.utils.workload import WorkloadModel, capture_stop
            capture_stop()
            recap = WorkloadModel.fit(seed=seed)
    finally:
        world.close()

    total = res["ok"] + res["fail"] + res["shed"]
    served = res["ok"] / total if total else 0.0
    p99 = _fleetlib.percentile(res["lat_s"], 99) * 1e3
    slo = {
        "hard_failures": _gate(res["fail"], 0, "<="),
        "served_ratio": _gate(served, served_floor, ">="),
        "p99_ms": _gate(p99, p99_ms, "<="),
    }
    report = {
        "seed": int(seed), "speed": float(speed),
        "schedule_hash": shash,
        "arrivals": len(sched["arrivals"]),
        "span_s": res["span_s"], "late_s": res["late_s"],
        "config": {"n_backends": n_backends, "workers": workers,
                   "overload": overload, "max_sessions": max_sessions},
        "results": {"ok": res["ok"], "fail": res["fail"],
                    "shed": res["shed"], "ids": res["ids"]},
        "p50_ms": round(_fleetlib.percentile(res["lat_s"], 50) * 1e3, 3),
        "p99_ms": round(p99, 3),
        "slo": slo,
    }
    if fidelity_gate and recap is not None:
        report["fidelity"] = fidelity(model, recap, speed,
                                      rate_band=rate_band)
        report["recaptured"] = recap.data
    report["pass"] = (all(g["pass"] for g in slo.values())
                      and (report.get("fidelity", {}).get("pass", True)))
    return report


# -------------------------------------------------- seeded source traffic

def drive_zipf_mix(port: int, seed: int, n: int = 200, clients: int = 8,
                   alpha: float = 1.2, keys: int = 12,
                   payload_bytes: int = 2048, timeout: float = 10.0,
                   pace_s: float = 0.0) -> dict:
    """The seeded SOURCE mix for bench/storm capture loops: n sessions
    across `clients` threads, each session's loopback source address
    drawn Zipf(alpha) over `keys` synthetic clients (127.0.1.x) — real
    traffic through the real accept path, with ground-truth heavy
    hitters known in advance. Returns {"ok","fail","shed",
    "true_top": [addr, ...]} ranked hottest first."""
    import random
    rng = random.Random(f"{seed}:mix")
    addrs = [f"127.0.1.{10 + i}" for i in range(keys)]
    weights = [(i + 1) ** -alpha for i in range(keys)]
    draws = rng.choices(range(keys), weights=weights, k=n)
    payload = _payload(payload_bytes)
    lock = threading.Lock()
    stats: dict = {"ok": 0, "fail": 0, "shed": 0}
    counts = [0] * keys

    def worker(idxs) -> None:
        for i in idxs:
            if pace_s:
                time.sleep(pace_s)
            try:
                _fleetlib.one_session(port, payload, timeout,
                                      src_ip=addrs[i])
            except OSError as e:
                with lock:
                    stats["shed" if getattr(e, "shed", False)
                          else "fail"] += 1
            else:
                with lock:
                    stats["ok"] += 1
                    counts[i] += 1
    ts = [threading.Thread(target=worker, args=(draws[c::clients],))
          for c in range(clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    order = sorted(range(keys), key=lambda i: -counts[i])
    stats["true_top"] = [addrs[i] for i in order if counts[i] > 0]
    return stats


# ------------------------------------------------------------------- main

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--model", help="workload model JSON file")
    src.add_argument("--url", help="live GET /workload URL")
    ap.add_argument("--seed", type=int, default=None,
                    help="schedule seed (default: the model's seed, "
                         "else 0); echoed into the report")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="replay rate multiplier (2.0 = twice as fast)")
    ap.add_argument("--max-arrivals", type=int,
                    default=MAX_ARRIVALS_DEFAULT)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="cap schedule span (source-time seconds)")
    ap.add_argument("--backends", type=int, default=2)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--overload", default="static",
                    choices=("static", "adaptive"))
    ap.add_argument("--max-sessions", type=int, default=0)
    ap.add_argument("--served-floor", type=float, default=0.9)
    ap.add_argument("--p99-ms", type=float, default=500.0)
    ap.add_argument("--fidelity", action="store_true",
                    help="re-capture the replayed traffic and gate "
                         "top-K identity + rate shape vs the source")
    ap.add_argument("--hash-only", action="store_true",
                    help="print the schedule hash and exit (the "
                         "cross-process determinism check)")
    ap.add_argument("--out", help="write the JSON report here")
    args = ap.parse_args(argv)

    model = load_model(args.model or args.url)
    seed = args.seed if args.seed is not None else (model.seed or 0)
    if args.hash_only:
        sched = build_schedule(model, seed, speed=args.speed,
                               max_arrivals=args.max_arrivals,
                               duration_s=args.duration)
        print(schedule_hash(sched))
        return 0
    report = run_replay(
        model, seed=seed, speed=args.speed,
        max_arrivals=args.max_arrivals, duration_s=args.duration,
        n_backends=args.backends, workers=args.workers,
        overload=args.overload, max_sessions=args.max_sessions,
        served_floor=args.served_floor, p99_ms=args.p99_ms,
        fidelity_gate=args.fidelity)
    blob = json.dumps(report, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(blob + "\n")
    print(blob)
    print(f"replay: {'PASS' if report['pass'] else 'FAIL'} "
          f"(seed={report['seed']} speed={report['speed']} "
          f"hash={report['schedule_hash'][:12]})", file=sys.stderr)
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
