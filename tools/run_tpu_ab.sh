#!/bin/bash
# One-shot on-chip member-mode A/B + headline + 200k proof.
# Run from the repo root in the DEFAULT env (tunnel attached), one TPU
# client at a time. Artifacts land in .ab_* result files; inspect, then
# copy the winners to BENCH_r05_builder*.json and commit.
set -u
cd "$(dirname "$0")/.."
PH=.ab_phases.jsonl
rm -f "$PH"

run_child() {  # name, extra env...
  local name=$1; shift
  echo "=== $name ==="
  env BENCH_STAGE="$name" BENCH_PHASE_FILE="$PH" \
      BENCH_RESULT_FILE=".ab_$name.json" "$@" \
      timeout -k 60 900 python bench.py --child
  echo "--- $name result:"; cat ".ab_$name.json" 2>/dev/null; echo
}

SMOKE="env BENCH_RULES=1000 BENCH_ROUTES=500 BENCH_ACLS=200 BENCH_BATCH=512 \
BENCH_STEPS_PER_DISPATCH=1024 BENCH_ITERS=32 BENCH_E2E_ITERS=4 \
BENCH_QUERY_SETS=2 BENCH_LAT_ITERS=16 BENCH_SVC_THREADS=4 \
BENCH_SVC_QUERIES=10 BENCH_SVC_POLICY_QUERIES=50 BENCH_CHILD_BUDGET=240"

# 1) smoke-scale verification+rate per lowering (compile-cache-cheap)
for MODE in reduce selgather gather; do
  run_child "ab-smoke-$MODE" $SMOKE VPROXY_TPU_FP_MEMBER="$MODE"
done

echo "*** pick the fastest mode with chk_ok+oracle_ok above, then:"
echo "  env VPROXY_TPU_FP_MEMBER=<mode> BENCH_CHILD_BUDGET=900 \\"
echo "      BENCH_STAGE=full BENCH_RESULT_FILE=.ab_full.json \\"
echo "      timeout -k 60 1200 python bench.py --child"
echo "  # 200k proof:"
echo "  env VPROXY_TPU_FP_MEMBER=<mode> BENCH_RULES=200000 \\"
echo "      BENCH_ROUTES=100000 BENCH_ACLS=10000 BENCH_BATCH=8192 \\"
echo "      BENCH_STAGE=full200k BENCH_RESULT_FILE=.ab_200k.json \\"
echo "      BENCH_CHILD_BUDGET=900 timeout -k 60 1200 python bench.py --child"
