"""vlint clean fixture: the same idioms as the bad fixtures, done
right — every pass must report ZERO findings here (the
no-false-positive contract)."""
import queue

jobs = queue.Queue()


class GatedTable:
    def __init__(self):
        self.version = 0
        self._e = {}

    def _bump(self):
        self.version += 1

    def record(self, k, v):
        self._e[k] = v
        self._bump()

    def _drop(self, k):
        self._e.pop(k, None)  # gated by every caller

    def expire(self, keys):
        for k in keys:
            self._drop(k)
        self._bump()


class CleanPublisher:
    def __init__(self):
        self._pub = (None, [])

    def _recompile(self):
        self._pub = (object(), [1])


class CleanComponent:
    def __init__(self, loop):
        self.loop = loop

    def start(self):
        self.loop.period(1000, self._tick)
        self.loop.delay(10, lambda: jobs.get(False))

    def _tick(self):
        try:
            jobs.get(timeout=0.01)
        except queue.Empty:
            pass


def count(gi):
    gi.get_counter("vproxy_fixture_registered_total").incr()
