"""vlint registry fixture: an increment site referencing a family no
registry ever eagerly creates — invisible on /metrics until the first
event fires (exactly when drop dashboards need the zero)."""


def count_drop(gi):
    gi.get_counter("vproxy_fixture_never_registered_total").incr()


def count_ok(gi):
    gi.get_counter("vproxy_fixture_registered_total").incr()
