"""vlint loop-affinity fixture: callables registered on an event loop
with blocking calls inside — directly, via a nested helper, and via a
lambda — plus non-blocking registrations that must NOT be flagged."""
import queue
import subprocess
import time

work_queue = queue.Queue()


class Component:
    def __init__(self, loop):
        self.loop = loop

    def start(self):
        self.loop.period(1000, self._tick)          # BUG: sleeps
        self.loop.delay(10, lambda: time.sleep(1))  # BUG: lambda sleeps
        self.loop.run_on_loop(self._drain)          # BUG: unbounded get
        self.loop.next_tick(self._rebuild)          # BUG: via helper
        self.loop.delay(20, self._forever)          # BUG: timeout=None
        self.loop.delay(50, self._fine)             # clean
        self.loop.delay(60, self._spawner)          # clean: worker fn

    def _tick(self):
        time.sleep(0.5)

    def _drain(self):
        return work_queue.get()

    def _rebuild(self):
        self._compile()

    def _compile(self):
        subprocess.run(["true"])

    def _forever(self):
        # timeout=None is NOT a bound — it blocks forever
        work_queue.get(timeout=None)

    def _fine(self):
        work_queue.get(timeout=0.1)
        work_queue.get(False)

    def _spawner(self):
        # a sleeping fn DEFINED here but never called on the loop
        # (handed to a worker thread) must not be attributed to the
        # callback — the nested-def subtree is a separate callable
        def worker():
            time.sleep(5)
            subprocess.run(["true"])
        return worker
