// vlint ABI-pass fixture: a mirror whose TOTAL size matches the
// python side exactly but whose fields drifted — the compensating-
// error case the old sizeof-only guards let through. The python half
// is bad_abi_vtl.py; tests/test_vlint.py asserts the pass flags the
// swapped pair field-by-field.
#include <stdint.h>

#pragma pack(push, 1)
struct BadRec {
  uint32_t conn_id;
  uint16_t flags;     // python mirror has `port` (u16) here — name drift
  uint8_t tag[4];     // python mirror has a u32 here — same size, wrong type
  int32_t backend;
};
struct CleanRec {
  uint32_t conn_id;
  uint16_t port;
  uint8_t v6;
  uint8_t weight;
};
#pragma pack(pop)
