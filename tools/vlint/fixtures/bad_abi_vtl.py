"""vlint ABI-pass fixture — the python half of bad_abi.cpp.

BAD_REC totals 14 bytes, exactly like the C BadRec, but field 2 is
named/typed differently and field 3 swapped a u32 for a 4-byte array:
total-size guards pass, the field-by-field pass must not. CLEAN_REC
mirrors CleanRec exactly (the no-false-positive case).
"""
import struct

BAD_REC = struct.Struct("<IHIi")
BAD_REC_FIELDS = ("conn_id", "port", "peer_ip", "backend")

CLEAN_REC = struct.Struct("<IHBB")
CLEAN_REC_FIELDS = ("conn_id", "port", "v6", "weight")
