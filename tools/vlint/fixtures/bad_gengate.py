"""vlint generation-gate fixture: FlowTable mirrors the guarded-store
idiom (a _bump gate over a compiled-state source of truth) with one
mutation path that skips the gate — the exact bug class the pass
exists for. tests/test_vlint.py runs the pass with a Guard spec
pointing here and asserts exactly the ungated path is flagged."""


class FlowTable:
    def __init__(self):
        self.version = 0
        self.on_change = None
        self._e = {}

    def _bump(self):
        self.version += 1
        if self.on_change is not None:
            self.on_change()

    def record(self, k, v):
        self._e[k] = v
        self._bump()

    def remove(self, k):
        self._e.pop(k, None)
        self._bump()

    def remove_silently(self, k):
        # BUG (seeded): mutation with no gate on any path
        del self._e[k]

    def _drop(self, k):
        # helper with no in-body gate: legal — every caller gates
        self._e.pop(k, None)

    def expire(self, keys):
        for k in keys:
            self._drop(k)
        self._bump()


class Publisher:
    def __init__(self):
        self._pub = (None, [])

    def _recompile(self):
        self._pub = (object(), [1])

    def hot_patch(self):
        # BUG (seeded): pub-tuple assignment outside the installer
        self._pub = (None, [2])
