"""vlint pass 2 — the generation-gate audit.

The native planes (flow cache, accept lanes) and the engine serve from
compiled state that is only correct while a generation atomic / atomic
pub-tuple says so: every mutation of the source-of-truth stores MUST
bump the gate on the same path, or a stale compiled entry keeps
serving traffic the mutation just outlawed (the exact failure the
`switch.flowcache.stale` / `lane.entry.stale` failpoints exist to
prove). The convention is enforced here as config: GUARDS names every
guarded store and the gate calls that protect it, and the pass flags
any function that mutates a guarded store with no gate reachable on
the path — in its own body, in a callee (the gate may be downstream:
add_route -> _sync_routes), or in every one of its callers (helpers
like SyntheticIpHolder._unindex_mac are gated by construction when all
call sites gate).

Publish-tuple stores (`_pub` on the matchers, the membership steering
tuple) use the stricter `only_in` form: assignment anywhere outside
the designated installer methods is a finding regardless of gating —
the TableInstaller swap IS the gate.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from . import Finding

MUT_METHODS = {"append", "add", "remove", "pop", "popitem", "clear",
               "update", "insert", "extend", "setdefault", "discard",
               "sort"}

_MAX_DEPTH = 4  # bounded closure over the intra-module call graph


@dataclass
class Guard:
    module: str                      # repo-relative source path
    cls: Optional[str]               # class scope; None = whole module
    attrs: frozenset = frozenset()   # guarded self.<attr> stores
    gates: frozenset = frozenset()   # gate call names
    elem_attrs: frozenset = frozenset()  # guarded <obj>.<attr> writes
    only_in: Optional[frozenset] = None  # publish-only methods
    exempt: frozenset = frozenset()  # deliberate exceptions (baselined
                                     # instead where possible)


# The guarded-store catalog. Growing a new generation-gated store
# (conntrack entries, O(delta) installs — the roadmap items this pass
# exists for) means adding its Guard here; tests/test_vlint.py's
# fixtures prove each rule form fires.
GUARDS: List[Guard] = [
    # switch flow cache (PR 5): MAC/ARP/synthetic-ip/route/iface
    # mutations must reach Switch._gen_bump (one C atomic)
    Guard("vproxy_tpu/vswitch/network.py", "MacTable",
          attrs=frozenset({"_e"}), gates=frozenset({"_bump"})),
    Guard("vproxy_tpu/vswitch/network.py", "ArpTable",
          attrs=frozenset({"_e"}), gates=frozenset({"_bump"})),
    Guard("vproxy_tpu/vswitch/network.py", "SyntheticIpHolder",
          attrs=frozenset({"_ips", "_by_mac"}),
          gates=frozenset({"on_change"})),
    Guard("vproxy_tpu/vswitch/network.py", "VpcNetwork",
          attrs=frozenset({"routes"}),
          gates=frozenset({"_sync_routes", "on_route_change"})),
    Guard("vproxy_tpu/vswitch/switch.py", "Switch",
          attrs=frozenset({"ifaces", "networks"}),
          gates=frozenset({"_bump_registry", "_gen_bump"})),
    # accept lanes (PR 8): backend membership / weight / health edges
    # and upstream/ACL mutations must fire the change listeners the
    # lane compiler subscribes to (lane_gen_bump rides them)
    Guard("vproxy_tpu/components/servergroup.py", "ServerGroup",
          attrs=frozenset({"servers"}),
          elem_attrs=frozenset({"weight", "healthy", "ejected"}),
          gates=frozenset({"_recalc", "_notify"})),
    Guard("vproxy_tpu/components/upstream.py", "Upstream",
          attrs=frozenset({"handles"}),
          gates=frozenset({"_fire"})),
    Guard("vproxy_tpu/components/secgroup.py", "SecurityGroup",
          attrs=frozenset({"_rules"}),
          gates=frozenset({"_fire"})),
    # matcher pub-tuples (PR 6/10/11): ONLY the installer swaps them
    Guard("vproxy_tpu/rules/engine.py", "HintMatcher",
          attrs=frozenset({"_pub"}),
          only_in=frozenset({"__init__", "_recompile"})),
    Guard("vproxy_tpu/rules/engine.py", "CidrMatcher",
          attrs=frozenset({"_pub"}),
          only_in=frozenset({"__init__", "_recompile"})),
    Guard("vproxy_tpu/rules/maglev.py", "MaglevMatcher",
          attrs=frozenset({"_pub"}),
          only_in=frozenset({"__init__", "_recompile"})),
    # cluster steering table (PR 10): atomic tuple publish, one builder
    Guard("vproxy_tpu/cluster/membership.py", "Membership",
          attrs=frozenset({"_maglev"}),
          only_in=frozenset({"__init__", "_maglev_build"})),
]


@dataclass
class _FnInfo:
    name: str
    node: ast.FunctionDef
    mutated: List = field(default_factory=list)  # (attr, lineno)
    gates: bool = False
    calls: Set[str] = field(default_factory=set)


def _self_attr(node, attrs: frozenset) -> Optional[str]:
    """node is `self.<a>` or `self.<a>[...]` for a guarded a -> a."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute) and node.attr in attrs
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _scan_fn(fn: ast.FunctionDef, g: Guard) -> _FnInfo:
    info = _FnInfo(fn.name, fn)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in elts:
                    a = _self_attr(e, g.attrs)
                    if a is not None:
                        info.mutated.append((a, node.lineno))
                    elif (g.elem_attrs and isinstance(e, ast.Attribute)
                          and e.attr in g.elem_attrs
                          and not (isinstance(e.value, ast.Name)
                                   and e.value.id == "self")):
                        info.mutated.append((e.attr, node.lineno))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                a = _self_attr(t, g.attrs)
                if a is not None:
                    info.mutated.append((a, node.lineno))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in g.gates:
                    info.gates = True
                if (f.attr in MUT_METHODS
                        and _self_attr(f.value, g.attrs) is not None):
                    info.mutated.append(
                        (_self_attr(f.value, g.attrs), node.lineno))
                if (isinstance(f.value, ast.Name)
                        and f.value.id == "self"):
                    info.calls.add(f.attr)
            elif isinstance(f, ast.Name):
                if f.id in g.gates:
                    info.gates = True
                info.calls.add(f.id)
    return info


def _functions(tree: ast.Module, cls: Optional[str]) -> List[ast.FunctionDef]:
    """Methods of `cls`, or every function/method in the module."""
    out: List[ast.FunctionDef] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and (cls is None
                                               or node.name == cls):
            out.extend(n for n in node.body
                       if isinstance(n, ast.FunctionDef))
        elif cls is None and isinstance(node, ast.FunctionDef):
            out.append(node)
    return out


def _downstream_gated(name: str, infos: Dict[str, _FnInfo],
                      seen: Set[str], depth: int = 0) -> bool:
    if name in seen or depth > _MAX_DEPTH:
        return False
    info = infos.get(name)
    if info is None:
        return False
    if info.gates:
        return True
    seen.add(name)
    return any(_downstream_gated(c, infos, seen, depth + 1)
               for c in info.calls if c in infos)


def _caller_gated(name: str, infos: Dict[str, _FnInfo],
                  callers: Dict[str, Set[str]], seen: Set[str],
                  depth: int = 0) -> bool:
    """Every caller reaches a gate (in its own downstream closure) or
    is itself fully caller-gated. Zero callers = not gated (dead or
    externally-called helper: the mutation escapes unguarded)."""
    if name in seen or depth > _MAX_DEPTH:
        return False
    seen.add(name)
    cs = callers.get(name, set())
    if not cs:
        return False
    for c in cs:
        if _downstream_gated(c, infos, set()):
            continue
        if not _caller_gated(c, infos, callers, seen, depth + 1):
            return False
    return True


def check_gengate(root: str,
                  guards: Optional[List[Guard]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for g in (guards if guards is not None else GUARDS):
        path = os.path.join(root, g.module)
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), path)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("gengate", f"gengate:{g.module}:parse",
                                    path, 0, f"cannot parse: {e}"))
            continue
        fns = _functions(tree, g.cls)
        if g.cls is not None and not fns:
            findings.append(Finding(
                "gengate", f"gengate:{g.module}:{g.cls}:missing", path, 0,
                f"guarded class {g.cls} not found (stale GUARDS entry?)"))
            continue
        infos = {fn.name: _scan_fn(fn, g) for fn in fns}
        callers: Dict[str, Set[str]] = {}
        for name, info in infos.items():
            for c in info.calls:
                callers.setdefault(c, set()).add(name)
        scope = g.cls or os.path.basename(g.module)
        for name, info in infos.items():
            if not info.mutated or name in g.exempt:
                continue
            if g.only_in is not None:
                if name not in g.only_in:
                    for attr, ln in info.mutated:
                        findings.append(Finding(
                            "gengate",
                            f"gengate:{scope}.{name}:{attr}", path, ln,
                            f"{scope}.{name} assigns {attr!r} outside "
                            f"the designated publish methods "
                            f"({', '.join(sorted(g.only_in))}) — "
                            f"published state must swap atomically "
                            f"through the installer"))
                continue
            if name == "__init__":
                continue  # construction precedes any compiled consumer
            if _downstream_gated(name, infos, set()):
                continue
            if _caller_gated(name, infos, callers, set()):
                continue
            for attr, ln in info.mutated:
                findings.append(Finding(
                    "gengate", f"gengate:{scope}.{name}:{attr}", path,
                    ln,
                    f"{scope}.{name} mutates guarded store {attr!r} "
                    f"with no {'/'.join(sorted(g.gates))} call "
                    f"reachable on the path — a compiled native/"
                    f"device entry can serve stale state"))
    return findings
