"""vlint pass 3 — metric-family and failpoint registry audit.

Two registries hold this repo's observability honest, and both drift
silently when unchecked:

* **Metrics** (PR-9 rule: silent drops counted, families pre-registered
  so a scrape shows the ZERO before the first event). The audit builds
  the eager set — the families a fresh `GlobalInspection.get()`
  registers, probed in a clean subprocess so test-session leftovers
  can't leak in — and flags every family referenced at a call site
  that is NOT eagerly registered: that family is invisible on /metrics
  until its first increment, which is exactly when dashboards alerting
  on "metric missing vs metric zero" stop working. Families whose
  label sets only exist at runtime (per-LB, per-group) are deliberate
  exceptions carried in baseline.toml with the justification inline.
  Docs naming a family that exists nowhere in code are findings too.

* **Failpoints** (utils/failpoint.py SITES is the catalog). A
  `failpoint.hit()` site whose name is not in SITES can never be
  armed; a SITES entry with no hit() site arms successfully and then
  never fires — a chaos run "passes" while injecting nothing. Both
  directions are findings, as are `arm()` calls and doc references to
  nonexistent sites.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
import subprocess
import sys
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, py_files

# dotted doc tokens that look like failpoint sites but are not
FAILPOINT_DOC_ALLOW = {"cluster.vproxy.local"}

# a family name is exactly this (the package name and dotted module
# paths also start with "vproxy_" — they are not metric families)
_FAMILY = re.compile(r"^vproxy_[a-z0-9_]+$")

# modules whose import-time registrations define the eager set: the
# core registry plus every subsystem that pre-registers its closed
# label vocabularies at import (a process that never imports a
# subsystem correctly never scrapes its families either)
REGISTRY_MODULES = ("vproxy_tpu.utils.metrics",
                    "vproxy_tpu.vswitch.swmetrics")

_EAGER_PROBE = r"""
import importlib
import sys
from vproxy_tpu.utils.metrics import GlobalInspection
for mod in %r[1:]:
    importlib.import_module(mod)
gi = GlobalInspection.get()
names = set()
with gi.registry._lock:
    for m in gi.registry._metrics:
        names.add(m.name)
for (name, _labels) in gi._named:
    names.add(name)
sys.stdout.write("\n".join(sorted(names)))
"""

_eager_cache: Dict[str, Optional[Set[str]]] = {}


def eager_metric_families(root: str) -> Optional[Set[str]]:
    """The families a fresh process registers before any traffic —
    probed in a subprocess (a test session's lazily-created families
    must not leak into the registered set and mask findings). None
    when the probe itself fails (reported as a finding, not a crash)."""
    if root in _eager_cache:
        return _eager_cache[root]
    try:
        r = subprocess.run(
            [sys.executable, "-c", _EAGER_PROBE % (REGISTRY_MODULES,)],
            cwd=root,
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "VPROXY_TPU_FD_PROVIDER": "py"})
        out = set(r.stdout.split()) if r.returncode == 0 else None
    except (OSError, subprocess.TimeoutExpired):
        out = None
    _eager_cache[root] = out
    return out


def _parse(path: str):
    try:
        with open(path) as f:
            return ast.parse(f.read(), path)
    except (OSError, SyntaxError):
        return None


def metric_references(root: str,
                      files: Optional[List[str]] = None
                      ) -> Dict[str, List[Tuple[str, int]]]:
    """Every call whose first positional argument is a "vproxy_*"
    string literal is a family reference — this catches the registry
    methods, the raw Metric constructors AND module-local memo wrappers
    (swmetrics._ctr) without a brittle method-name list."""
    refs: Dict[str, List[Tuple[str, int]]] = {}
    for path in files if files is not None else py_files(
            root, ["vproxy_tpu"]):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _FAMILY.match(node.args[0].value)
                    and node.args[0].value != "vproxy_tpu"):
                refs.setdefault(node.args[0].value, []).append(
                    (path, node.lineno))
    return refs


_DOC_METRIC = re.compile(r"\bvproxy_[a-z0-9_]+\b")
_EXPO_SUFFIXES = ("_bucket", "_sum", "_count")


def _doc_files(root: str) -> List[str]:
    docs = os.path.join(root, "docs")
    if not os.path.isdir(docs):
        return []
    return sorted(os.path.join(docs, f) for f in os.listdir(docs)
                  if f.endswith(".md"))


def check_metrics(root: str,
                  files: Optional[List[str]] = None,
                  eager_override: Optional[Set[str]] = None
                  ) -> List[Finding]:
    findings: List[Finding] = []
    eager = eager_override if eager_override is not None \
        else eager_metric_families(root)
    refs = metric_references(root, files=files)
    if eager is None:
        findings.append(Finding(
            "registry", "metric-probe", root, 0,
            "could not probe the eager metric registry (fresh "
            "GlobalInspection subprocess failed)"))
        eager = set()
    else:
        for name, sites in sorted(refs.items()):
            if name in eager:
                continue
            path, line = sites[0]
            findings.append(Finding(
                "registry", f"metric-unregistered:{name}", path, line,
                f"metric family {name!r} is created at its increment "
                f"site only — never eagerly registered, so /metrics "
                f"cannot show the zero before the first event "
                f"(PR-9 silent-drops rule)"))
    if files is not None:
        return findings  # fixture run: no doc cross-check
    known = eager | set(refs)
    for path in _doc_files(root):
        with open(path) as f:
            text = f.read()
        for ln, line in enumerate(text.splitlines(), 1):
            for tok in _DOC_METRIC.findall(line):
                name = tok
                # the package name, and prose family-prefix references
                # like "vproxy_cluster_{peers_up,...}" (token ends at
                # the brace, leaving a trailing underscore)
                if name == "vproxy_tpu" or name.endswith("_"):
                    continue
                if name not in known:
                    for suf in _EXPO_SUFFIXES:
                        if name.endswith(suf) and name[:-len(suf)] in known:
                            name = name[:-len(suf)]
                            break
                if name not in known:
                    findings.append(Finding(
                        "registry", f"metric-doc:{tok}", path, ln,
                        f"docs reference metric family {tok!r} which "
                        f"exists nowhere in the tree"))
    return findings


# ------------------------------------------------------------ failpoints

def failpoint_sites(root: str) -> Set[str]:
    """The SITES catalog, from utils/failpoint.py's AST."""
    path = os.path.join(root, "vproxy_tpu", "utils", "failpoint.py")
    tree = _parse(path)
    if tree is None:
        return set()
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SITES"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)}
    return set()


def _call_name_refs(root: str, dirs, method: str
                    ) -> Dict[str, List[Tuple[str, int]]]:
    """First-arg string literals of every `<x>.method("...")` /
    `method("...")` call under dirs."""
    refs: Dict[str, List[Tuple[str, int]]] = {}
    for path in py_files(root, dirs):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name == method:
                refs.setdefault(node.args[0].value, []).append(
                    (path, node.lineno))
    return refs


def _site_token_re(sites: Set[str]) -> re.Pattern:
    prefixes = sorted({s.split(".")[0] for s in sites})
    return re.compile(r"\b(?:" + "|".join(prefixes)
                      + r")(?:\.[a-z_*]+)+\b")


def _two_seg(tok: str) -> str:
    return ".".join(tok.split(".")[:2])


def check_failpoints(root: str) -> List[Finding]:
    findings: List[Finding] = []
    sites = failpoint_sites(root)
    fp_py = os.path.join(root, "vproxy_tpu", "utils", "failpoint.py")
    if not sites:
        return [Finding("registry", "failpoint-catalog", fp_py, 0,
                        "could not extract the SITES catalog")]
    hits = _call_name_refs(root, ["vproxy_tpu"], "hit")
    # hit() names that aren't sites never fire (hit() returns False
    # silently for unknown names — the injection is dead code)
    for name, where in sorted(hits.items()):
        if name not in sites:
            path, line = where[0]
            findings.append(Finding(
                "registry", f"failpoint-unknown-hit:{name}", path, line,
                f"failpoint.hit({name!r}) names a site missing from "
                f"SITES — it can never be armed and never fires"))
    # sites with no hit() anywhere arm successfully and inject nothing
    for name in sorted(sites):
        if name not in hits:
            findings.append(Finding(
                "registry", f"failpoint-orphan:{name}", fp_py, 0,
                f"failpoint site {name!r} is in SITES but has no "
                f"failpoint.hit() site — arming it injects nothing "
                f"and every chaos run 'passes'"))
    # arm() references in tests/tools/verify drives must resolve
    arm_dirs = ["vproxy_tpu", "tests", "tools"]
    arm_dirs += [f for f in os.listdir(root)
                 if f.startswith("_verify") and f.endswith(".py")]
    for name, where in sorted(_call_name_refs(root, arm_dirs,
                                              "arm").items()):
        if name not in sites:
            path, line = where[0]
            findings.append(Finding(
                "registry", f"failpoint-unknown-arm:{name}", path, line,
                f"arm({name!r}) names a site missing from SITES"))
    # docs: dotted tokens in site namespaces must be sites (or site
    # prefixes / globs — "backend.connect.*" is a family reference).
    # Docs also mention python attributes ("engine.flush_installs") in
    # the same first-segment namespaces, so a token is only suspicious
    # when its two-segment prefix matches a real site family — the
    # precision/recall trade documented in docs/static-analysis.md.
    tok_re = _site_token_re(sites)
    two_segs = {_two_seg(s) for s in sites}
    for path in _doc_files(root):
        with open(path) as f:
            text = f.read()
        for ln, line in enumerate(text.splitlines(), 1):
            for tok in tok_re.findall(line):
                if tok in sites or tok in FAILPOINT_DOC_ALLOW:
                    continue
                if _two_seg(tok) not in two_segs:
                    continue
                if "*" in tok and any(fnmatch.fnmatch(s, tok)
                                      for s in sites):
                    continue
                if any(s.startswith(tok + ".") for s in sites):
                    continue
                findings.append(Finding(
                    "registry", f"failpoint-doc:{tok}", path, ln,
                    f"docs reference failpoint {tok!r} which is not "
                    f"in SITES"))
    return findings


def check_registry(root: str) -> List[Finding]:
    return check_metrics(root) + check_failpoints(root)
