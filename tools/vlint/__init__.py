"""vlint — the repo's invariant-checking static analyzer.

Eleven PRs grew a system whose correctness rests on conventions no
compiler checks: C structs mirrored byte-for-byte in net/vtl.py, every
mutation of replicated state bumping a generation atomic, every metric
family pre-registered so scrapes show the zero, and event-loop
callbacks that must never block. The reference survives on Java's
memory model and type system; this Python+C+device split has neither,
so the invariants are machine-enforced here — run as a tier-1 test
(tests/test_vlint.py) and as `python -m tools.vlint` locally.

Four passes (docs/static-analysis.md is the operator reference):

* abi      — field-by-field C/python struct parity (structs.py)
* gengate  — generation-gate audit over guarded stores (gengate.py)
* registry — metric + failpoint registry audit (registry.py)
* loop     — loop-affinity lint: no blocking calls in callables
             registered on a SelectorEventLoop (loopcheck.py)

Findings carry a stable `key`; deliberate exceptions live in
baseline.toml next to this file with one-line justifications, so the
tier-1 gate is delta-based: new findings fail, baselined ones don't,
and a baseline entry whose finding disappeared is reported stale.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Finding:
    pass_name: str   # abi | gengate | registry | loop
    key: str         # stable identity for baseline matching
    path: str
    line: int
    message: str
    baselined: bool = False
    baseline_reason: str = ""

    def format(self) -> str:
        loc = f"{os.path.relpath(self.path)}:{self.line}" if self.line \
            else os.path.relpath(self.path)
        tag = " [baselined]" if self.baselined else ""
        return f"[{self.pass_name}] {loc}: {self.message} " \
               f"(key={self.key}){tag}"


# ------------------------------------------------------------- baseline
#
# baseline.toml is a flat [[finding]] list:
#
#   [[finding]]
#   pass = "registry"
#   key = "metric-unregistered:vproxy_lb_retries_total"
#   reason = "per-LB label set exists only after an LB is configured"
#
# Python 3.10 has no tomllib and the container must not grow deps, so
# this is a parser for exactly that subset: [[finding]] table headers
# and `key = "string"` pairs. Anything fancier is a config error.

def py_files(root: str, rel_dirs) -> List[str]:
    """Sorted .py paths under root-relative dirs/files, skipping
    __pycache__ and dot-dirs (shared by the registry and loop passes)."""
    out: List[str] = []
    for rel in rel_dirs:
        base = os.path.join(root, rel)
        if os.path.isfile(base):
            out.append(base)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            out.extend(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py"))
    return sorted(out)


def parse_baseline(path: str) -> List[Dict[str, str]]:
    if not os.path.exists(path):
        return []
    out: List[Dict[str, str]] = []
    cur: Optional[Dict[str, str]] = None
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[finding]]":
                cur = {}
                out.append(cur)
                continue
            if "=" in line and cur is not None:
                k, _, v = line.partition("=")
                k, v = k.strip(), v.strip()
                if not (len(v) >= 2 and v[0] == '"' and v[-1] == '"'):
                    raise ValueError(
                        f"{path}:{ln}: expected key = \"string\"")
                cur[k] = v[1:-1]
                continue
            raise ValueError(f"{path}:{ln}: unparseable line {line!r}")
    for i, ent in enumerate(out):
        if "key" not in ent or "reason" not in ent:
            raise ValueError(
                f"{path}: finding #{i + 1} needs both key and reason")
    return out


def apply_baseline(findings: List[Finding],
                   baseline: List[Dict[str, str]]) -> List[str]:
    """Mark baselined findings in place; -> stale baseline keys (entries
    whose finding no longer occurs — prune them, they hide nothing)."""
    by_key = {e["key"]: e for e in baseline}
    seen = set()
    for f in findings:
        ent = by_key.get(f.key)
        if ent is not None and ent.get("pass", f.pass_name) == f.pass_name:
            f.baselined = True
            f.baseline_reason = ent["reason"]
            seen.add(f.key)
    return [k for k in by_key if k not in seen]


# -------------------------------------------------------------- run_all

@dataclass
class Report:
    findings: List[Finding]
    stale_baseline: List[str]
    elapsed_s: float
    counts: Dict[str, int] = field(default_factory=dict)

    @property
    def open_findings(self) -> List[Finding]:
        return [f for f in self.findings if not f.baselined]


def run_all(root: Optional[str] = None,
            baseline_path: Optional[str] = None) -> Report:
    """Run all four passes over the tree; apply the committed baseline
    (pass baseline_path="" to skip). The whole run must stay inside the
    tier-1 10s budget — every pass is parse-only plus one in-process
    metrics-registry instantiation."""
    from . import gengate, loopcheck, registry, structs
    t0 = time.monotonic()
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    findings: List[Finding] = []
    findings += structs.check_abi(root)
    findings += gengate.check_gengate(root)
    findings += registry.check_registry(root)
    findings += loopcheck.check_loops(root)
    # the baseline belongs to the ANALYZED tree (a --root run over a
    # checkout must honor that checkout's exceptions, not the ones
    # committed next to whichever copy of the analyzer is imported)
    bp = os.path.join(root, "tools", "vlint", "baseline.toml") \
        if baseline_path is None else baseline_path
    stale = apply_baseline(findings, parse_baseline(bp)) if bp else []
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.pass_name] = counts.get(f.pass_name, 0) + 1
    return Report(findings, stale, time.monotonic() - t0, counts)


def snapshot(report: Report) -> dict:
    """The bench.py `static_analysis` artifact row: finding counts by
    pass + baseline totals, so the trajectory artifacts show drift."""
    return {
        "findings_by_pass": dict(sorted(report.counts.items())),
        "findings_total": len(report.findings),
        "baselined": sum(1 for f in report.findings if f.baselined),
        "open": len(report.open_findings),
        "stale_baseline": len(report.stale_baseline),
        "elapsed_s": round(report.elapsed_s, 3),
    }
