"""vlint pass 4 — loop-affinity lint.

A SelectorEventLoop is a single thread: every registered callable —
readiness handlers (`loop.add`), timers (`delay`/`period`),
cross-thread submits (`run_on_loop`/`call_sync`/`next_tick`) — runs
inline on it, and one blocking call stalls every session, timer and
health probe that loop owns. PR 10 learned this the hard way when a
65537-slot maglev table build landed on a serving loop via a listener
callback; the stall counters (vproxy_loop_callback_us_max) only show
the damage after the fact. This pass flags the known blocking families
*statically*, at registration time:

* time.sleep
* subprocess.* (run/call/check_*/Popen)
* blocking socket module ops (create_connection, getaddrinfo,
  gethostby*) — loop code uses the nonblocking vtl layer
* unbounded queue.get (no timeout, block not False)

Resolution is bounded and honest: the callback expression is resolved
within its module (lambda bodies, nested defs, same-class methods,
module functions, functools.partial), and its callees are followed two
levels inside the same scope. Cross-module calls are not followed —
a deliberate precision/recall trade documented in
docs/static-analysis.md; deliberate exceptions go in baseline.toml.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from . import Finding, py_files

# sink method name -> callback argument index
SINKS = {"delay": 1, "period": 1, "next_tick": 0, "run_on_loop": 0,
         "call_sync": 0, "add": 2}

_SOCKET_BLOCKING = {"create_connection", "getaddrinfo", "gethostbyname",
                    "gethostbyaddr", "getfqdn"}

_MAX_DEPTH = 2


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _looks_like_loop(recv: ast.AST) -> bool:
    return "loop" in _unparse(recv).lower()


def _walk_own_code(body: List[ast.stmt]):
    """Yield this body's nodes WITHOUT descending into nested
    defs/lambdas — those are separate callables (a sleeping worker-
    thread fn defined inline must not be attributed to the enclosing
    callback; it is followed only if actually called). ast.walk +
    `continue` cannot express this: continue skips the def node itself
    but its subtree is already queued."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # children deliberately NOT pushed
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_calls(fn_body: List[ast.stmt]) -> List[Tuple[int, str]]:
    """(lineno, description) for every blocking call directly in this
    body (nested defs/lambdas excluded — see _walk_own_code)."""
    out: List[Tuple[int, str]] = []
    for node in _walk_own_code(fn_body):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        recv = _unparse(f.value)
        if f.attr == "sleep" and recv == "time":
            out.append((node.lineno, "time.sleep"))
        elif recv in ("subprocess",) and f.attr in (
                "run", "call", "check_call", "check_output",
                "Popen"):
            out.append((node.lineno, f"subprocess.{f.attr}"))
        elif recv in ("socket", "_socket") \
                and f.attr in _SOCKET_BLOCKING:
            out.append((node.lineno, f"socket.{f.attr}"))
        elif f.attr == "get" and ("queue" in recv.lower()
                                  or recv.lower().endswith("_q")):
            if _queue_get_unbounded(node):
                out.append((node.lineno, f"{recv}.get() without "
                            "timeout"))
    return out


def _queue_get_unbounded(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            # timeout=None blocks forever — only a real value bounds it
            return isinstance(kw.value, ast.Constant) \
                and kw.value.value is None
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return False  # q.get(False) is nonblocking
        if len(call.args) >= 2:
            return False  # q.get(block, timeout)
    return True


class _Scope:
    """Resolution environment for one registration site."""

    def __init__(self, module_fns: Dict[str, ast.FunctionDef],
                 class_fns: Dict[str, ast.FunctionDef],
                 local_fns: Dict[str, ast.FunctionDef]):
        self.module_fns = module_fns
        self.class_fns = class_fns
        self.local_fns = local_fns

    def resolve(self, name: str) -> Optional[ast.FunctionDef]:
        return (self.local_fns.get(name) or self.class_fns.get(name)
                or self.module_fns.get(name))


def _callee_names(body: List[ast.stmt]) -> List[str]:
    out = []
    for node in _walk_own_code(body):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.append(f.id)
            elif isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name) and f.value.id == "self":
                out.append(f.attr)
    return out


def _scan_callable(body: List[ast.stmt], scope: _Scope, depth: int,
                   seen: set) -> List[Tuple[int, str]]:
    found = _blocking_calls(body)
    if depth >= _MAX_DEPTH:
        return found
    for name in _callee_names(body):
        if name in seen:
            continue
        seen.add(name)
        fn = scope.resolve(name)
        if fn is not None:
            for ln, what in _scan_callable(fn.body, scope, depth + 1,
                                           seen):
                found.append((ln, f"{what} (via {name}())"))
    return found


def _resolve_cb(expr: ast.AST, scope: _Scope):
    """-> (body, label) for the callback expression, or None."""
    if isinstance(expr, ast.Lambda):
        return [ast.Expr(expr.body)], "<lambda>"
    if isinstance(expr, ast.Call):  # functools.partial(fn, ...)
        f = expr.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if fname == "partial" and expr.args:
            return _resolve_cb(expr.args[0], scope)
        return None
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name) and expr.value.id == "self":
        name = expr.attr
    if name is None:
        return None
    fn = scope.resolve(name)
    if fn is None:
        return None
    return fn.body, name


def check_loops(root: str,
                files: Optional[List[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in files if files is not None else py_files(
            root, ["vproxy_tpu"]):
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), path)
        except (OSError, SyntaxError):
            continue
        module_fns = {n.name: n for n in tree.body
                      if isinstance(n, ast.FunctionDef)}
        units: List[Tuple[Dict, ast.FunctionDef]] = []
        for n in tree.body:
            if isinstance(n, ast.FunctionDef):
                units.append(({}, n))
            elif isinstance(n, ast.ClassDef):
                cls_fns = {m.name: m for m in n.body
                           if isinstance(m, ast.FunctionDef)}
                units.extend((cls_fns, m) for m in cls_fns.values())
        rel = os.path.relpath(path, root)
        for cls_fns, fn in units:
            local_fns = {d.name: d for d in ast.walk(fn)
                         if isinstance(d, ast.FunctionDef) and d is not fn}
            scope = _Scope(module_fns, cls_fns, local_fns)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in SINKS):
                    continue
                idx = SINKS[node.func.attr]
                if len(node.args) <= idx:
                    continue
                if not _looks_like_loop(node.func.value):
                    continue
                resolved = _resolve_cb(node.args[idx], scope)
                if resolved is None:
                    continue
                body, label = resolved
                for ln, what in _scan_callable(body, scope, 0,
                                               {label}):
                    findings.append(Finding(
                        "loop", f"loop:{rel}:{fn.name}:{label}:{what}",
                        path, ln,
                        f"{label} is registered on an event loop at "
                        f"{rel}:{node.lineno} ({node.func.attr}) but "
                        f"contains blocking call {what} — one call "
                        f"stalls every session on that loop"))
    return findings
