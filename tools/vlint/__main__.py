"""`python -m tools.vlint` — run the analyzer from the repo root.

Exit codes: 0 clean (baselined findings allowed), 1 open findings or
stale baseline entries, 2 the analyzer itself failed. `--json` emits
the bench.py snapshot row; `--all` lists baselined findings too;
`--no-baseline` shows the raw findings (the triage view).
"""
from __future__ import annotations

import json
import os
import sys

from . import run_all, snapshot


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = None
    for i, a in enumerate(argv):
        if a == "--root" and i + 1 < len(argv):
            root = argv[i + 1]
    if root is None:
        root = os.getcwd()
    baseline_path = "" if "--no-baseline" in argv else None
    rep = run_all(root, baseline_path=baseline_path)
    if "--json" in argv:
        print(json.dumps(snapshot(rep), indent=2))
    else:
        shown = rep.findings if "--all" in argv else rep.open_findings
        for f in shown:
            print(f.format())
        for k in rep.stale_baseline:
            print(f"[baseline] stale entry {k!r}: finding no longer "
                  f"occurs — prune it from baseline.toml")
        print(f"# vlint: {len(rep.findings)} findings "
              f"({len(rep.open_findings)} open, "
              f"{sum(1 for f in rep.findings if f.baselined)} "
              f"baselined, {len(rep.stale_baseline)} stale baseline) "
              f"in {rep.elapsed_s:.2f}s")
    return 1 if (rep.open_findings or rep.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
