"""vlint pass 1 — ABI parity between native/vtl.cpp and net/vtl.py.

The C structs shared across the ctypes boundary (#pragma pack(push, 1)
blocks in vtl.cpp) are mirrored byte-for-byte by struct.Struct format
strings in net/vtl.py. Until this pass, the only guards were total-size
asserts (`static_assert(sizeof(...))` in C, `vtl_*_rec_size()` at load
time) — which let two compensating field errors through: swap a u32
with a 4-byte array, or reorder two u16s, and every size check still
passes while C and Python silently read each other's fields.

This module extracts BOTH sides into one field-level model:

* C side: a small parser over the packed regions of vtl.cpp — struct
  defs, per-field type/name/array-length, nested packed structs
  flattened (FlowRec embeds FlowKey), offsets/sizes computed from the
  pack(1) rule (no padding, declaration order).
* Python side: the struct.Struct("<...>") format strings plus the
  *_FIELDS name tuples in net/vtl.py, parsed from the AST (never
  imported — the analyzer must run on a tree that does not build).

check_abi() compares the mapped records field-by-field — name, offset,
size and type kind must all agree — and is also the single source of
truth for tests/test_native_build.py's generated assertions (the
runtime vtl_*_rec_size guards stay as the load-time backstop).
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from . import Finding

# the shared-record map: python struct.Struct name -> C struct name.
# Everything else inside pack(1) regions (the self-defined io_uring
# ABI) is kernel-facing, not python-facing, and is not mirrored.
SHARED_RECORDS = {
    "FLOW_REC": "FlowRec",
    "LANE_REC": "LaneRec",
    "LANE_PUNT": "LanePunt",
    "MAGLEV_REC": "MaglevRec",
    "TRACE_REC": "TraceRec",
    "HH_REC": "HHRec",
    "POLICE_REC": "PoliceRec",
}

# scalar C types we allow in shared records: name -> (size, kind)
C_SCALARS = {
    "uint8_t": (1, "uint"), "int8_t": (1, "int"),
    "uint16_t": (2, "uint"), "int16_t": (2, "int"),
    "uint32_t": (4, "uint"), "int32_t": (4, "int"),
    "uint64_t": (8, "uint"), "int64_t": (8, "int"),
    "char": (1, "bytes"), "int": (4, "int"),
}

# python struct codes we allow: code -> (size, kind)
PY_CODES = {
    "B": (1, "uint"), "b": (1, "int"),
    "H": (2, "uint"), "h": (2, "int"),
    "I": (4, "uint"), "i": (4, "int"),
    "Q": (8, "uint"), "q": (8, "int"),
    "s": (1, "bytes"),
}


@dataclass
class Field:
    name: str
    offset: int
    size: int
    kind: str  # "uint" | "int" | "bytes"


@dataclass
class Record:
    name: str
    fields: List[Field]

    @property
    def size(self) -> int:
        return sum(f.size for f in self.fields)


# --------------------------------------------------------------- C side

_C_COMMENT = re.compile(r"//[^\n]*|/\*.*?\*/", re.S)
_C_STRUCT = re.compile(r"struct\s+(\w+)\s*\{([^{}]*)\}\s*;", re.S)
_C_FIELD = re.compile(
    r"^\s*(struct\s+)?([A-Za-z_]\w*)\s+([^;]+)$")
_C_DECL = re.compile(r"([A-Za-z_]\w*)\s*(?:\[\s*(\d+)\s*\])?\s*$")


def parse_c_structs(cpp_path: str) -> Dict[str, List[Tuple[str, str, int]]]:
    """-> {struct name: [(type, field name, array_len or 0), ...]} for
    every struct inside a #pragma pack(push, 1) ... pack(pop) region.
    Structs with members this parser cannot model (unions, bitfields,
    anonymous members) parse as None-typed fields and fail loudly only
    if they are in SHARED_RECORDS."""
    with open(cpp_path) as f:
        text = f.read()
    out: Dict[str, List[Tuple[str, str, int]]] = {}
    pos = 0
    while True:
        start = text.find("#pragma pack(push, 1)", pos)
        if start < 0:
            break
        end = text.find("#pragma pack(pop)", start)
        if end < 0:
            break
        region = _C_COMMENT.sub("", text[start:end])
        for m in _C_STRUCT.finditer(region):
            name, body = m.group(1), m.group(2)
            fields: List[Tuple[str, str, int]] = []
            for stmt in body.split(";"):
                stmt = stmt.strip()
                if not stmt:
                    continue
                fm = _C_FIELD.match(stmt)
                if fm is None:
                    fields.append(("?", stmt, 0))
                    continue
                ctype = fm.group(2)
                for decl in fm.group(3).split(","):
                    dm = _C_DECL.match(decl.strip())
                    if dm is None:
                        fields.append(("?", decl.strip(), 0))
                        continue
                    fields.append((ctype, dm.group(1),
                                   int(dm.group(2) or 0)))
            out[name] = fields
        pos = end + 1
    return out


def c_record(raw: Dict[str, List[Tuple[str, str, int]]],
             name: str) -> Record:
    """Flatten one parsed struct into an offset/size/kind Record;
    nested packed structs (FlowRec's FlowKey) inline their fields.
    Raises ValueError on anything the model cannot express."""
    fields: List[Field] = []
    off = 0
    for ctype, fname, arr in raw.get(name, ()):
        if ctype in raw:  # nested packed struct: flatten
            if arr:
                raise ValueError(f"{name}.{fname}: struct arrays "
                                 "unsupported")
            inner = c_record(raw, ctype)
            for f in inner.fields:
                fields.append(Field(f.name, off + f.offset, f.size,
                                    f.kind))
            off += inner.size
            continue
        if ctype not in C_SCALARS:
            raise ValueError(f"{name}.{fname}: unmodelled C type "
                             f"{ctype!r}")
        size, kind = C_SCALARS[ctype]
        if arr:
            size, kind = size * arr, "bytes"
        fields.append(Field(fname, off, size, kind))
        off += size
    if not fields:
        raise ValueError(f"struct {name} not found in any packed region")
    return Record(name, fields)


# ---------------------------------------------------------- python side

_FMT = re.compile(r"(\d*)([a-zA-Z])")


def parse_py_format(fmt: str) -> List[Tuple[int, int, str]]:
    """-> [(offset, size, kind), ...] for a '<'-prefixed struct format."""
    if not fmt.startswith("<"):
        raise ValueError(f"format {fmt!r} must pin little-endian ('<') "
                         "— native byte order would unpack padding")
    out: List[Tuple[int, int, str]] = []
    off = 0
    for count, code in _FMT.findall(fmt[1:]):
        if code not in PY_CODES:
            raise ValueError(f"format {fmt!r}: unmodelled code {code!r}")
        size, kind = PY_CODES[code]
        n = int(count) if count else 1
        if code == "s":
            out.append((off, n, "bytes"))
            off += n
        else:
            for _ in range(n):
                out.append((off, size, kind))
                off += size
    return out


def parse_py_structs(py_path: str):
    """-> ({NAME: fmt}, {NAME_FIELDS: (names...)}) from net/vtl.py's
    AST: `X = struct.Struct("<fmt>")` and `X_FIELDS = ("a", ...)`."""
    with open(py_path) as f:
        tree = ast.parse(f.read(), py_path)
    fmts: Dict[str, str] = {}
    names: Dict[str, Tuple[str, ...]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tgt = node.targets[0].id
        v = node.value
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr == "Struct" and v.args
                and isinstance(v.args[0], ast.Constant)
                and isinstance(v.args[0].value, str)):
            fmts[tgt] = v.args[0].value
        elif (tgt.endswith("_FIELDS") and isinstance(v, ast.Tuple)
              and all(isinstance(e, ast.Constant) for e in v.elts)):
            names[tgt] = tuple(e.value for e in v.elts)
    return fmts, names


def py_record(fmts: Dict[str, str], names: Dict[str, Tuple[str, ...]],
              name: str) -> Record:
    if name not in fmts:
        raise ValueError(f"{name}: no struct.Struct definition found")
    elems = parse_py_format(fmts[name])
    fnames = names.get(name + "_FIELDS")
    if fnames is None:
        raise ValueError(f"{name}_FIELDS: missing field-name tuple "
                         "(the name half of the ABI contract)")
    if len(fnames) != len(elems):
        raise ValueError(
            f"{name}_FIELDS has {len(fnames)} names for "
            f"{len(elems)} format elements")
    return Record(name, [Field(n, o, s, k)
                         for n, (o, s, k) in zip(fnames, elems)])


# -------------------------------------------------------------- the pass

def shared_model(root: str):
    """-> {py_name: (py Record, c Record)} for every SHARED_RECORDS
    entry, raising on unparseable definitions (a parse failure on a
    shared record is itself an ABI-guard failure)."""
    cpp = os.path.join(root, "vproxy_tpu", "native", "vtl.cpp")
    pyf = os.path.join(root, "vproxy_tpu", "net", "vtl.py")
    raw = parse_c_structs(cpp)
    fmts, fnames = parse_py_structs(pyf)
    out = {}
    for py_name, c_name in SHARED_RECORDS.items():
        out[py_name] = (py_record(fmts, fnames, py_name),
                        c_record(raw, c_name))
    return out


def check_abi(root: str,
              records: Optional[Dict[str, str]] = None,
              cpp_path: Optional[str] = None,
              py_path: Optional[str] = None) -> List[Finding]:
    """Field-by-field parity over the shared records. `records` /
    `cpp_path` / `py_path` override the defaults for fixture runs."""
    findings: List[Finding] = []
    cpp = cpp_path or os.path.join(root, "vproxy_tpu", "native",
                                   "vtl.cpp")
    pyf = py_path or os.path.join(root, "vproxy_tpu", "net", "vtl.py")
    try:
        raw = parse_c_structs(cpp)
        fmts, fnames = parse_py_structs(pyf)
    except (OSError, ValueError, SyntaxError) as e:
        return [Finding("abi", "abi:parse", cpp, 0,
                        f"cannot extract struct model: {e}")]
    for py_name, c_name in (records or SHARED_RECORDS).items():
        try:
            py = py_record(fmts, fnames, py_name)
        except ValueError as e:
            findings.append(Finding("abi", f"abi:{py_name}:py", pyf, 0,
                                    str(e)))
            continue
        try:
            c = c_record(raw, c_name)
        except ValueError as e:
            findings.append(Finding("abi", f"abi:{py_name}:c", cpp, 0,
                                    str(e)))
            continue
        if len(py.fields) != len(c.fields):
            findings.append(Finding(
                "abi", f"abi:{py_name}:count", cpp, 0,
                f"{py_name} has {len(py.fields)} fields, C {c_name} "
                f"has {len(c.fields)}"))
            continue
        for pf, cf in zip(py.fields, c.fields):
            mismatches = []
            if pf.name != cf.name:
                mismatches.append(f"name {pf.name!r} vs C {cf.name!r}")
            if pf.offset != cf.offset:
                mismatches.append(
                    f"offset {pf.offset} vs C {cf.offset}")
            if pf.size != cf.size:
                mismatches.append(f"size {pf.size} vs C {cf.size}")
            if pf.kind != cf.kind:
                mismatches.append(f"type {pf.kind} vs C {cf.kind}")
            if mismatches:
                findings.append(Finding(
                    "abi", f"abi:{py_name}:{cf.name}", cpp, 0,
                    f"{py_name}.{pf.name} / {c_name}.{cf.name}: "
                    + "; ".join(mismatches)))
        if py.size != c.size:
            findings.append(Finding(
                "abi", f"abi:{py_name}:size", cpp, 0,
                f"{py_name} totals {py.size}B, C {c_name} {c.size}B"))
    return findings
