#!/usr/bin/env python
"""traceview — offline text waterfalls for committed trace artifacts.

Reads the `bench.py --trace` stage's artifact (BENCH_r*_trace.json:
`slowest_traces` = [{"trace": id, "total_us": ..., "spans": [...]}]),
a `GET /trace?id=` dump ({"trace": id, "spans": [...]}) or a bare
span list, and renders the same per-span waterfall the live
`trace <id>` command shows — so a committed BENCH round's worst
requests stay inspectable without a live process.

    python tools/traceview.py BENCH_r13_builder_trace.json
    python tools/traceview.py BENCH_r13_builder_trace.json --id 42
    curl -s lb:18776/trace?id=42 | python tools/traceview.py -

The attribution table (per-stage p50/p99) is printed when the artifact
carries one (`stage_table`).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vproxy_tpu.utils.trace import render_spans  # noqa: E402


def _traces_of(doc) -> list:
    """-> [(trace_id, spans)] from any of the accepted shapes."""
    if isinstance(doc, list):  # bare span list
        if doc and isinstance(doc[0], dict) and "span" in doc[0]:
            return [(doc[0].get("trace", 0), doc)]
        return [(t.get("trace", 0), t.get("spans", [])) for t in doc]
    if isinstance(doc, dict):
        if "spans" in doc:  # one GET /trace?id= dump
            return [(doc.get("trace", 0), doc["spans"])]
        for key in ("slowest_traces", "traces"):
            if key in doc:
                return _traces_of(doc[key])
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="artifact json, or - for stdin")
    ap.add_argument("--id", type=int, default=0,
                    help="render only this trace id")
    ap.add_argument("--width", type=int, default=48)
    ap.add_argument("--limit", type=int, default=0,
                    help="render at most N traces (0 = all)")
    args = ap.parse_args(argv)
    doc = json.load(sys.stdin if args.path == "-" else open(args.path))

    table = (doc.get("stage_table") or doc.get("trace_stage_table")) \
        if isinstance(doc, dict) else None
    if table and not args.id:
        w = max(len(k) for k in table) + 2
        print(f"{'stage':<{w}} {'n':>8} {'p50_us':>10} {'p99_us':>10}")
        for k, v in table.items():
            print(f"{k:<{w}} {v['n']:>8} {v['p50_us']:>10} "
                  f"{v['p99_us']:>10}")
        print()

    traces = _traces_of(doc)
    if args.id:
        traces = [(tid, sp) for tid, sp in traces if tid == args.id]
        if not traces:
            print(f"trace {args.id}: not in this artifact",
                  file=sys.stderr)
            return 1
    if args.limit > 0:
        traces = traces[: args.limit]
    for tid, spans in traces:
        if not spans:
            continue
        for line in render_spans(tid, spans, args.width):
            print(line)
        print()
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # `traceview ... | head` is the normal use
        raise SystemExit(0)
