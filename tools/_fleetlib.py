"""Fleet bring-up/teardown + load-generation helpers shared by the
scenario harnesses.

One home for what tools/chaos.py and _verify_cluster.py used to carry
as private copies (and tools/storm.py must NOT become a third copy of):

* `free_port` / `wait_for` — the socket/timing primitives every
  scenario script opens with;
* `cluster_spec` / `make_node` / `boot_node_env` — a localhost cluster
  fleet (real UDP membership + TCP replication), either constructed
  directly with test-sized timers or through the production env-boot
  path (`VPROXY_TPU_CLUSTER_PEERS` -> ClusterNode.boot_from_env);
* `EchoBackend` / `one_session` / `blast` — the id-echo backend and the
  byte-verified closed-loop client used to drive a TcpLB, with
  per-session latency capture and RST-shed accounting so storm SLO
  gates can distinguish "served slowly" from "refused fast".

Import with the tools directory on sys.path (`import _fleetlib`), the
same convention tests/test_chaos.py already uses for chaos.py.
"""
from __future__ import annotations

import os
import socket
import threading
import time


def free_port(kind=socket.SOCK_STREAM) -> int:
    s = socket.socket(socket.AF_INET, kind)
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def wait_for(pred, timeout: float = 15.0, poll: float = 0.02) -> bool:
    """Poll pred() until true or the deadline; returns the final
    verdict (callers assert or record it)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return bool(pred())


# --------------------------------------------------------- cluster fleet

def cluster_spec(n: int = 3) -> str:
    """A VPROXY_TPU_CLUSTER_PEERS spec for n localhost nodes: UDP
    heartbeat port / TCP replication port per node."""
    return ",".join(
        f"127.0.0.1:{free_port(socket.SOCK_DGRAM)}"
        f"/{free_port(socket.SOCK_STREAM)}" for _ in range(n))


def make_node(i: int, spec: str, hb_ms: int = 300, poll_ms: int = 120,
              workers: int = 1):
    """Direct construction with test-sized timers (the chaos idiom):
    -> (Application, ClusterNode), membership + replication started."""
    from vproxy_tpu.cluster import ClusterNode, parse_peers
    from vproxy_tpu.control.app import Application
    app = Application(workers=workers)
    node = ClusterNode(app, i, parse_peers(spec), hb_ms=hb_ms,
                       poll_ms=poll_ms)
    app.cluster = node
    node.membership.start()
    node.replicator.start()
    return app, node


def boot_node_env(i: int, spec: str, workers: int = 1):
    """The production boot path (the _verify_cluster idiom): env vars ->
    ClusterNode.boot_from_env. -> (Application, ClusterNode)."""
    from vproxy_tpu.cluster import ClusterNode
    from vproxy_tpu.control.app import Application
    os.environ["VPROXY_TPU_CLUSTER_PEERS"] = spec
    os.environ["VPROXY_TPU_CLUSTER_SELF"] = str(i)
    app = Application(workers=workers)
    app.cluster = ClusterNode.boot_from_env(app)
    assert app.cluster is not None and app.cluster.self_id == i
    return app, app.cluster


def close_fleet(nodes, apps) -> None:
    """Teardown tolerant of mid-scenario kills (already-closed nodes)."""
    for n in nodes:
        try:
            n.close()
        except Exception:
            pass
    for a in apps:
        try:
            a.close()
        except Exception:
            pass


# ------------------------------------------------------- LB load helpers

class EchoBackend:
    """Sends its 1-byte id, then echoes; tracks sessions served.
    Optional per-session accept delay models a slow backend."""

    def __init__(self, sid: bytes):
        self.sid = sid
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(512)
        self.port = self.sock.getsockname()[1]
        self.hits = 0
        self.alive = True
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while self.alive:
            try:
                c, _ = self.sock.accept()
            except OSError:
                return
            self.hits += 1
            threading.Thread(target=self._conn, args=(c,),
                             daemon=True).start()

    def _conn(self, c):
        try:
            c.sendall(self.sid)
            while True:
                d = c.recv(65536)
                if not d:
                    break
                c.sendall(d)
        except OSError:
            pass
        finally:
            c.close()

    def close(self):
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


def one_session(port: int, payload: bytes, timeout: float = 5.0,
                src_ip: str = None) -> str:
    """One byte-verified echo session; returns the backend id or raises
    OSError. Exceptions from the PRE-DATA window (refused connect, RST
    or clean close before the first byte arrived) carry `.shed = True`:
    that is the overload guard refusing fast — the designed degrade —
    and SLO gates score it apart from a session that broke after it
    was accepted for service (a reset mid-echo is a REAL failure, and
    must never hide inside the shed column). `src_ip` binds the client
    side to a specific loopback address (any 127/8 works unbound on
    Linux) — the replay engine (tools/replay.py) uses it to give every
    synthesized client a distinct identity the analytics/workload
    planes can re-capture."""
    _PRE = (ConnectionRefusedError, ConnectionResetError,
            ConnectionAbortedError)
    try:
        c = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout,
            source_address=(src_ip, 0) if src_ip else None)
    except _PRE as e:
        e.shed = True
        raise
    c.settimeout(timeout)
    try:
        try:
            sid = c.recv(1)
        except _PRE as e:
            e.shed = True  # killed before a single byte: a shed
            raise
        if len(sid) != 1:
            e = OSError("no backend id (closed early)")
            e.shed = True  # clean pre-data close: the static FIN shed
            raise e
        c.sendall(payload)
        got = b""
        while len(got) < len(payload):
            d = c.recv(65536)
            if not d:
                raise OSError(f"echo truncated at {len(got)}/{len(payload)}")
            got += d
        if got != payload:
            raise OSError("echo corrupted")
        return sid.decode()
    finally:
        c.close()


def _is_shed(e: OSError) -> bool:
    """True only for pre-data refusals tagged by one_session — never
    for timeouts or post-admission breakage."""
    return bool(getattr(e, "shed", False))


def blast(port: int, n: int, clients: int, payload: bytes,
          timeout: float = 5.0, latencies: bool = False,
          retry_shed: int = 0, pace_s: float = 0.0) -> dict:
    """n sessions across `clients` threads ->
    {"ok", "fail", "shed", "ids"[, "lat_s"]}. `retry_shed` re-attempts a
    shed connection up to that many times (a flash-crowd client retrying
    an RST) — each refusal still counts into "shed". `pace_s` sleeps
    between a client's iterations (a paced open-ish arrival instead of
    a pure closed loop)."""
    lock = threading.Lock()
    stats: dict = {"ok": 0, "fail": 0, "shed": 0, "ids": {}}
    lats: list = []

    def worker(count: int) -> None:
        for _ in range(count):
            if pace_s:
                time.sleep(pace_s)
            attempt = 0
            while True:
                t0 = time.monotonic()
                try:
                    sid = one_session(port, payload, timeout)
                except OSError as e:
                    shed = _is_shed(e)
                    with lock:
                        stats["shed" if shed else "fail"] += 1
                    if shed and attempt < retry_shed:
                        # a refused client backs off for real (tens of
                        # ms): an instant-retry storm would just convert
                        # every shed into fresh connect load — the
                        # amplification shedding exists to prevent
                        attempt += 1
                        time.sleep(0.04 * attempt)
                        continue
                    break
                with lock:
                    stats["ok"] += 1
                    stats["ids"][sid] = stats["ids"].get(sid, 0) + 1
                    if latencies:
                        lats.append(time.monotonic() - t0)
                break

    per = max(1, n // clients)
    ts = [threading.Thread(target=worker, args=(per,))
          for _ in range(clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if latencies:
        stats["lat_s"] = sorted(lats)
    return stats


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])
