"""Chaos driver — a loopback LB under client load while failpoints toggle.

The acceptance harness for the failure-containment layer
(docs/robustness.md): builds 3 id-echo backends behind a TcpLB, hammers
it with short byte-verified sessions, and walks the failure script:

  1. warmup       — all backends healthy, traffic flows
  2. backend kill — `backend.connect.refuse` armed on one backend
                    mid-run; clients must keep completing (retry
                    failover) and the refuser must be passively ejected
                    within the failure threshold, NOT a health-check
                    interval (the hc period here is 60s to prove it)
  3. recovery     — fault disarmed; the backend re-admits via the eject
                    backoff (halved on each passing probe)
  4. device drop  — `device.dispatch.error` armed against a classify
                    dispatch; the batch degrades to the host oracle and
                    still delivers
  5. drain        — `drain` issued mid-traffic: in-flight pumps finish,
                    new accepts are shed, the process-level wait
                    completes inside the drain window

Run standalone (`python tools/chaos.py [--clients N] [--requests N]`)
for a JSON report, or via `pytest -m chaos` (tests/test_chaos.py
asserts the success-rate floor and every phase outcome). Kept out of
tier-1 by the `chaos`/`slow` markers.

`--cluster` runs the CLUSTER-plane scenario instead (run_cluster):
three localhost nodes, one killed mid-traffic — survivors must keep
>= 99% classify success through the barrier-timeout degrade, and the
killed node must re-join at the current rule generation.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from vproxy_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(8)

import _fleetlib  # noqa: E402  (tools/_fleetlib.py — shared fleet helpers)

from vproxy_tpu.components import servergroup as SG                # noqa: E402
from vproxy_tpu.components.elgroup import EventLoopGroup           # noqa: E402
from vproxy_tpu.components.servergroup import (HealthCheckConfig,  # noqa: E402
                                               ServerGroup)
from vproxy_tpu.components.tcplb import TcpLB                      # noqa: E402
from vproxy_tpu.components.upstream import Upstream                # noqa: E402
from vproxy_tpu.utils import failpoint, lifecycle                  # noqa: E402
from vproxy_tpu.utils.events import FlightRecorder                 # noqa: E402


# fleet/load helpers live in tools/_fleetlib.py (shared with storm.py
# and _verify_cluster.py — no per-harness copies). The chaos floor
# counts a shed (RST/refusal) as a failed session: nothing in this
# scenario is SUPPOSED to shed.
_EchoBackend = _fleetlib.EchoBackend


def _blast(port: int, n: int, clients: int, payload: bytes):
    st = _fleetlib.blast(port, n, clients, payload)
    return {"ok": st["ok"], "fail": st["fail"] + st["shed"],
            "ids": st["ids"]}


def _classify_device_drop() -> dict:
    """Phase 4: a device dispatch raises via the failpoint; the batch
    must degrade to the host oracle and still deliver."""
    from vproxy_tpu.rules.ir import Hint, HintRule
    from vproxy_tpu.rules.service import ClassifyService

    ups = Upstream("chaos-classify")
    ups._matcher.set_rules([HintRule(host="chaos.example.com")],
                           payload=["g0"])
    svc = ClassifyService(mode="device")
    delivered = []
    done = threading.Event()

    def cb(idx, payload):
        delivered.append(idx)
        if len(delivered) >= 2:
            done.set()

    failpoint.arm("device.dispatch.error", count=1)
    try:
        svc.submit_hint(ups._matcher, Hint(host="chaos.example.com"), cb)
        svc.submit_hint(ups._matcher, Hint(host="nomatch.org"), cb)
        ok = done.wait(20)
    finally:
        failpoint.disarm("device.dispatch.error")
        svc.close()
    return {"delivered": ok, "failovers": svc.stats.failovers,
            "answers": sorted(delivered)}


def run(clients: int = 4, requests: int = 120, payload_len: int = 4096,
        eject_base_s: float = 0.5, drain_s: float = 10.0,
        seed: int = None, log=lambda *_: None) -> dict:
    """Full chaos script; returns the report dict (see test_chaos.py
    for the asserted floor on every field). `seed` pins every
    probability failpoint arm (VPROXY_TPU_FAILPOINT_SEED) and the
    payload bytes, and rides into the report so a failing run replays."""
    import random as _random
    if seed is not None:
        os.environ["VPROXY_TPU_FAILPOINT_SEED"] = str(seed)
        payload = bytes(_random.Random(seed).randbytes(payload_len))
    else:
        payload = os.urandom(payload_len)
    report: dict = {"seed": seed}
    saved = (SG.EJECT_FAILURES, SG.EJECT_BASE_S)
    SG.EJECT_FAILURES, SG.EJECT_BASE_S = 3, eject_base_s
    failpoint.clear()
    lifecycle.reset()
    FlightRecorder.reset()

    from vproxy_tpu.control.app import Application
    from vproxy_tpu.control.command import Command

    backends = [_EchoBackend(b"%d" % i) for i in range(3)]
    elg = EventLoopGroup("chaos", 2)
    # the refuse failpoint gates Connection.connect (the data plane),
    # NOT the health checker's raw tcp probe — so the hc keeps passing
    # and can never mark the victim down. Any DOWN observed below is
    # provably passive ejection; the fast period only serves backoff
    # halving on the re-admission side.
    group = ServerGroup("chaos-g", elg, HealthCheckConfig(
        timeout_ms=500, period_ms=200, up=1, down=100), "wrr")
    for i, b in enumerate(backends):
        group.add(f"b{i}", "127.0.0.1", b.port)
    deadline = time.time() + 5
    while sum(1 for s in group.servers if s.healthy) < 3:
        if time.time() > deadline:
            raise TimeoutError("backends never came healthy")
        time.sleep(0.02)
    ups = Upstream("chaos-u")
    ups.add(group)
    # warm backend pool ON (round 6): the chaos floor must hold with
    # pooled handovers in the path — eject drains pools, stale sockets
    # fall back to fresh connects, server-first id bytes survive parking
    pool_size = int(os.environ.get("CHAOS_POOL", "4"))
    lb = TcpLB("chaos-lb", elg, elg, "127.0.0.1", 0, ups, protocol="tcp",
               pool_size=pool_size)
    lb.start()
    app = Application.create(workers=1)
    app.tcp_lbs["chaos-lb"] = lb

    try:
        # -------- phase 1: warmup
        log("phase 1: warmup")
        warm = _blast(lb.bind_port, requests, clients, payload)
        report["warmup"] = warm

        # -------- phase 2: refuse one backend mid-run
        log("phase 2: backend kill (connect refuse)")
        victim = group.servers[0]
        t_arm = time.monotonic()
        failpoint.arm("backend.connect.refuse",
                      match=f":{backends[0].port}")
        poll = {"eject_latency_s": None}

        def watch_eject():
            while time.monotonic() - t_arm < 10:
                if victim.ejected:
                    poll["eject_latency_s"] = time.monotonic() - t_arm
                    return
                time.sleep(0.005)

        w = threading.Thread(target=watch_eject)
        w.start()
        kill = _blast(lb.bind_port, requests, clients, payload)
        w.join()
        report["kill"] = kill
        report["eject_latency_s"] = poll["eject_latency_s"]
        report["ejected"] = victim.ejected

        # -------- phase 3: disarm -> backoff re-admission
        log("phase 3: recovery (backoff re-admission)")
        failpoint.clear()
        deadline = time.time() + eject_base_s * 8 + 5
        while not victim.healthy and time.time() < deadline:
            time.sleep(0.02)
        report["readmitted"] = victim.healthy
        recov = _blast(lb.bind_port, requests // 2, clients, payload)
        report["recovery"] = recov
        report["victim_served_after_readmit"] = \
            recov["ids"].get("0", 0) > 0

        # -------- phase 4: device drop in the classify path
        log("phase 4: device dispatch drop")
        report["classify"] = _classify_device_drop()

        # -------- phase 5: drain mid-traffic
        log("phase 5: drain mid-traffic")
        held = []
        for _ in range(3):  # long-lived sessions that outlive the drain
            c = socket.create_connection(("127.0.0.1", lb.bind_port),
                                         timeout=5)
            c.settimeout(5)
            assert c.recv(1)
            held.append(c)
        t_drain = time.monotonic()
        assert Command.execute(app, "drain") == "OK"
        # new accepts shed (refused or closed-on-accept)
        shed_ok = False
        try:
            c2 = socket.create_connection(("127.0.0.1", lb.bind_port),
                                          timeout=2)
            c2.settimeout(2)
            shed_ok = c2.recv(8) == b""
            c2.close()
        except OSError:
            shed_ok = True
        report["drain_sheds_new_accepts"] = shed_ok
        # in-flight sessions still move bytes, then finish
        drained_bytes = all(
            (c.sendall(b"drain-ok") or c.recv(16) == b"drain-ok")
            for c in held)
        report["drain_inflight_alive"] = drained_bytes
        for c in held:
            c.close()
        report["drain_clean"] = app.drain_wait(drain_s)
        report["drain_elapsed_s"] = time.monotonic() - t_drain
        report["healthz"] = lifecycle.state()
    finally:
        SG.EJECT_FAILURES, SG.EJECT_BASE_S = saved
        failpoint.clear()
        lifecycle.reset()
        app.tcp_lbs.pop("chaos-lb", None)
        app.close()
        lb.stop()
        group.close()
        for b in backends:
            b.close()
        elg.close()

    total = (warm["ok"] + warm["fail"] + kill["ok"] + kill["fail"]
             + recov["ok"] + recov["fail"])
    ok = warm["ok"] + kill["ok"] + recov["ok"]
    report["total_sessions"] = total
    report["ok_sessions"] = ok
    report["success_rate"] = ok / total if total else 0.0
    report["pool_size"] = pool_size
    # chaos runs under VPROXY_TPU_TRACE_SAMPLE dump their worst traces
    # like the bench --trace stage (docs/observability.md)
    from vproxy_tpu.utils import trace as TR
    if TR.enabled():
        report["slowest_traces"] = TR.slowest(8)
        report["stage_table"] = TR.stage_table()
    return report


# ------------------------------------------------------- cluster scenario

def run_cluster(n_rules: int = 24, queries_per_node: int = 120,
                log=lambda *_: None) -> dict:
    """Cluster-plane chaos (vproxy_tpu/cluster): three localhost nodes
    on real UDP membership + TCP replication + the step-synchronized
    submit clock. Script:

      1. convergence — 3 nodes up, node 0 leads, leader rules
         replicate, all checksums equal
      2. kill        — node 2 dies MID-TRAFFIC. The barrier timeout is
         set BELOW the membership down-detection, so survivors go
         through the barrier-timeout degrade edge (host-index serving,
         no failed query) — the floor is >= 99% classify success on
         the survivors
      3. rejoin      — node 2 restarts fresh, re-syncs replication to
         the CURRENT generation; the next leader mutation moves the
         fleet to a new generation and every host (survivors included)
         re-joins step dispatch on it
    """
    from vproxy_tpu.control.command import Command
    from vproxy_tpu.rules import oracle
    from vproxy_tpu.rules.ir import Hint

    wait_for = _fleetlib.wait_for

    failpoint.clear()
    FlightRecorder.reset()
    report: dict = {}
    spec = _fleetlib.cluster_spec(3)  # UDP heartbeat / TCP replication
    # hb 300ms x down 3 = 900ms down-detection > 400ms barrier timeout:
    # a killed node hits the barrier-timeout degrade edge, not the
    # quiet membership eviction
    HB, POLL, STEP_TO = 300, 120, 400

    def mk_node(i):
        return _fleetlib.make_node(i, spec, hb_ms=HB, poll_ms=POLL)

    log("phase 1: convergence")
    apps, nodes = zip(*[mk_node(i) for i in range(3)])
    apps, nodes = list(apps), list(nodes)
    try:
        report["converged"] = wait_for(
            lambda: all(n.membership.peers_up() == 3 for n in nodes))
        Command.execute(apps[0], "add upstream u0")
        for i in range(n_rules):
            Command.execute(
                apps[0], f"add server-group g{i} timeout 500 period 60000 "
                "up 1 down 2 annotations "
                f'{{"vproxy/hint-host":"s{i}.corp.example"}}')
            Command.execute(apps[0],
                            f"add server-group g{i} to upstream u0 weight 10")
        gen0 = nodes[0].replicator.generation
        report["replicated"] = wait_for(
            lambda: all(n.replicator.generation == gen0 for n in nodes))
        sums = {n.replicator.checksum() for n in nodes}
        report["checksums_equal"] = len(sums) == 1
        rules = [h.merged_rule() for h in apps[0].upstreams["u0"].handles]

        loops = [nodes[i].attach_submit(
            apps[i].upstreams["u0"]._matcher, step_ms=20, batch_cap=8,
            timeout_ms=STEP_TO) for i in range(3)]

        # traffic: a steady trickle on every node; per-query verdicts
        # checked against the oracle, 15s delivery deadline
        lock = threading.Lock()
        stats = {i: {"ok": 0, "bad": 0, "lost": 0} for i in range(3)}
        stop_traffic = [threading.Event() for _ in range(3)]

        def traffic(i):
            pending = []
            q = 0
            while q < queries_per_node and not stop_traffic[i].is_set():
                h = Hint(host=f"s{(q * 7) % (n_rules + 3)}.corp.example")
                got = {"e": threading.Event(), "idx": None}

                def cb(idx, payload, got=got):
                    got["idx"] = idx
                    got["e"].set()
                try:
                    loops[i].submit(h, cb)
                except OSError:
                    break
                pending.append((h, got))
                q += 1
                time.sleep(0.01)
            for h, got in pending:
                if not got["e"].wait(15):
                    with lock:
                        stats[i]["lost"] += 1
                    continue
                with lock:
                    key = ("ok" if got["idx"] == oracle.search(rules, h)
                           else "bad")
                    stats[i][key] += 1

        threads = [threading.Thread(target=traffic, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()

        log("phase 2: kill node 2 mid-traffic")
        time.sleep(0.4)  # mid-traffic, not before it
        stop_traffic[2].set()
        nodes[2].close()
        apps[2].close()
        for t in threads:
            t.join(60)
        report["traffic"] = {str(i): dict(stats[i]) for i in range(3)}
        surv_ok = stats[0]["ok"] + stats[1]["ok"]
        surv_all = sum(stats[i][k] for i in (0, 1)
                       for k in ("ok", "bad", "lost"))
        report["survivor_success_rate"] = (surv_ok / surv_all
                                           if surv_all else 0.0)
        report["survivors_degraded"] = [loops[i].degraded for i in (0, 1)]
        report["survivor_barrier_stalls"] = [loops[i].barrier_stalls
                                             for i in (0, 1)]

        log("phase 3: node 2 rejoins at the current generation")
        apps[2], nodes[2] = mk_node(2)
        report["rejoin_member"] = wait_for(
            lambda: all(n.membership.peers_up() == 3 for n in nodes))
        report["rejoin_caught_up"] = wait_for(
            lambda: nodes[2].replicator.generation
            == nodes[0].replicator.generation)
        # a fresh generation moves the whole fleet (survivors re-join
        # step dispatch, the restarted node steps with them)
        loops[2] = nodes[2].attach_submit(
            apps[2].upstreams["u0"]._matcher, step_ms=20, batch_cap=8,
            timeout_ms=STEP_TO)
        Command.execute(apps[0], 'update server-group g0 annotations '
                        '{"vproxy/hint-host":"swapped.corp.example"}')
        gen2 = nodes[0].replicator.generation
        report["rejoin_generation"] = gen2
        report["fleet_at_generation"] = wait_for(
            lambda: all(n.replicator.generation == gen2 for n in nodes))
        report["survivors_rejoined"] = wait_for(
            lambda: not any(lp.degraded for lp in loops))
        report["checksums_equal_after_rejoin"] = len(
            {n.replicator.checksum() for n in nodes}) == 1
    finally:
        for n in nodes:
            n.close()
        for a in apps:
            a.close()
        failpoint.clear()
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=120,
                    help="sessions per phase")
    ap.add_argument("--payload", type=int, default=4096)
    ap.add_argument("--eject-base", type=float, default=0.5,
                    help="eject backoff base seconds (test-sized)")
    ap.add_argument("--drain-s", type=float, default=10.0)
    ap.add_argument("--cluster", action="store_true",
                    help="run the cluster-plane scenario instead")
    ap.add_argument("--seed", type=int, default=None,
                    help="pin failpoint RNGs + payload bytes "
                    "(VPROXY_TPU_FAILPOINT_SEED); echoed into the report")
    args = ap.parse_args(argv)
    if args.cluster:
        report = run_cluster(
            log=lambda m: print(f"[chaos] {m}", file=sys.stderr))
        print(json.dumps(report, indent=2, default=str))
        floor_ok = report["survivor_success_rate"] >= 0.99
        print(f"[chaos] survivor success rate "
              f"{report['survivor_success_rate']:.4f} "
              f"({'PASS' if floor_ok else 'FAIL'} at 0.99 floor)",
              file=sys.stderr)
        return 0 if floor_ok else 1
    report = run(clients=args.clients, requests=args.requests,
                 payload_len=args.payload, eject_base_s=args.eject_base,
                 drain_s=args.drain_s, seed=args.seed,
                 log=lambda m: print(f"[chaos] {m}", file=sys.stderr))
    print(json.dumps(report, indent=2, default=str))
    floor_ok = report["success_rate"] >= 0.99
    print(f"[chaos] success rate {report['success_rate']:.4f} "
          f"({'PASS' if floor_ok else 'FAIL'} at 0.99 floor)",
          file=sys.stderr)
    return 0 if floor_ok else 1


if __name__ == "__main__":
    sys.exit(main())
