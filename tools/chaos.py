"""Chaos driver — a loopback LB under client load while failpoints toggle.

The acceptance harness for the failure-containment layer
(docs/robustness.md): builds 3 id-echo backends behind a TcpLB, hammers
it with short byte-verified sessions, and walks the failure script:

  1. warmup       — all backends healthy, traffic flows
  2. backend kill — `backend.connect.refuse` armed on one backend
                    mid-run; clients must keep completing (retry
                    failover) and the refuser must be passively ejected
                    within the failure threshold, NOT a health-check
                    interval (the hc period here is 60s to prove it)
  3. recovery     — fault disarmed; the backend re-admits via the eject
                    backoff (halved on each passing probe)
  4. device drop  — `device.dispatch.error` armed against a classify
                    dispatch; the batch degrades to the host oracle and
                    still delivers
  5. drain        — `drain` issued mid-traffic: in-flight pumps finish,
                    new accepts are shed, the process-level wait
                    completes inside the drain window

Run standalone (`python tools/chaos.py [--clients N] [--requests N]`)
for a JSON report, or via `pytest -m chaos` (tests/test_chaos.py
asserts the success-rate floor and every phase outcome). Kept out of
tier-1 by the `chaos`/`slow` markers.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vproxy_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(8)

from vproxy_tpu.components import servergroup as SG                # noqa: E402
from vproxy_tpu.components.elgroup import EventLoopGroup           # noqa: E402
from vproxy_tpu.components.servergroup import (HealthCheckConfig,  # noqa: E402
                                               ServerGroup)
from vproxy_tpu.components.tcplb import TcpLB                      # noqa: E402
from vproxy_tpu.components.upstream import Upstream                # noqa: E402
from vproxy_tpu.utils import failpoint, lifecycle                  # noqa: E402
from vproxy_tpu.utils.events import FlightRecorder                 # noqa: E402


class _EchoBackend:
    """Sends its 1-byte id, then echoes; tracks sessions served."""

    def __init__(self, sid: bytes):
        self.sid = sid
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(128)
        self.port = self.sock.getsockname()[1]
        self.hits = 0
        self.alive = True
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while self.alive:
            try:
                c, _ = self.sock.accept()
            except OSError:
                return
            self.hits += 1
            threading.Thread(target=self._conn, args=(c,),
                             daemon=True).start()

    def _conn(self, c):
        try:
            c.sendall(self.sid)
            while True:
                d = c.recv(65536)
                if not d:
                    break
                c.sendall(d)
        except OSError:
            pass
        finally:
            c.close()

    def close(self):
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


def _one_session(port: int, payload: bytes) -> str:
    """One byte-verified session; returns the backend id or raises."""
    c = socket.create_connection(("127.0.0.1", port), timeout=5)
    c.settimeout(5)
    try:
        sid = c.recv(1)
        if len(sid) != 1:
            raise OSError("no backend id (closed early)")
        c.sendall(payload)
        got = b""
        while len(got) < len(payload):
            d = c.recv(65536)
            if not d:
                raise OSError(f"echo truncated at {len(got)}/{len(payload)}")
            got += d
        if got != payload:
            raise OSError("echo corrupted")
        return sid.decode()
    finally:
        c.close()


def _blast(port: int, n: int, clients: int, payload: bytes):
    """n sessions across `clients` threads -> (ok, fail, id-counts)."""
    lock = threading.Lock()
    stats = {"ok": 0, "fail": 0, "ids": {}}

    def worker(count: int) -> None:
        for _ in range(count):
            try:
                sid = _one_session(port, payload)
                with lock:
                    stats["ok"] += 1
                    stats["ids"][sid] = stats["ids"].get(sid, 0) + 1
            except OSError:
                with lock:
                    stats["fail"] += 1

    per = max(1, n // clients)
    ts = [threading.Thread(target=worker, args=(per,)) for _ in range(clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return stats


def _classify_device_drop() -> dict:
    """Phase 4: a device dispatch raises via the failpoint; the batch
    must degrade to the host oracle and still deliver."""
    from vproxy_tpu.rules.ir import Hint, HintRule
    from vproxy_tpu.rules.service import ClassifyService

    ups = Upstream("chaos-classify")
    ups._matcher.set_rules([HintRule(host="chaos.example.com")],
                           payload=["g0"])
    svc = ClassifyService(mode="device")
    delivered = []
    done = threading.Event()

    def cb(idx, payload):
        delivered.append(idx)
        if len(delivered) >= 2:
            done.set()

    failpoint.arm("device.dispatch.error", count=1)
    try:
        svc.submit_hint(ups._matcher, Hint(host="chaos.example.com"), cb)
        svc.submit_hint(ups._matcher, Hint(host="nomatch.org"), cb)
        ok = done.wait(20)
    finally:
        failpoint.disarm("device.dispatch.error")
        svc.close()
    return {"delivered": ok, "failovers": svc.stats.failovers,
            "answers": sorted(delivered)}


def run(clients: int = 4, requests: int = 120, payload_len: int = 4096,
        eject_base_s: float = 0.5, drain_s: float = 10.0,
        log=lambda *_: None) -> dict:
    """Full chaos script; returns the report dict (see test_chaos.py
    for the asserted floor on every field)."""
    payload = os.urandom(payload_len)
    report: dict = {}
    saved = (SG.EJECT_FAILURES, SG.EJECT_BASE_S)
    SG.EJECT_FAILURES, SG.EJECT_BASE_S = 3, eject_base_s
    failpoint.clear()
    lifecycle.reset()
    FlightRecorder.reset()

    from vproxy_tpu.control.app import Application
    from vproxy_tpu.control.command import Command

    backends = [_EchoBackend(b"%d" % i) for i in range(3)]
    elg = EventLoopGroup("chaos", 2)
    # the refuse failpoint gates Connection.connect (the data plane),
    # NOT the health checker's raw tcp probe — so the hc keeps passing
    # and can never mark the victim down. Any DOWN observed below is
    # provably passive ejection; the fast period only serves backoff
    # halving on the re-admission side.
    group = ServerGroup("chaos-g", elg, HealthCheckConfig(
        timeout_ms=500, period_ms=200, up=1, down=100), "wrr")
    for i, b in enumerate(backends):
        group.add(f"b{i}", "127.0.0.1", b.port)
    deadline = time.time() + 5
    while sum(1 for s in group.servers if s.healthy) < 3:
        if time.time() > deadline:
            raise TimeoutError("backends never came healthy")
        time.sleep(0.02)
    ups = Upstream("chaos-u")
    ups.add(group)
    # warm backend pool ON (round 6): the chaos floor must hold with
    # pooled handovers in the path — eject drains pools, stale sockets
    # fall back to fresh connects, server-first id bytes survive parking
    pool_size = int(os.environ.get("CHAOS_POOL", "4"))
    lb = TcpLB("chaos-lb", elg, elg, "127.0.0.1", 0, ups, protocol="tcp",
               pool_size=pool_size)
    lb.start()
    app = Application.create(workers=1)
    app.tcp_lbs["chaos-lb"] = lb

    try:
        # -------- phase 1: warmup
        log("phase 1: warmup")
        warm = _blast(lb.bind_port, requests, clients, payload)
        report["warmup"] = warm

        # -------- phase 2: refuse one backend mid-run
        log("phase 2: backend kill (connect refuse)")
        victim = group.servers[0]
        t_arm = time.monotonic()
        failpoint.arm("backend.connect.refuse",
                      match=f":{backends[0].port}")
        poll = {"eject_latency_s": None}

        def watch_eject():
            while time.monotonic() - t_arm < 10:
                if victim.ejected:
                    poll["eject_latency_s"] = time.monotonic() - t_arm
                    return
                time.sleep(0.005)

        w = threading.Thread(target=watch_eject)
        w.start()
        kill = _blast(lb.bind_port, requests, clients, payload)
        w.join()
        report["kill"] = kill
        report["eject_latency_s"] = poll["eject_latency_s"]
        report["ejected"] = victim.ejected

        # -------- phase 3: disarm -> backoff re-admission
        log("phase 3: recovery (backoff re-admission)")
        failpoint.clear()
        deadline = time.time() + eject_base_s * 8 + 5
        while not victim.healthy and time.time() < deadline:
            time.sleep(0.02)
        report["readmitted"] = victim.healthy
        recov = _blast(lb.bind_port, requests // 2, clients, payload)
        report["recovery"] = recov
        report["victim_served_after_readmit"] = \
            recov["ids"].get("0", 0) > 0

        # -------- phase 4: device drop in the classify path
        log("phase 4: device dispatch drop")
        report["classify"] = _classify_device_drop()

        # -------- phase 5: drain mid-traffic
        log("phase 5: drain mid-traffic")
        held = []
        for _ in range(3):  # long-lived sessions that outlive the drain
            c = socket.create_connection(("127.0.0.1", lb.bind_port),
                                         timeout=5)
            c.settimeout(5)
            assert c.recv(1)
            held.append(c)
        t_drain = time.monotonic()
        assert Command.execute(app, "drain") == "OK"
        # new accepts shed (refused or closed-on-accept)
        shed_ok = False
        try:
            c2 = socket.create_connection(("127.0.0.1", lb.bind_port),
                                          timeout=2)
            c2.settimeout(2)
            shed_ok = c2.recv(8) == b""
            c2.close()
        except OSError:
            shed_ok = True
        report["drain_sheds_new_accepts"] = shed_ok
        # in-flight sessions still move bytes, then finish
        drained_bytes = all(
            (c.sendall(b"drain-ok") or c.recv(16) == b"drain-ok")
            for c in held)
        report["drain_inflight_alive"] = drained_bytes
        for c in held:
            c.close()
        report["drain_clean"] = app.drain_wait(drain_s)
        report["drain_elapsed_s"] = time.monotonic() - t_drain
        report["healthz"] = lifecycle.state()
    finally:
        SG.EJECT_FAILURES, SG.EJECT_BASE_S = saved
        failpoint.clear()
        lifecycle.reset()
        app.tcp_lbs.pop("chaos-lb", None)
        app.close()
        lb.stop()
        group.close()
        for b in backends:
            b.close()
        elg.close()

    total = (warm["ok"] + warm["fail"] + kill["ok"] + kill["fail"]
             + recov["ok"] + recov["fail"])
    ok = warm["ok"] + kill["ok"] + recov["ok"]
    report["total_sessions"] = total
    report["ok_sessions"] = ok
    report["success_rate"] = ok / total if total else 0.0
    report["pool_size"] = pool_size
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=120,
                    help="sessions per phase")
    ap.add_argument("--payload", type=int, default=4096)
    ap.add_argument("--eject-base", type=float, default=0.5,
                    help="eject backoff base seconds (test-sized)")
    ap.add_argument("--drain-s", type=float, default=10.0)
    args = ap.parse_args(argv)
    report = run(clients=args.clients, requests=args.requests,
                 payload_len=args.payload, eject_base_s=args.eject_base,
                 drain_s=args.drain_s,
                 log=lambda m: print(f"[chaos] {m}", file=sys.stderr))
    print(json.dumps(report, indent=2, default=str))
    floor_ok = report["success_rate"] >= 0.99
    print(f"[chaos] success rate {report['success_rate']:.4f} "
          f"({'PASS' if floor_ok else 'FAIL'} at 0.99 floor)",
          file=sys.stderr)
    return 0 if floor_ok else 1


if __name__ == "__main__":
    sys.exit(main())
