"""Scenario drive: the admission-policing plane through the operator
surfaces (the verify-skill recipe, round 19 — docs/robustness.md
"admission policing").

Covers: a grammar-built lanes LB with a `policy` resource added via the
command grammar, a herd address detected by the analytics sketches and
then SHED IN C (RST, zero python accepts) with the legacy + policing
metric families and per-LB attribution all moving, `list[-detail]
policy` / `GET /policing` / `GET /analytics` serving the live table,
the `plane=policing` flight-recorder drill-down, DNS qname quarantine
(REFUSED ahead of the answer cache, innocent names unaffected), a
fleet-merged peer table arriving over a REAL heartbeat datagram (the
`police` meta field), the knob-off zero-cost check (C counter frozen),
and seeded shed-set determinism via the policing.decision.force coin.

Run: env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python _verify_policing.py
"""
import json
import socket
import time
import urllib.request

from vproxy_tpu.control.app import Application
from vproxy_tpu.control.command import CmdError, Command
from vproxy_tpu.control.http_controller import HttpController
from vproxy_tpu.net import vtl
from vproxy_tpu.policing import engine as policing
from vproxy_tpu.utils import failpoint, lifecycle, sketch

HERD = "127.0.7.7"


class IdSrv:
    def __init__(self, ident):
        self.ident = ident.encode()
        self.s = socket.socket()
        self.s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.s.bind(("127.0.0.1", 0))
        self.s.listen(64)
        self.port = self.s.getsockname()[1]
        import threading
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while True:
            try:
                c, _ = self.s.accept()
            except OSError:
                return
            try:
                c.sendall(self.ident)
                c.close()
            except OSError:
                pass


def herd_get(port, src=HERD):
    """One session from the herd address: the backend id, or
    'refused' when the policing plane RSTs the accept."""
    try:
        c = socket.create_connection(("127.0.0.1", port), timeout=5,
                                     source_address=(src, 0))
    except OSError:
        return "refused"
    c.settimeout(5)
    try:
        b = c.recv(16)
    except OSError:
        b = b""
    finally:
        c.close()
    return b.decode() if b else "refused"


def wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def main():
    assert vtl.police_supported(), "native policing surface unavailable"
    assert sketch.enabled(), "set VPROXY_TPU_ANALYTICS=1 for the drive"
    lifecycle.reset()
    sketch.reset()
    policing.configure(True)
    eng = policing.default()
    eng.set_policies([])
    eng.reset()
    app = Application.create(workers=2)
    ctl = HttpController(app, "127.0.0.1", 0)
    ctl.start()
    srv = IdSrv("A")
    for cmd in (
            "add upstream u0",
            "add server-group g0 timeout 500 period 100 up 1 down 1",
            "add server-group g0 to upstream u0 weight 10",
            f"add server sA to server-group g0 address "
            f"127.0.0.1:{srv.port} weight 10"):
        assert Command.execute(app, cmd) == "OK", cmd
    g = app.server_groups["g0"]
    assert wait_for(lambda: any(s.healthy for s in g.servers))
    assert Command.execute(
        app, "add tcp-lb lb0 address 127.0.0.1:0 upstream u0 "
        "protocol tcp lanes 2") == "OK"
    lb = app.tcp_lbs["lb0"]
    assert lb.lanes is not None

    # ---- policy resource via the command grammar ------------------
    assert Command.execute(
        app, "add policy crowd dim=clients rate=2 burst=4 action=shed"
    ) == "OK"
    assert Command.execute(app, "list policy") == ["crowd"]
    try:
        Command.execute(app, "add policy crowd dim=clients rate=9 "
                             "burst=9 action=shed")
        raise AssertionError("duplicate policy accepted")
    except CmdError:
        pass

    # ---- detection precedes enforcement ---------------------------
    # the herd must SURFACE through the lane HH-shard drain before a
    # tick can bucket it (the adversarial_crowd discipline)
    for _ in range(10):
        assert herd_get(lb.bind_port) == "A"
    assert wait_for(lambda: any(r["key"] == HERD
                                for r in sketch.top_table("clients", 0)))
    policing.tick()
    assert any(e["key"] == HERD for e in eng.table_snapshot())
    print(f"# detection: {HERD} surfaced via the C shard drain and is "
          "bucketed in the decision table")

    # ---- enforcement IN C: RST sheds, zero python accepts ---------
    served = refused = 0
    for _ in range(40):
        if herd_get(lb.bind_port) == "A":
            served += 1
        else:
            refused += 1
    assert lb.accepted == 0, "python accept path fired"
    assert refused >= 20, (served, refused)
    c_checked, c_shed = vtl.police_counters(lb.lanes.handle)[:2]
    assert c_checked >= refused and c_shed >= refused
    # the C deltas fold on the lane-0 drain into BOTH the policing
    # attribution and the legacy families pre-r19 dashboards alert on
    assert wait_for(lambda: eng.policed_total(
        lb="lb0", action="shed", dim="clients") >= refused)
    from vproxy_tpu.utils.metrics import GlobalInspection
    text = GlobalInspection.get().prometheus_string()
    assert 'vproxy_lb_policed_total{action="shed",dim="clients"}' in text
    assert 'reason="policed"' in text
    print(f"# enforcement: {refused}/40 herd sessions RST-shed in C "
          f"(served={served}, C checked={c_checked} shed={c_shed}, "
          "0 python accepts), attribution + legacy families moved")

    # ---- operator surfaces ----------------------------------------
    det = Command.execute(app, "list-detail policy")
    assert any("crowd -> dim clients" in line for line in det), det
    assert any(line.startswith("policing on") for line in det), det
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ctl.bind_port}/policing",
            timeout=5) as r:
        doc = json.loads(r.read())
    assert doc["enabled"] is True
    assert any(e["key"] == HERD for e in doc["table"]), doc["table"]
    assert sum(doc["policed_by_node"].values()) >= refused
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ctl.bind_port}/analytics",
            timeout=5) as r:
        adoc = json.loads(r.read())
    assert "policing" in adoc, list(adoc)
    print("# surfaces: list[-detail] policy / GET /policing / "
          "GET /analytics all serve the live table")

    # ---- DNS qname quarantine -------------------------------------
    assert Command.execute(
        app, "add dns-server dns0 address 127.0.0.1:0 upstream u0"
    ) == "OK"
    assert Command.execute(
        app, "add policy qhot dim=qnames rate=1 burst=2 action=shed"
    ) == "OK"
    d = app.dns_servers["dns0"]
    from vproxy_tpu.dns import packet as P

    def dns_rcode(name):
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(3)
        pkt = P.Packet(id=99, rd=True, questions=[P.Question(name, P.A)])
        s.sendto(pkt.encode(), ("127.0.0.1", d.bind_port))
        data, _ = s.recvfrom(4096)
        s.close()
        return P.parse(data).rcode

    saw_refused = False
    for _ in range(60):
        if dns_rcode("flood.example.com.") == 5:  # REFUSED
            saw_refused = True
            break
        time.sleep(0.05)
    assert saw_refused, "qname flood never quarantined"
    assert d.quarantines > 0
    assert dns_rcode("innocent.example.com.") != 5  # isolation
    print(f"# dns: flood.example.com. quarantined (REFUSED, "
          f"{d.quarantines} refusals); innocent names still answer")

    # ---- flight-recorder drill-down -------------------------------
    # C-lane sheds fold COUNTERS only (no per-shed event spam); the
    # python-plane verdicts — the DNS quarantine above — carry the
    # policy_shed/quarantine events, and every tick logs its install
    evs = Command.execute(app, "list-detail event-log plane policing")
    kinds = {e["kind"] for e in evs}
    assert {"policy_install", "policy_shed", "quarantine"} <= kinds, \
        kinds
    print(f"# events: plane=policing -> {len(evs)} events "
          f"(install/shed/quarantine kinds present)")

    # ---- fleet: a peer's table over a REAL heartbeat --------------
    import os
    peer_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    peer_sock.bind(("127.0.0.1", 0))
    peer_port = peer_sock.getsockname()[1]
    os.environ["VPROXY_TPU_CLUSTER_SELF"] = "0"
    from vproxy_tpu.cluster import ClusterNode, parse_peers
    peers = parse_peers(f"127.0.0.1:0,127.0.0.1:{peer_port}")
    node = ClusterNode(app, 0, peers)
    app.cluster = node
    node.membership.start()
    me = node.membership.peers[0]
    hb = {"t": "hb", "id": 1, "inc": time.time(), "gen": 0,
          "stepping": False,
          "police": {"seq": 3, "t": [["clients", "10.88.0.1",
                                      1000, 2000, 2]]}}

    def pump_hb():
        peer_sock.sendto(json.dumps(hb).encode(), ("127.0.0.1", me.port))
        return any(e["key"] == "10.88.0.1" and e["origin"] == "peer"
                   for e in eng.table_snapshot())

    assert wait_for(pump_hb, 15), "peer table never merged"
    st = eng.status()
    assert st["gossip_merges_total"] >= 1
    print(f"# fleet: peer entry 10.88.0.1 merged from a protocol-level "
          f"heartbeat (gossip_merges={st['gossip_merges_total']})")

    # ---- knob-off zero-cost ---------------------------------------
    policing.configure(False)
    c_before = vtl.police_counters(lb.lanes.handle)[0]
    for _ in range(10):
        assert herd_get(lb.bind_port) == "A"  # all admitted while off
    time.sleep(0.3)
    assert vtl.police_counters(lb.lanes.handle)[0] == c_before
    det = Command.execute(app, "list-detail policy")
    assert any(line.startswith("policing off") for line in det), det
    policing.configure(True)
    print("# knob-off: 10 herd sessions admitted with the C counter "
          "FROZEN; surface reports off; re-enabled")

    # ---- seeded shed-set determinism ------------------------------
    os.environ["VPROXY_TPU_FAILPOINT_SEED"] = "1719"
    seq = [f"10.9.{i % 7}.{i % 11}" for i in range(60)]

    def receipt():
        e2 = policing.PolicingEngine()
        failpoint.arm("policing.decision.force", probability=0.3,
                      seed=1719)
        try:
            for k in seq:
                e2.check("clients", k, lb="drive")
        finally:
            failpoint.clear()
        return e2.shed_receipt()

    r_a, r_b = receipt(), receipt()
    assert r_a == r_b and len(r_a) == 16
    print(f"# determinism: same seed + same arrivals -> same shed set "
          f"(receipt {r_a})")

    # ---- teardown -------------------------------------------------
    assert Command.execute(app, "remove policy qhot") == "OK"
    assert Command.execute(app, "remove policy crowd") == "OK"
    assert Command.execute(app, "list policy") == []
    node.close()
    peer_sock.close()
    ctl.stop()
    app.close()
    eng.set_policies([])
    eng.reset()
    print("# VERIFY POLICING: ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
