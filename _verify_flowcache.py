"""Scenario drive for the native switch datapath (docs/perf.md "Native
switch datapath: the flow cache") — the round-8 verify flow. Public
surfaces only, the way an operator meets them:

  1. a switch + vpcs + routes + remote-switch egress built entirely
     through the command grammar (Command.execute), multiqueue pollers
     on (VPROXY_TPU_SWITCH_POLLERS=2);
  2. real VXLAN datagrams blasted at the switch's bound UDP socket from
     several sender sockets; deliveries byte-verified at a receiver
     socket (vni rewrite, mac pair, ttl-1, checksum still valid);
  3. steady state must be served by C: flowcache hit counters move,
     `list-detail switch` shows `flowcache on(...)` with occupancy, and
     the /metrics text exposes the vproxy_switch_flowcache_* /
     vproxy_switch_native_* families;
  4. a route removed through the command grammar mid-traffic: ZERO
     stale-forwarded packets after the mutation (the generation gate),
     stale counter moves, and re-adding the route restores forwarding.

Run: env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python _verify_flowcache.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("VPROXY_TPU_SWITCH_POLLERS", "2")
os.environ.setdefault("VPROXY_TPU_FLOWCACHE_TTL_MS", "60000")

from vproxy_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(8)

from vproxy_tpu.control.app import Application  # noqa: E402
from vproxy_tpu.control.command import Command  # noqa: E402
from vproxy_tpu.net import vtl  # noqa: E402
from vproxy_tpu.utils.ip import parse_ip  # noqa: E402
from vproxy_tpu.utils.metrics import GlobalInspection  # noqa: E402
from vproxy_tpu.vswitch.packets import Ethernet, Ipv4, Vxlan  # noqa: E402
from vproxy_tpu.vswitch.switch import synthetic_mac  # noqa: E402

DST_MAC = b"\x02\xfe\x00\x00\x00\x01"
N_FLOWS = 32


def step(msg):
    print(f"== {msg}", flush=True)


def drain(rx, expect=0, timeout=2.0):
    got, t0 = [], time.monotonic()
    while time.monotonic() - t0 < timeout:
        r = vtl.recvmmsg(rx)
        if r:
            got.extend(r)
            if expect and len(got) >= expect:
                break
        else:
            time.sleep(0.01)
    return got


def main() -> int:
    if not (vtl.PROVIDER == "native" and vtl.flowcache_supported()):
        print("native flow cache unavailable; nothing to verify")
        return 1
    import vproxy_tpu.vswitch.fastpath as fp
    fp.MIN_BURST = 1  # small scripted waves must still compile entries

    app = Application(workers=1)
    rx = vtl.udp_bind("127.0.0.1", 0)
    _, rx_port = vtl.sock_name(rx)
    vtl.set_rcvbuf(rx, 4 << 20)
    try:
        step("build the switch through the command grammar")
        Command.execute(app, "add switch sw0 address 127.0.0.1:0")
        sw = app.switches["sw0"]
        assert sw._fc is not None and sw._fc_active, "flow cache not armed"
        assert len(sw._pollers) == 2, "multiqueue pollers not running"
        Command.execute(app, "add vpc 101 to switch sw0 "
                             "v4network 10.1.0.0/16")
        Command.execute(app, "add vpc 102 to switch sw0 "
                             "v4network 10.2.0.0/16")
        Command.execute(app, "add ip 10.1.0.1 to vpc 101 in switch sw0")
        Command.execute(app, "add ip 10.2.255.254 to vpc 102 in switch sw0")
        Command.execute(app, "add route r0 to vpc 101 in switch sw0 "
                             "network 10.2.0.0/16 vni 102")
        Command.execute(app, f"add switch out to switch sw0 "
                             f"address 127.0.0.1:{rx_port}")
        n2 = sw.networks[102]
        n2.macs.record(DST_MAC, sw.ifaces[("remote", "out")][0])
        gw_mac = synthetic_mac(101, parse_ip("10.1.0.1"))

        # each sender socket impersonates a DISTINCT host set (own src
        # mac + ip range): one mac arriving from several sender ifaces
        # would flap the mac table and keep the generation moving
        per_tx = []
        for k in range(3):
            dgrams = []
            for i in range(N_FLOWS):
                dst = parse_ip(f"10.2.0.{1 + i}")
                n2.arps.record(dst, DST_MAC)
                ip = Ipv4(src=parse_ip(f"10.1.{1 + k}.{2 + i}"), dst=dst,
                          proto=17, payload=b"verify!!", ttl=64)
                eth = Ethernet(gw_mac,
                               b"\x02\xaa\x00\x00\x00" + bytes([k + 1]),
                               0x0800, b"", packet=ip)
                dgrams.append(Vxlan(101, eth).to_bytes())
            per_tx.append(dgrams)

        step("blast real datagrams from several senders until C serves")
        txs = [vtl.udp_socket() for _ in range(3)]
        hits_delta = 0
        for _ in range(8):
            h0 = vtl.flowcache_counters()[0]
            for tx, dgrams in zip(txs, per_tx):
                for d in dgrams:
                    vtl.sendto(tx, d, "127.0.0.1", sw.bind_port)
            got = drain(rx, expect=3 * N_FLOWS)
            assert len(got) == 3 * N_FLOWS, \
                f"delivered {len(got)}/{3 * N_FLOWS}"
            hits_delta = vtl.flowcache_counters()[0] - h0
            if hits_delta >= 3 * N_FLOWS:
                break
        assert hits_delta >= 3 * N_FLOWS, \
            f"steady state never reached C ({hits_delta} hits/wave)"
        d0 = got[0][0]
        assert d0[4:7] == (102).to_bytes(3, "big"), "vni not rewritten"
        assert d0[8:14] == DST_MAC, "dst mac not rewritten"
        assert d0[30] == 63, "ttl not decremented"
        csum = sum((d0[22 + k] << 8) | d0[23 + k] for k in range(0, 20, 2))
        csum = (csum & 0xFFFF) + (csum >> 16)
        csum = (csum & 0xFFFF) + (csum >> 16)
        assert csum == 0xFFFF, "rewritten header checksum invalid"
        print(f"   {hits_delta} hits/wave, rewrite byte-verified")

        step("operator surfaces: list-detail switch + /metrics")
        detail = Command.execute(app, "list-detail switch")[0]
        print(f"   {detail}")
        assert "flowcache on(" in detail and "hit-rate=" in detail
        metrics = GlobalInspection.get().prometheus_string()
        for fam in ("vproxy_switch_flowcache_hit_total",
                    "vproxy_switch_flowcache_stale_total",
                    "vproxy_switch_native_fwd_total",
                    'vproxy_switch_native_drop_total{reason="acl_deny"}'):
            assert fam in metrics, f"{fam} missing from /metrics"

        step("route removed via the command grammar: generation gate")
        s0 = vtl.flowcache_counters()[3]
        Command.execute(app, "remove route r0 from vpc 101 in switch sw0")
        for tx in txs:
            for d in dgrams:
                vtl.sendto(tx, d, "127.0.0.1", sw.bind_port)
        leaked = drain(rx, timeout=1.0)
        assert leaked == [], \
            f"{len(leaked)} STALE packets forwarded through a dead route"
        assert vtl.flowcache_counters()[3] > s0, "stale gate never probed"
        print(f"   zero stale forwards, stale probes "
              f"{vtl.flowcache_counters()[3] - s0}")

        step("route restored: forwarding resumes")
        Command.execute(app, "add route r0 to vpc 101 in switch sw0 "
                             "network 10.2.0.0/16 vni 102")
        back = 0
        for _ in range(6):
            for tx, dgrams in zip(txs, per_tx):
                for d in dgrams:
                    vtl.sendto(tx, d, "127.0.0.1", sw.bind_port)
            back = len(drain(rx, expect=3 * N_FLOWS))
            if back == 3 * N_FLOWS:
                break
        assert back == 3 * N_FLOWS, f"only {back} delivered after restore"
        for tx in txs:
            vtl.close(tx)
        print("VERIFY-FLOWCACHE OK")
        return 0
    finally:
        try:
            Command.execute(app, "remove switch sw0")
        except Exception:
            pass
        vtl.close(rx)
        app.close()


if __name__ == "__main__":
    sys.exit(main())
