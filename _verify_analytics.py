"""Scenario drive: the live traffic-analytics plane through the
operator surfaces (the verify-skill recipe, round 16 —
docs/observability.md "traffic analytics").

Covers: a grammar-built lanes LB whose traffic lands in the top tables
with ZERO python accepts (the C HH-shard drain), the python accept
path and the DNS qname dimension, `top <dim>` / `list[-detail]
analytics` via Command.execute, `GET /analytics` on the HTTP
controller, the vproxy_hh_* / vproxy_analytics_* metric families, the
`GET /events?plane=` drill-down filter, a 2-node fleet-merged view
(a peer's gossiped top-K arriving over a REAL heartbeat datagram), and
the knob-off zero-cost check (C shard counters frozen, python sites
one branch).

Run: env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python _verify_analytics.py
"""
import json
import socket
import time
import urllib.request

from vproxy_tpu.control.app import Application
from vproxy_tpu.control.command import CmdError, Command
from vproxy_tpu.control.http_controller import HttpController
from vproxy_tpu.net import vtl
from vproxy_tpu.utils import lifecycle, sketch


class IdSrv:
    def __init__(self, ident):
        self.ident = ident.encode()
        self.s = socket.socket()
        self.s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.s.bind(("127.0.0.1", 0))
        self.s.listen(64)
        self.port = self.s.getsockname()[1]
        import threading
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while True:
            try:
                c, _ = self.s.accept()
            except OSError:
                return
            try:
                c.sendall(self.ident)
                c.close()
            except OSError:
                pass


def get_id(port):
    c = socket.create_connection(("127.0.0.1", port), timeout=5)
    c.settimeout(5)
    sid = c.recv(16)
    c.close()
    return sid.decode()


def wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def main():
    assert vtl.hh_supported(), "native analytics surface unavailable"
    assert sketch.enabled(), "set VPROXY_TPU_ANALYTICS=1 for the drive"
    lifecycle.reset()
    sketch.reset()
    app = Application.create(workers=2)
    ctl = HttpController(app, "127.0.0.1", 0)
    ctl.start()
    srv = IdSrv("A")
    for cmd in (
            "add upstream u0",
            "add server-group g0 timeout 500 period 100 up 1 down 1",
            "add server-group g0 to upstream u0 weight 10",
            f"add server sA to server-group g0 address "
            f"127.0.0.1:{srv.port} weight 10"):
        assert Command.execute(app, cmd) == "OK", cmd
    g = app.server_groups["g0"]
    assert wait_for(lambda: any(s.healthy for s in g.servers))
    assert Command.execute(
        app, "add tcp-lb lb0 address 127.0.0.1:0 upstream u0 "
        "protocol tcp lanes 2") == "OK"
    lb = app.tcp_lbs["lb0"]
    assert lb.lanes is not None

    # ---- C lanes feed the top tables (zero python accepts) --------
    for _ in range(25):
        assert get_id(lb.bind_port) == "A"
    assert lb.accepted == 0, "python accept path fired"
    assert wait_for(lambda: sketch.top_table("clients")
                    and sketch.top_table("clients")[0]["key"]
                    == "127.0.0.1")
    assert wait_for(lambda: any(
        e["key"] == f"127.0.0.1:{srv.port}"
        for e in sketch.top_table("backends")))
    assert wait_for(lambda: any(e["key"] == "lb0"
                                for e in sketch.top_table("routes")))
    assert sketch.plane_updates_total("lane") >= 50  # client+backend
    print(f"# lane plane: top client 127.0.0.1 "
          f"count={sketch.top_table('clients')[0]['count']} with "
          f"0 python accepts; C shard updates="
          f"{vtl.hh_counters()[0]} overflows={vtl.hh_counters()[1]}")

    # ---- operator surfaces ----------------------------------------
    out = Command.execute(app, "top clients")
    assert any("127.0.0.1" in line for line in out[1:]), out
    print("\n".join(out[:3]))
    out = Command.execute(app, "top backends")
    assert any(f"127.0.0.1:{srv.port}" in line for line in out), out
    try:
        Command.execute(app, "top nonsense")
        raise AssertionError("bad dimension accepted")
    except CmdError:
        pass
    lst = Command.execute(app, "list analytics")
    assert lst[0].startswith("analytics on"), lst
    det = Command.execute(app, "list-detail analytics")
    assert det["top"]["clients"][0]["key"] == "127.0.0.1"
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ctl.bind_port}/analytics",
            timeout=5) as r:
        doc = json.loads(r.read())
    assert doc["top"]["clients"][0]["key"] == "127.0.0.1"
    assert doc["status"]["enabled"] is True
    from vproxy_tpu.utils.metrics import GlobalInspection
    text = GlobalInspection.get().prometheus_string()
    assert 'vproxy_hh_count{dim="clients",slot="0"}' in text
    assert 'vproxy_analytics_drop_total{reason="shard_overflow"} 0' \
        in text
    print(f"# surfaces: top/list[-detail]/GET /analytics/metrics all "
          f"serve the table ({len(doc['top']['clients'])} client rows)")

    # ---- python accept path (lanes off LB) ------------------------
    assert Command.execute(
        app, "add tcp-lb lb1 address 127.0.0.1:0 upstream u0 "
        "protocol tcp") == "OK"
    lb1 = app.tcp_lbs["lb1"]
    assert lb1.lanes is None
    for _ in range(8):
        assert get_id(lb1.bind_port) == "A"
    assert any(e["key"] == "lb1" for e in sketch.top_table("routes"))
    assert sketch.plane_updates_total("accept") >= 8
    print("# python plane: lb1 attributed in top routes "
          f"(accept updates={sketch.plane_updates_total('accept')})")

    # ---- DNS qname dimension --------------------------------------
    assert Command.execute(
        app, "add dns-server dns0 address 127.0.0.1:0 upstream u0"
    ) == "OK"
    d = app.dns_servers["dns0"]
    from vproxy_tpu.dns import packet as P
    q = P.Packet(id=7, questions=[P.Question("hot.example.com.", P.A)])
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    for _ in range(6):
        tx.sendto(q.encode(), ("127.0.0.1", d.bind_port))
    tx.close()
    assert wait_for(lambda: any(
        e["key"] == "hot.example.com."
        for e in sketch.top_table("qnames")))
    print("# dns plane: hot.example.com. in top qnames "
          f"(dns updates={sketch.plane_updates_total('dns')})")

    # ---- events plane drill-down ----------------------------------
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ctl.bind_port}/analytics",
            timeout=5) as r:
        pass  # warm: the filter below must not depend on this
    evs = Command.execute(app, "list-detail event-log plane lane")
    assert evs and all(e["kind"] == "lanes" for e in evs), evs[:2]
    print(f"# events drill-down: plane=lane -> {len(evs)} lane events "
          "(no cluster/accept noise)")

    # ---- 2-node fleet-merged view ---------------------------------
    # node 0 boots the production way; node 1 is impersonated at the
    # PROTOCOL level — a real heartbeat datagram carrying a gossiped
    # top-K, exactly what a remote peer sends (cluster/membership.py)
    import os
    peer_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    peer_sock.bind(("127.0.0.1", 0))
    peer_port = peer_sock.getsockname()[1]
    os.environ["VPROXY_TPU_CLUSTER_SELF"] = "0"
    from vproxy_tpu.cluster import ClusterNode, parse_peers
    peers = parse_peers(f"127.0.0.1:0,127.0.0.1:{peer_port}")
    node = ClusterNode(app, 0, peers)
    app.cluster = node
    node.membership.start()
    me = node.membership.peers[0]
    hb = {"t": "hb", "id": 1, "inc": time.time(), "gen": 0,
          "stepping": False,
          "hh": {"clients": [["10.77.0.1", 900], ["127.0.0.1", 50]]}}

    def pump_hb():
        peer_sock.sendto(json.dumps(hb).encode(),
                         ("127.0.0.1", me.port))
        return node.membership.peers[1].up

    assert wait_for(pump_hb), "peer 1 never came UP"
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ctl.bind_port}/analytics",
            timeout=5) as r:
        doc = json.loads(r.read())
    fleet = doc["fleet"]["clients"]
    rows = {e["key"]: e for e in fleet}
    assert rows["10.77.0.1"]["count"] == 900  # peer-only key
    assert rows["127.0.0.1"]["nodes"] == 2    # merged across nodes
    assert rows["127.0.0.1"]["count"] > 50    # local + gossiped
    out = Command.execute(app, "top clients fleet")
    assert any("10.77.0.1" in line for line in out), out
    print(f"# fleet merge: peer key 10.77.0.1=900 + local 127.0.0.1 "
          f"summed across 2 nodes ({len(fleet)} rows)")

    # ---- knob-off zero-cost ---------------------------------------
    sketch.configure(on=False)
    c_before = vtl.hh_counters()[0]
    py_before = sketch.plane_updates_total("accept")
    for _ in range(10):
        assert get_id(lb.bind_port) == "A"
        assert get_id(lb1.bind_port) == "A"
    time.sleep(0.4)
    assert vtl.hh_counters()[0] == c_before, "C shards moved while off"
    assert sketch.plane_updates_total("accept") == py_before
    # the operator surface reports the state, not a stale window
    assert "disabled" in Command.execute(app, "top clients")[0]
    sketch.configure(on=True)
    assert get_id(lb.bind_port) == "A"
    assert wait_for(lambda: vtl.hh_counters()[0] > c_before)
    print("# knob-off: 20 sessions with ZERO sketch work (C counter "
          "frozen, python counter frozen); re-enable resumes")

    node.close()
    peer_sock.close()
    ctl.stop()
    app.close()
    print("# VERIFY ANALYTICS: ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
