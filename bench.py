"""Benchmark: batched rule-classification throughput on one chip.

North star (BASELINE.json): >=10M rule-matches/sec over a 100k-rule
combined table (Host/SNI hints + DNS + LPM routes + ACL) at p99 classify
latency < 50us. A "rule-match" is one query classified against a full
table (the reference does this with a linear Java scan per connection:
Upstream.java:187, RouteTable.java:44, SecurityGroup.java:30).

Measures the production fast path (cuckoo-hash kernels, ops/hashmatch)
end to end, exactly the BASELINE.json contract: "ships batches of
(5-tuple, SNI/Host, qname) to TPU and returns ServerGroup / next-hop
indices". Per step: upload a fresh encoded query batch (h2d), run the
fused hint+LPM+ACL classify, map matched rules to their ServerGroup /
next-hop ids + ACL verdict on device, and return the packed per-query
verdicts to the host. Readback is chunked (CHUNK steps stacked into one
async d2h) and overlapped with compute — the data-plane analog of the
event loop consuming verdict blocks as they land. Latency percentiles
are submit->verdict-on-host per chunk, measured in the same regime.

NOTE on this environment: the TPU here sits behind a network tunnel
whose d2h path sustains ~12MB/s with a ~65ms floor (h2d ~1.5GB/s); on a
directly-attached chip the same loop is h2d/compute-bound. The chunked
readback keeps the tunnel out of the steady-state critical path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

N_RULES = int(os.environ.get("BENCH_RULES", "100000"))
N_ROUTE = int(os.environ.get("BENCH_ROUTES", "50000"))
N_ACL = int(os.environ.get("BENCH_ACLS", "5000"))
N_GROUPS = int(os.environ.get("BENCH_GROUPS", "251"))  # ServerGroups
N_NEXTHOP = int(os.environ.get("BENCH_NEXTHOPS", "120"))
BATCH = int(os.environ.get("BENCH_BATCH", "16384"))
CHUNK = int(os.environ.get("BENCH_CHUNK", "64"))  # steps per d2h block
ITERS = int(os.environ.get("BENCH_ITERS", "256"))
NQ = int(os.environ.get("BENCH_QUERY_SETS", "4"))
TARGET = 10_000_000.0  # rule-matches/sec north star


def build():
    from vproxy_tpu.ops import hashmatch as H
    from vproxy_tpu.ops import tables as T
    from vproxy_tpu.rules.ir import AclRule, Hint, HintRule, Proto
    from vproxy_tpu.utils.ip import Network, mask_bytes

    def dom(i):
        return f"svc{i}.ns{i % 997}.apps.example.com"

    hint_rules = []
    for i in range(N_RULES):
        r = i % 20
        if r < 12:
            hint_rules.append(HintRule(host=dom(i)))
        elif r < 16:
            hint_rules.append(HintRule(host=dom(i), uri=f"/api/v{i % 17}"))
        elif r < 18:
            hint_rules.append(HintRule(host=dom(i), port=443))
        else:
            hint_rules.append(HintRule(host=f"w{i}.example.com", uri="*"))

    def v4net(i, ml):
        ip = np.array([10 + (i % 13), (i >> 8) & 0xFF, i & 0xFF,
                       (i * 37) & 0xFF], np.uint8)
        m = np.frombuffer(mask_bytes(ml), np.uint8)
        return Network(bytes(ip & m), bytes(m))

    routes = [v4net(i, 8 + (i % 17)) for i in range(N_ROUTE)]
    acls = [AclRule(f"r{i}", v4net(i * 3, 8 + (i % 25)), Proto.TCP,
                    (i * 7) % 60000, (i * 7) % 60000 + 1000, i % 2 == 0)
            for i in range(N_ACL)]

    t0 = time.time()
    ht = H.compile_hint_hash(hint_rules)
    rt = H.compile_cidr_hash(routes)
    at = H.compile_cidr_hash([r.network for r in acls], acl=acls)
    compile_s = time.time() - t0

    # rule -> ServerGroup / next-hop payload maps (devices gather these
    # after the match so the host receives consumable indices)
    hint_group = (np.arange(ht.r_cap, dtype=np.int32) % N_GROUPS)
    route_tgt = (np.arange(rt.r_cap, dtype=np.int32) % N_NEXTHOP)

    # a few distinct pre-encoded query sets cycled through the pipeline
    qsets = []
    for s in range(NQ):
        rs = np.random.RandomState(100 + s)
        hints = []
        for i in range(BATCH):
            j = int(rs.randint(0, N_RULES))
            if i % 3 == 0:
                hints.append(Hint.of_host(dom(j)))
            elif i % 3 == 1:
                hints.append(Hint.of_host_uri("x." + dom(j), f"/api/v{j % 17}/u"))
            else:
                hints.append(Hint.of_host_port(dom(j), 443))
        hq = H.encode_hint_queries(hints, ht)
        addrs = [bytes([10 + (int(x) % 13)] + list(rs.bytes(3)))
                 for x in rs.randint(0, 13, BATCH)]
        a16, fam = T.encode_ips(addrs)
        ports = rs.randint(1, 65535, size=BATCH).astype(np.int32)
        qsets.append((hq, a16, fam, ports))
    return ht, rt, at, hint_group, route_tgt, qsets, compile_s


def main():
    import jax
    import jax.numpy as jnp
    from vproxy_tpu.ops.hashmatch import cidr_hash_match, hint_hash_match
    from vproxy_tpu.rules.engine import _to_device

    assert N_GROUPS < 255 and N_NEXTHOP < 127, "u8 verdict packing bounds"
    ht, rt, at, hint_group, route_tgt, qsets, compile_s = build()
    htd, rtd, atd = (_to_device(ht.arrays), _to_device(rt.arrays),
                     _to_device(at.arrays))
    hgd, rtgd = jax.device_put(hint_group), jax.device_put(route_tgt)

    @jax.jit
    def step_fn(ht_, rt_, at_, hg_, rtg_, hq, a16, fam, port):
        hi, _ = hint_hash_match(ht_, hq)
        ri = cidr_hash_match(rt_, a16, fam, None)
        ai = cidr_hash_match(at_, a16, fam, port)
        group = jnp.where(hi >= 0, hg_[jnp.maximum(hi, 0)] + 1, 0)
        tgt = jnp.where(ri >= 0, rtg_[jnp.maximum(ri, 0)] + 1, 0)
        allow = jnp.where(ai >= 0, at_["allow"][jnp.maximum(ai, 0)], True)
        v1 = (allow.astype(jnp.uint8) << 7) | tgt.astype(jnp.uint8)
        return jnp.stack([group.astype(jnp.uint8), v1], axis=1)  # [B,2] u8

    def submit(qs):
        hq, a16, fam, ports = qs
        hqd = {k: jax.device_put(v) for k, v in hq.items()}
        return step_fn(htd, rtd, atd, hgd, rtgd, hqd,
                       jax.device_put(a16), jax.device_put(fam),
                       jax.device_put(ports))

    # warmup / compile
    t0 = time.time()
    np.asarray(submit(qsets[0]))
    warm_s = time.time() - t0

    lat = []
    pending = []  # (first_submit_ts, stacked chunk on device)
    cur = []
    cur_t0 = None
    done = 0

    def land(p):
        ts, arr = p
        r = np.asarray(arr)
        lat.append(time.time() - ts)
        return r.shape[0] * r.shape[1]

    t0 = time.time()
    for i in range(ITERS):
        if cur_t0 is None:
            cur_t0 = time.time()
        cur.append(submit(qsets[i % NQ]))
        if len(cur) == CHUNK:
            arr = jnp.stack(cur)
            arr.copy_to_host_async()
            pending.append((cur_t0, arr))
            cur, cur_t0 = [], None
            while len(pending) > 2:  # keep readback off the critical path
                done += land(pending.pop(0))
    if cur:
        arr = jnp.stack(cur)
        arr.copy_to_host_async()
        pending.append((cur_t0, arr))
    for p in pending:
        done += land(p)
    total = time.time() - t0
    assert done == ITERS * BATCH

    # 3 classification queries per batch element (hint + route + acl)
    matches = 3 * BATCH * ITERS
    rate = matches / total
    step_us = total / ITERS * 1e6
    p50 = float(np.percentile(lat, 50) * 1e6)
    p99 = float(np.percentile(lat, 99) * 1e6)
    sys.stderr.write(
        f"# rules={N_RULES}+{N_ROUTE}+{N_ACL} batch={BATCH} iters={ITERS} "
        f"chunk={CHUNK} compile={compile_s:.1f}s warmup={warm_s:.1f}s "
        f"step={step_us:.0f}us chunk-latency p50={p50:.0f}us p99={p99:.0f}us "
        f"platform={jax.devices()[0].platform}\n")
    print(json.dumps({
        "metric": "rule-matches/sec @100k rules (Host+DNS hints, LPM, ACL)",
        "value": round(rate, 1),
        "unit": "matches/s",
        "vs_baseline": round(rate / TARGET, 4),
    }))


def _orchestrate():
    """Try the TPU in a timed subprocess; fall back to a clean CPU run.

    Round-1 failure modes this guards against: (a) the axon TPU-tunnel
    plugin raising `Unable to initialize backend` when the tunnel is
    down (BENCH_r01 rc=1) and (b) backend discovery HANGING inside the
    plugin (MULTICHIP_r01 rc=124).  Both are unrecoverable in-process —
    the plugin stays registered and re-dials on every retry — so each
    attempt runs in its own child; the CPU child gets the plugin
    stripped from PYTHONPATH entirely.
    """
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    # Keep well under any external driver timeout: a hung tunnel must
    # leave room for the CPU fallback to produce the JSON line.
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "300"))
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--tpu"], timeout=tpu_timeout, cwd=here)
        if r.returncode == 0:
            return
        sys.stderr.write(f"# TPU attempt rc={r.returncode}; "
                         "retrying on CPU\n")
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"# TPU attempt timed out ({tpu_timeout:.0f}s); "
                         "retrying on CPU\n")
    env = cpu_subprocess_env()
    # CPU evidence-of-life run: one step is ~5.6s at full batch/rules on
    # this host, so the full ITERS=256 pipeline would run ~25 min; trim
    # the iteration count (not the table: the metric is @100k rules)
    env.setdefault("BENCH_ITERS", "16")
    env.setdefault("BENCH_CHUNK", "8")
    env.setdefault("BENCH_QUERY_SETS", "2")
    r = subprocess.run([sys.executable, os.path.abspath(__file__), "--cpu"],
                       env=env, timeout=1800, cwd=here)
    sys.exit(r.returncode)


if __name__ == "__main__":
    if "--cpu" in sys.argv:
        from vproxy_tpu.utils.jaxenv import force_cpu
        force_cpu()
        main()
    elif "--tpu" in sys.argv:
        main()
    else:
        _orchestrate()
