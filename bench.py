"""Benchmark: batched rule-classification throughput on one chip.

North star (BASELINE.json): >=10M rule-matches/sec over a 100k-rule
combined table (Host/SNI hints + DNS + LPM routes + ACL) at p99 classify
latency < 50us. A "rule-match" is one query classified against a full
table (the reference does this with a linear Java scan per connection:
Upstream.java:187, RouteTable.java:44, SecurityGroup.java:30).

The TPU in this environment sits behind a tunnel with ~65ms per-dispatch
round trip and ~0.7 MB/s d2h (measured, r3). The headline section
therefore amortizes the RPC with DEVICE-SIDE MULTI-STEP EXECUTION: one
jitted `lax.fori_loop` classifies K pre-uploaded query batches per
dispatch and returns only [K] u32 verdict checksums (K*4 bytes d2h), so
one ~65ms round trip buys K*B queries. Verdicts stay on device — which
is also the production shape: the consumer of a verdict (routing
decision feeding a device-resident table, or a host that reads back
per-CONNECTION results far smaller than per-query batches) does not pay
per-query d2h. The e2e section then measures the OTHER contract — full
[B,2] verdict readback per dispatch — and reports the measured tunnel
ceiling (d2h_MBps / 2 bytes-per-verdict) beside it, honestly.

Staged orchestration (each stage is its own child process so a hung TPU
tunnel cannot eat the whole budget, and every stage leaves per-phase
timing evidence behind even when killed):

  1. tpu-smoke — small config (1k rules, batch 512): proves device-up
     and records import/devices/build/upload/compile/step/d2h timings.
  2. tpu-full  — the real 100k-rule, batch-16384 config, only if smoke
     passed, within the remaining budget.
  3. cpu       — evidence-of-life fallback only if no TPU stage landed.

Children are ADAPTIVE: each measured section times one dispatch first
and sizes its iteration count to a deadline derived from
BENCH_CHILD_BUDGET, and the result file is rewritten after EVERY
section, so a SIGTERM mid-stage still leaves the sections that finished
(the orchestrator accepts partial results). Compilations go through a
persistent cache (.jax_cache/) so repeated runs skip the 14-25s
warmup_compile cost.

Measured sections per child:
  * throughput_device — the headline: pipelined multi-step dispatches,
    kernel-resident verdicts, checksum readback. Also yields
    kernel_step_us = dispatch_time / K.
  * throughput_e2e — single-step dispatches with full [B,2] verdict
    readback (chunked, async) — the end-to-end number, bounded by the
    tunnel; reported with the measured ceiling.
  * latency_b1 / latency_bN — per-dispatch submit->verdict-on-host
    p50/p99, measured blocking, steady state.
  * service — ClassifyService accept->verdict under synthetic load,
    BOTH contracts: mode=device (raw device round trip at the service
    boundary) and mode=auto with the latency budget policy (lone
    queries ride the host oracle when the device blows the budget —
    the accept-path p99 story).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

TARGET = 10_000_000.0  # rule-matches/sec north star


def _env_int(k, d):
    return int(os.environ.get(k, str(d)))


def _env_float(k, d):
    return float(os.environ.get(k, str(d)))


# ----------------------------------------------------------------- phases

class Phases:
    """Incremental phase evidence: one JSON line per phase, flushed
    immediately so a killed child still leaves a trail."""

    def __init__(self, path, stage):
        self.path = path
        self.stage = stage
        self._t0 = None
        self._name = None

    def start(self, name):
        self._name = name
        self._t0 = time.time()
        sys.stderr.write(f"# [{self.stage}] {name}...\n")
        sys.stderr.flush()

    def done(self, **detail):
        dt = time.time() - self._t0
        rec = {"stage": self.stage, "phase": self._name,
               "seconds": round(dt, 3), **detail}
        sys.stderr.write(f"# [{self.stage}] {self._name} {dt:.2f}s "
                         f"{detail if detail else ''}\n")
        sys.stderr.flush()
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return dt


# ------------------------------------------------------------- table build

def kernel_select():
    """BENCH_KERNEL: 'fp' (default) = packed fingerprint kernels
    (ops/fphash.py, ~100 gathered rows/query); 'cuckoo' = byte-verified
    cuckoo kernels (ops/hashmatch.py). Returns (compile_hint,
    compile_cidr, encode_hints, hint_match, cidr_match, pad_keys)."""
    if os.environ.get("BENCH_KERNEL", "fp") == "fp":
        from vproxy_tpu.ops import fphash as F
        return (F.compile_hint_fp, F.compile_cidr_fp,
                F.encode_hint_queries_fp, F.hint_fp_match, F.cidr_fp_match,
                ("hp_slot", "hp_fp1", "hp_fp2", "hp_level"),
                ("up_slot", "up_fp1", "up_fp2", "up_score"))
    from vproxy_tpu.ops import hashmatch as H
    return (H.compile_hint_hash,
            lambda nets, acl=None: H.compile_cidr_hash(nets, acl=acl),
            H.encode_hint_queries, H.hint_hash_match, H.cidr_hash_match,
            ("hp_len", "hp_slot1", "hp_slot2"), ())


def build(ph):
    from vproxy_tpu.ops import tables as T
    from vproxy_tpu.rules.ir import AclRule, Hint, HintRule, Proto
    from vproxy_tpu.utils.ip import Network, mask_bytes

    n_rules = _env_int("BENCH_RULES", 100000)
    n_route = _env_int("BENCH_ROUTES", 50000)
    n_acl = _env_int("BENCH_ACLS", 5000)
    batch = _env_int("BENCH_BATCH", 16384)
    # >= 2 sets so the multi-step loop body's gathers depend on the
    # iteration counter (s = i % nq) — with one set the hint-match leg
    # would be loop-invariant and XLA could hoist it out of the loop,
    # inflating the headline rate
    nq = max(2, _env_int("BENCH_QUERY_SETS", 4))

    def dom(i):
        return f"svc{i}.ns{i % 997}.apps.example.com"

    ph.start("build_tables")
    hint_rules = []
    for i in range(n_rules):
        r = i % 20
        if r < 12:
            hint_rules.append(HintRule(host=dom(i)))
        elif r < 16:
            hint_rules.append(HintRule(host=dom(i), uri=f"/api/v{i % 17}"))
        elif r < 18:
            hint_rules.append(HintRule(host=dom(i), port=443))
        else:
            hint_rules.append(HintRule(host=f"w{i}.example.com", uri="*"))

    def v4net(i, ml):
        ip = np.array([10 + (i % 13), (i >> 8) & 0xFF, i & 0xFF,
                       (i * 37) & 0xFF], np.uint8)
        m = np.frombuffer(mask_bytes(ml), np.uint8)
        return Network(bytes(ip & m), bytes(m))

    routes = [v4net(i, 8 + (i % 17)) for i in range(n_route)]
    acls = [AclRule(f"r{i}", v4net(i * 3, 8 + (i % 25)), Proto.TCP,
                    (i * 7) % 60000, (i * 7) % 60000 + 1000, i % 2 == 0)
            for i in range(n_acl)]
    (compile_hint, compile_cidr, encode_hints, _, _, pad_keys,
     upad_keys) = kernel_select()
    ht = compile_hint(hint_rules)
    rt = compile_cidr(routes)
    at = compile_cidr([r.network for r in acls], acl=acls)
    ph.done(rules=n_rules, routes=n_route, acls=n_acl)

    # rule -> ServerGroup / next-hop payload maps (device gathers these
    # after the match so the host receives consumable indices)
    n_groups = _env_int("BENCH_GROUPS", 251)
    n_nexthop = _env_int("BENCH_NEXTHOPS", 120)
    hint_group = (np.arange(ht.r_cap, dtype=np.int32) % n_groups)
    route_tgt = (np.arange(rt.r_cap, dtype=np.int32) % n_nexthop)

    ph.start("encode_queries")
    qsets = []
    sample_hints = None
    sample_addrs = None
    for s in range(nq):
        rs = np.random.RandomState(100 + s)
        hints = []
        for i in range(batch):
            j = int(rs.randint(0, n_rules))
            if i % 3 == 0:
                hints.append(Hint.of_host(dom(j)))
            elif i % 3 == 1:
                hints.append(Hint.of_host_uri("x." + dom(j), f"/api/v{j % 17}/u"))
            else:
                hints.append(Hint.of_host_port(dom(j), 443))
        hq = encode_hints(hints, ht)
        addrs = [bytes([10 + (int(x) % 13)] + list(rs.bytes(3)))
                 for x in rs.randint(0, 13, batch)]
        a16, fam = T.encode_ips(addrs)
        ports = rs.randint(1, 65535, size=batch).astype(np.int32)
        qsets.append((hq, a16, fam, ports))
        if s == 0:
            sample_hints, sample_addrs = hints[:8], addrs[:8]

    # unify the probe tiers across sets so they stack on one axis
    # (invalid pad: -1 lens for cuckoo, level/slot 0 for fp); the fp
    # uri probes are content-trimmed per set and need the same treatment
    padval = -1 if pad_keys[0] == "hp_len" else 0
    # um_* exist iff that set's uri probes were trimmed; sets must agree
    # on the key set to stack (and the fallback reads up_* PRE-padding)
    if any("um_fp1" in q[0] for q in qsets):
        for hq, _, _, _ in qsets:
            for mk_, pk_ in (("um_fp1", "up_fp1"), ("um_fp2", "up_fp2"),
                             ("um_score", "up_score")):
                hq.setdefault(mk_, hq[pk_])
    for keys in (pad_keys, upad_keys):
        if not keys:
            continue
        maxp = max(q[0][keys[0]].shape[1] for q in qsets)
        for hq, _, _, _ in qsets:
            cur = hq[keys[0]].shape[1]
            if cur < maxp:
                pad = np.full((batch, maxp - cur), padval, np.int32)
                for k in keys:
                    hq[k] = np.concatenate([hq[k], pad], axis=1)
    ph.done(batch=batch, sets=nq)

    # host-side oracle answers for the first 8 set-0 queries — the
    # device verdicts are checked against these after warmup
    ph.start("oracle_sample")
    from vproxy_tpu.rules import oracle
    expect = []
    for i in range(len(sample_hints)):
        hi = oracle.search(hint_rules, sample_hints[i])
        a = sample_addrs[i]
        ri = next((j for j, nt in enumerate(routes) if nt.contains_ip(a)), -1)
        port = int(qsets[0][3][i])
        ai = next((j for j, r in enumerate(acls)
                   if r.network.contains_ip(a)
                   and r.min_port <= port <= r.max_port), -1)
        expect.append((hi, ri, ai))
    ph.done(n=len(expect))
    return ht, rt, at, hint_group, route_tgt, qsets, expect


# ------------------------------------------------------------------ child

def _enable_compile_cache(here):
    """Persistent XLA compilation cache: repeated runs (same shapes) skip
    the 14-25s trace+compile entirely. Best-effort — an axon/plugin
    backend that cannot serialize executables just misses the cache."""
    import jax
    cache = os.environ.get("BENCH_COMPILE_CACHE",
                           os.path.join(here, ".jax_cache"))
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    except Exception:
        return False


class Deadline:
    """Child-side budget: sections size their iteration counts to what is
    left so the child exits cleanly instead of being SIGTERMed."""

    def __init__(self, budget_s):
        self.t0 = time.time()
        self.budget = budget_s

    def remaining(self):
        return self.budget - (time.time() - self.t0)

    def iters(self, t_each, target_frac, lo=3, hi=4096, reserve=10.0):
        avail = max(0.0, (self.remaining() - reserve) * target_frac)
        if t_each <= 0:
            return hi
        return int(max(lo, min(hi, avail / t_each)))


def child():
    try:
        if os.environ.get("BENCH_STAGE") == "pjit":
            return _pjit_child()
        if os.environ.get("BENCH_STAGE") == "fused":
            return _fused_child()
        return _child_run()
    except BaseException as e:
        _write_child_error(e)
        raise


def _write_child_error(e) -> None:
    """A claim/import/build failure must leave a self-explaining result
    file: the orchestrator folds the error string into the artifact so
    a platform:"cpu" fallback says WHY the chip contributed nothing
    (VERDICT r5 item 2 — two 0.0s tpu-smoke phases with no recorded
    cause)."""
    rf = os.environ.get("BENCH_RESULT_FILE")
    if not rf:
        return
    try:
        try:
            with open(rf) as f:
                res = json.load(f)
        except (OSError, ValueError):
            res = {"metric": "rule-matches/sec (failed child)",
                   "value": 0.0, "unit": "matches/s", "vs_baseline": 0.0,
                   "platform": "none"}
        res.setdefault("stage", os.environ.get("BENCH_STAGE", "child"))
        res["partial"] = True
        res["error"] = repr(e)[:500]
        with open(rf + ".tmp", "w") as f:
            json.dump(res, f)
        os.replace(rf + ".tmp", rf)
    except Exception:
        pass  # best-effort: the original exception still propagates


def _child_run():
    stage = os.environ.get("BENCH_STAGE", "child")
    ph = Phases(os.environ.get("BENCH_PHASE_FILE", ""), stage)
    here = os.path.dirname(os.path.abspath(__file__))
    dl = Deadline(_env_float("BENCH_CHILD_BUDGET", 600.0))

    ph.start("import_jax")
    cache_ok = _enable_compile_cache(here)
    import jax
    import jax.numpy as jnp
    ph.done(compile_cache=cache_ok)

    nr = _env_int("BENCH_RULES", 100000)
    label = "%dk" % (nr // 1000) if nr >= 1000 else str(nr)
    result = {
        "metric": "rule-matches/sec @%s rules (Host+DNS hints, LPM, ACL)"
                  % label,
        "value": 0.0, "unit": "matches/s", "vs_baseline": 0.0,
        "platform": "unknown", "stage": stage, "partial": True,
    }
    if os.environ.get("BENCH_KERNEL", "fp") == "fp":
        from vproxy_tpu.ops.fphash import default_member_mode
        result["fp_member_mode"] = default_member_mode()
    result_file = os.environ.get("BENCH_RESULT_FILE")

    def flush():
        if result_file:
            with open(result_file + ".tmp", "w") as f:
                json.dump(result, f)
            os.replace(result_file + ".tmp", result_file)

    # accept-path latency contract FIRST: host-only (no device claim
    # needed), so the BASELINE p99<50us fields land in the artifact even
    # when the tunnel wedges the very next phase forever
    accept_path_section(ph, dl, result)
    flush()
    cluster_section(ph, result)
    flush()

    ph.start("devices")
    dev = jax.devices()[0]
    platform = dev.platform
    ph.done(platform=platform, n=len(jax.devices()))
    result["platform"] = platform

    # fixed-shape canary: the SAME gather-bound kernel every round, so
    # artifacts from different rounds/hours can be normalized against
    # the tunnel's measured 2.2x hour-to-hour variance (PERF_NOTES).
    # 65536 scalar gathers per step x 64 steps — gathers are THE cost
    # driver, so this measures the hour-class of exactly what matters.
    ph.start("canary")
    ctab = jnp.arange(1 << 20, dtype=jnp.int32)
    cidx = ((jnp.arange(65536, dtype=jnp.uint32) * jnp.uint32(2654435761))
            & ((1 << 20) - 1)).astype(jnp.int32)

    @jax.jit
    def canary_fn(tab, ix):
        def body(i, acc):
            return acc + jnp.sum(tab[(ix + i) & ((1 << 20) - 1)]
                                 .astype(jnp.uint32))
        return jax.lax.fori_loop(0, 64, body, jnp.uint32(0))

    np.asarray(canary_fn(ctab, cidx))  # compile + warm
    csamp = []
    for _ in range(5):  # median: one tunnel stall must not skew the
        t0 = time.time()  # normalization baseline for the whole round
        np.asarray(canary_fn(ctab, cidx))
        csamp.append(time.time() - t0)
    canary_ms = float(np.median(csamp)) / 64 * 1000
    ph.done(canary_step_ms=round(canary_ms, 3))
    result["canary_step_ms"] = round(canary_ms, 3)
    flush()

    from vproxy_tpu.rules.engine import _to_device
    _, _, _, hint_match, cidr_match, _, _ = kernel_select()

    n_groups = _env_int("BENCH_GROUPS", 251)
    n_nexthop = _env_int("BENCH_NEXTHOPS", 120)
    assert n_groups < 255 and n_nexthop < 127, "u8 verdict packing bounds"
    batch = _env_int("BENCH_BATCH", 16384)
    ksteps = _env_int("BENCH_STEPS_PER_DISPATCH", 512)

    ht, rt, at, hint_group, route_tgt, qsets, expect = build(ph)

    # h2d/d2h bandwidth probe: says whether a later stall is the tunnel
    ph.start("bw_probe")
    mb8 = np.ones((4 << 20,), np.uint8)
    t0 = time.time()
    x = jax.device_put(mb8)
    np.asarray(x[:1])  # real sync: block_until_ready lies on axon
    h2d = 4.0 / max(time.time() - t0, 1e-9)
    t0 = time.time()
    np.asarray(x[: 256 << 10])
    d2h = 0.25 / max(time.time() - t0, 1e-9)
    ph.done(h2d_MBps=round(h2d, 1), d2h_MBps=round(d2h, 1))
    result["h2d_MBps"] = round(h2d, 1)
    result["d2h_MBps"] = round(d2h, 1)
    # full-verdict-readback ceiling in the headline's unit (matches/s):
    # 2 bytes of verdict buy 3 rule-matches per query over the d2h path
    result["tunnel_ceiling_matches_s"] = round(d2h * 1e6 / 2.0 * 3.0, 1)

    ph.start("upload_tables")
    # fp cidr tables expose an all-V4 group slice (arrays_v4) — the bench
    # batches are entirely v4, so the v4-in-v6 duplicate groups that only
    # serve V6-typed queries are dead rows and are not shipped
    rt_arr = getattr(rt, "arrays_v4", rt.arrays)
    at_arr = getattr(at, "arrays_v4", at.arrays)
    htd, rtd, atd = (_to_device(ht.arrays), _to_device(rt_arr),
                     _to_device(at_arr))
    hgd, rtgd = jax.device_put(hint_group), jax.device_put(route_tgt)
    jax.block_until_ready([htd, rtd, atd, hgd, rtgd])
    ph.done()

    # pre-upload every query set ONCE — steady state has no h2d at all.
    # Sets are STACKED on a leading axis so the device-side loop can
    # index them with the iteration counter.
    ph.start("upload_queries")
    nq = len(qsets)
    hq_stack = {k: jax.device_put(np.stack([q[0][k] for q in qsets]))
                for k in qsets[0][0]}
    a16s = jax.device_put(np.stack([q[1] for q in qsets]))
    fams = jax.device_put(np.stack([q[2] for q in qsets]))
    portss = jax.device_put(np.stack([q[3] for q in qsets]))
    dsets = [({k: v[s] for k, v in hq_stack.items()},
              a16s[s], fams[s], portss[s]) for s in range(nq)]
    jax.block_until_ready([hq_stack, a16s, fams, portss])
    ph.done()

    def _verdict(ht_, rt_, at_, hg_, rtg_, hq, a16, fam, port):
        hi, _ = hint_match(ht_, hq)
        ri = cidr_match(rt_, a16, fam, None)
        ai = cidr_match(at_, a16, fam, port)
        group = jnp.where(hi >= 0, hg_[jnp.maximum(hi, 0)] + 1, 0)
        tgt = jnp.where(ri >= 0, rtg_[jnp.maximum(ri, 0)] + 1, 0)
        allow = jnp.where(ai >= 0, at_["allow"][jnp.maximum(ai, 0)], True)
        v1 = (allow.astype(jnp.uint8) << 7) | tgt.astype(jnp.uint8)
        return jnp.stack([group.astype(jnp.uint8), v1], axis=1)  # [B,2] u8

    @jax.jit
    def step_fn(ht_, rt_, at_, hg_, rtg_, hq, a16, fam, port):
        return _verdict(ht_, rt_, at_, hg_, rtg_, hq, a16, fam, port)

    @jax.jit
    def multi_fn(ht_, rt_, at_, hg_, rtg_, hqs, a16s_, fams_, portss_):
        """K classify steps per dispatch, verdicts reduced on device to
        [K] u32 checksums (K*4 bytes d2h). The query sets unroll
        STATICALLY inside each fori iteration — selecting the set with a
        traced `i % S` index measured ~32ms/iteration of pure
        dynamic_slice overhead through this backend (probe, r4) vs ~0
        for static indexing; ports rotate by the iteration counter so no
        step is loop-invariant. acc[i, s] = checksum of set s at
        rotation i; chks[0] (i=0, s=0, identity rotation) stays
        reproducible by step_fn on set 0 (verified below)."""
        s_count = fams_.shape[0]

        def body(i, acc):
            for s in range(s_count):  # static unroll: no dynamic_slice
                hq = {k: v[s] for k, v in hqs.items()}
                hq = dict(hq, port=(hq["port"] + i) % 65536)
                port = (portss_[s] + i) % 65536
                v = _verdict(ht_, rt_, at_, hg_, rtg_, hq,
                             a16s_[s], fams_[s], port)
                acc = acc.at[i, s].set(jnp.sum(v.astype(jnp.uint32)))
            return acc

        out = jax.lax.fori_loop(0, ksteps // s_count, body,
                                jnp.zeros((ksteps // s_count, s_count),
                                          jnp.uint32))
        return out.reshape(-1)

    # steps per dispatch must divide evenly into iterations x sets
    # (floor to a multiple of nq, but never to 0)
    ksteps = max(nq, (ksteps // nq) * nq)

    def submit(ds):
        hq, a16, fam, ports = ds
        return step_fn(htd, rtd, atd, hgd, rtgd, hq, a16, fam, ports)

    def submit_multi():
        return multi_fn(htd, rtd, atd, hgd, rtgd,
                        hq_stack, a16s, fams, portss)

    ph.start("warmup_compile")
    first = np.asarray(submit(dsets[0]))
    t_multi_c = time.time()
    chks = np.asarray(submit_multi())
    compile_s = ph.done(multi_extra_s=round(time.time() - t_multi_c, 2))
    result["compile_s"] = round(compile_s, 2)

    # verify: (a) device loop agrees with the single-step kernel,
    # (b) device verdicts agree with the host ORACLE on the sampled
    # queries — the oracle indices repacked through the same u8 format
    ph.start("verify_checksum")
    chk_host = int(first.astype(np.uint32).sum())
    chk_ok = int(chks[0]) == chk_host
    allow_arr = at.arrays["allow"]
    want = []
    for hi, ri, ai in expect:
        g = hint_group[hi] + 1 if hi >= 0 else 0
        tg = route_tgt[ri] + 1 if ri >= 0 else 0
        al = bool(allow_arr[ai]) if ai >= 0 else True
        want.append((g, (int(al) << 7) | tg))
    oracle_ok = bool((first[: len(want)] ==
                      np.asarray(want, np.uint8)).all())
    ph.done(chk_ok=chk_ok, oracle_ok=oracle_ok,
            device=int(chks[0]), host=chk_host)
    result["chk_ok"] = bool(chk_ok)
    result["oracle_ok"] = oracle_ok
    flush()

    # ---- headline: device-side multi-step, checksum readback only.
    # MEASUREMENT NOTE (discovered r4): on the axon tunnel backend,
    # block_until_ready() is NOT a true barrier — it can return before
    # remote execution finishes. Every timing boundary here therefore
    # syncs with a real d2h pull (np.asarray), and the final pull of the
    # stacked [iters, K] checksums (a few KB) is INSIDE the timed span.
    ph.start("throughput_device")
    t0 = time.time()
    np.asarray(submit_multi())
    t_one = time.time() - t0
    iters = dl.iters(t_one, 0.35, lo=3,
                     hi=_env_int("BENCH_ITERS", 4096))
    outs = []
    t0 = time.time()
    for _ in range(iters):
        outs.append(submit_multi())
    # pull each [K] checksum directly — a jnp.stack here would compile a
    # fresh concatenate program (iters varies run to run) inside the
    # timed span; pulls are a few KB total
    all_chk = np.stack([np.asarray(o) for o in outs])
    total = time.time() - t0
    assert all_chk.shape == (iters, ksteps)
    matches = 3 * batch * ksteps * iters  # hint + route + acl per element
    rate = matches / total
    dispatch_us = total / iters * 1e6
    kernel_step_us = dispatch_us / ksteps
    ph.done(rate=round(rate, 1), iters=iters, k=ksteps,
            dispatch_us=round(dispatch_us, 1),
            kernel_step_us=round(kernel_step_us, 1))
    result.update({
        "value": round(rate, 1),
        "vs_baseline": round(rate / TARGET, 4),
        "steps_per_dispatch": ksteps,
        "dispatch_us": round(dispatch_us, 1),
        "kernel_step_us": round(kernel_step_us, 1),
        "kernel_matches_s": round(
            3 * batch / max(kernel_step_us, 1e-9) * 1e6, 1),
    })
    flush()

    # ---- e2e: full [B,2] verdict readback per dispatch (tunnel-bound)
    ph.start("throughput_e2e")
    t0 = time.time()
    np.asarray(submit(dsets[0]))
    t_one = time.time() - t0
    e2e_iters = dl.iters(t_one, 0.25, lo=3,
                         hi=_env_int("BENCH_E2E_ITERS", 256))
    pending = []
    done = 0
    t0 = time.time()
    for i in range(e2e_iters):
        arr = submit(dsets[i % nq])
        arr.copy_to_host_async()
        pending.append(arr)
        while len(pending) > 2:
            r = np.asarray(pending.pop(0))
            done += r.shape[0]
    for p in pending:
        r = np.asarray(p)
        done += r.shape[0]
    total = time.time() - t0
    assert done == e2e_iters * batch
    e2e_rate = 3 * batch * e2e_iters / total
    e2e_step_us = total / e2e_iters * 1e6
    ph.done(rate=round(e2e_rate, 1), iters=e2e_iters,
            step_us=round(e2e_step_us, 1))
    result["e2e_rate"] = round(e2e_rate, 1)
    result["e2e_step_us"] = round(e2e_step_us, 1)
    result["step_us"] = round(e2e_step_us, 1)
    flush()

    # ---- tunnel RTT probe: a trivial kernel (4-int add) measures what
    # the TRANSPORT costs per dispatch, so the latency sections below can
    # be decomposed into design cost vs environment cost
    # (latency_floor_us mirrors tunnel_ceiling_matches_s for throughput)
    ph.start("rtt_probe")
    tiny = jax.device_put(np.arange(4, dtype=np.int32))
    inc = jax.jit(lambda v: v + 1)
    np.asarray(inc(tiny))  # compile
    rtts = []
    for _ in range(_env_int("BENCH_RTT_ITERS", 20)):
        t0 = time.time()
        np.asarray(inc(tiny))
        rtts.append(time.time() - t0)
    rtt_p50 = float(np.percentile(rtts, 50) * 1e6)
    ph.done(rtt_p50_us=round(rtt_p50, 1))
    result["tunnel_rtt_p50_us"] = round(rtt_p50, 1)
    # device-side latency floor for a batched classify = one kernel step
    # (what a directly-attached chip would charge the whole batch)
    result["latency_floor_us"] = result.get("kernel_step_us", 0.0)

    # ---- latency: per-dispatch submit->verdict-on-host, steady state
    lat_batch = _env_int("BENCH_LAT_BATCH", 256)
    lat = {}
    for b, frac in ((1, 0.25), (lat_batch, 0.3)):
        if dl.remaining() < 45:
            break
        ph.start(f"latency_b{b}")
        small = tuple(
            {k: v[:b] for k, v in ds.items()} if isinstance(ds, dict)
            else ds[:b] for ds in dsets[0])
        t0 = time.time()
        np.asarray(submit(small))  # warm this shape (compile)
        t_one = max(time.time() - t0, 1e-4)
        n_iter = dl.iters(min(t_one, 0.2), frac, lo=10,
                          hi=_env_int("BENCH_LAT_ITERS", 100))
        samples = []
        for _ in range(n_iter):
            t0 = time.time()
            np.asarray(submit(small))
            samples.append(time.time() - t0)
        lat[b] = (float(np.percentile(samples, 50) * 1e6),
                  float(np.percentile(samples, 99) * 1e6))
        ph.done(p50_us=round(lat[b][0], 1), p99_us=round(lat[b][1], 1),
                iters=n_iter)
        result["dispatch_p50_us" if b == 1 else
               "dispatch_b%d_p50_us" % b] = round(lat[b][0], 1)
        result["dispatch_p99_us" if b == 1 else
               "dispatch_b%d_p99_us" % b] = round(lat[b][1], 1)
        # design cost of this dispatch = measured p50 minus what the
        # trivial-kernel probe says the transport alone costs
        result["design_p50_us" if b == 1 else
               "design_b%d_p50_us" % b] = round(
            max(0.0, lat[b][0] - rtt_p50), 1)
        flush()

    # ---- ClassifyService accept->verdict under synthetic load
    if dl.remaining() > 40:
        result.update(service_section(ph, dl))
        # /metrics snapshot: the vproxy_classify_latency_us histogram
        # (the service_* percentiles above are sourced FROM it — same
        # series a production scrape sees) plus the classify queue
        # gauges, so the latency contract lives in the artifact
        from vproxy_tpu.utils.metrics import GlobalInspection
        result["classify_metrics"] = {
            k: v for k, v in GlobalInspection.get().bench_snapshot().items()
            if k.startswith(("vproxy_classify_",))}
        flush()

    result["partial"] = False
    flush()
    print(json.dumps(result))
    return 0


def accept_path_section(ph, dl, result) -> None:
    """The BASELINE latency half of the north star, measured on the path
    real accepts take: lone queries through ClassifyService's inline
    fast lane (rules/service.py -> rules/index.py O(probes) host index,
    winner bit-for-bit vs the oracle), submit -> callback-returned, per
    query, at 20k AND 100k rules over >= BENCH_ACCEPT_QUERIES queries
    each. First-class artifact fields:

      accept_path_{20k,100k}_{p50,p99,p999}_us  (+ un-suffixed aliases
      for the largest scale) — contract: p99 < 50us at 100k rules, and
      no unexplained multi-ms p999 spikes (`over_1ms` counts them).

    Host-only by construction (backend="host" skips the device-table
    compile; the host index is built for every backend past
    SMALL_TABLE), so this section needs no device claim and survives a
    wedged tunnel."""
    queries = _env_int("BENCH_ACCEPT_QUERIES", 5000)
    scales = [int(s) for s in os.environ.get(
        "BENCH_ACCEPT_SCALES", "20000,100000").split(",")]
    detail = {}
    last_label = None
    for n in scales:
        label = "%dk" % (n // 1000) if n >= 1000 else str(n)
        ph.start(f"accept_path_{label}")
        try:
            _accept_path_scale(ph, result, detail, n, label, queries)
            last_label = label
        except MemoryError:
            raise
        except Exception as e:
            # this section must never cost the child its later (device)
            # sections — record the failure and move on
            result[f"accept_path_{label}_error"] = repr(e)[:300]
            ph.done(error=repr(e)[:120])
    result["accept_path"] = detail
    result["accept_path_queries"] = queries
    if last_label is not None:  # un-suffixed aliases = the largest scale
        for k in ("p50_us", "p99_us", "p999_us"):
            result[f"accept_path_{k}"] = detail[last_label][k]
        result["accept_path_oracle_ok"] = all(
            d["oracle_ok"] and d["mismatches"] == 0
            for d in detail.values())


def _accept_path_scale(ph, result, detail, n, label, queries) -> None:
    import random as _random

    from vproxy_tpu.rules import oracle
    from vproxy_tpu.rules.engine import HintMatcher
    from vproxy_tpu.rules.ir import Hint, HintRule
    from vproxy_tpu.rules.service import ClassifyService

    rules = [HintRule(host=f"svc{i}.ap.bench.example.com")
             for i in range(n)]
    m = HintMatcher(rules, backend="host")
    svc = ClassifyService(mode="auto")
    # measure THE lane regardless of the process-wide knob: this section
    # exists to report the inline contract (backend="host" inlines
    # anyway, but be explicit so VPROXY_TPU_INLINE_LONE=0 can't skew it)
    svc.inline_lone = True
    try:
        rng = _random.Random(7)
        order = [rng.randrange(n) for _ in range(queries)]
        hints = [Hint.of_host(f"svc{i}.ap.bench.example.com")
                 for i in order]
        got = []
        cb = (lambda idx, _pl: got.append(idx))
        for h in hints[:256]:  # warm caches/alloc paths out of the window
            svc.submit_hint(m, h, cb)
        got.clear()
        lat_us = np.empty(queries, np.float64)
        pc = time.perf_counter_ns
        for q in range(queries):
            t0 = pc()
            svc.submit_hint(m, hints[q], cb)  # inline: cb ran already
            lat_us[q] = (pc() - t0) / 1000.0
        assert len(got) == queries, "inline answers must be synchronous"
        mism = sum(1 for q in range(queries) if got[q] != order[q])
        # tie the winner to the reference scan semantics, not just the
        # construction: a sampled check against the linear oracle
        sample = rng.sample(range(queries), min(16, queries))
        oracle_ok = all(oracle.search(rules, hints[q]) == got[q]
                        for q in sample)
        st = svc.stats
        p50, p99, p999 = np.percentile(lat_us, (50.0, 99.0, 99.9))
        rec = {"n": queries, "p50_us": round(float(p50), 2),
               "p99_us": round(float(p99), 2),
               "p999_us": round(float(p999), 2),
               "max_us": round(float(lat_us.max()), 1),
               "over_1ms": int((lat_us > 1000.0).sum()),
               "mismatches": mism, "oracle_ok": oracle_ok,
               "inline_only": st.dispatches == 0
               and st.oracle_queries >= queries}
        detail[label] = rec
        for k in ("p50_us", "p99_us", "p999_us"):
            result[f"accept_path_{label}_{k}"] = rec[k]
        ph.done(**rec)
    finally:
        svc.close()


def cluster_section(ph, result) -> None:
    """Cluster-plane artifact rows (docs/cluster.md), host-only by
    construction so a wedged tunnel can't cost them:

    * cluster_step_rate — steps/s of a solo StepLoop serving from the
      host-index path (the degrade lane): the cluster layer's clock +
      queue + delivery floor, independent of any device.
    * generation_swap_ms — leader mutation -> follower
      checksum-verified generation install over real localhost TCP
      (median of 5), the control-plane convergence latency.
    """
    import socket as _s
    import threading

    ph.start("cluster_step_rate")
    try:
        from vproxy_tpu.cluster.submit import StepLoop
        from vproxy_tpu.rules.engine import HintMatcher
        from vproxy_tpu.rules.ir import Hint, HintRule
        rules = [HintRule(host=f"c{i}.cl.bench.example.com")
                 for i in range(1000)]
        m = HintMatcher(rules, backend="host")
        loop = StepLoop(m, None, step_ms=1, batch_cap=16,
                        timeout_ms=1000)
        loop.degraded = True  # host-index serving lane, no device
        loop.start(warm=False)
        served = [0]
        stop = threading.Event()

        def feed():
            cb = (lambda idx, _pl: served.__setitem__(0, served[0] + 1))
            i = 0
            while not stop.is_set():
                loop.submit(Hint(host=f"c{i % 1000}.cl.bench.example.com"),
                            cb)
                i += 1
                if i % 64 == 0:
                    time.sleep(0.001)

        t = threading.Thread(target=feed, daemon=True)
        span = 0.7
        t0 = time.time()
        t.start()
        time.sleep(span)
        stop.set()
        steps = loop.steps_total
        dt = time.time() - t0
        loop.stop()
        t.join(2)
        result["cluster_step_rate"] = round(steps / dt, 1)
        result["cluster_step_queries_s"] = round(served[0] / dt, 1)
        ph.done(steps_per_s=result["cluster_step_rate"],
                queries_per_s=result["cluster_step_queries_s"])
    except MemoryError:
        raise
    except Exception as e:  # the artifact survives a section failure
        result["cluster_step_rate_error"] = repr(e)[:200]
        ph.done(error=repr(e)[:120])

    ph.start("generation_swap_ms")
    apps, nodes = [], []
    try:
        from vproxy_tpu.cluster import ClusterNode, parse_peers
        from vproxy_tpu.control.app import Application
        from vproxy_tpu.control.command import Command

        def free_port(kind):
            sk = _s.socket(_s.AF_INET, kind)
            sk.bind(("127.0.0.1", 0))
            p = sk.getsockname()[1]
            sk.close()
            return p

        spec = ",".join(
            f"127.0.0.1:{free_port(_s.SOCK_DGRAM)}"
            f"/{free_port(_s.SOCK_STREAM)}" for _ in range(2))
        for i in (0, 1):
            app = Application(workers=1)
            node = ClusterNode(app, i, parse_peers(spec), hb_ms=50,
                               poll_ms=5000)  # we drive sync_once by hand
            app.cluster = node
            node.membership.start()
            node.replicator.start()
            apps.append(app)
            nodes.append(node)
        deadline = time.time() + 5
        while time.time() < deadline and any(
                n.membership.peers_up() < 2 for n in nodes):
            time.sleep(0.02)
        Command.execute(apps[0], "add upstream u-swap")
        nodes[1].replicator.sync_once()  # baseline state transferred
        samples = []
        for i in range(5):
            t0 = time.time()
            Command.execute(
                apps[0], f"add server-group sw{i} timeout 500 period "
                "60000 up 1 down 2 annotations "
                f'{{"vproxy/hint-host":"sw{i}.bench.example"}}')
            assert nodes[1].replicator.sync_once()
            samples.append((time.time() - t0) * 1e3)
            assert (nodes[1].replicator.generation
                    == nodes[0].replicator.generation)
        result["generation_swap_ms"] = round(float(np.median(samples)), 2)
        ph.done(generation_swap_ms=result["generation_swap_ms"],
                samples=[round(s, 1) for s in samples])
    except MemoryError:
        raise
    except Exception as e:
        result["generation_swap_ms_error"] = repr(e)[:200]
        ph.done(error=repr(e)[:120])
    finally:
        for n in nodes:
            n.close()
        for a in apps:
            a.close()


def service_section(ph, dl):
    """ClassifyService end-to-end, both contracts:

    * device — N threads of lone classifies + bursts with mode=device:
      the raw submit->verdict round trip at the service boundary.
    * policy — mode=auto (the production default: the inline fast lane
      serves lone queries from the host index, micro-batches ride the
      device), same concurrency — GIL and queueing effects under real
      submitter pressure, p999 included (VERDICT r5 item 8: the old
      200-query rows were smoke, not load)."""
    import threading

    from vproxy_tpu.rules.engine import HintMatcher
    from vproxy_tpu.rules.ir import Hint, HintRule
    from vproxy_tpu.rules.service import ClassifyService

    n_rules = min(_env_int("BENCH_RULES", 100000), 20000)
    # real load: >= 8 concurrent submitters, >= 10k queries total
    n_threads = _env_int("BENCH_SVC_THREADS", 16)
    per = _env_int("BENCH_SVC_QUERIES", 625)

    ph.start("service_setup")
    rules = [HintRule(host=f"svc{i}.bench.example.com")
             for i in range(n_rules)]
    m = HintMatcher(rules)
    for k in (4, 8, 16):  # warm every service pad bucket (PAD_LO=4)
        m.match([Hint.of_host("warm.example.com")] * k)
    ph.done(rules=n_rules)

    out = {}

    def load(svc, tag, threads, per):
        errs = []
        t_done = threading.Event()
        remaining = [threads]
        lock = threading.Lock()

        def worker(tid):
            try:
                for i in range(per):
                    ev = threading.Event()
                    want = (tid * per + i) % n_rules

                    def cb(idx, _pl, want=want, ev=ev):
                        if idx != want:
                            errs.append((want, idx))
                        ev.set()

                    svc.submit_hint(m, Hint.of_host(
                        f"svc{want}.bench.example.com"), cb)
                    ev.wait(30)
            finally:
                with lock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        t_done.set()

        t0 = time.time()
        for t in range(threads):
            threading.Thread(target=worker, args=(t,), daemon=True).start()
        # bounded by the child budget so a wedged tunnel degrades to a
        # partial result instead of an orchestrator SIGTERM mid-wait
        t_done.wait(min(120, max(5, dl.remaining() - 10)))
        wall = time.time() - t0
        lat = svc.stats.latency_percentiles() or {"p50_us": -1, "p99_us": -1}
        st = svc.stats
        ph.done(queries=st.queries, dispatches=st.dispatches,
                max_batch=st.max_batch, p50_us=round(lat["p50_us"], 1),
                p99_us=round(lat["p99_us"], 1), wall_s=round(wall, 2),
                errors=len(errs), reroutes=st.budget_reroutes)
        svc.close()
        assert not errs, errs[:5]
        out[f"service_{tag}_p50_us"] = round(lat["p50_us"], 1)
        out[f"service_{tag}_p99_us"] = round(lat["p99_us"], 1)
        out[f"service_{tag}_p999_us"] = round(lat.get("p999_us", -1), 1)
        out[f"service_{tag}_max_batch"] = st.max_batch
        out[f"service_{tag}_dispatches"] = st.dispatches
        out[f"service_{tag}_queries"] = st.queries
        out[f"service_{tag}_threads"] = threads
        if tag == "policy":
            out["service_policy_reroutes"] = st.budget_reroutes
            out["service_policy_inline_fast"] = st.inline_fast
            out["service_policy_oracle_queries"] = st.oracle_queries

    ph.start("service_device_load")
    load(ClassifyService(mode="device"), "device", n_threads, per)

    if dl.remaining() > 25:
        # accept-path contract under CONCURRENT submitters: the inline
        # fast lane on every thread, so GIL interleaving shows in p999
        ph.start("service_policy_load")
        svc = ClassifyService(mode="auto")
        svc.budget_us = _env_float("BENCH_SVC_BUDGET_US", 5000.0)
        load(svc, "policy", n_threads,
             _env_int("BENCH_SVC_POLICY_QUERIES", 625))
    # legacy field names point at the device contract
    out["service_p50_us"] = out.get("service_device_p50_us")
    out["service_p99_us"] = out.get("service_device_p99_us")
    return out


# ------------------------------------------------------ pjit-sharded stage

def _pjit_child():
    """The mesh-serving stage (forced-8-device CPU mesh, own process —
    the device count is frozen at backend init). Rows:

    * classify_1m_rules_mps — aggregate matches/s with 1M-rule hint AND
      1M-rule cidr tables sharded over the rules axis (+ build seconds
      and per-table device bytes; host copies are freed post-upload).
    * classify_scaling — same 100k workload on rules-axis meshes of
      1/2/4/8 devices: per-device table bytes prove the capacity
      sharding; the throughput column documents this container's
      ceiling honestly (virtual CPU devices share one socket — ICI-
      style scaling needs real chips).
    * generation_swap_under_load_p99_us — 8-thread dispatch load on the
      sharded engine with ~1 install/s vs the no-install baseline p99:
      the stall-free double-buffer contract as a measured ratio.
    * service_* — the BENCH_r06-shape ClassifyService load rows (same
      rules/threads/queries), carrying the dispatch-path latency work.
    """
    stage = os.environ.get("BENCH_STAGE", "pjit")
    ph = Phases(os.environ.get("BENCH_PHASE_FILE", ""), stage)
    here = os.path.dirname(os.path.abspath(__file__))
    dl = Deadline(_env_float("BENCH_CHILD_BUDGET", 900.0))
    _enable_compile_cache(here)
    import jax
    result = {"stage": stage, "partial": True,
              "pjit_devices": len(jax.devices()),
              "pjit_platform": jax.devices()[0].platform}
    result_file = os.environ.get("BENCH_RESULT_FILE")

    def flush():
        if result_file:
            with open(result_file + ".tmp", "w") as f:
                json.dump(result, f)
            os.replace(result_file + ".tmp", result_file)

    if len(jax.devices()) < 8:
        result["pjit_error"] = (
            f"only {len(jax.devices())} devices — "
            "xla_force_host_platform_device_count did not take")
        flush()
        print(json.dumps(result))
        return 1

    pjit_swap_section(ph, result)
    flush()
    pjit_scaling_section(ph, result, dl)
    flush()
    if dl.remaining() > 240:
        pjit_1m_section(ph, result, dl)
        flush()
    if dl.remaining() > 60:
        result.update(service_section(ph, dl))
        flush()
    from vproxy_tpu.utils.metrics import GlobalInspection
    result["engine_metrics"] = {
        k: v for k, v in GlobalInspection.get().bench_snapshot().items()
        if k.startswith("vproxy_engine_")}
    result["partial"] = False
    flush()
    print(json.dumps(result))
    return 0


def _pjit_hint_rules(n):
    from vproxy_tpu.rules.ir import HintRule
    return [HintRule(host=f"svc{i}.ns{i % 997}.pjit.example.com")
            for i in range(n)]


def _pjit_nets(n):
    """Distinct /20-/24 prefixes (a realistic routing-table shape: the
    ordered-scan semantics allow overlap, but a synthetic table of 15k
    identical /8s would measure bucket-expansion pathology, not LPM)."""
    from vproxy_tpu.utils.ip import Network, mask_bytes
    import numpy as _np
    nets = []
    for i in range(n):
        ml = 24 if i % 4 else 20
        ip = bytes([10 + ((i >> 18) & 0x3F), (i >> 10) & 0xFF,
                    (i >> 2) & 0xFF, (i & 3) << 6])
        mk = mask_bytes(ml)
        nets.append(Network(bytes(_np.frombuffer(ip, _np.uint8) &
                                  _np.frombuffer(mk, _np.uint8)), mk))
    return nets


def _pjit_load(matcher, kind, n_threads, per, hints=None, queries=None):
    """Closed-loop ClassifyService load (mode=device); returns stats."""
    import threading

    from vproxy_tpu.rules.service import ClassifyService
    svc = ClassifyService(mode="device")
    errs = []
    ths = []

    def worker(tid):
        for i in range(per):
            ev = threading.Event()
            if kind == "hint":
                q = hints[(tid * per + i) % len(hints)]
                submit = lambda cb: svc.submit_hint(matcher, q, cb)
            else:
                a, p = queries[(tid * per + i) % len(queries)]
                submit = lambda cb: svc.submit_cidr(matcher, a, p, cb)
            submit(lambda idx, _pl, ev=ev: ev.set())
            if not ev.wait(60):
                errs.append((tid, i, "timeout"))

    t0 = time.time()
    for t in range(n_threads):
        th = threading.Thread(target=worker, args=(t,), daemon=True)
        th.start()
        ths.append(th)
    for th in ths:
        th.join(180)
    wall = time.time() - t0
    lat = svc.stats.latency_percentiles() or {}
    st = svc.stats
    out = {"wall_s": round(wall, 2), "queries": st.queries,
           "dispatches": st.dispatches, "errors": len(errs),
           "p50_us": round(lat.get("p50_us", -1), 1),
           "p99_us": round(lat.get("p99_us", -1), 1),
           "p999_us": round(lat.get("p999_us", -1), 1)}
    svc.close()
    return out


def pjit_swap_section(ph, result) -> None:
    """generation_swap_under_load_p99_us: the double-buffered install is
    invisible to serving (Maglev's operational bar). Same 8-thread
    dispatch load twice — without installs, then with a swapper thread
    pushing a fresh same-shape generation ~1/s through set_rules()
    (standby compile on the TableInstaller, atomic publish)."""
    import threading

    from vproxy_tpu.rules.engine import HintMatcher
    from vproxy_tpu.rules.ir import Hint
    try:
        n_rules = _env_int("BENCH_SWAP_RULES", 20000)
        rules = _pjit_hint_rules(n_rules)
        m = HintMatcher(rules, backend="jax-sharded")
        hints = [Hint.of_host(f"svc{i}.ns{i % 997}.pjit.example.com")
                 for i in range(512)]
        m.match(hints[:16])  # warm jit
        threads = _env_int("BENCH_SWAP_THREADS", 8)
        per = _env_int("BENCH_SWAP_QUERIES", 1200)

        # INTERLEAVED reps (base, under, base, under, ...): the
        # 8-thread closed-loop p99 swings ~±15-25% run to run, so one
        # pair cannot carry a 1.2x claim either way — the committed
        # ratio is median(under)/median(base) with every rep in the
        # artifact
        reps = _env_int("BENCH_SWAP_REPS", 5)
        bases, unders = [], []
        installs = [0]
        for rep in range(reps):
            ph.start(f"swap_baseline_{rep}")
            b = _pjit_load(m, "hint", threads, per, hints=hints)
            bases.append(b)
            ph.done(**b)
            ph.start(f"swap_under_load_{rep}")
            stop = threading.Event()

            def swapper():
                k = 0
                while not stop.is_set():
                    k += 1
                    alt = list(rules)
                    alt[0] = type(rules[0])(
                        host=f"gen{installs[0] + k}.pjit.example.com")
                    m.set_rules(alt)  # waits for the standby publish
                    installs[0] += 1
                    stop.wait(1.0)

            sw = threading.Thread(target=swapper, daemon=True)
            sw.start()
            u = _pjit_load(m, "hint", threads, per, hints=hints)
            stop.set()
            sw.join(60)
            unders.append(u)
            ph.done(installs=installs[0], **u)

        from vproxy_tpu.utils.metrics import GlobalInspection
        hist = GlobalInspection.get().get_histogram("vproxy_engine_swap_ms",
                                                    reservoir=512)
        pct = hist.percentiles() or {}
        base_p99 = float(np.median([b["p99_us"] for b in bases]))
        under_p99 = float(np.median([u["p99_us"] for u in unders]))
        ratio = under_p99 / base_p99 if base_p99 > 0 else -1.0
        result.update({
            "generation_swap_baseline_p99_us": round(base_p99, 1),
            "generation_swap_baseline_p99_us_reps":
                [b["p99_us"] for b in bases],
            "generation_swap_under_load_p99_us": round(under_p99, 1),
            "generation_swap_under_load_p99_us_reps":
                [u["p99_us"] for u in unders],
            "generation_swap_under_load_p50_us": float(np.median(
                [u["p50_us"] for u in unders])),
            "generation_swap_baseline_p50_us": float(np.median(
                [b["p50_us"] for b in bases])),
            "generation_swap_p99_ratio": round(ratio, 3),
            "generation_swap_installs": installs[0],
            "generation_swap_load_errors": sum(
                r["errors"] for r in bases + unders),
            "engine_swap_ms_p50": round(pct.get("p50", -1), 1),
            "engine_swap_ms_p99": round(pct.get("p99", -1), 1),
        })
    except MemoryError:
        raise
    except Exception as e:
        result["generation_swap_error"] = repr(e)[:300]
        ph.done(error=repr(e)[:120])


def pjit_scaling_section(ph, result, dl) -> None:
    """Per-device-count scaling at 100k rules: meshes with rules axis
    1/2/4/8 over the same workload. Proves the sharding (per-device
    table bytes ~1/N, parity already covered by tests/) and documents
    this container's compute ceiling per count."""
    import jax

    from vproxy_tpu.parallel.mesh import make_mesh
    from vproxy_tpu.rules.engine import HintMatcher
    from vproxy_tpu.rules.ir import Hint
    n_rules = _env_int("BENCH_SCALING_RULES", 100000)
    batch = _env_int("BENCH_SCALING_BATCH", 4096)
    rules = _pjit_hint_rules(n_rules)
    hints = [Hint.of_host(f"svc{i % n_rules}.ns{i % 997}.pjit.example.com")
             for i in range(batch)]
    scaling = {}
    for nd in (1, 2, 4, 8):
        if dl.remaining() < 120:
            break
        ph.start(f"scaling_mesh_{nd}")
        try:
            t0 = time.time()
            m = HintMatcher(rules, backend="jax-sharded",
                            mesh=make_mesh(nd))
            build_s = time.time() - t0
            np.asarray(m.match(hints[:batch]))  # warm/compile
            iters = _env_int("BENCH_SCALING_ITERS", 5)
            t0 = time.time()
            for _ in range(iters):
                np.asarray(m.match(hints))
            dt = time.time() - t0
            mps = batch * iters / dt
            dev_bytes = m.published_table_bytes()
            scaling[str(nd)] = {
                "matches_s": round(mps, 1),
                "build_s": round(build_s, 1),
                "table_bytes_total": dev_bytes,
                "table_bytes_per_device": dev_bytes // nd,
            }
            ph.done(**scaling[str(nd)])
        except MemoryError:
            raise
        except Exception as e:
            scaling[str(nd)] = {"error": repr(e)[:200]}
            ph.done(error=repr(e)[:120])
    result["classify_scaling"] = scaling
    ok = [k for k, v in scaling.items() if "error" not in v]
    if len(ok) >= 2:
        lo, hi = ok[0], ok[-1]
        result["classify_scaling_bytes_ratio"] = round(
            scaling[lo]["table_bytes_per_device"]
            / max(1, scaling[hi]["table_bytes_per_device"]), 2)


def pjit_1m_section(ph, result, dl) -> None:
    """1M-rule hint + cidr tables: compile, upload, serve on the forced
    8-device mesh; aggregate matches/s (both tables driven in one
    loop, production classify shape) + honest ceiling accounting."""
    from vproxy_tpu.rules.engine import CidrMatcher, HintMatcher
    from vproxy_tpu.rules.ir import Hint
    n = _env_int("BENCH_1M_RULES", 1_000_000)
    batch = _env_int("BENCH_1M_BATCH", 4096)
    try:
        ph.start("build_1m_hint")
        rules = _pjit_hint_rules(n)
        t0 = time.time()
        hm = HintMatcher(rules, backend="jax-sharded")
        hint_build = time.time() - t0
        ph.done(build_s=round(hint_build, 1),
                table_bytes=hm.published_table_bytes())

        ph.start("build_1m_cidr")
        nets = _pjit_nets(n)
        t0 = time.time()
        cm = CidrMatcher(nets, backend="jax-sharded")
        cidr_build = time.time() - t0
        ph.done(build_s=round(cidr_build, 1),
                table_bytes=cm.published_table_bytes())

        hints = [Hint.of_host(f"svc{i % n}.ns{i % 997}.pjit.example.com")
                 for i in range(batch)]
        addrs = [bytes([10 + ((i * 7 >> 18) & 0x3F), (i * 7 >> 10) & 0xFF,
                        (i * 7 >> 2) & 0xFF, i & 0xFF])
                 for i in range(batch)]

        ph.start("serve_1m")
        np.asarray(hm.match(hints))  # compile+warm
        np.asarray(cm.match(addrs))
        # parity spot-check against the host index (oracle-parity
        # winners) before timing — a fast wrong answer is worthless
        hsnap, csnap = hm.snapshot(), cm.snapshot()
        for i in range(0, batch, max(1, batch // 16)):
            assert int(hm.match([hints[i]])[0]) == hm.index_snap(
                hsnap, hints[i]), f"hint parity @{i}"
            assert int(cm.match([addrs[i]])[0]) == cm.index_snap(
                csnap, addrs[i]), f"cidr parity @{i}"
        iters = _env_int("BENCH_1M_ITERS", 5)
        t0 = time.time()
        for _ in range(iters):
            ha = hm.dispatch_snap(hsnap, hints)
            ca = cm.dispatch_snap(csnap, addrs, None)
            np.asarray(ha)
            np.asarray(ca)
        dt = time.time() - t0
        mps = 2 * batch * iters / dt
        ph.done(mps=round(mps, 1), iters=iters)
        result.update({
            "classify_1m_rules_mps": round(mps, 1),
            "classify_1m_hint_build_s": round(hint_build, 1),
            "classify_1m_cidr_build_s": round(cidr_build, 1),
            "classify_1m_hint_table_bytes": hm.published_table_bytes(),
            "classify_1m_cidr_table_bytes": cm.published_table_bytes(),
            "classify_1m_batch": batch,
            "classify_1m_parity_ok": True,
        })
    except MemoryError:
        raise
    except Exception as e:
        result["classify_1m_error"] = repr(e)[:300]
        ph.done(error=repr(e)[:120])


# ------------------------------------------------------- fused stage

def _fused_child():
    """The fused classify+pick stage (single-device CPU env — the fused
    path is the single-table "jax" backend; the forced-8 virtual mesh
    of the pjit stage is exactly the overhead fusion routes around).
    Same-run fused/unfused A/B at 100k and 1M rules on the BENCH_r08
    load shape (batch 4096, mps = 2*batch*iters/dt for the hint+cidr
    pair — picks ride along free on the fused path), median-of-3
    interleaved (the PR-8 discipline), launch-counter deltas as the
    one-launch evidence. The committed artifact is
    BENCH_r12_builder_fused.json."""
    stage = os.environ.get("BENCH_STAGE", "fused")
    ph = Phases(os.environ.get("BENCH_PHASE_FILE", ""), stage)
    here = os.path.dirname(os.path.abspath(__file__))
    dl = Deadline(_env_float("BENCH_CHILD_BUDGET", 900.0))
    _enable_compile_cache(here)
    import jax
    result = {"stage": stage, "partial": True,
              "fused_platform": jax.devices()[0].platform,
              "fused_devices": len(jax.devices())}
    result_file = os.environ.get("BENCH_RESULT_FILE")

    def flush():
        if result_file:
            with open(result_file + ".tmp", "w") as f:
                json.dump(result, f)
            os.replace(result_file + ".tmp", result_file)

    fused_ab_section(ph, result, dl,
                     _env_int("BENCH_FUSED_SMALL_RULES", 100_000), "100k")
    flush()
    if dl.remaining() > 240:
        fused_ab_section(ph, result, dl,
                         _env_int("BENCH_FUSED_BIG_RULES", 1_000_000),
                         "1m")
        flush()
    # the acceptance comparison: fused 1M throughput vs the committed
    # BENCH_r08 dispatch-chain number at the same load shape
    try:
        with open(os.path.join(here, "BENCH_r08_builder_pjit.json")) as f:
            r08 = json.load(f).get("classify_1m_rules_mps")
        if r08 and result.get("fused_1m_mps"):
            result["r08_classify_1m_rules_mps"] = r08
            result["fused_1m_vs_r08_chain"] = round(
                result["fused_1m_mps"] / r08, 2)
    except (OSError, ValueError):
        pass
    from vproxy_tpu.utils.metrics import GlobalInspection
    result["engine_metrics"] = {
        k: v for k, v in GlobalInspection.get().bench_snapshot().items()
        if k.startswith("vproxy_engine_")}
    result["partial"] = False
    flush()
    print(json.dumps(result))
    return 0


def fused_ab_section(ph, result, dl, n_rules, label) -> None:
    """One table size: build "jax" hint+cidr tables + the maglev
    column, parity spot-check the fused program, then interleaved
    unfused/fused reps. Launch accounting rides engine.note_launch."""
    import gc

    from vproxy_tpu.rules import engine as E
    from vproxy_tpu.rules.engine import (CidrMatcher, HintMatcher,
                                         fused_dispatch_all)
    from vproxy_tpu.rules.ir import Hint
    from vproxy_tpu.rules.maglev import MaglevMatcher
    batch = _env_int("BENCH_FUSED_BATCH", 4096)
    try:
        ph.start(f"fused_{label}_build")
        rules = _pjit_hint_rules(n_rules)
        t0 = time.time()
        hm = HintMatcher(rules, backend="jax")
        hint_build = time.time() - t0
        nets = _pjit_nets(n_rules)
        t0 = time.time()
        cm = CidrMatcher(nets, backend="jax")
        cidr_build = time.time() - t0
        mm = MaglevMatcher([(f"10.8.{i}.1:80", 1 + i % 4)
                            for i in range(12)])
        packed = (hm.fused_stat().get("packed_bytes", 0)
                  + cm.fused_stat().get("packed_bytes", 0))
        ph.done(hint_build_s=round(hint_build, 1),
                cidr_build_s=round(cidr_build, 1), packed_bytes=packed)

        hints = [Hint.of_host(
            f"svc{i % n_rules}.ns{i % 997}.pjit.example.com")
            for i in range(batch)]
        addrs = [bytes([10 + ((i * 7 >> 18) & 0x3F), (i * 7 >> 10) & 0xFF,
                        (i * 7 >> 2) & 0xFF, i & 0xFF])
                 for i in range(batch)]
        ips = [bytes([10 + ((i * 13 >> 18) & 0x3F), (i * 13 >> 10) & 0xFF,
                      (i * 13 >> 2) & 0xFF, (i * 5) & 0xFF])
               for i in range(batch)]
        hsnap, csnap, msnap = hm.snapshot(), cm.snapshot(), mm.snapshot()

        ph.start(f"fused_{label}_warm_parity")
        out = np.asarray(fused_dispatch_all(
            hm, hsnap, cm, csnap, mm, msnap, hints, addrs, ips))[:batch]
        np.asarray(hm.dispatch_snap(hsnap, hints))  # warm unfused too
        np.asarray(cm.dispatch_snap(csnap, addrs, None))
        np.asarray(mm.dispatch_snap(msnap, ips))
        # parity spot-check against the host planes before timing —
        # a fast wrong answer is worthless
        for i in range(0, batch, max(1, batch // 16)):
            assert int(out[i, 0]) == hm.index_snap(hsnap, hints[i]), \
                f"verdict parity @{i}"
            assert int(out[i, 1]) == mm.pick_snap(msnap, ips[i]), \
                f"pick parity @{i}"
            assert int(out[i, 2]) == cm.index_snap(csnap, addrs[i]), \
                f"route parity @{i}"
        ph.done()

        iters = _env_int("BENCH_FUSED_ITERS", 5)
        reps = _env_int("BENCH_FUSED_REPS", 3)
        fused_mps, unfused_mps = [], []
        fused_lpb, unfused_lpb = [], []
        for rep in range(reps):  # interleaved: every rep runs BOTH
            ph.start(f"fused_{label}_unfused_{rep}")
            l0 = E.dispatch_launches_total()
            t0 = time.time()
            for _ in range(iters):
                ha = hm.dispatch_snap(hsnap, hints)
                ca = cm.dispatch_snap(csnap, addrs, None)
                pa = mm.dispatch_snap(msnap, ips)
                np.asarray(ha)
                np.asarray(ca)
                np.asarray(pa)
            dt = time.time() - t0
            unfused_mps.append(2 * batch * iters / dt)
            unfused_lpb.append(
                (E.dispatch_launches_total() - l0) / iters)
            ph.done(mps=round(unfused_mps[-1], 1),
                    launches_per_batch=unfused_lpb[-1])
            ph.start(f"fused_{label}_fused_{rep}")
            l0 = E.dispatch_launches_total()
            t0 = time.time()
            for _ in range(iters):
                np.asarray(fused_dispatch_all(
                    hm, hsnap, cm, csnap, mm, msnap, hints, addrs, ips))
            dt = time.time() - t0
            fused_mps.append(2 * batch * iters / dt)
            fused_lpb.append((E.dispatch_launches_total() - l0) / iters)
            ph.done(mps=round(fused_mps[-1], 1),
                    launches_per_batch=fused_lpb[-1])
        f_med = float(np.median(fused_mps))
        u_med = float(np.median(unfused_mps))
        result.update({
            f"fused_{label}_mps": round(f_med, 1),
            f"fused_{label}_mps_reps": [round(x, 1) for x in fused_mps],
            f"unfused_{label}_mps": round(u_med, 1),
            f"unfused_{label}_mps_reps":
                [round(x, 1) for x in unfused_mps],
            f"fused_{label}_vs_unfused": round(f_med / u_med, 3)
                if u_med > 0 else -1.0,
            f"fused_{label}_launches_per_batch": fused_lpb[-1],
            f"unfused_{label}_launches_per_batch": unfused_lpb[-1],
            f"fused_{label}_batch": batch,
            f"fused_{label}_hint_build_s": round(hint_build, 1),
            f"fused_{label}_cidr_build_s": round(cidr_build, 1),
            f"fused_{label}_hint_table_bytes": hm.published_table_bytes(),
            f"fused_{label}_packed_bytes": packed,
            f"fused_{label}_parity_ok": True,
        })
        del hm, cm, mm, hsnap, csnap, msnap, out
        gc.collect()
    except MemoryError:
        raise
    except Exception as e:
        result[f"fused_{label}_error"] = repr(e)[:300]
        ph.done(error=repr(e)[:120])


def _run_fused_stage(timeout):
    """The fused stage in a single-device CPU subprocess; folds the
    headline A/B + launch rows into the round artifact."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_fused.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["BENCH_STAGE"] = "fused"
    env["BENCH_PHASE_FILE"] = os.environ.get("BENCH_PHASE_FILE", "")
    env["BENCH_RESULT_FILE"] = result_file
    env.setdefault("BENCH_CHILD_BUDGET", str(max(60.0, timeout - 15.0)))
    sys.stderr.write(f"# === stage fused (timeout {timeout:.0f}s) ===\n")
    sys.stderr.flush()
    p = _run_child([sys.executable, os.path.abspath(__file__),
                    "--child"], env, here)
    _wait_stage(p, "fused", timeout, term_grace=20)
    if os.path.exists(result_file):
        try:
            with open(result_file) as f:
                res = json.load(f)
            out = {k: v for k, v in res.items()
                   if k not in ("stage", "partial", "engine_metrics")}
            if res.get("partial"):
                out["fused_partial"] = True
            return out
        except ValueError:
            pass
    sys.stderr.write("# stage fused: no result\n")
    return {}


def _wait_stage(p, name, timeout, term_grace=10):
    """Shared stage-child lifecycle: wait, SIGTERM (the child's handler
    runs its own cleanup), SIGKILL, abandon — ONE copy; this block used
    to be pasted (and drift) across every stage runner."""
    try:
        p.wait(timeout)
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"# stage {name}: timeout, SIGTERM\n")
        p.terminate()
        try:
            p.wait(term_grace)
        except subprocess.TimeoutExpired:
            p.kill()
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                sys.stderr.write(f"# stage {name}: unkillable, abandoned\n")
    _reap_child(p)


def _run_pjit_stage(timeout):
    """The pjit-sharded stage in a forced-8-device CPU subprocess (the
    host-platform device count is frozen at backend init, so it cannot
    share the single-device cpu child)."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_pjit.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env(n_devices=8)
    env["BENCH_STAGE"] = "pjit"
    env["BENCH_PHASE_FILE"] = os.environ.get("BENCH_PHASE_FILE", "")
    env["BENCH_RESULT_FILE"] = result_file
    env.setdefault("BENCH_CHILD_BUDGET", str(max(60.0, timeout - 15.0)))
    # service rows at the BENCH_r06 load shape (8 threads x 1250), so
    # service_device_p99_us stays comparable round over round
    env.setdefault("BENCH_SVC_THREADS", "8")
    env.setdefault("BENCH_SVC_QUERIES", "1250")
    env.setdefault("BENCH_SVC_POLICY_QUERIES", "1250")
    sys.stderr.write(f"# === stage pjit (timeout {timeout:.0f}s) ===\n")
    sys.stderr.flush()
    p = _run_child([sys.executable, os.path.abspath(__file__), "--child"],
                   env, here)
    _wait_stage(p, "pjit", timeout, term_grace=20)
    if os.path.exists(result_file):
        try:
            with open(result_file) as f:
                res = json.load(f)
            # service_* rows from the single-device cpu/tpu child keep
            # priority: the pjit child's service copy is labeled; a
            # timed-out child's partial flush stays MARKED (truncated
            # rows must never read as a completed stage)
            out = {("pjit_" + k if k.startswith("service_") else k): v
                   for k, v in res.items()
                   if k not in ("stage", "partial")}
            if res.get("partial"):
                out["pjit_partial"] = True
            return out
        except ValueError:
            pass
    sys.stderr.write("# stage pjit: no result\n")
    return {}


# ----------------------------------------------------------- orchestrator

SMOKE_ENV = {"VPROXY_TPU_FP_MEMBER": "reduce",  # verification-gated below
             "BENCH_RULES": "1000", "BENCH_ROUTES": "500",
             "BENCH_ACLS": "200", "BENCH_BATCH": "512",
             "BENCH_STEPS_PER_DISPATCH": "1024",
             "BENCH_ITERS": "32", "BENCH_E2E_ITERS": "16",
             "BENCH_QUERY_SETS": "2", "BENCH_LAT_ITERS": "32",
             # smoke keeps the service rows light (it proves device-up,
             # not load); tpu-full/cpu carry the >=10k-query load rows
             "BENCH_SVC_THREADS": "8", "BENCH_SVC_QUERIES": "150",
             "BENCH_SVC_POLICY_QUERIES": "150"}

CPU_ENV = {"VPROXY_TPU_FP_MEMBER": "reduce",  # CPU lowering is trusted
           "BENCH_ITERS": "16", "BENCH_E2E_ITERS": "8",
           "BENCH_STEPS_PER_DISPATCH": "8",
           "BENCH_QUERY_SETS": "2", "BENCH_LAT_ITERS": "16",
           # real load (VERDICT r5 item 8): 8 threads x 1250 = 10k
           "BENCH_SVC_THREADS": "8", "BENCH_SVC_QUERIES": "1250",
           "BENCH_SVC_POLICY_QUERIES": "1250"}


_LIVE_CHILDREN: list = []  # stage subprocesses, for SIGTERM cleanup


def _run_child(cmd, env, cwd):
    p = subprocess.Popen(cmd, env=env, cwd=cwd, stdout=sys.stderr)
    _LIVE_CHILDREN.append(p)
    return p


def _reap_child(p):
    if p in _LIVE_CHILDREN:
        _LIVE_CHILDREN.remove(p)


def _run_stage(name, env_over, timeout, phase_file, cpu=False):
    """Run one measured child; returns its result dict or None.
    Children rewrite their result file after every section, so a timed-
    out child still contributes a partial result. SIGTERM first (a
    SIGKILLed TPU-tunnel client wedges the device pool for minutes —
    demonstrated in this environment), SIGKILL only as a last resort."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, f".bench_result_{name}.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    if cpu:
        from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
        env = cpu_subprocess_env()
    else:
        env = dict(os.environ)
    env.update(env_over)
    env["BENCH_STAGE"] = name
    env["BENCH_PHASE_FILE"] = phase_file
    env["BENCH_RESULT_FILE"] = result_file
    env.setdefault("BENCH_CHILD_BUDGET", str(max(30.0, timeout - 15.0)))
    sys.stderr.write(f"# === stage {name} (timeout {timeout:.0f}s) ===\n")
    sys.stderr.flush()
    p = _run_child([sys.executable, os.path.abspath(__file__),
                    "--child"], env, here)
    deadline = time.time() + timeout
    while p.poll() is None and time.time() < deadline:
        time.sleep(0.5)
    if p.poll() is None:
        sys.stderr.write(f"# stage {name}: timeout, SIGTERM\n")
        p.send_signal(signal.SIGTERM)
        try:
            p.wait(20)
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"# stage {name}: SIGKILL\n")
            p.kill()
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                # D-state child stuck on the wedged tunnel: abandon it —
                # the final JSON line must still be printed
                sys.stderr.write(f"# stage {name}: unkillable, abandoned\n")
    _reap_child(p)
    if os.path.exists(result_file):
        try:
            with open(result_file) as f:
                res = json.load(f)
            if res.get("partial"):
                sys.stderr.write(f"# stage {name}: partial result "
                                 f"(rc={p.returncode})\n")
            res["stage_rc"] = p.returncode
            return res
        except ValueError:
            pass
    sys.stderr.write(f"# stage {name}: rc={p.returncode}, no result\n")
    return None


def _run_host_stage(timeout):
    """bench_host.py in a CPU-env subprocess (no TPU tunnel): TcpLB
    tcp-splice / http-splice req/s over loopback via the native epoll
    load tool. Returns the host_* fields or {}."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_host.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(f"# === stage host (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py")],
                   env, here)
    sys.stderr.flush()
    _wait_stage(p, "host", timeout)
    if os.path.exists(result_file):
        try:
            with open(result_file) as f:
                return json.load(f)
        except ValueError:
            pass
    sys.stderr.write("# stage host: no result\n")
    return {}


def _run_switch_stage(timeout):
    """bench_switch.py in a CPU-env subprocess: BASELINE config #4 —
    50k-route LPM + 5k ACL synthetic packet replay through the real
    switch data plane. Returns the switch_* fields or {}."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_switch.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["SWBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(f"# === stage switch (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_switch.py")],
                   env, here)
    sys.stderr.flush()
    _wait_stage(p, "switch", timeout)
    if os.path.exists(result_file):
        try:
            with open(result_file) as f:
                return json.load(f)
        except ValueError:
            pass
    sys.stderr.write("# stage switch: no result\n")
    return {}


def _run_storm_stage(timeout):
    """bench_host.py --storm in a CPU-env subprocess: the adversarial
    scenario suite (tools/storm.py, docs/robustness.md) with its SLO
    gates. The FULL report is the committed BENCH_r10_builder_storm.json
    artifact; the orchestrator folds a compact per-scenario pass/fail +
    headline-SLO snapshot into the round artifact."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_storm.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(f"# === stage storm (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py"),
                    "--storm"], env, here)
    sys.stderr.flush()
    _wait_stage(p, "storm", timeout)
    if not os.path.exists(result_file):
        sys.stderr.write("# stage storm: no result\n")
        return {}
    try:
        with open(result_file) as f:
            rep = json.load(f)
    except ValueError:
        return {}
    out = {"storm_pass": rep.get("pass"), "storm_seed": rep.get("seed"),
           "storm": {}}
    for name, s in rep.get("scenarios", {}).items():
        out["storm"][name] = {
            "pass": s.get("pass"),
            "slo": {k: [g.get("value"), g.get("limit"), g.get("pass")]
                    for k, g in s.get("slo", {}).items()}}
    fc = rep.get("scenarios", {}).get("flash_crowd", {}).get("rows", {})
    for mode in ("static", "adaptive"):
        if mode in fc:
            out[f"storm_flash_{mode}_p99_ms"] = fc[mode].get("p99_ms")
    return out


def _run_maglev_stage(timeout):
    """bench_host.py --maglev in a CPU-env subprocess: consistent-hash
    rows (docs/perf.md maglev section). The FULL report is the committed
    BENCH_r11_builder_maglev.json artifact; the orchestrator folds the
    headline rows — backend-pick A/B (maglev vs wrr p99 on the accept
    path), the lane short-connection A/B, and churn-on-resize for a
    1-of-4 peer death vs the mod-hash baseline — into the round."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_maglev.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(f"# === stage maglev (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py"),
                    "--maglev"], env, here)
    sys.stderr.flush()
    _wait_stage(p, "maglev", timeout)
    if not os.path.exists(result_file):
        sys.stderr.write("# stage maglev: no result\n")
        return {}
    try:
        with open(result_file) as f:
            rep = json.load(f)
    except ValueError:
        return {}
    keys = ("host_pick_wrr_p99_us", "host_pick_maglev_p99_us",
            "host_pick_maglev_vs_wrr_p99", "host_pick_maglev_no_slower_pass",
            "host_lanes_short_wrr_rps", "host_lanes_short_maglev_rps",
            "host_lanes_maglev_vs_wrr", "cluster_maglev_churn_1of4",
            "cluster_maglev_churn_pass", "cluster_modhash_churn_1of4",
            "cluster_maglev_table_m", "cluster_maglev_error")
    return {k: rep[k] for k in keys if k in rep}


def _run_trace_stage(timeout):
    """bench_host.py --trace in a CPU-env subprocess: the request-
    tracing round (docs/observability.md). The FULL report — per-stage
    attribution table, slowest traces with spans, the sampling-off
    zero-overhead A/B — is the committed BENCH trace artifact; the
    orchestrator folds the headline gates into the round so every
    future BENCH carries the attribution table."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_trace.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(f"# === stage trace (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py"),
                    "--trace"], env, here)
    sys.stderr.flush()
    _wait_stage(p, "trace", timeout)
    if not os.path.exists(result_file):
        sys.stderr.write("# stage trace: no result\n")
        return {}
    try:
        with open(result_file) as f:
            rep = json.load(f)
    except ValueError:
        return {}
    keys = ("trace_overhead_off_vs_absent", "trace_overhead_pass",
            "trace_overhead_sampled_vs_off", "trace_reconcile_lane",
            "trace_reconcile_py", "trace_reconcile_pass",
            "trace_stage_table", "trace_c_spans", "trace_c_ring_drops",
            "trace_stitched", "trace_install_phases", "trace_error")
    return {k: rep[k] for k in keys if k in rep}


def _run_analytics_stage(timeout):
    """bench_host.py --analytics in a CPU-env subprocess: the traffic-
    analytics round (docs/observability.md). The FULL report — the
    off-vs-on overhead pairs, both-plane top-table capture, the
    seeded-Zipf sketch-accuracy rows — is the committed BENCH analytics
    artifact; the orchestrator folds the headline gates in so every
    future round carries them."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_analytics.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(
        f"# === stage analytics (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py"),
                    "--analytics"], env, here)
    sys.stderr.flush()
    _wait_stage(p, "analytics", timeout)
    if not os.path.exists(result_file):
        sys.stderr.write("# stage analytics: no result\n")
        return {}
    try:
        with open(result_file) as f:
            rep = json.load(f)
    except ValueError:
        return {}
    keys = ("analytics_overhead_off_vs_on", "analytics_overhead_pass",
            "analytics_overhead_off_vs_absent",
            "analytics_offcost_pass", "analytics_capture",
            "analytics_capture_pass", "analytics_zipf",
            "analytics_zipf_pass", "analytics_error")
    return {k: rep[k] for k in keys if k in rep}


def _run_replay_stage(timeout):
    """bench_host.py --replay in a CPU-env subprocess: the workload
    capture -> replay -> fidelity loop (docs/replay.md). The FULL
    report — source mix, schedule hashes, fidelity ratios, the
    capture-off overhead pairs, the capacity-planning row — is the
    committed BENCH replay artifact; the orchestrator folds the
    headline gates in so every future round carries them."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_replay.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(
        f"# === stage replay (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py"),
                    "--replay"], env, here)
    sys.stderr.flush()
    _wait_stage(p, "replay", timeout)
    if not os.path.exists(result_file):
        sys.stderr.write("# stage replay: no result\n")
        return {}
    try:
        with open(result_file) as f:
            rep = json.load(f)
    except ValueError:
        return {}
    keys = ("replay_seed", "replay_schedule_hash",
            "replay_determinism_pass", "replay_fidelity",
            "replay_fidelity_pass", "replay_1x",
            "replay_overhead_off_vs_on", "replay_overhead_pass",
            "replay_overhead_off_vs_absent", "replay_offcost_pass",
            "replay_capacity", "replay_error")
    return {k: rep[k] for k in keys if k in rep}


def _run_policing_stage(timeout):
    """bench_host.py --policing in a CPU-env subprocess: the admission
    policing rows (docs/robustness.md "admission policing"). The FULL
    report — paired lane-overhead pairs with the probe-liveness
    evidence, plus the whole adversarial_crowd storm verdict — is the
    committed BENCH policing artifact; the orchestrator folds the
    headline gates in so every future round carries them."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, ".bench_result_policing.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["HOSTBENCH_RESULT_FILE"] = result_file
    sys.stderr.write(
        f"# === stage policing (timeout {timeout:.0f}s) ===\n")
    p = _run_child([sys.executable, os.path.join(here, "bench_host.py"),
                    "--policing"], env, here)
    sys.stderr.flush()
    _wait_stage(p, "policing", timeout)
    if not os.path.exists(result_file):
        sys.stderr.write("# stage policing: no result\n")
        return {}
    try:
        with open(result_file) as f:
            rep = json.load(f)
    except ValueError:
        return {}
    keys = ("policing_seed", "policing_lane_engine",
            "policing_overhead_off_vs_on", "policing_overhead_pass",
            "policing_overhead_off_vs_absent", "policing_offcost_pass",
            "policing_probe_checked", "policing_probe_active",
            "policing_storm_pass", "policing_error")
    out = {k: rep[k] for k in keys if k in rep}
    # the headline SLO row only — the full scenario lives in the
    # stage artifact (BENCH_r19), not every future round
    slo = rep.get("policing_storm", {}).get("slo")
    if slo is not None:
        out["policing_storm_slo"] = slo
    return out


def _run_static_analysis_stage():
    """tools/vlint over the tree, in-process (parse-only + one clean
    metrics-registry subprocess — seconds, not minutes): the finding
    counts by pass ride in every round artifact so the trajectory
    shows invariant drift over time (docs/static-analysis.md). An
    analyzer failure is recorded, never fatal to the round."""
    sys.stderr.write("# === stage static_analysis ===\n")
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        if here not in sys.path:
            sys.path.insert(0, here)
        from tools import vlint
        rep = vlint.run_all(here)
        return {"static_analysis": vlint.snapshot(rep)}
    except Exception as e:  # noqa: BLE001 — artifact must survive
        return {"static_analysis": {"error": repr(e)[:300]}}


def _note_phase(phase_file, phase, seconds, **detail):
    """Orchestrator-side phase evidence (same stream the children write):
    backoff sleeps and abandonments become visible, dated records in the
    artifact's `phases` list instead of an unprovable claim."""
    rec = {"stage": "orchestrator", "phase": phase,
           "seconds": round(seconds, 3), **detail}
    sys.stderr.write(f"# [orchestrator] {phase} {seconds:.1f}s {detail}\n")
    sys.stderr.flush()
    if phase_file:
        try:
            with open(phase_file, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass


def _read_phases(phase_file):
    out = []
    if os.path.exists(phase_file):
        with open(phase_file) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    out.append([r.get("stage"), r.get("phase"),
                                r.get("seconds")] +
                               ([{k: v for k, v in r.items() if k not in
                                  ("stage", "phase", "seconds")}]
                                if len(r) > 3 else []))
                except ValueError:
                    pass
    return out


def orchestrate():
    here = os.path.dirname(os.path.abspath(__file__))
    phase_file = os.path.join(here, ".bench_phases.jsonl")
    if os.path.exists(phase_file):
        os.unlink(phase_file)
    budget = float(os.environ.get("BENCH_BUDGET", "900"))

    # The headline JSON line must survive an external wall-clock kill:
    # print the best result published so far on SIGTERM, kill any
    # in-flight stage child, then exit — stages flush partial results
    # continuously, so whatever was mid-flight still contributed what it
    # finished. One-slot container, build-then-swap: the handler can run
    # between any two bytecodes and must never observe a half-built dict.
    best_box: list = [None]

    def publish(res):
        best_box[0] = dict(res)

    def on_term(signum, frame):
        res = best_box[0] or {
            "metric": "rule-matches/sec @100k rules "
                      "(Host+DNS hints, LPM, ACL)",
            "value": 0.0, "unit": "matches/s", "vs_baseline": 0.0,
            "platform": "none", "stage": "killed"}
        res["phases"] = _read_phases(phase_file)
        res["terminated"] = True
        for c in list(_LIVE_CHILDREN):  # don't orphan a running stage
            try:
                c.terminate()
            except OSError:
                pass
        print(json.dumps(res))
        sys.stdout.flush()
        os._exit(143)

    signal.signal(signal.SIGTERM, on_term)
    smoke_timeout = min(float(os.environ.get("BENCH_SMOKE_TIMEOUT", "240")),
                        budget * 0.45)
    t_start = time.time()

    def usable(res):
        """A stage result is only publishable when its own verification
        passed: device/single-step checksum AND the host-oracle sample."""
        return (res is not None and res.get("value", 0) > 0
                and res.get("chk_ok") and res.get("oracle_ok"))

    result = None
    # tunnel wedges are transient (a dying previous claimant blocks the
    # claim) but can last many minutes: retry with exponential backoff
    # for as long as the budget allows — r4's single immediate retry
    # lost the TPU headline to a 45-minute wedge. Compiles ride the
    # persistent cache, so a retried smoke costs seconds, not minutes.
    smoke_env = dict(SMOKE_ENV)
    smoke_errors: list = []

    def smoke_err(res):
        """Harvest the child's recorded failure cause (claim error,
        import error, ...) so the final artifact can carry it."""
        if res is None:
            smoke_errors.append("no result file (child killed?)")
        elif res.get("error"):
            smoke_errors.append(res["error"])
        elif not (res.get("chk_ok") and res.get("oracle_ok")):
            smoke_errors.append(
                f"verification failed (chk_ok={res.get('chk_ok')}, "
                f"oracle_ok={res.get('oracle_ok')}, "
                f"mode={smoke_env.get('VPROXY_TPU_FP_MEMBER')})")
        else:
            smoke_errors.append(f"unusable result (value="
                                f"{res.get('value')}, platform="
                                f"{res.get('platform')})")

    smoke = _run_stage("tpu-smoke", smoke_env, smoke_timeout, phase_file)
    attempt = 0
    # verification-gated lowering ladder: fastest first, r4-verified last
    MODE_LADDER = {"reduce": "selgather", "selgather": "gather"}
    while not (usable(smoke) and smoke.get("platform") != "cpu"):
        smoke_err(smoke)
        cur_mode = smoke_env.get("VPROXY_TPU_FP_MEMBER", "gather")
        if (smoke is not None and smoke.get("value", 0) > 0
                and smoke.get("platform") != "cpu"
                and not (smoke.get("chk_ok") and smoke.get("oracle_ok"))
                and cur_mode in MODE_LADDER
                and budget - (time.time() - t_start) > smoke_timeout + 120):
            # device up but verification FAILED: the backend miscompiled
            # this member-eval lowering — step down the ladder toward
            # the verified-safe gather forms instead of burning retries
            nxt = MODE_LADDER[cur_mode]
            sys.stderr.write(f"# tpu-smoke verification failed on "
                             f"{cur_mode}; retrying with "
                             f"VPROXY_TPU_FP_MEMBER={nxt}\n")
            _note_phase(phase_file, "smoke_mode_ladder", 0.0,
                        from_mode=cur_mode, to_mode=nxt)
            smoke_env["VPROXY_TPU_FP_MEMBER"] = nxt
            smoke = _run_stage("tpu-smoke", smoke_env, smoke_timeout,
                               phase_file)
            continue
        wait = min(20 * (2 ** attempt), 300)
        attempt += 1
        remaining = budget - (time.time() - t_start)
        if remaining < smoke_timeout + wait + 120 or attempt > 6:
            # the r5 artifact showed zero visible waiting — record WHY
            # the retry ladder stops, so a cpu fallback is self-explaining
            _note_phase(phase_file, "smoke_retries_abandoned", 0.0,
                        attempt=attempt, budget_remaining_s=round(
                            remaining, 1),
                        reason=smoke_errors[-1][:200] if smoke_errors
                        else "")
            break
        sys.stderr.write(f"# tpu-smoke failed; retry {attempt} in "
                         f"{wait}s (tunnel claims are transient)\n")
        t_sleep = time.time()
        time.sleep(wait)
        # provable backoff: the sleep itself is a dated phase record
        _note_phase(phase_file, f"smoke_backoff_{attempt}",
                    time.time() - t_sleep, wait_s=wait,
                    reason=smoke_errors[-1][:200] if smoke_errors else "")
        smoke = _run_stage("tpu-smoke", smoke_env, smoke_timeout,
                           phase_file)
    if usable(smoke) and smoke.get("platform") != "cpu":
        result = smoke
        publish(smoke)
        remaining = budget - (time.time() - t_start) - 15
        if remaining > 90:
            full_env = {k: v for k, v in smoke_env.items()
                        if k == "VPROXY_TPU_FP_MEMBER"}
            full = _run_stage("tpu-full", full_env, remaining, phase_file)
            while (full is not None and full.get("value", 0) > 0
                   and not (full.get("chk_ok") and full.get("oracle_ok"))
                   and full_env.get("VPROXY_TPU_FP_MEMBER", "gather")
                   in MODE_LADDER
                   and budget - (time.time() - t_start) > 120):
                # full-size shapes can fuse differently: same ladder
                nxt = MODE_LADDER[full_env.get("VPROXY_TPU_FP_MEMBER",
                                               "gather")]
                sys.stderr.write(f"# tpu-full verification failed; "
                                 f"retrying with {nxt} member mode\n")
                full_env["VPROXY_TPU_FP_MEMBER"] = nxt
                full = _run_stage(
                    "tpu-full", full_env,
                    budget - (time.time() - t_start) - 15, phase_file)
            if usable(full):
                result = full
                publish(full)
    if result is None:
        # no TPU evidence: CPU evidence-of-life run (trimmed iterations;
        # the table is NOT trimmed — the metric is @100k rules)
        cpu = _run_stage("cpu", CPU_ENV, 1800, phase_file, cpu=True)
        result = cpu if usable(cpu) else None
    if result is None:
        result = {"metric": "rule-matches/sec @100k rules "
                            "(Host+DNS hints, LPM, ACL)",
                  "value": 0.0, "unit": "matches/s", "vs_baseline": 0.0,
                  "platform": "none", "stage": "failed"}
    if result.get("platform") != "tpu" and smoke_errors:
        # a cpu/none artifact must say WHY the chip contributed nothing
        result["tpu_smoke_error"] = smoke_errors[-1]
        result["tpu_smoke_attempts"] = len(smoke_errors)
    # host-path req/s (native splice pump) rides along in every run
    publish(result)
    result.update(_run_host_stage(
        float(os.environ.get("BENCH_HOST_TIMEOUT", "120"))))
    publish(result)
    # switch data plane (BASELINE config #4) rides along too
    result.update(_run_switch_stage(
        float(os.environ.get("BENCH_SWITCH_TIMEOUT", "240"))))
    publish(result)
    # pjit-sharded mesh stage: 1M-rule sharded serving + stall-free
    # generation-swap rows on the forced-8-device CPU mesh
    result.update(_run_pjit_stage(
        float(os.environ.get("BENCH_PJIT_TIMEOUT", "900"))))
    publish(result)
    # adversarial storm suite: SLO-gated pass/fail snapshot rides along
    result.update(_run_storm_stage(
        float(os.environ.get("BENCH_STORM_TIMEOUT", "300"))))
    publish(result)
    # maglev consistent-hash rows: pick A/B + churn-on-resize gates
    result.update(_run_maglev_stage(
        float(os.environ.get("BENCH_MAGLEV_TIMEOUT", "300"))))
    publish(result)
    # fused classify+pick: one-launch A/B + launch-counter evidence
    result.update(_run_fused_stage(
        float(os.environ.get("BENCH_FUSED_TIMEOUT", "900"))))
    publish(result)
    # request tracing: per-stage attribution table + zero-overhead gate
    result.update(_run_trace_stage(
        float(os.environ.get("BENCH_TRACE_TIMEOUT", "300"))))
    publish(result)
    # traffic analytics: off-vs-on overhead gate + top-table capture
    result.update(_run_analytics_stage(
        float(os.environ.get("BENCH_ANALYTICS_TIMEOUT", "300"))))
    publish(result)
    # workload replay: capture->replay fidelity + capacity row
    result.update(_run_replay_stage(
        float(os.environ.get("BENCH_REPLAY_TIMEOUT", "300"))))
    publish(result)
    # admission policing: lane-overhead gate + adversarial_crowd verdict
    result.update(_run_policing_stage(
        float(os.environ.get("BENCH_POLICING_TIMEOUT", "300"))))
    publish(result)
    # static analysis: vlint finding counts by pass (invariant drift)
    result.update(_run_static_analysis_stage())
    publish(result)
    result["phases"] = _read_phases(phase_file)
    # complete: disarm the handler so a late SIGTERM can't emit a second
    # (or interleaved) headline line after this one
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child())
    elif "--cpu" in sys.argv:  # manual: one CPU child in-process
        from vproxy_tpu.utils.jaxenv import force_cpu
        force_cpu()
        os.environ.setdefault("BENCH_STAGE", "cpu-manual")
        sys.exit(child())
    elif "--pjit" in sys.argv:  # manual: the mesh stage in-process
        from vproxy_tpu.utils.jaxenv import force_cpu
        force_cpu(8)
        os.environ["BENCH_STAGE"] = "pjit"
        sys.exit(child())
    elif "--maglev" in sys.argv:  # manual: just the maglev stage
        print(json.dumps(_run_maglev_stage(
            float(os.environ.get("BENCH_MAGLEV_TIMEOUT", "300")))))
        sys.exit(0)
    elif "--trace" in sys.argv:  # manual: just the tracing stage
        print(json.dumps(_run_trace_stage(
            float(os.environ.get("BENCH_TRACE_TIMEOUT", "300")))))
        sys.exit(0)
    elif "--analytics" in sys.argv:  # manual: just the analytics stage
        print(json.dumps(_run_analytics_stage(
            float(os.environ.get("BENCH_ANALYTICS_TIMEOUT", "300")))))
        sys.exit(0)
    elif "--replay" in sys.argv:  # manual: just the replay stage
        print(json.dumps(_run_replay_stage(
            float(os.environ.get("BENCH_REPLAY_TIMEOUT", "300")))))
        sys.exit(0)
    elif "--policing" in sys.argv:  # manual: just the policing stage
        print(json.dumps(_run_policing_stage(
            float(os.environ.get("BENCH_POLICING_TIMEOUT", "300")))))
        sys.exit(0)
    elif "--static-analysis" in sys.argv:  # manual: just the vlint row
        print(json.dumps(_run_static_analysis_stage()))
        sys.exit(0)
    elif "--fused" in sys.argv:  # manual: the fused stage in-process
        from vproxy_tpu.utils.jaxenv import force_cpu
        force_cpu()
        os.environ["BENCH_STAGE"] = "fused"
        sys.exit(_fused_child())
    else:
        sys.exit(orchestrate())
