"""Benchmark: batched rule-classification throughput on one chip.

North star (BASELINE.json): >=10M rule-matches/sec over a 100k-rule
combined table (Host/SNI hints + DNS + LPM routes + ACL) at p99 classify
latency < 50us. A "rule-match" is one query classified against a full
table (the reference does this with a linear Java scan per connection:
Upstream.java:187, RouteTable.java:44, SecurityGroup.java:30).

Staged orchestration (each stage is its own child process so a hung TPU
tunnel cannot eat the whole budget, and every stage leaves per-phase
timing evidence behind even when killed):

  1. tpu-smoke — small config (1k rules, batch 512): proves device-up
     and records import/devices/build/upload/compile/step/d2h timings.
  2. tpu-full  — the real 100k-rule, batch-16384 config, only if smoke
     passed, within the remaining budget.
  3. cpu       — evidence-of-life fallback only if no TPU stage landed.

Each child appends one JSON line per completed phase to
BENCH_PHASE_FILE; the final stdout JSON embeds the phase evidence, so a
timeout still tells you WHERE the time went.

Measured sections per child:
  * throughput — async pipelined steady state: per step run the fused
    hint+LPM+ACL classify over a PRE-UPLOADED query batch (no h2d on
    the critical path), chunked async d2h readback.
  * latency — per-dispatch submit->verdict-on-host p50/p99, measured
    blocking (batch=1 and batch=LAT_BATCH), steady state.
  * service — ClassifyService accept->verdict latency under synthetic
    multi-threaded connection load (the BASELINE contract measured at
    the service boundary).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

TARGET = 10_000_000.0  # rule-matches/sec north star


def _env_int(k, d):
    return int(os.environ.get(k, str(d)))


# ----------------------------------------------------------------- phases

class Phases:
    """Incremental phase evidence: one JSON line per phase, flushed
    immediately so a killed child still leaves a trail."""

    def __init__(self, path, stage):
        self.path = path
        self.stage = stage
        self._t0 = None
        self._name = None

    def start(self, name):
        self._name = name
        self._t0 = time.time()
        sys.stderr.write(f"# [{self.stage}] {name}...\n")
        sys.stderr.flush()

    def done(self, **detail):
        dt = time.time() - self._t0
        rec = {"stage": self.stage, "phase": self._name,
               "seconds": round(dt, 3), **detail}
        sys.stderr.write(f"# [{self.stage}] {self._name} {dt:.2f}s "
                         f"{detail if detail else ''}\n")
        sys.stderr.flush()
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return dt


# ------------------------------------------------------------- table build

def build(ph):
    from vproxy_tpu.ops import hashmatch as H
    from vproxy_tpu.ops import tables as T
    from vproxy_tpu.rules.ir import AclRule, Hint, HintRule, Proto
    from vproxy_tpu.utils.ip import Network, mask_bytes

    n_rules = _env_int("BENCH_RULES", 100000)
    n_route = _env_int("BENCH_ROUTES", 50000)
    n_acl = _env_int("BENCH_ACLS", 5000)
    batch = _env_int("BENCH_BATCH", 16384)
    nq = _env_int("BENCH_QUERY_SETS", 4)

    def dom(i):
        return f"svc{i}.ns{i % 997}.apps.example.com"

    ph.start("build_tables")
    hint_rules = []
    for i in range(n_rules):
        r = i % 20
        if r < 12:
            hint_rules.append(HintRule(host=dom(i)))
        elif r < 16:
            hint_rules.append(HintRule(host=dom(i), uri=f"/api/v{i % 17}"))
        elif r < 18:
            hint_rules.append(HintRule(host=dom(i), port=443))
        else:
            hint_rules.append(HintRule(host=f"w{i}.example.com", uri="*"))

    def v4net(i, ml):
        ip = np.array([10 + (i % 13), (i >> 8) & 0xFF, i & 0xFF,
                       (i * 37) & 0xFF], np.uint8)
        m = np.frombuffer(mask_bytes(ml), np.uint8)
        return Network(bytes(ip & m), bytes(m))

    routes = [v4net(i, 8 + (i % 17)) for i in range(n_route)]
    acls = [AclRule(f"r{i}", v4net(i * 3, 8 + (i % 25)), Proto.TCP,
                    (i * 7) % 60000, (i * 7) % 60000 + 1000, i % 2 == 0)
            for i in range(n_acl)]
    ht = H.compile_hint_hash(hint_rules)
    rt = H.compile_cidr_hash(routes)
    at = H.compile_cidr_hash([r.network for r in acls], acl=acls)
    ph.done(rules=n_rules, routes=n_route, acls=n_acl)

    # rule -> ServerGroup / next-hop payload maps (device gathers these
    # after the match so the host receives consumable indices)
    n_groups = _env_int("BENCH_GROUPS", 251)
    n_nexthop = _env_int("BENCH_NEXTHOPS", 120)
    hint_group = (np.arange(ht.r_cap, dtype=np.int32) % n_groups)
    route_tgt = (np.arange(rt.r_cap, dtype=np.int32) % n_nexthop)

    ph.start("encode_queries")
    qsets = []
    for s in range(nq):
        rs = np.random.RandomState(100 + s)
        hints = []
        for i in range(batch):
            j = int(rs.randint(0, n_rules))
            if i % 3 == 0:
                hints.append(Hint.of_host(dom(j)))
            elif i % 3 == 1:
                hints.append(Hint.of_host_uri("x." + dom(j), f"/api/v{j % 17}/u"))
            else:
                hints.append(Hint.of_host_port(dom(j), 443))
        hq = H.encode_hint_queries(hints, ht)
        addrs = [bytes([10 + (int(x) % 13)] + list(rs.bytes(3)))
                 for x in rs.randint(0, 13, batch)]
        a16, fam = T.encode_ips(addrs)
        ports = rs.randint(1, 65535, size=batch).astype(np.int32)
        qsets.append((hq, a16, fam, ports))
    ph.done(batch=batch, sets=nq)
    return ht, rt, at, hint_group, route_tgt, qsets


# ------------------------------------------------------------------ child

def child():
    stage = os.environ.get("BENCH_STAGE", "child")
    ph = Phases(os.environ.get("BENCH_PHASE_FILE", ""), stage)

    ph.start("import_jax")
    import jax
    import jax.numpy as jnp
    ph.done()

    ph.start("devices")
    dev = jax.devices()[0]
    platform = dev.platform
    ph.done(platform=platform, n=len(jax.devices()))

    from vproxy_tpu.ops.hashmatch import cidr_hash_match, hint_hash_match
    from vproxy_tpu.rules.engine import _to_device

    n_groups = _env_int("BENCH_GROUPS", 251)
    n_nexthop = _env_int("BENCH_NEXTHOPS", 120)
    assert n_groups < 255 and n_nexthop < 127, "u8 verdict packing bounds"
    batch = _env_int("BENCH_BATCH", 16384)
    iters = _env_int("BENCH_ITERS", 256)
    chunk = _env_int("BENCH_CHUNK", 64)

    ht, rt, at, hint_group, route_tgt, qsets = build(ph)

    # h2d/d2h bandwidth probe: says whether a later stall is the tunnel
    ph.start("bw_probe")
    mb8 = np.ones((8 << 20,), np.uint8)
    t0 = time.time()
    x = jax.device_put(mb8)
    x.block_until_ready()
    h2d = 8.0 / max(time.time() - t0, 1e-9)
    t0 = time.time()
    np.asarray(x[: 1 << 20])
    d2h = 1.0 / max(time.time() - t0, 1e-9)
    ph.done(h2d_MBps=round(h2d, 1), d2h_MBps=round(d2h, 1))

    ph.start("upload_tables")
    htd, rtd, atd = (_to_device(ht.arrays), _to_device(rt.arrays),
                     _to_device(at.arrays))
    hgd, rtgd = jax.device_put(hint_group), jax.device_put(route_tgt)
    jax.block_until_ready([htd, rtd, atd, hgd, rtgd])
    ph.done()

    # pre-upload every query set ONCE — steady state has no h2d at all
    ph.start("upload_queries")
    dsets = []
    for hq, a16, fam, ports in qsets:
        dsets.append(({k: jax.device_put(v) for k, v in hq.items()},
                      jax.device_put(a16), jax.device_put(fam),
                      jax.device_put(ports)))
    jax.block_until_ready(dsets)
    ph.done()

    @jax.jit
    def step_fn(ht_, rt_, at_, hg_, rtg_, hq, a16, fam, port):
        hi, _ = hint_hash_match(ht_, hq)
        ri = cidr_hash_match(rt_, a16, fam, None)
        ai = cidr_hash_match(at_, a16, fam, port)
        group = jnp.where(hi >= 0, hg_[jnp.maximum(hi, 0)] + 1, 0)
        tgt = jnp.where(ri >= 0, rtg_[jnp.maximum(ri, 0)] + 1, 0)
        allow = jnp.where(ai >= 0, at_["allow"][jnp.maximum(ai, 0)], True)
        v1 = (allow.astype(jnp.uint8) << 7) | tgt.astype(jnp.uint8)
        return jnp.stack([group.astype(jnp.uint8), v1], axis=1)  # [B,2] u8

    def submit(ds):
        hq, a16, fam, ports = ds
        return step_fn(htd, rtd, atd, hgd, rtgd, hq, a16, fam, ports)

    ph.start("warmup_compile")
    np.asarray(submit(dsets[0]))
    ph.done()

    # ---- throughput: async pipeline, chunked d2h off the critical path
    ph.start("throughput")
    nq = len(dsets)
    pending, cur = [], []
    done = 0
    t0 = time.time()
    for i in range(iters):
        cur.append(submit(dsets[i % nq]))
        if len(cur) == chunk:
            arr = jnp.stack(cur)
            arr.copy_to_host_async()
            pending.append(arr)
            cur = []
            while len(pending) > 2:  # keep readback off the critical path
                r = np.asarray(pending.pop(0))
                done += r.shape[0] * r.shape[1]
    if cur:
        arr = jnp.stack(cur)
        arr.copy_to_host_async()
        pending.append(arr)
    for p in pending:
        r = np.asarray(p)
        done += r.shape[0] * r.shape[1]
    total = time.time() - t0
    assert done == iters * batch
    matches = 3 * batch * iters  # hint + route + acl per element
    rate = matches / total
    step_us = total / iters * 1e6
    ph.done(rate=round(rate, 1), step_us=round(step_us, 1))

    # ---- latency: per-dispatch submit->verdict-on-host, steady state
    lat_iters = _env_int("BENCH_LAT_ITERS", 100)
    lat_batch = _env_int("BENCH_LAT_BATCH", 256)
    lat = {}
    for b in (1, lat_batch):
        ph.start(f"latency_b{b}")
        small = tuple(
            {k: v[:b] for k, v in ds.items()} if isinstance(ds, dict)
            else ds[:b] for ds in dsets[0])
        np.asarray(submit(small))  # warm this shape
        samples = []
        for _ in range(lat_iters):
            t0 = time.time()
            np.asarray(submit(small))
            samples.append(time.time() - t0)
        lat[b] = (float(np.percentile(samples, 50) * 1e6),
                  float(np.percentile(samples, 99) * 1e6))
        ph.done(p50_us=round(lat[b][0], 1), p99_us=round(lat[b][1], 1))

    # ---- ClassifyService accept->verdict under synthetic load
    svc_stats = service_section(ph)

    nr = _env_int("BENCH_RULES", 100000)
    label = "%dk" % (nr // 1000) if nr >= 1000 else str(nr)
    result = {
        "metric": "rule-matches/sec @%s rules (Host+DNS hints, LPM, ACL)"
                  % label,
        "value": round(rate, 1),
        "unit": "matches/s",
        "vs_baseline": round(rate / TARGET, 4),
        "platform": platform,
        "stage": stage,
        "step_us": round(step_us, 1),
        "dispatch_p50_us": round(lat[1][0], 1),
        "dispatch_p99_us": round(lat[1][1], 1),
        "dispatch_b%d_p50_us" % lat_batch: round(lat[lat_batch][0], 1),
        "dispatch_b%d_p99_us" % lat_batch: round(lat[lat_batch][1], 1),
    }
    result.update(svc_stats)
    out = os.environ.get("BENCH_RESULT_FILE")
    if out:
        with open(out, "w") as f:
            json.dump(result, f)
    print(json.dumps(result))
    return 0


def service_section(ph):
    """ClassifyService end-to-end: N threads each performing sequential
    accept-like lone classifies + bursts, against a big HintMatcher in
    mode=device. Reports submit->verdict-on-host percentiles measured by
    the service's own reservoir (the BASELINE latency contract at the
    component boundary)."""
    import threading

    from vproxy_tpu.rules.engine import HintMatcher
    from vproxy_tpu.rules.ir import Hint, HintRule
    from vproxy_tpu.rules.service import ClassifyService

    n_rules = min(_env_int("BENCH_RULES", 100000), 20000)
    n_threads = _env_int("BENCH_SVC_THREADS", 16)
    per = _env_int("BENCH_SVC_QUERIES", 50)

    ph.start("service_setup")
    rules = [HintRule(host=f"svc{i}.bench.example.com")
             for i in range(n_rules)]
    m = HintMatcher(rules)
    svc = ClassifyService(mode="device")
    m.match([Hint.of_host("warm.example.com")] * 16)  # warm jit
    ph.done(rules=n_rules)

    ph.start("service_load")
    errs = []
    t_done = threading.Event()
    remaining = [n_threads]
    lock = threading.Lock()

    def worker(tid):
        try:
            for i in range(per):
                ev = threading.Event()
                want = (tid * per + i) % n_rules

                def cb(idx, _pl, want=want, ev=ev):
                    if idx != want:
                        errs.append((want, idx))
                    ev.set()

                svc.submit_hint(m, Hint.of_host(
                    f"svc{want}.bench.example.com"), cb)
                ev.wait(30)
        finally:
            with lock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    t_done.set()

    t0 = time.time()
    for t in range(n_threads):
        threading.Thread(target=worker, args=(t,), daemon=True).start()
    t_done.wait(120)
    wall = time.time() - t0
    lat = svc.stats.latency_percentiles() or {"p50_us": -1, "p99_us": -1}
    st = svc.stats
    ph.done(queries=st.queries, dispatches=st.dispatches,
            max_batch=st.max_batch, p50_us=round(lat["p50_us"], 1),
            p99_us=round(lat["p99_us"], 1), wall_s=round(wall, 2),
            errors=len(errs))
    svc.close()
    assert not errs, errs[:5]
    return {"service_p50_us": round(lat["p50_us"], 1),
            "service_p99_us": round(lat["p99_us"], 1),
            "service_max_batch": st.max_batch,
            "service_dispatches": st.dispatches,
            "service_queries": st.queries}


# ----------------------------------------------------------- orchestrator

SMOKE_ENV = {"BENCH_RULES": "1000", "BENCH_ROUTES": "500",
             "BENCH_ACLS": "200", "BENCH_BATCH": "512",
             "BENCH_ITERS": "16", "BENCH_CHUNK": "4",
             "BENCH_QUERY_SETS": "2", "BENCH_LAT_ITERS": "32",
             "BENCH_SVC_THREADS": "8", "BENCH_SVC_QUERIES": "25"}

CPU_ENV = {"BENCH_ITERS": "16", "BENCH_CHUNK": "8",
           "BENCH_QUERY_SETS": "2", "BENCH_LAT_ITERS": "16",
           "BENCH_SVC_THREADS": "8", "BENCH_SVC_QUERIES": "25"}


def _run_stage(name, env_over, timeout, phase_file, cpu=False):
    """Run one measured child; returns its result dict or None.
    SIGTERM first (a SIGKILLed TPU-tunnel client wedges the device pool
    for minutes — demonstrated in this environment), SIGKILL only as a
    last resort."""
    here = os.path.dirname(os.path.abspath(__file__))
    result_file = os.path.join(here, f".bench_result_{name}.json")
    if os.path.exists(result_file):
        os.unlink(result_file)
    if cpu:
        from vproxy_tpu.utils.jaxenv import cpu_subprocess_env
        env = cpu_subprocess_env()
    else:
        env = dict(os.environ)
    env.update(env_over)
    env["BENCH_STAGE"] = name
    env["BENCH_PHASE_FILE"] = phase_file
    env["BENCH_RESULT_FILE"] = result_file
    sys.stderr.write(f"# === stage {name} (timeout {timeout:.0f}s) ===\n")
    sys.stderr.flush()
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__),
                          "--child"], env=env, cwd=here, stdout=sys.stderr)
    deadline = time.time() + timeout
    while p.poll() is None and time.time() < deadline:
        time.sleep(0.5)
    if p.poll() is None:
        sys.stderr.write(f"# stage {name}: timeout, SIGTERM\n")
        p.send_signal(signal.SIGTERM)
        try:
            p.wait(20)
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"# stage {name}: SIGKILL\n")
            p.kill()
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                # D-state child stuck on the wedged tunnel: abandon it —
                # the final JSON line must still be printed
                sys.stderr.write(f"# stage {name}: unkillable, abandoned\n")
    if p.returncode == 0 and os.path.exists(result_file):
        with open(result_file) as f:
            return json.load(f)
    sys.stderr.write(f"# stage {name}: rc={p.returncode}, no result\n")
    return None


def _read_phases(phase_file):
    out = []
    if os.path.exists(phase_file):
        with open(phase_file) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    out.append([r.get("stage"), r.get("phase"),
                                r.get("seconds")] +
                               ([{k: v for k, v in r.items() if k not in
                                  ("stage", "phase", "seconds")}]
                                if len(r) > 3 else []))
                except ValueError:
                    pass
    return out


def orchestrate():
    here = os.path.dirname(os.path.abspath(__file__))
    phase_file = os.path.join(here, ".bench_phases.jsonl")
    if os.path.exists(phase_file):
        os.unlink(phase_file)
    budget = float(os.environ.get("BENCH_BUDGET", "900"))
    smoke_timeout = min(float(os.environ.get("BENCH_SMOKE_TIMEOUT", "240")),
                        budget)
    t_start = time.time()

    result = None
    smoke = _run_stage("tpu-smoke", SMOKE_ENV, smoke_timeout, phase_file)
    if smoke is not None and smoke.get("platform") != "cpu":
        result = smoke
        remaining = budget - (time.time() - t_start)
        if remaining > 120:
            full = _run_stage(
                "tpu-full",
                {"BENCH_ITERS": "128", "BENCH_CHUNK": "32"},
                remaining, phase_file)
            if full is not None:
                result = full
    if result is None:
        # no TPU evidence: CPU evidence-of-life run (trimmed iterations;
        # the table is NOT trimmed — the metric is @100k rules)
        result = _run_stage("cpu", CPU_ENV, 1800, phase_file, cpu=True)
    if result is None:
        result = {"metric": "rule-matches/sec @100k rules "
                            "(Host+DNS hints, LPM, ACL)",
                  "value": 0.0, "unit": "matches/s", "vs_baseline": 0.0,
                  "platform": "none", "stage": "failed"}
    result["phases"] = _read_phases(phase_file)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child())
    elif "--cpu" in sys.argv:  # manual: one CPU child in-process
        from vproxy_tpu.utils.jaxenv import force_cpu
        force_cpu()
        os.environ.setdefault("BENCH_STAGE", "cpu-manual")
        sys.exit(child())
    else:
        sys.exit(orchestrate())
