"""Benchmark: batched rule-classification throughput on one chip.

North star (BASELINE.json): >=10M rule-matches/sec over a 100k-rule
combined table (Host/SNI hints + DNS + LPM routes + ACL) at p99 classify
latency < 50us. A "rule-match" is one query classified against a full
table (the reference does this with a linear Java scan per connection:
Upstream.java:187, RouteTable.java:44, SecurityGroup.java:30).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

# honor the driver's environment; only force CPU if explicitly asked
if "--cpu" in sys.argv:
    os.environ["JAX_PLATFORMS"] = "cpu"

N_RULES = int(os.environ.get("BENCH_RULES", "100000"))
N_ROUTE = int(os.environ.get("BENCH_ROUTES", "50000"))
N_ACL = int(os.environ.get("BENCH_ACLS", "5000"))
BATCH = int(os.environ.get("BENCH_BATCH", "4096"))
TARGET = 10_000_000.0  # rule-matches/sec north star


def build():
    from vproxy_tpu.ops import tables as T
    from vproxy_tpu.ops.matchers import table_arrays
    from vproxy_tpu.rules.ir import AclRule, Hint, HintRule, Proto
    from vproxy_tpu.utils.ip import Network, mask_bytes

    rnd = np.random.RandomState(11)

    def dom(i):
        return f"svc{i}.ns{i % 997}.apps.example.com"

    hint_rules = []
    for i in range(N_RULES):
        r = i % 20
        if r < 12:
            hint_rules.append(HintRule(host=dom(i)))
        elif r < 16:
            hint_rules.append(HintRule(host=dom(i), uri=f"/api/v{i % 17}"))
        elif r < 18:
            hint_rules.append(HintRule(host=dom(i), port=443))
        else:
            hint_rules.append(HintRule(host=f"w{i}.example.com", uri="*"))

    def v4net(i, ml):
        ip = np.array([10 + (i % 13), (i >> 8) & 0xFF, i & 0xFF,
                       (i * 37) & 0xFF], np.uint8)
        m = np.frombuffer(mask_bytes(ml), np.uint8)
        return Network(bytes(ip & m), bytes(m))

    routes = [v4net(i, 8 + (i % 17)) for i in range(N_ROUTE)]
    acls = [AclRule(f"r{i}", v4net(i * 3, 8 + (i % 25)), Proto.TCP,
                    (i * 7) % 60000, (i * 7) % 60000 + 1000, i % 2 == 0)
            for i in range(N_ACL)]

    t0 = time.time()
    ht = table_arrays(T.compile_hint_rules(hint_rules))
    rt = table_arrays(T.compile_cidr_rules(routes))
    at = table_arrays(T.compile_acl(acls, Proto.TCP))
    compile_s = time.time() - t0

    hints = []
    for i in range(BATCH):
        j = int(rnd.randint(0, N_RULES))
        if i % 3 == 0:
            hints.append(Hint.of_host(dom(j)))
        elif i % 3 == 1:
            hints.append(Hint.of_host_uri("x." + dom(j), f"/api/v{j % 17}/u"))
        else:
            hints.append(Hint.of_host_port(dom(j), 443))
    hq = T.encode_hints(hints)
    addrs = [bytes([10 + (int(x) % 13)] + list(np.random.bytes(3)))
             for x in rnd.randint(0, 13, BATCH)]
    a16, fam = T.encode_ips(addrs)
    ports = rnd.randint(1, 65535, size=BATCH).astype(np.int32)
    return ht, rt, at, hq, (a16, fam), ports, compile_s


def main():
    import jax
    from vproxy_tpu.ops.bitmatch import unpack_bits
    from vproxy_tpu.ops.matchers import cidr_match_jit, hint_match_jit
    from vproxy_tpu.rules.engine import _to_device

    ht, rt, at, hq, (a16, fam), ports, compile_s = build()
    ht, rt, at = _to_device(ht), _to_device(rt), _to_device(at)
    uri_bits = np.asarray(unpack_bits(hq["uri"]))

    def step():
        hi, _ = hint_match_jit(ht, hq["host"], hq["has_host"], uri_bits,
                               hq["has_uri"], hq["port"])
        ri = cidr_match_jit(rt, a16, fam, None)
        ai = cidr_match_jit(at, a16, fam, ports)
        return hi, ri, ai

    # warmup / compile
    t0 = time.time()
    out = step()
    [o.block_until_ready() for o in out]
    warm_s = time.time() - t0

    iters = int(os.environ.get("BENCH_ITERS", "30"))
    lat = []
    t0 = time.time()
    for _ in range(iters):
        t1 = time.time()
        out = step()
        [o.block_until_ready() for o in out]
        lat.append(time.time() - t1)
    total = time.time() - t0

    # 3 classification queries per batch element (hint + route + acl)
    matches = 3 * BATCH * iters
    rate = matches / total
    p50 = float(np.percentile(lat, 50) * 1e6)
    p99 = float(np.percentile(lat, 99) * 1e6)
    sys.stderr.write(
        f"# rules={N_RULES}+{N_ROUTE}+{N_ACL} batch={BATCH} iters={iters} "
        f"compile={compile_s:.1f}s warmup={warm_s:.1f}s "
        f"step p50={p50:.0f}us p99={p99:.0f}us platform={jax.devices()[0].platform}\n")
    print(json.dumps({
        "metric": "rule-matches/sec @100k rules (Host+DNS hints, LPM, ACL)",
        "value": round(rate, 1),
        "unit": "matches/s",
        "vs_baseline": round(rate / TARGET, 4),
    }))


if __name__ == "__main__":
    main()
