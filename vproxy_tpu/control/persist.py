"""Config persistence — the config IS a replayable command script.

Parity: app process/Shutdown.java — currentConfig() walks live resources
emitting `add ...` commands in dependency order (:269-760), save writes
the last-config file, load replays each line through the normal command
engine (:761). Auto-save runs hourly on the control loop (Main.java:371).
"""
from __future__ import annotations

import json
import os
from typing import Optional

from .app import (Application, DEFAULT_ACCEPTOR_ELG, DEFAULT_CONTROL_ELG,
                  DEFAULT_WORKER_ELG)
from .command import Command, _rule_to_anno

DEFAULT_DIR = os.environ.get("VPROXY_TPU_HOME", os.path.expanduser("~/.vproxy_tpu"))
LAST_CONFIG = os.path.join(DEFAULT_DIR, "vproxy.last")
_BUILTIN_ELGS = {DEFAULT_ACCEPTOR_ELG, DEFAULT_WORKER_ELG, DEFAULT_CONTROL_ELG}


def current_config(app: Application) -> str:
    """Serialize the resource graph to `add ...` commands in dependency
    order: elgs, security-groups(+rules), server-groups(+servers),
    upstreams(+attachments), then the frontends."""
    lines: list[str] = []
    for name, elg in app.elgs.items():
        if name in _BUILTIN_ELGS:
            continue
        lines.append(f"add event-loop-group {name}")
        for ln in elg.loop_names():
            lines.append(f"add event-loop {ln} to event-loop-group {name}")
    for g in app.security_groups.values():
        lines.append(f"add security-group {g.alias} default "
                     f"{'allow' if g.default_allow else 'deny'}")
        for r in g.rules:
            lines.append(
                f"add security-group-rule {r.alias} to security-group {g.alias} "
                f"network {r.network} protocol {r.protocol.value} "
                f"port-range {r.min_port},{r.max_port} "
                f"default {'allow' if r.allow else 'deny'}")
    for g in app.server_groups.values():
        elg_part = "" if g.elg is app.worker_elg else f" event-loop-group {g.elg.name}"
        anno = _rule_to_anno(g.annotations)
        anno_part = f" annotations {anno}" if anno != "{}" else ""
        lines.append(
            f"add server-group {g.alias} timeout {g.hc.timeout_ms} "
            f"period {g.hc.period_ms} up {g.hc.up} down {g.hc.down} "
            f"protocol {g.hc.protocol} method {g.method}{elg_part}{anno_part}")
        for s in g.servers:
            lines.append(f"add server {s.name} to server-group {g.alias} "
                         f"address {s.ip}:{s.port} weight {s.weight}")
    for u in app.upstreams.values():
        lines.append(f"add upstream {u.alias}")
        for h in u.handles:
            anno = _rule_to_anno(h.annotations)
            anno_part = f" annotations {anno}" if anno != "{}" else ""
            lines.append(f"add server-group {h.alias} to upstream {u.alias} "
                         f"weight {h.weight}{anno_part}")
    for ck in app.cert_keys.values():
        lines.append(f"add cert-key {ck.alias} cert {ck.cert_path} "
                     f"key {ck.key_path}")
    from ..components.tcplb import MAX_SESSIONS as _MAX_SESSIONS
    from ..components.tcplb import POOL_SIZE as _POOL_SIZE
    for lb in app.tcp_lbs.values():
        secg_part = ("" if lb.security_group.alias == "(allow-all)"
                     else f" security-group {lb.security_group.alias}")
        ck_part = ("" if not lb.cert_keys else
                   " cert-key " + ",".join(ck.alias for ck in lb.cert_keys))
        ms_part = ("" if lb.max_sessions == _MAX_SESSIONS
                   else f" max-sessions {lb.max_sessions}")
        pool_part = ("" if lb.pool_size == _POOL_SIZE
                     else f" pool-size {lb.pool_size}")
        lines.append(
            f"add tcp-lb {lb.alias} address {lb.bind_ip}:{lb.bind_port} "
            f"upstream {lb.backend.alias} protocol {lb.protocol} "
            f"timeout {lb.timeout_ms} "
            f"in-buffer-size {lb.in_buffer_size}{secg_part}{ck_part}"
            f"{ms_part}{pool_part}")
    for s in app.socks5_servers.values():
        flag = " allow-non-backend" if s.allow_non_backend else ""
        secg_part = ("" if s.security_group.alias == "(allow-all)"
                     else f" security-group {s.security_group.alias}")
        lines.append(
            f"add socks5-server {s.alias} address {s.bind_ip}:{s.bind_port} "
            f"upstream {s.backend.alias} timeout {s.timeout_ms}"
            f"{secg_part}{flag}")
    for d in app.dns_servers.values():
        secg_part = ("" if d.security_group.alias == "(allow-all)"
                     else f" security-group {d.security_group.alias}")
        lines.append(f"add dns-server {d.alias} address {d.bind_ip}:{d.bind_port} "
                     f"upstream {d.rrsets.alias} ttl {d.ttl}{secg_part}")
    for sw in app.switches.values():
        secg_part = ("" if sw.bare_access.alias == "(allow-all)"
                     else f" security-group {sw.bare_access.alias}")
        lines.append(
            f"add switch {sw.alias} address {sw.bind_ip}:{sw.bind_port} "
            f"mac-table-timeout {sw.mac_table_timeout_ms} "
            f"arp-table-timeout {sw.arp_table_timeout_ms}{secg_part}")
        for net in sw.networks.values():
            v6 = f" v6network {net.v6net}" if net.v6net else ""
            anno = (" annotations " + json.dumps(net.annotations,
                                                 separators=(",", ":"))
                    if net.annotations else "")
            lines.append(f"add vpc {net.vni} to switch {sw.alias} "
                         f"v4network {net.v4net}{v6}{anno}")
            from ..utils.ip import format_ip
            from ..vswitch.packets import mac_str
            from ..vswitch.switch import synthetic_mac
            for ip, mac in net.ips.ips().items():
                # non-default macs (e.g. the docker gateway mac) must
                # survive the replay or post-reload Joins break
                mac_part = ("" if mac == synthetic_mac(net.vni, ip)
                            else f" mac {mac_str(mac)}")
                lines.append(f"add ip {format_ip(ip)} to vpc {net.vni} "
                             f"in switch {sw.alias}{mac_part}")
            for r in net.routes.rules:
                tgt = f"vni {r.to_vni}" if r.to_vni else \
                    f"via {format_ip(r.via_ip)}"
                lines.append(f"add route {r.alias} to vpc {net.vni} "
                             f"in switch {sw.alias} network {r.rule} {tgt}")
        from ..vswitch.switch import display_user_name
        for user, (_key, vni, password) in sw.users.items():
            lines.append(f"add user {display_user_name(user)} "
                         f"to switch {sw.alias} "
                         f"password {password} vni {vni}")
        for iface in sw.list_ifaces():
            if iface.name.startswith("remote:"):
                lines.append(
                    f"add switch {iface.alias} to switch {sw.alias} "
                    f"address {iface.remote[0]}:{iface.remote[1]}")
            elif iface.name.startswith("tap:"):
                ps = (f" post-script {iface.post_script}"
                      if iface.post_script else "")
                anno = (" annotations " + json.dumps(
                    iface.annotations, separators=(",", ":"))
                    if iface.annotations else "")
                lines.append(f"add tap {iface.dev} to switch {sw.alias} "
                             f"vni {iface.local_side_vni}{ps}{anno}")
    for a, ctl in app.docker_controllers.items():
        lines.append(f"add docker-network-plugin-controller {a} "
                     f"path {ctl.path}")
    from ..policing import engine as _policing
    for p in _policing.default().list_policies():
        tenant_part = f" tenant={p['tenant']}" if p["tenant"] else ""
        lines.append(f"add policy {p['name']} dim={p['dim']} "
                     f"rate={p['rate']:g} burst={p['burst']:g} "
                     f"action={p['action']}{tenant_part}")
    return "\n".join(lines) + ("\n" if lines else "")


def save(app: Application, path: Optional[str] = None) -> str:
    path = path or LAST_CONFIG
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(current_config(app))
    return path


def load(app: Application, path: Optional[str] = None) -> int:
    """Replay a config file through the command engine; returns the number
    of commands executed."""
    path = path or LAST_CONFIG
    n = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            Command.execute(app, line)
            n += 1
    return n


def start_auto_save(app: Application, interval_ms: int = 3600_000,
                    path: Optional[str] = None):
    """Hourly auto-save on the control loop (Main.java:369-371)."""
    return app.control_loop.period(interval_ms, lambda: save(app, path))
