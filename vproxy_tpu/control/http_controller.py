"""HttpController — REST control surface.

Parity: app controller/HttpController.java (routes :59-320, swagger
doc/api.yaml): CRUD under /api/v1/module/<resource>, /healthz, plus a
raw command endpoint. Built on the embeddable vserver HTTP lib exactly
as the reference's controller is built on its vserver. JSON bodies use
the command grammar's param names; list endpoints return JSON arrays.
"""
from __future__ import annotations

import json
from typing import Optional

from ..lib.vserver import HttpServer, RoutingContext
from ..net.eventloop import SelectorEventLoop
from .app import Application
from .command import CmdError, Command

# url segment -> command resource type
MODULES = {
    "tcp-lb": "tcp-lb", "socks5-server": "socks5-server",
    "dns-server": "dns-server", "event-loop-group": "event-loop-group",
    "upstream": "upstream", "server-group": "server-group",
    "security-group": "security-group", "cert-key": "cert-key",
    "switch": "switch",
    "resp-controller": "resp-controller",
    "http-controller": "http-controller",
}
FLAG_KEYS = {"allow-non-backend", "deny-non-backend"}


def _anno(rule) -> dict:
    d = {}
    if getattr(rule, "host", None) is not None:
        d["vproxy/hint-host"] = rule.host
    if getattr(rule, "port", 0):
        d["vproxy/hint-port"] = str(rule.port)
    if getattr(rule, "uri", None) is not None:
        d["vproxy/hint-uri"] = rule.uri
    return d


# typed list-detail JSON per module (HttpController.java:59-320 returns
# per-resource objects, doc/api.yaml schemas) — built straight from the
# component objects, not by re-parsing command-grammar strings
def _details(app, rtype: str) -> list:
    if rtype == "tcp-lb":
        return [{
            "name": a, "address": f"{lb.bind_ip}:{lb.bind_port}",
            "protocol": lb.protocol, "backend": lb.backend.alias,
            "securityGroup": lb.security_group.alias,
            "inBufferSize": lb.in_buffer_size, "timeout": lb.timeout_ms,
            "activeSessions": getattr(lb, "active_sessions", 0),
            "listOfCertKey": [ck.alias for ck in lb.cert_keys],
            "lanes": (lambda _l: _l.stat() if _l is not None
                      else {"on": False})(lb.lanes),
            # consistent-hash routing state (docs/perf.md maglev):
            # table sizes, generations, last-resize remap fractions
            "maglev": lb.maglev_stat(),
            # admission state (docs/robustness.md adaptive overload):
            # mode, bounds, and the controller EWMAs when adaptive
            "overload": lb.overload_stat(),
        } for a, lb in app.tcp_lbs.items()]
    if rtype == "socks5-server":
        return [{
            "name": a, "address": f"{s.bind_ip}:{s.bind_port}",
            "backend": s.backend.alias,
            "securityGroup": s.security_group.alias,
            "allowNonBackend": getattr(s, "allow_non_backend", False),
        } for a, s in app.socks5_servers.items()]
    if rtype == "dns-server":
        return [{
            "name": a, "address": f"{d.bind_ip}:{d.bind_port}",
            "rrsets": d.rrsets.alias, "ttl": d.ttl,
            "securityGroup": d.security_group.alias,
            "queries": getattr(d, "queries", 0),
        } for a, d in app.dns_servers.items()]
    if rtype == "event-loop-group":
        return [{"name": a, "eventLoopList": elg.loop_names()}
                for a, elg in app.elgs.items()]
    if rtype == "upstream":
        return [{
            "name": a, "serverGroupList": [{
                "name": h.alias, "weight": h.weight,
                "annotations": _anno(h.annotations),
            } for h in u.handles],
            # classify-engine state (docs/perf.md sharded engine):
            # generation bumps on every atomic standby-table swap
            "engine": {
                "backend": u._matcher.backend,
                "rules": u._matcher.size(),
                "generation": u._matcher.generation,
                "tableBytes": u._matcher.published_table_bytes(),
                "checksum": u._matcher.checksum(),
                # fused classify+pick state (docs/perf.md fused
                # dispatch): packed-table availability, serving kernel
                # tier, packed device bytes — with the launch counters
                # on /metrics this makes "one launch per batch"
                # operator-verifiable
                "fused": (u._matcher.fused_stat()
                          if hasattr(u._matcher, "fused_stat")
                          else {"available": False}),
            },
        } for a, u in app.upstreams.items()]
    if rtype == "server-group":
        return [{
            "name": a, "method": g.method,
            "timeout": g.hc.timeout_ms, "period": g.hc.period_ms,
            "up": g.hc.up, "down": g.hc.down,
            "protocol": g.hc.protocol,
            "annotations": _anno(g.annotations),
            "serverList": [{
                "name": s.name, "address": f"{s.ip}:{s.port}",
                "weight": s.weight, "currentlyUp": s.healthy,
                "connCount": getattr(s, "conn_count", 0),
            } for s in g.servers],
        } for a, g in app.server_groups.items()]
    if rtype == "security-group":
        return [{
            "name": a,
            "defaultRule": "allow" if sg.default_allow else "deny",
            "ruleList": [{
                "name": r.alias,
                "network": f"{r.network}",
                "protocol": r.protocol.value,
                "portRange": f"{r.min_port},{r.max_port}",
                "rule": "allow" if r.allow else "deny",
            } for r in sg.rules],
        } for a, sg in app.security_groups.items()]
    if rtype == "cert-key":
        return [{"name": a, "cert": ck.cert_path, "key": ck.key_path,
                 "dnsNames": ck.dns_names}
                for a, ck in app.cert_keys.items()]
    if rtype == "switch":
        return [{
            "name": a, "address": f"{sw.bind_ip}:{sw.bind_port}",
            "vpcList": sorted(sw.networks.keys()),
            "ifaceCount": len(sw.list_ifaces()),
        } for a, sw in app.switches.items()]
    if rtype == "resp-controller":
        return [{"name": a, "address": f"{c.bind_ip}:{c.bind_port}"}
                for a, c in app.resp_controllers.items()]
    if rtype == "http-controller":
        return [{"name": a, "address": f"{c.bind_ip}:{c.bind_port}"}
                for a, c in app.http_controllers.items()]
    raise CmdError(f"no detail view for {rtype}")


class HttpController:
    def __init__(self, app: Application, bind_ip: str, bind_port: int,
                 loop: Optional[SelectorEventLoop] = None):
        self.app = app
        self.loop = loop or app.control_loop
        self.bind_ip, self.bind_port = bind_ip, bind_port
        self._srv: Optional[HttpServer] = None

    def start(self) -> None:
        from ..utils import failpoint, lifecycle
        srv = HttpServer(self.loop)

        def healthz(r: RoutingContext) -> None:
            # `draining` + 503 once graceful drain begins, so upstream
            # LBs probing this controller steer traffic away
            if lifecycle.is_draining():
                r.resp.status(503).end({"status": "draining"})
            else:
                r.resp.end({"status": "ok"})

        srv.get("/healthz", healthz)
        srv.get("/faults", lambda r: r.resp.end(failpoint.active()))

        def cluster(r: RoutingContext) -> None:
            # fleet view (cluster plane, docs/cluster.md): membership,
            # leader, rule generation + lag, step-loop state
            node = self.app.cluster
            r.resp.end({"enabled": False} if node is None
                       else node.status())

        srv.get("/cluster", cluster)

        def trace_ep(r: RoutingContext) -> None:
            # span-level request tracing (docs/observability.md):
            # summaries, or one trace's spans via ?id= — the same
            # payloads the inspection server's /trace serves
            from ..utils import trace as TR
            try:
                tid = int(r.req.query.get("id", "0"))
            except ValueError:
                tid = 0
            if tid:
                r.resp.end({"trace": tid, "spans": TR.get_trace(tid)})
            else:
                r.resp.end({"sample_every": TR.sample_every(),
                            "traces": TR.summaries()})

        srv.get("/trace", trace_ep)

        def analytics_ep(r: RoutingContext) -> None:
            # heavy-hitter tables (docs/observability.md traffic
            # analytics): local top-K per dimension + the fleet-merged
            # view when a cluster is booted — same payload as the
            # inspection server's /analytics (one shared assembly)
            from ..utils import sketch as SK
            out = SK.snapshot_with_fleet()
            # per-node policed attribution (the enforcement half of
            # the analytics loop)
            from ..policing import engine as PE
            node = self.app.cluster
            out["policing"] = (
                node.fleet_policing() if node is not None
                else {"self": PE.default().policed_by_node(),
                      "peers": {}})
            r.resp.end(out)

        srv.get("/analytics", analytics_ep)

        def policing_ep(r: RoutingContext) -> None:
            # Guardian enforcement surface (docs/robustness.md): engine
            # status + declared policies + the live per-key bucket
            # table — same payload as the inspection server's /policing
            from ..policing import engine as PE
            eng = PE.default()
            st = eng.status()
            st["policy_list"] = eng.list_policies()
            st["table"] = eng.table_snapshot()
            st["policed_by_node"] = eng.policed_by_node()
            st["shed_receipt"] = eng.shed_receipt()
            r.resp.end(st)

        srv.get("/policing", policing_ep)

        def workload_ep(r: RoutingContext) -> None:
            # the workload-capture artifact (utils/workload): the
            # current window's fitted model — same payload as the
            # inspection server's /workload, consumed live by
            # tools/replay.py (docs/replay.md)
            from ..utils import workload as WL
            r.resp.end(WL.export_model())

        srv.get("/workload", workload_ep)
        srv.post("/api/v1/command", self._command)
        srv.all("/api/v1/module/*", self._module)
        srv.listen(self.bind_port, self.bind_ip)
        self.bind_port = srv.port
        self._srv = srv

    def stop(self) -> None:
        if self._srv is not None:
            srv, self._srv = self._srv, None
            srv.close()

    # ----------------------------------------------------------- handlers

    def _command(self, r: RoutingContext) -> None:
        try:
            cmd = r.req.json().get("command", "")
            r.resp.end({"result": Command.execute(self.app, cmd)})
        except CmdError as e:
            r.resp.status(400).end({"error": str(e)})
        except json.JSONDecodeError as e:
            r.resp.status(400).end({"error": f"bad json: {e}"})
        except Exception as e:
            r.resp.status(500).end({"error": f"{type(e).__name__}: {e}"})

    def _module(self, r: RoutingContext) -> None:
        parts = [p for p in r.req.params.get("*", "").split("/") if p]
        if not parts or parts[0] not in MODULES:
            r.resp.status(404).end({"error": "no such module"})
            return
        rtype = MODULES[parts[0]]
        name = parts[1] if len(parts) > 1 else None
        sub = parts[2:] if len(parts) > 2 else []
        try:
            status, payload = self._dispatch(r.req.method, rtype, name, sub,
                                             r.req.body)
        except CmdError as e:
            status, payload = 400, {"error": str(e)}
        except json.JSONDecodeError as e:
            status, payload = 400, {"error": f"bad json: {e}"}
        except Exception as e:
            status, payload = 500, {"error": f"{type(e).__name__}: {e}"}
        r.resp.status(status).end(payload)

    @staticmethod
    def _cmdline(action: str, rtype: str, name: str, params: dict) -> str:
        toks = [action, rtype, name]
        for k, v in params.items():
            if k in FLAG_KEYS:
                if v:
                    toks.append(k)
            elif k == "annotations":
                toks += [k, json.dumps(v, separators=(",", ":"))
                         if isinstance(v, dict) else str(v)]
            else:
                toks += [k, str(v)]
        return " ".join(toks)

    # GET /module/{name}/<sub> answers from the typed object directly
    SUB_KEYS = {"server": "serverList", "server-group": "serverGroupList",
                "security-group-rule": "ruleList",
                "event-loop": "eventLoopList"}

    def _dispatch(self, method: str, rtype: str, name, sub, body: bytes):
        app = self.app
        if method == "GET":
            details = _details(app, rtype)
            if name is None:
                return 200, details
            obj = next((d for d in details if d["name"] == name), None)
            if obj is None:
                return 404, {"error": f"{rtype} {name} not found"}
            if not sub or sub == ["detail"]:
                return 200, obj
            key = self.SUB_KEYS.get(sub[0])
            if key is not None and key in obj:
                return 200, obj[key]
            return 200, Command.execute(
                app, f"list-detail {sub[0]} in {rtype} {name}")
        if method == "POST":
            params = json.loads(body or b"{}")
            if name is None:
                name = params.pop("name", None)
                if not name:
                    return 400, {"error": "name required"}
            if sub:  # POST /module/server-group/sg0/server {name, address,...}
                sname = params.pop("name", None)
                line = self._cmdline("add", sub[0], sname, params)
                line += f" to {rtype} {name}"
                return 200, {"result": Command.execute(app, line)}
            return 200, {"result": Command.execute(
                app, self._cmdline("add", rtype, name, params))}
        if method == "PUT":
            if name is None:
                return 405, {"error": "PUT requires a resource name"}
            params = json.loads(body or b"{}")
            return 200, {"result": Command.execute(
                app, self._cmdline("update", rtype, name, params))}
        if method == "DELETE":
            if name is None:
                return 405, {"error": "DELETE requires a resource name"}
            if sub:
                return 200, {"result": Command.execute(
                    app, f"remove {sub[0]} {sub[1]} from {rtype} {name}")}
            return 200, {"result": Command.execute(app,
                                                   f"force-remove {rtype} {name}")}
        return 405, {"error": f"method {method} not allowed"}
