"""HttpController — REST control surface.

Parity: app controller/HttpController.java (routes :59-320, swagger
doc/api.yaml): CRUD under /api/v1/module/<resource>, /healthz, plus a
raw command endpoint. Built on the embeddable vserver HTTP lib exactly
as the reference's controller is built on its vserver. JSON bodies use
the command grammar's param names; list endpoints return JSON arrays.
"""
from __future__ import annotations

import json
from typing import Optional

from ..lib.vserver import HttpServer, RoutingContext
from ..net.eventloop import SelectorEventLoop
from .app import Application
from .command import CmdError, Command

# url segment -> command resource type
MODULES = {
    "tcp-lb": "tcp-lb", "socks5-server": "socks5-server",
    "dns-server": "dns-server", "event-loop-group": "event-loop-group",
    "upstream": "upstream", "server-group": "server-group",
    "security-group": "security-group", "cert-key": "cert-key",
    "switch": "switch",
}
FLAG_KEYS = {"allow-non-backend", "deny-non-backend"}


class HttpController:
    def __init__(self, app: Application, bind_ip: str, bind_port: int,
                 loop: Optional[SelectorEventLoop] = None):
        self.app = app
        self.loop = loop or app.control_loop
        self.bind_ip, self.bind_port = bind_ip, bind_port
        self._srv: Optional[HttpServer] = None

    def start(self) -> None:
        srv = HttpServer(self.loop)
        srv.get("/healthz", lambda r: r.resp.end({"status": "ok"}))
        srv.post("/api/v1/command", self._command)
        srv.all("/api/v1/module/*", self._module)
        srv.listen(self.bind_port, self.bind_ip)
        self.bind_port = srv.port
        self._srv = srv

    def stop(self) -> None:
        if self._srv is not None:
            srv, self._srv = self._srv, None
            srv.close()

    # ----------------------------------------------------------- handlers

    def _command(self, r: RoutingContext) -> None:
        try:
            cmd = r.req.json().get("command", "")
            r.resp.end({"result": Command.execute(self.app, cmd)})
        except CmdError as e:
            r.resp.status(400).end({"error": str(e)})
        except json.JSONDecodeError as e:
            r.resp.status(400).end({"error": f"bad json: {e}"})
        except Exception as e:
            r.resp.status(500).end({"error": f"{type(e).__name__}: {e}"})

    def _module(self, r: RoutingContext) -> None:
        parts = [p for p in r.req.params.get("*", "").split("/") if p]
        if not parts or parts[0] not in MODULES:
            r.resp.status(404).end({"error": "no such module"})
            return
        rtype = MODULES[parts[0]]
        name = parts[1] if len(parts) > 1 else None
        sub = parts[2:] if len(parts) > 2 else []
        try:
            status, payload = self._dispatch(r.req.method, rtype, name, sub,
                                             r.req.body)
        except CmdError as e:
            status, payload = 400, {"error": str(e)}
        except json.JSONDecodeError as e:
            status, payload = 400, {"error": f"bad json: {e}"}
        except Exception as e:
            status, payload = 500, {"error": f"{type(e).__name__}: {e}"}
        r.resp.status(status).end(payload)

    @staticmethod
    def _cmdline(action: str, rtype: str, name: str, params: dict) -> str:
        toks = [action, rtype, name]
        for k, v in params.items():
            if k in FLAG_KEYS:
                if v:
                    toks.append(k)
            elif k == "annotations":
                toks += [k, json.dumps(v, separators=(",", ":"))
                         if isinstance(v, dict) else str(v)]
            else:
                toks += [k, str(v)]
        return " ".join(toks)

    def _dispatch(self, method: str, rtype: str, name, sub, body: bytes):
        app = self.app
        if method == "GET":
            if name is None:
                return 200, Command.execute(app, f"list-detail {rtype}")
            if sub:
                return 200, Command.execute(
                    app, f"list-detail {sub[0]} in {rtype} {name}")
            detail = Command.execute(app, f"list-detail {rtype}")
            for line in detail:
                if line.split(" ")[0] == name:
                    return 200, {"name": name, "detail": line}
            return 404, {"error": f"{rtype} {name} not found"}
        if method == "POST":
            params = json.loads(body or b"{}")
            if name is None:
                name = params.pop("name", None)
                if not name:
                    return 400, {"error": "name required"}
            if sub:  # POST /module/server-group/sg0/server {name, address,...}
                sname = params.pop("name", None)
                line = self._cmdline("add", sub[0], sname, params)
                line += f" to {rtype} {name}"
                return 200, {"result": Command.execute(app, line)}
            return 200, {"result": Command.execute(
                app, self._cmdline("add", rtype, name, params))}
        if method == "PUT":
            if name is None:
                return 405, {"error": "PUT requires a resource name"}
            params = json.loads(body or b"{}")
            return 200, {"result": Command.execute(
                app, self._cmdline("update", rtype, name, params))}
        if method == "DELETE":
            if name is None:
                return 405, {"error": "DELETE requires a resource name"}
            if sub:
                return 200, {"result": Command.execute(
                    app, f"remove {sub[0]} {sub[1]} from {rtype} {name}")}
            return 200, {"result": Command.execute(app,
                                                   f"force-remove {rtype} {name}")}
        return 405, {"error": f"method {method} not allowed"}
