"""HttpController — REST control surface.

Parity: app controller/HttpController.java (routes :59-320, swagger
doc/api.yaml): CRUD under /api/v1/module/<resource>, /healthz, plus a
raw command endpoint. JSON bodies use the command grammar's param names;
results of list endpoints are JSON arrays.
"""
from __future__ import annotations

import json
from typing import Optional

from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from ..processors.http1 import HeadParser
from .app import Application
from .command import CmdError, Command

# url segment -> command resource type
MODULES = {
    "tcp-lb": "tcp-lb", "socks5-server": "socks5-server",
    "dns-server": "dns-server", "event-loop-group": "event-loop-group",
    "upstream": "upstream", "server-group": "server-group",
    "security-group": "security-group", "cert-key": "cert-key",
}
FLAG_KEYS = {"allow-non-backend", "deny-non-backend"}


def _resp(status: int, body, ctype: str = "application/json") -> bytes:
    if isinstance(body, (dict, list)):
        data = json.dumps(body).encode()
    elif isinstance(body, str):
        data = body.encode()
    else:
        data = body or b""
    reason = {200: "OK", 204: "No Content", 400: "Bad Request",
              404: "Not Found", 405: "Method Not Allowed",
              500: "Internal Server Error"}.get(status, "OK")
    head = (f"HTTP/1.1 {status} {reason}\r\ncontent-type: {ctype}\r\n"
            f"content-length: {len(data)}\r\nconnection: close\r\n\r\n")
    return head.encode() + data


class _HttpConn(Handler):
    def __init__(self, ctl: "HttpController", conn: Connection):
        self.ctl = ctl
        self.conn = conn
        self.parser = HeadParser()
        self.body = b""
        self.handled = False
        conn.set_handler(self)

    def on_data(self, conn: Connection, data: bytes) -> None:
        if self.handled:
            # request already executed; the conn closes shortly — drop any
            # pipelined bytes rather than re-running the command
            return
        if not self.parser.done:
            self.parser.feed(data)
            if self.parser.error:
                conn.write(_resp(400, {"error": self.parser.error}))
                self.ctl.loop.delay(50, conn.close)
                return
            if not self.parser.done:
                return
            self.body = bytes(self.parser.buf[self.parser.head_len:])
        else:
            self.body += data
        cl = int(self.parser.header("content-length") or 0)
        if len(self.body) < cl:
            return
        self.handled = True
        status, payload = self._route(self.parser.method,
                                      self.parser.uri, self.body[:cl])
        conn.write(_resp(status, payload))
        self.ctl.loop.delay(50, conn.close)

    def _route(self, method: str, uri: str, body: bytes):
        app = self.ctl.app
        path = uri.split("?")[0].rstrip("/")
        try:
            if path == "/healthz":
                return 200, {"status": "ok"}
            if path == "/api/v1/command" and method == "POST":
                cmd = json.loads(body or b"{}").get("command", "")
                result = Command.execute(app, cmd)
                return 200, {"result": result}
            parts = [p for p in path.split("/") if p]
            # /api/v1/module/<type>[/<name>]
            if len(parts) >= 4 and parts[0] == "api" and parts[1] == "v1" \
                    and parts[2] == "module" and parts[3] in MODULES:
                rtype = MODULES[parts[3]]
                name = parts[4] if len(parts) > 4 else None
                sub = parts[5:] if len(parts) > 5 else []
                return self._module(method, rtype, name, sub, body)
            return 404, {"error": f"no such endpoint {path}"}
        except CmdError as e:
            return 400, {"error": str(e)}
        except json.JSONDecodeError as e:
            return 400, {"error": f"bad json: {e}"}
        except Exception as e:
            return 500, {"error": f"{type(e).__name__}: {e}"}

    @staticmethod
    def _cmdline(action: str, rtype: str, name: str, params: dict) -> str:
        toks = [action, rtype, name]
        for k, v in params.items():
            if k in FLAG_KEYS:
                if v:
                    toks.append(k)
            elif k == "annotations":
                toks += [k, json.dumps(v, separators=(",", ":"))
                         if isinstance(v, dict) else str(v)]
            else:
                toks += [k, str(v)]
        return " ".join(toks)

    def _module(self, method: str, rtype: str, name, sub, body: bytes):
        app = self.ctl.app
        if method == "GET":
            if name is None:
                return 200, Command.execute(app, f"list-detail {rtype}")
            # sub-resource listing e.g. /server-group/sg0/server
            if sub:
                return 200, Command.execute(
                    app, f"list-detail {sub[0]} in {rtype} {name}")
            detail = Command.execute(app, f"list-detail {rtype}")
            for line in detail:
                if line.split(" ")[0] == name:
                    return 200, {"name": name, "detail": line}
            return 404, {"error": f"{rtype} {name} not found"}
        if method == "POST":
            params = json.loads(body or b"{}")
            if name is None:
                name = params.pop("name", None)
                if not name:
                    return 400, {"error": "name required"}
            if sub:  # POST /module/server-group/sg0/server {name, address,...}
                sname = params.pop("name", None)
                line = self._cmdline("add", sub[0], sname, params)
                line += f" to {rtype} {name}"
                return 200, {"result": Command.execute(app, line)}
            return 200, {"result": Command.execute(
                app, self._cmdline("add", rtype, name, params))}
        if method == "PUT":
            if name is None:
                return 405, {"error": "PUT requires a resource name"}
            params = json.loads(body or b"{}")
            return 200, {"result": Command.execute(
                app, self._cmdline("update", rtype, name, params))}
        if method == "DELETE":
            if name is None:
                return 405, {"error": "DELETE requires a resource name"}
            if sub:
                return 200, {"result": Command.execute(
                    app, f"remove {sub[0]} {sub[1]} from {rtype} {name}")}
            return 200, {"result": Command.execute(app, f"force-remove {rtype} {name}")}
        return 405, {"error": f"method {method} not allowed"}


class HttpController:
    def __init__(self, app: Application, bind_ip: str, bind_port: int,
                 loop: Optional[SelectorEventLoop] = None):
        self.app = app
        self.loop = loop or app.control_loop
        self.bind_ip, self.bind_port = bind_ip, bind_port
        self._srv: Optional[ServerSock] = None

    def start(self) -> None:
        def mk() -> None:
            self._srv = ServerSock(self.loop, self.bind_ip, self.bind_port,
                                   self._on_accept)
            self.bind_port = self._srv.port
        self.loop.call_sync(mk)

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        _HttpConn(self, Connection(self.loop, fd, (ip, port)))

    def stop(self) -> None:
        if self._srv is not None:
            srv = self._srv
            self._srv = None
            self.loop.run_on_loop(srv.close)
