"""Command engine — the operator-facing grammar.

Parity: app cmd/Command.java (parse+validate+dispatch, 8 actions) and
cmd/handle/resource/* handlers, with the same vocabulary as
doc/command.md:

    $action $type [$alias] [in $type $alias] [to|from $type $alias]
            [$param-key $param-value]... [$flag]...

Actions: add(a), list(l), list-detail(L), update(u), remove(r),
force-remove(R); `add ... to ...` attaches, `remove ... from ...`
detaches. All controllers (stdio / RESP / HTTP) funnel into
Command.execute on the control loop, mirroring the reference's
control-plane isolation (doc/architecture.md:64-66).
"""
from __future__ import annotations

import json
from typing import Optional

from ..components.secgroup import SecurityGroup
from ..components.servergroup import HealthCheckConfig, ServerGroup
from ..components.socks5 import Socks5Server
from ..components.tcplb import TcpLB
from ..components.upstream import Upstream
from ..components.elgroup import EventLoopGroup
from ..dns.server import DNSServer
from ..rules.ir import AclRule, HintRule, Proto
from ..utils.ip import Network, format_ip
from .app import (Application, DEFAULT_ACCEPTOR_ELG, DEFAULT_WORKER_ELG)

ACTIONS = {"add": "add", "a": "add", "list": "list", "l": "list",
           "list-detail": "list-detail", "L": "list-detail",
           "update": "update", "u": "update", "remove": "remove",
           "r": "remove", "force-remove": "force-remove", "R": "force-remove"}

TYPES = {
    "tcp-lb": "tcp-lb", "tl": "tcp-lb",
    "socks5-server": "socks5-server", "socks5": "socks5-server",
    "dns-server": "dns-server", "dns": "dns-server",
    "event-loop-group": "event-loop-group", "elg": "event-loop-group",
    "event-loop": "event-loop", "el": "event-loop",
    "upstream": "upstream", "ups": "upstream",
    "server-group": "server-group", "sg": "server-group",
    "server": "server", "svr": "server",
    "security-group": "security-group", "secg": "security-group",
    "security-group-rule": "security-group-rule", "secgr": "security-group-rule",
    "cert-key": "cert-key", "ck": "cert-key",
    "switch": "switch", "sw": "switch",
    "vpc": "vpc",
    "iface": "iface",
    "route": "route",
    "arp": "arp",
    "user": "user",
    "user-client": "user-client", "ucli": "user-client",
    "tap": "tap",
    "ip": "ip",
    "server-sock": "server-sock", "ss": "server-sock",
    "connection": "connection", "conn": "connection",
    "session": "session", "sess": "session",
    "bytes-in": "bytes-in", "bin": "bytes-in",
    "bytes-out": "bytes-out", "bout": "bytes-out",
    "accepted-conn-count": "accepted-conn-count",
    "dns-cache": "dns-cache",
    "resolver": "resolver",
    "proxy": "proxy",
    "resp-controller": "resp-controller",
    "http-controller": "http-controller",
    "docker-network-plugin-controller": "docker-network-plugin-controller",
    "event-log": "event-log", "events": "event-log",
    "fault": "fault", "failpoint": "fault",
    "cluster-node": "cluster-node", "cn": "cluster-node",
    "trace": "trace",
    "analytics": "analytics",
    "policy": "policy", "pol": "policy",
}

PARAM_KEYS = {
    "address": "address", "addr": "address",
    "upstream": "upstream", "ups": "upstream",
    "event-loop-group": "elg", "elg": "elg",
    "acceptor-elg": "aelg", "aelg": "aelg",
    "in-buffer-size": "in-buffer-size", "out-buffer-size": "out-buffer-size",
    "protocol": "protocol",
    "security-group": "secg", "secg": "secg",
    "cert-key": "ck", "ck": "ck",
    "cert": "cert", "key": "key",
    "ttl": "ttl", "timeout": "timeout", "period": "period",
    "up": "up", "down": "down", "method": "method",
    "weight": "weight", "w": "weight",
    "annotations": "annotations", "default": "default",
    "network": "network", "net": "network",
    "port-range": "port-range",
    "vni": "vni", "v4network": "v4network", "v6network": "v6network",
    "password": "password", "pass": "password",
    "via": "via", "mac": "mac",
    "mac-table-timeout": "mac-table-timeout",
    "arp-table-timeout": "arp-table-timeout",
    "path": "path", "post-script": "post-script",
    "probability": "probability", "prob": "probability",
    "count": "count", "match": "match",
    "max-sessions": "max-sessions",
    "pool-size": "pool-size",
    "lanes": "lanes",
    "overload": "overload",
    "seed": "seed",
    "plane": "plane",
    "since": "since", "until": "until",
    "dim": "dim", "rate": "rate", "burst": "burst",
    "action": "action", "tenant": "tenant",
}

FLAGS = {"allow-non-backend", "deny-non-backend", "noipv4", "noipv6"}

ANNO_HOST = "vproxy/hint-host"
ANNO_PORT = "vproxy/hint-port"
ANNO_URI = "vproxy/hint-uri"


class CmdError(Exception):
    pass


class Command:
    def __init__(self):
        self.action = ""
        self.type = ""
        self.alias: Optional[str] = None
        self.contexts: list[tuple[str, str]] = []  # `in` chain, innermost first
        self.target: Optional[tuple[str, str]] = None  # to/from
        self.params: dict[str, str] = {}
        self.flags: set[str] = set()

    # ------------------------------------------------------------ parsing

    @staticmethod
    def parse(line: str) -> "Command":
        toks = line.split()
        if not toks:
            raise CmdError("empty command")
        c = Command()
        if toks[0] not in ACTIONS:
            raise CmdError(f"unknown action {toks[0]!r}")
        c.action = ACTIONS[toks[0]]
        if len(toks) < 2 or toks[1] not in TYPES:
            raise CmdError(f"unknown resource type {toks[1] if len(toks) > 1 else ''!r}")
        c.type = TYPES[toks[1]]
        i = 2
        if c.action not in ("list", "list-detail"):
            if i >= len(toks):
                raise CmdError("resource alias required")
            c.alias = toks[i]
            i += 1
        while i < len(toks):
            t = toks[i]
            if t == "in":
                if i + 2 >= len(toks) - 0 and i + 2 > len(toks) - 1:
                    raise CmdError("`in` requires type and alias")
                if toks[i + 1] not in TYPES:
                    raise CmdError(f"unknown resource type {toks[i+1]!r}")
                c.contexts.append((TYPES[toks[i + 1]], toks[i + 2]))
                i += 3
            elif t in ("to", "from"):
                if i + 2 > len(toks) - 1:
                    raise CmdError(f"`{t}` requires type and alias")
                if toks[i + 1] not in TYPES:
                    raise CmdError(f"unknown resource type {toks[i+1]!r}")
                c.target = (TYPES[toks[i + 1]], toks[i + 2])
                i += 3
            elif t in PARAM_KEYS:
                if i + 1 > len(toks) - 1:
                    raise CmdError(f"param {t} requires a value")
                key = PARAM_KEYS[t]
                val = toks[i + 1]
                # annotations value is json and may contain spaces: re-join
                if key == "annotations" and val.startswith("{") and not val.endswith("}"):
                    j = i + 2
                    while j < len(toks) and not toks[j - 1].endswith("}"):
                        val += " " + toks[j]
                        j += 1
                    i = j - 2
                c.params[key] = val
                i += 2
            elif t in FLAGS:
                c.flags.add(t)
                i += 1
            elif "=" in t and t.split("=", 1)[0] in PARAM_KEYS:
                # k=v param form (`add policy gold dim=clients rate=50
                # burst=100 action=shed`): same keys, same params dict —
                # the compact spelling the policing grammar and the
                # persisted command log use
                k, v = t.split("=", 1)
                if not v:
                    raise CmdError(f"param {k} requires a value")
                c.params[PARAM_KEYS[k]] = v
                i += 1
            else:
                raise CmdError(f"unexpected token {t!r}")
        return c

    # ---------------------------------------------------------- execution

    @staticmethod
    def execute(app: Application, line: str):
        if line.strip() == "drain":
            # bare verb outside the resource grammar (like the repl's
            # `exit`): begin graceful drain — close listeners, flip
            # /healthz to draining, let pumps finish, then main exits
            return app.request_drain()
        toks = line.split()
        if toks and toks[0] == "top" and len(toks) <= 3:
            # `top [clients|backends|routes|flows|qnames] [fleet]`: the
            # heavy-hitter table of one dimension (utils/sketch), local
            # or fleet-merged. Bare verb like `drain`/`trace <id>`;
            # `list[-detail] analytics` is the full-surface view.
            from ..utils import sketch as SK
            if len(toks) == 1:
                raise CmdError("top requires a dimension: "
                               + "|".join(SK.DIMS))
            dim = toks[1]
            if dim not in SK.DIMS:
                raise CmdError(f"unknown top dimension {dim!r} "
                               f"(one of {', '.join(SK.DIMS)})")
            if len(toks) == 3 and toks[2] != "fleet":
                raise CmdError(f"unexpected token {toks[2]!r} "
                               "(only `fleet`)")
            if not SK.enabled():
                return ["analytics disabled (VPROXY_TPU_ANALYTICS=0)"]
            if len(toks) == 3:
                cluster = getattr(app, "cluster", None)
                if cluster is None:
                    raise CmdError("no cluster plane booted; `top "
                                   f"{dim}` serves the local view")
                rows = cluster.fleet_analytics()[dim]
                return SK.render_top(dim, rows)
            return SK.render_top(dim)
        if len(toks) == 2 and toks[0] == "trace":
            # `trace <id>`: one sampled request's span waterfall (the
            # cross-plane attribution view — utils/trace). Bare verb
            # like `drain`; `list[-detail] trace` lists the buffer.
            from ..utils import trace as TR
            try:
                tid = int(toks[1])
            except ValueError:
                raise CmdError(f"trace id must be an integer, "
                               f"got {toks[1]!r}")
            return TR.waterfall(tid)
        if toks and toks[0] == "capture" and len(toks) <= 3:
            # `capture start|stop|export|status [seed <n>]`: the
            # workload-capture window (utils/workload). Bare verb like
            # `drain`/`top`; export prints the versioned model JSON a
            # replay run consumes (docs/replay.md), with the seed
            # stamped in so the artifact carries its own determinism.
            from ..utils import workload as WL
            if len(toks) == 1:
                raise CmdError("capture requires a verb: "
                               "start|stop|export|status")
            verb, seed = toks[1], None
            if len(toks) == 3:
                k, _, v = toks[2].partition("=")
                if k != "seed" or not v:
                    raise CmdError(f"unexpected token {toks[2]!r} "
                                   "(only seed=<int>)")
                try:
                    seed = int(v)
                except ValueError:
                    raise CmdError(f"seed must be an integer, got {v!r}")
            try:
                out = WL.capture(verb, seed=seed)
            except ValueError as e:
                raise CmdError(str(e))
            if verb == "export":
                return [WL.WorkloadModel(out).to_json()]
            return [f"capture {out['state']} "
                    f"(enabled={out['enabled']}, "
                    f"window={out['window_s']}s)"]
        c = Command.parse(line)
        handler = _HANDLERS.get(c.type)
        if handler is None:
            raise CmdError(f"no handler for resource type {c.type}")
        # cluster replication hook (cluster/replicate.py): a mutation
        # against a replicated resource type becomes the next rule
        # generation on the LEADER. Followers reject it outright —
        # accepting it would silently diverge their tables until the
        # next checksum heal tore the mutation (and every live
        # listener) back down. The mutation lock makes (apply, bump)
        # atomic against concurrent follower syncs.
        cluster = getattr(app, "cluster", None)
        replicated = False
        if cluster is not None and c.action not in ("list", "list-detail"):
            from ..cluster.replicate import REPLICATED_TYPES
            replicated = c.type in REPLICATED_TYPES
        if replicated:
            repl = cluster.replicator
            if not repl._applying:
                if not cluster.membership.is_leader():
                    raise CmdError(
                        f"this node is a cluster follower; issue "
                        f"mutations on the leader (node "
                        f"{cluster.membership.leader_id()}) — followers "
                        "converge via replication (docs/cluster.md)")
                behind = repl._fleet_ahead()
                if behind is not None:
                    # leader by id, stale by state (a rolling restart
                    # brought the lowest id back behind the fleet):
                    # accepting a mutation here would journal it into a
                    # generation the catch-up snapshot is about to wipe
                    # — acknowledged, then silently lost. Refuse until
                    # the catch-up sync converges.
                    raise CmdError(
                        f"this node leads by id but is behind the "
                        f"fleet (peer {behind[0]} at generation "
                        f"{behind[1]}, local {repl.generation}); "
                        "catching up — retry once converged")
            with repl.mutation_lock:
                result = handler(app, c)
                cluster.on_command(line)
            return result
        return handler(app, c)


# ---------------------------------------------------------------- helpers

def _need(app_dict: dict, alias: str, kind: str):
    if alias not in app_dict:
        raise CmdError(f"{kind} {alias!r} not found")
    return app_dict[alias]


def _opt_elg(app: Application, c: Command, key: str, default):
    if key not in c.params:
        return default
    return _need(app.elgs, c.params[key], "event-loop-group")


def _opt_secg(app: Application, c: Command):
    if "secg" not in c.params:
        return None
    return _need(app.security_groups, c.params["secg"], "security-group")


def _addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    try:
        return host, int(port)
    except ValueError:
        raise CmdError(f"invalid address {s!r}")


def _anno_to_rule(anno_json: str) -> HintRule:
    try:
        d = json.loads(anno_json)
    except json.JSONDecodeError as e:
        raise CmdError(f"annotations must be json: {e}")
    return HintRule(host=d.get(ANNO_HOST), port=int(d.get(ANNO_PORT, 0)),
                    uri=d.get(ANNO_URI))


def _anno_dict(raw: str) -> dict:
    """Generic annotations param (json object) for vpc/tap resources."""
    try:
        d = json.loads(raw)
    except json.JSONDecodeError:
        raise CmdError(f"bad annotations json {raw!r}")
    if not isinstance(d, dict):
        raise CmdError("annotations must be a json object")
    return d


def _rule_to_anno(rule: HintRule) -> str:
    d = {}
    if rule.host is not None:
        d[ANNO_HOST] = rule.host
    if rule.port:
        d[ANNO_PORT] = str(rule.port)
    if rule.uri is not None:
        d[ANNO_URI] = rule.uri
    return json.dumps(d, separators=(",", ":"))


# ---------------------------------------------------------------- handlers

def _h_elg(app: Application, c: Command):
    if c.action == "add":
        if c.alias in app.elgs:
            raise CmdError(f"event-loop-group {c.alias} already exists")
        app.elgs[c.alias] = EventLoopGroup(c.alias, 0)
        return "OK"
    if c.action in ("list", "list-detail"):
        return list(app.elgs.keys())
    if c.action in ("remove", "force-remove"):
        elg = _need(app.elgs, c.alias, "event-loop-group")
        if c.alias in (DEFAULT_WORKER_ELG, DEFAULT_ACCEPTOR_ELG, "(control-elg)"):
            raise CmdError(f"cannot remove built-in {c.alias}")
        elg.close()
        del app.elgs[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for event-loop-group")


def _h_el(app: Application, c: Command):
    ctx = c.target or (c.contexts[0] if c.contexts else None)
    if ctx is None or ctx[0] != "event-loop-group":
        raise CmdError("event-loop requires `in/to event-loop-group <name>`")
    elg = _need(app.elgs, ctx[1], "event-loop-group")
    if c.action == "add":
        elg.add_loop(c.alias)
        return "OK"
    if c.action in ("list", "list-detail"):
        return elg.loop_names()
    if c.action in ("remove", "force-remove"):
        try:
            elg.remove_loop(c.alias)
        except KeyError:
            raise CmdError(f"event-loop {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for event-loop")


def _h_ups(app: Application, c: Command):
    if c.action == "add":
        if c.alias in app.upstreams:
            raise CmdError(f"upstream {c.alias} already exists")
        app.upstreams[c.alias] = Upstream(c.alias)
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.upstreams.keys())
        out = []
        for u in app.upstreams.values():
            m = u._matcher
            fs = m.fused_stat() if hasattr(m, "fused_stat") \
                else {"available": False}
            fused = (f"fused on({fs.get('kernel')},"
                     f"{fs.get('packed_bytes', 0)}B)"
                     if fs.get("available") else "fused off")
            out.append(
                f"{u.alias} -> groups {len(u.handles)} backend {m.backend} "
                f"rules {m.size()} generation {m.generation} "
                f"table-bytes {m.published_table_bytes()} "
                f"checksum {m.checksum():#010x} {fused}")
        return out
    if c.action in ("remove", "force-remove"):
        ups = _need(app.upstreams, c.alias, "upstream")
        if c.action == "remove":
            users = [lb.alias for lb in list(app.tcp_lbs.values())
                     + list(app.socks5_servers.values()) if lb.backend is ups]
            users += [d.alias for d in app.dns_servers.values() if d.rrsets is ups]
            if users:
                raise CmdError(f"upstream {c.alias} is in use by {users}")
        del app.upstreams[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for upstream")


def _h_sg(app: Application, c: Command):
    if c.action == "add" and c.target is not None:
        # attach: add server-group sg0 to upstream ups0 weight 10
        if c.target[0] != "upstream":
            raise CmdError("server-group can only be attached to upstream")
        sg = _need(app.server_groups, c.alias, "server-group")
        ups = _need(app.upstreams, c.target[1], "upstream")
        weight = int(c.params.get("weight", 10))
        anno = _anno_to_rule(c.params["annotations"]) if "annotations" in c.params else None
        ups.add(sg, weight, anno)
        return "OK"
    if c.action == "add":
        if c.alias in app.server_groups:
            raise CmdError(f"server-group {c.alias} already exists")
        hc = HealthCheckConfig(
            timeout_ms=int(c.params.get("timeout", 2000)),
            period_ms=int(c.params.get("period", 5000)),
            up=int(c.params.get("up", 2)),
            down=int(c.params.get("down", 3)),
            protocol=c.params.get("protocol", "tcp"))
        elg = _opt_elg(app, c, "elg", app.worker_elg)
        anno = _anno_to_rule(c.params["annotations"]) if "annotations" in c.params else None
        app.server_groups[c.alias] = ServerGroup(
            c.alias, elg, hc, c.params.get("method", "wrr"), anno)
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.contexts and c.contexts[0][0] == "upstream":
            ups = _need(app.upstreams, c.contexts[0][1], "upstream")
            if c.action == "list":
                return [h.alias for h in ups.handles]
            return [f"{h.alias} -> weight {h.weight} annotations {_rule_to_anno(h.merged_rule())}"
                    for h in ups.handles]
        if c.action == "list":
            return list(app.server_groups.keys())
        out = []
        for g in app.server_groups.values():
            out.append(f"{g.alias} -> timeout {g.hc.timeout_ms} period {g.hc.period_ms} "
                       f"up {g.hc.up} down {g.hc.down} protocol {g.hc.protocol} "
                       f"method {g.method} event-loop-group {g.elg.name} "
                       f"annotations {_rule_to_anno(g.annotations)}")
        return out
    if c.action == "update":
        sg = _need(app.server_groups, c.alias, "server-group")
        if c.contexts and c.contexts[0][0] == "upstream":
            ups = _need(app.upstreams, c.contexts[0][1], "upstream")
            for h in ups.handles:
                if h.group is sg:
                    if "weight" in c.params:
                        h.weight = int(c.params["weight"])
                    if "annotations" in c.params:
                        h.annotations = _anno_to_rule(c.params["annotations"])
                    ups._recalc()
                    return "OK"
            raise CmdError(f"server-group {c.alias} not attached to {c.contexts[0][1]}")
        if any(k in c.params for k in ("timeout", "period", "up", "down", "protocol")):
            sg.hc = HealthCheckConfig(
                timeout_ms=int(c.params.get("timeout", sg.hc.timeout_ms)),
                period_ms=int(c.params.get("period", sg.hc.period_ms)),
                up=int(c.params.get("up", sg.hc.up)),
                down=int(c.params.get("down", sg.hc.down)),
                protocol=c.params.get("protocol", sg.hc.protocol))
        if "method" in c.params:
            if c.params["method"] not in ServerGroup.METHODS:
                raise CmdError(f"unknown method {c.params['method']}")
            sg.method = c.params["method"]
        if "annotations" in c.params:
            sg.annotations = _anno_to_rule(c.params["annotations"])
            for ups in app.upstreams.values():
                if any(h.group is sg for h in ups.handles):
                    ups._recalc()
        return "OK"
    if c.action in ("remove", "force-remove"):
        sg = _need(app.server_groups, c.alias, "server-group")
        if c.target is not None:  # remove ... from upstream
            if c.target[0] != "upstream":
                raise CmdError("server-group can only be detached from upstream")
            ups = _need(app.upstreams, c.target[1], "upstream")
            ups.remove(sg)
            return "OK"
        users = [u.alias for u in app.upstreams.values()
                 if any(h.group is sg for h in u.handles)]
        if users and c.action == "remove":
            raise CmdError(f"server-group {c.alias} is in use by upstream {users}")
        for u in app.upstreams.values():
            if any(h.group is sg for h in u.handles):
                u.remove(sg)
        sg.close()
        del app.server_groups[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for server-group")


def _h_svr(app: Application, c: Command):
    ctx = c.target or (c.contexts[0] if c.contexts else None)
    if ctx is None or ctx[0] != "server-group":
        raise CmdError("server requires `in/to server-group <name>`")
    sg = _need(app.server_groups, ctx[1], "server-group")
    if c.action == "add":
        ip, port = _addr(c.params["address"])
        sg.add(c.alias, ip, port, int(c.params.get("weight", 10)))
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return [s.name for s in sg.servers]
        return [f"{s.name} -> connect-to {s.ip}:{s.port} weight {s.weight} "
                f"currently {'UP' if s.healthy else 'DOWN'}"
                for s in sg.servers]
    if c.action == "update":
        sg.set_weight(c.alias, int(c.params["weight"]))
        return "OK"
    if c.action in ("remove", "force-remove"):
        try:
            sg.remove(c.alias)
        except KeyError:
            raise CmdError(f"server {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for server")


def _h_secg(app: Application, c: Command):
    if c.action == "add":
        if c.alias in app.security_groups:
            raise CmdError(f"security-group {c.alias} already exists")
        default = c.params.get("default", "allow")
        if default not in ("allow", "deny"):
            raise CmdError("default must be allow or deny")
        app.security_groups[c.alias] = SecurityGroup(c.alias, default == "allow")
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.security_groups.keys())
        return [f"{g.alias} -> default {'allow' if g.default_allow else 'deny'}"
                for g in app.security_groups.values()]
    if c.action == "update":
        g = _need(app.security_groups, c.alias, "security-group")
        if "default" in c.params:
            g.default_allow = c.params["default"] == "allow"
        return "OK"
    if c.action in ("remove", "force-remove"):
        g = _need(app.security_groups, c.alias, "security-group")
        users = [lb.alias for lb in list(app.tcp_lbs.values())
                 + list(app.socks5_servers.values()) if lb.security_group is g]
        if users and c.action == "remove":
            raise CmdError(f"security-group {c.alias} is in use by {users}")
        del app.security_groups[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for security-group")


def _h_secgr(app: Application, c: Command):
    ctx = c.target or (c.contexts[0] if c.contexts else None)
    if ctx is None or ctx[0] != "security-group":
        raise CmdError("security-group-rule requires `in/to security-group <name>`")
    g = _need(app.security_groups, ctx[1], "security-group")
    if c.action == "add":
        net = Network.parse(c.params["network"])
        proto = Proto(c.params.get("protocol", "tcp").lower())
        pr = c.params.get("port-range", "1,65535").split(",")
        default = c.params.get("default", "allow")
        g.add_rule(AclRule(c.alias, net, proto, int(pr[0]), int(pr[1]),
                           default == "allow"))
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return [r.alias for r in g.rules]
        return [f"{r.alias} -> allow {r.network} protocol {r.protocol.value} "
                f"port [{r.min_port},{r.max_port}] {'allow' if r.allow else 'deny'}"
                for r in g.rules]
    if c.action in ("remove", "force-remove"):
        try:
            g.remove_rule(c.alias)
        except KeyError:
            raise CmdError(f"security-group-rule {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for security-group-rule")


def _h_ck(app: Application, c: Command):
    from ..components.certkey import CertKey
    if c.action == "add":
        if c.alias in app.cert_keys:
            raise CmdError(f"cert-key {c.alias} already exists")
        if "cert" not in c.params or "key" not in c.params:
            raise CmdError("cert-key requires `cert <pem>` and `key <pem>`")
        try:
            app.cert_keys[c.alias] = CertKey(c.alias, c.params["cert"],
                                             c.params["key"])
        except (OSError, ValueError) as e:
            raise CmdError(f"cannot load cert-key: {e}")
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.cert_keys.keys())
        return [f"{ck.alias} -> cert {ck.cert_path} key {ck.key_path} "
                f"names {','.join(ck.dns_names)}"
                for ck in app.cert_keys.values()]
    if c.action in ("remove", "force-remove"):
        ck = _need(app.cert_keys, c.alias, "cert-key")
        users = [lb.alias for lb in app.tcp_lbs.values() if ck in lb.cert_keys]
        if users and c.action == "remove":
            raise CmdError(f"cert-key {c.alias} is in use by {users}")
        del app.cert_keys[c.alias]
        ck.close_native()  # release the native SSL_CTX (live refs stay)
        return "OK"
    raise CmdError(f"unsupported action {c.action} for cert-key")


def _h_tl(app: Application, c: Command):
    if c.action == "add":
        if c.alias in app.tcp_lbs:
            raise CmdError(f"tcp-lb {c.alias} already exists")
        ip, port = _addr(c.params["address"])
        ups = _need(app.upstreams, c.params["upstream"], "upstream")
        aelg = _opt_elg(app, c, "aelg", app.acceptor_elg)
        elg = _opt_elg(app, c, "elg", app.worker_elg)
        secg = _opt_secg(app, c)
        cks = None
        if "ck" in c.params:
            cks = [_need(app.cert_keys, a, "cert-key")
                   for a in c.params["ck"].split(",")]
        if c.params.get("overload", "") not in ("", "static", "adaptive"):
            raise CmdError(f"overload {c.params['overload']!r}: "
                           "expected static or adaptive")
        lb = TcpLB(c.alias, aelg, elg, ip, port, ups,
                   protocol=c.params.get("protocol", "tcp"),
                   security_group=secg,
                   in_buffer_size=int(c.params.get("in-buffer-size", 16384)),
                   timeout_ms=(_pos_int(c, "timeout")
                               if "timeout" in c.params else 900_000),
                   cert_keys=cks,
                   max_sessions=(_nonneg_int(c, "max-sessions")
                                 if "max-sessions" in c.params else 0),
                   pool_size=(_nonneg_int(c, "pool-size")
                              if "pool-size" in c.params else -1),
                   lanes=(_nonneg_int(c, "lanes")
                          if "lanes" in c.params else -1),
                   overload=c.params.get("overload", ""))
        lb.start()
        app.tcp_lbs[c.alias] = lb
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.tcp_lbs.keys())
        return [f"{lb.alias} -> acceptor {lb.acceptor.name} worker {lb.worker.name} "
                f"bind {lb.bind_ip}:{lb.bind_port} backend {lb.backend.alias} "
                f"in-buffer-size {lb.in_buffer_size} protocol {lb.protocol} "
                f"security-group {lb.security_group.alias}"
                + _lane_summary(lb) + _maglev_summary(lb)
                + _overload_summary(lb)
                for lb in app.tcp_lbs.values()]
    if c.action == "update":
        lb = _need(app.tcp_lbs, c.alias, "tcp-lb")
        if "in-buffer-size" in c.params:
            lb.in_buffer_size = int(c.params["in-buffer-size"])
        if "secg" in c.params:
            lb.set_security_group(_need(app.security_groups,
                                        c.params["secg"],
                                        "security-group"))
        # validate/build EVERYTHING before applying anything: a failed
        # command must not leave the LB half-updated
        new_timeout = _pos_int(c, "timeout") if "timeout" in c.params else None
        if "ck" in c.params:
            cks = [_need(app.cert_keys, a, "cert-key")
                   for a in c.params["ck"].split(",")]
            try:
                lb.set_cert_keys(cks)  # builds the holder first; may raise
            except Exception as e:  # bad cert/key file: old certs stay
                raise CmdError(f"cert swap failed (nothing changed): {e}")
        if new_timeout is not None:  # hot-settable (TcpLB.java:294-320)
            lb.set_timeout(new_timeout)
        if "max-sessions" in c.params:  # hot-set the overload guard;
            # 0 restores the default ceiling (same convention as add).
            # set_max_sessions also forwards the bound to the C lanes.
            lb.set_max_sessions(_nonneg_int(c, "max-sessions"))
        if "pool-size" in c.params:  # hot-set the warm backend pool
            # (0 = off); existing pools drain and respawn at the new size
            lb.set_pool_size(_nonneg_int(c, "pool-size"))
        if "overload" in c.params:  # hot-flip static <-> adaptive
            try:
                lb.set_overload_mode(c.params["overload"])
            except ValueError as e:
                raise CmdError(str(e))
        return "OK"
    if c.action in ("remove", "force-remove"):
        lb = _need(app.tcp_lbs, c.alias, "tcp-lb")
        lb.stop()
        del app.tcp_lbs[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for tcp-lb")


def _lane_summary(lb) -> str:
    """`list-detail tcp-lb` lane column: off, or
    on(n,engine=uring|epoll,gen,served,punts,hit-rate)."""
    lanes = lb.lanes  # local: a concurrent stop() may None the attr
    if lanes is None:
        return " lanes off"
    st = lanes.stat()  # stat() itself locks against lanes_free
    if not st.get("on"):
        return " lanes off"
    return (f" lanes on(n={st['lanes']},engine={st['engine']},"
            f"gen={st['gen']},served={st['served']},punts={st['punts']},"
            f"hit-rate={st['hit_rate']})")


def _maglev_summary(lb) -> str:
    """`list-detail tcp-lb` maglev column: off, or the consistent-hash
    tables this LB routes through (C lane route and/or source-method
    group tables) with size, generation and last-resize remap."""
    st = lb.maglev_stat()
    parts = []
    if st["lanes"] is not None:
        ln = st["lanes"]
        parts.append(f"lanes(m={ln.get('m')},gen={ln.get('gen')},"
                     f"remap={ln.get('last_remap')})")
    for g in st["groups"]:
        parts.append(f"{g['group']}(m={g['m']},backends={g['backends']},"
                     f"remap={g['last_remap']})")
    if not parts:
        return " maglev off"
    return " maglev " + "+".join(parts)


def _overload_summary(lb) -> str:
    """`list-detail tcp-lb` overload column: the admission mode and,
    when adaptive, the live controller state (moving ceiling + the
    EWMAs it is steering on)."""
    st = lb.overload_stat()
    if st["mode"] == "static":
        return f" overload static(max={st['maxSessions']})"
    return (f" overload adaptive(ceiling={st['ceiling']},"
            f"max={st['maxSessions']},floor={st['floor']},"
            f"stall-ewma-ms={st['stallEwmaMs']},"
            f"accept-ewma-ms={st['acceptEwmaMs']})")


def _h_socks5(app: Application, c: Command):
    if c.action == "add":
        if c.alias in app.socks5_servers:
            raise CmdError(f"socks5-server {c.alias} already exists")
        ip, port = _addr(c.params["address"])
        ups = _need(app.upstreams, c.params["upstream"], "upstream")
        aelg = _opt_elg(app, c, "aelg", app.acceptor_elg)
        elg = _opt_elg(app, c, "elg", app.worker_elg)
        secg = _opt_secg(app, c)
        s = Socks5Server(c.alias, aelg, elg, ip, port, ups,
                         security_group=secg,
                         allow_non_backend="allow-non-backend" in c.flags,
                         in_buffer_size=int(c.params.get("in-buffer-size", 16384)),
                         timeout_ms=(_pos_int(c, "timeout")
                                     if "timeout" in c.params else 900_000))
        s.start()
        app.socks5_servers[c.alias] = s
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.socks5_servers.keys())
        return [f"{s.alias} -> bind {s.bind_ip}:{s.bind_port} backend {s.backend.alias} "
                f"{'allow' if s.allow_non_backend else 'deny'}-non-backend"
                for s in app.socks5_servers.values()]
    if c.action == "update":
        s = _need(app.socks5_servers, c.alias, "socks5-server")
        if "allow-non-backend" in c.flags:
            s.allow_non_backend = True
        if "deny-non-backend" in c.flags:
            s.allow_non_backend = False
        if "in-buffer-size" in c.params:
            s.in_buffer_size = int(c.params["in-buffer-size"])
        if "secg" in c.params:
            s.security_group = _need(app.security_groups, c.params["secg"],
                                     "security-group")
        if "timeout" in c.params:
            s.set_timeout(_pos_int(c, "timeout"))
        return "OK"
    if c.action in ("remove", "force-remove"):
        s = _need(app.socks5_servers, c.alias, "socks5-server")
        s.stop()
        del app.socks5_servers[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for socks5-server")


def _mk_resource_resolver(app: Application):
    """`<alias>.<type>.vproxy.local` -> the live resource's bind address
    (the resource-introspection arm of DNSServer._run_internal). Types:
    tcp-lb, socks5-server, dns-server, switch."""
    from ..utils.ip import parse_ip as _pip

    def resolve(sub: str):
        if "." not in sub:
            return None
        alias, rtype = sub.split(".", 1)
        holder = {"tcp-lb": app.tcp_lbs,
                  "socks5-server": app.socks5_servers,
                  "dns-server": app.dns_servers,
                  "switch": app.switches}.get(rtype)
        res = holder.get(alias) if holder is not None else None
        if res is None:
            return None
        ip = getattr(res, "bind_ip", None)
        if ip is None:
            return None
        try:
            return _pip(ip)
        except (OSError, ValueError):
            return None

    return resolve


def _h_dns(app: Application, c: Command):
    if c.action == "add":
        if c.alias in app.dns_servers:
            raise CmdError(f"dns-server {c.alias} already exists")
        ip, port = _addr(c.params["address"])
        ups = _need(app.upstreams, c.params["upstream"], "upstream")
        elg = _opt_elg(app, c, "elg", app.worker_elg)
        secg = _opt_secg(app, c)
        d = DNSServer(c.alias, elg.next(), ip, port, ups, elg=elg,
                      ttl=int(c.params.get("ttl", 0)), security_group=secg,
                      resource_resolver=_mk_resource_resolver(app))
        d.start()
        app.dns_servers[c.alias] = d
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.dns_servers.keys())
        return [f"{d.alias} -> bind {d.bind_ip}:{d.bind_port} rrsets {d.rrsets.alias} "
                f"ttl {d.ttl}" for d in app.dns_servers.values()]
    if c.action == "update":
        d = _need(app.dns_servers, c.alias, "dns-server")
        if "ttl" in c.params:
            d.ttl = int(c.params["ttl"])
        return "OK"
    if c.action in ("remove", "force-remove"):
        d = _need(app.dns_servers, c.alias, "dns-server")
        d.stop()
        del app.dns_servers[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for dns-server")


# ------------------------------------------------------------- vswitch

def _ctx_switch(app: Application, c: Command):
    chain = ([c.target] if c.target else []) + c.contexts
    for kind, alias in chain:
        if kind == "switch":
            return _need(app.switches, alias, "switch")
    raise CmdError(f"{c.type} requires `in/to switch <name>`")


def _ctx_vpc(app: Application, c: Command):
    """Resolve `... in vpc <vni> in switch <sw>` chains."""
    sw = _ctx_switch(app, c)
    chain = ([c.target] if c.target else []) + c.contexts
    for kind, alias in chain:
        if kind == "vpc":
            try:
                vni = int(alias)
            except ValueError:
                raise CmdError(f"bad vni {alias!r}")
            if vni not in sw.networks:
                raise CmdError(f"vpc {vni} not found in switch {sw.alias}")
            return sw, sw.networks[vni]
    raise CmdError(f"{c.type} requires `in vpc <vni> in switch <name>`")


def _h_switch(app: Application, c: Command):
    from ..vswitch.switch import Switch
    if c.action == "add" and c.target is not None:
        # remote switch link: add switch sw1 to switch sw0 address ip:port
        sw = _ctx_switch(app, c)
        ip, port = _addr(c.params["address"])
        sw.add_remote_switch(c.alias, ip, port)
        return "OK"
    if c.action == "add":
        if c.alias in app.switches:
            raise CmdError(f"switch {c.alias} already exists")
        ip, port = _addr(c.params["address"])
        elg = _opt_elg(app, c, "elg", app.worker_elg)
        secg = _opt_secg(app, c)
        sw = Switch(c.alias, elg.next(), ip, port,
                    mac_table_timeout_ms=int(c.params.get("mac-table-timeout",
                                                          300_000)),
                    arp_table_timeout_ms=int(c.params.get("arp-table-timeout",
                                                          4 * 3600_000)),
                    bare_vxlan_access=secg, elg=elg)
        sw.start()
        app.switches[c.alias] = sw
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return list(app.switches.keys())

        def fc_str(s) -> str:
            fc = s.flowcache_info()
            if fc is None:
                return "off"
            state = "on" if fc["active"] else "idle"
            return (f"{state}(size={fc['size']},used={fc['used']},"
                    f"gen={fc['gen']},hit-rate={fc['hit_rate']})")
        return [f"{s.alias} -> bind {s.bind_ip}:{s.bind_port} "
                f"mac-table-timeout {s.mac_table_timeout_ms} "
                f"arp-table-timeout {s.arp_table_timeout_ms} "
                f"bare-vxlan-access {s.bare_access.alias} "
                f"flowcache {fc_str(s)}"
                for s in app.switches.values()]
    if c.action == "update":
        sw = _need(app.switches, c.alias, "switch")
        # hot-set table timeouts (SwitchHandle update): existing VPC
        # tables adopt the new TTLs immediately
        if "mac-table-timeout" in c.params:
            sw.mac_table_timeout_ms = _pos_int(c, "mac-table-timeout")
            for net in sw.networks.values():
                net.macs.timeout_ms = sw.mac_table_timeout_ms
        if "arp-table-timeout" in c.params:
            sw.arp_table_timeout_ms = _pos_int(c, "arp-table-timeout")
            for net in sw.networks.values():
                net.arps.timeout_ms = sw.arp_table_timeout_ms
        return "OK"
    if c.action in ("remove", "force-remove"):
        if c.target is not None:
            sw = _ctx_switch(app, c)
            try:
                sw.remove_iface(f"remote:{c.alias}")
            except KeyError:
                raise CmdError(f"remote switch {c.alias!r} not found")
            return "OK"
        sw = _need(app.switches, c.alias, "switch")
        # vpc proxies bound to this switch die with it
        for key in [k for k in app.vpc_proxies if k[0] == c.alias]:
            for p in app.vpc_proxies.pop(key).values():
                p.close()
        sw.stop()
        del app.switches[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for switch")


def _h_vpc(app: Application, c: Command):
    sw = _ctx_switch(app, c)
    if c.action == "add":
        try:
            vni = int(c.alias)
        except ValueError:
            raise CmdError(f"bad vni {c.alias!r}")
        if "v4network" not in c.params:
            raise CmdError("vpc requires v4network")
        v6 = Network.parse(c.params["v6network"]) if "v6network" in c.params else None
        anno = _anno_dict(c.params["annotations"]) if "annotations" in c.params else None
        try:
            sw.add_network(vni, Network.parse(c.params["v4network"]), v6,
                           annotations=anno)
        except ValueError as e:
            raise CmdError(str(e))
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return [str(v) for v in sw.networks]
        return [f"{n.vni} -> v4network {n.v4net}"
                + (f" v6network {n.v6net}" if n.v6net else "")
                + (f" annotations {json.dumps(n.annotations, separators=(',', ':'))}"
                   if n.annotations else "")
                for n in sw.networks.values()]
    if c.action in ("remove", "force-remove"):
        try:
            vni = int(c.alias)
            sw.del_network(vni)
        except (KeyError, ValueError):
            raise CmdError(f"vpc {c.alias!r} not found")
        for p in app.vpc_proxies.pop((sw.alias, vni), {}).values():
            p.close()
        return "OK"
    raise CmdError(f"unsupported action {c.action} for vpc")


def _h_iface(app: Application, c: Command):
    sw = _ctx_switch(app, c)
    if c.action in ("list", "list-detail"):
        return [i.name for i in sw.list_ifaces()]
    if c.action in ("remove", "force-remove"):
        try:
            sw.remove_iface(c.alias)
        except KeyError:
            raise CmdError(f"iface {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for iface")


def _h_route(app: Application, c: Command):
    from ..rules.ir import RouteRule
    sw, net = _ctx_vpc(app, c)
    if c.action == "add":
        network = Network.parse(c.params["network"])
        if "vni" in c.params:
            rule = RouteRule(c.alias, network, to_vni=int(c.params["vni"]))
        elif "via" in c.params:
            rule = RouteRule(c.alias, network,
                             via_ip=_parse_ip_str(c.params["via"]))
        else:
            raise CmdError("route requires `vni <n>` or `via <ip>`")
        try:
            net.add_route(rule)
        except ValueError as e:
            raise CmdError(str(e))
        return "OK"
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return [r.alias for r in net.routes.rules]
        out = []
        for r in net.routes.rules:
            tgt = f"vni {r.to_vni}" if r.to_vni else \
                f"via {format_ip(r.via_ip)}"
            out.append(f"{r.alias} -> network {r.rule} {tgt}")
        return out
    if c.action in ("remove", "force-remove"):
        try:
            net.remove_route(c.alias)
        except KeyError:
            raise CmdError(f"route {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for route")


def _h_arp(app: Application, c: Command):
    sw, net = _ctx_vpc(app, c)
    if c.action == "add":
        # alias is the mac; `ip` given via address param? use network-less ip
        if "address" not in c.params:
            raise CmdError("arp add requires `address <ip>`")
        net.arps.record(_parse_ip_str(c.params["address"]),
                        _parse_mac_str(c.alias))
        return "OK"
    if c.action in ("list", "list-detail"):
        macs = {m: getattr(i, "name", "?") for m, i in net.macs.entries()}
        out = []
        for ip_s, mac_s in net.arps.entries():
            out.append(f"{mac_s} -> ip {ip_s} iface {macs.get(mac_s, '?')}")
        return out
    raise CmdError(f"unsupported action {c.action} for arp")


def _h_user(app: Application, c: Command):
    sw = _ctx_switch(app, c)
    if c.action == "add":
        if "password" not in c.params or "vni" not in c.params:
            raise CmdError("user requires `password <p>` and `vni <n>`")
        try:
            sw.add_user(c.alias, c.params["password"], int(c.params["vni"]))
        except ValueError as e:
            raise CmdError(str(e))
        return "OK"
    from ..vswitch.switch import display_user_name
    if c.action in ("list", "list-detail"):
        if c.action == "list":
            return [display_user_name(u) for u in sw.users]
        return [f"{display_user_name(u)} -> vni {vni}"
                for u, (_, vni, _pw) in sw.users.items()]
    if c.action in ("remove", "force-remove"):
        try:
            sw.del_user(c.alias)
        except KeyError:
            raise CmdError(f"user {c.alias!r} not found")
        except ValueError as e:  # format-invalid alias, e.g. too short
            raise CmdError(str(e))
        return "OK"
    raise CmdError(f"unsupported action {c.action} for user")


def _h_ucli(app: Application, c: Command):
    sw = _ctx_switch(app, c)
    if c.action == "add":
        for k in ("password", "vni", "address"):
            if k not in c.params:
                raise CmdError(f"user-client requires `{k}`")
        ip, port = _addr(c.params["address"])
        sw.add_user_client(c.alias, c.params["password"],
                           int(c.params["vni"]), ip, port)
        return "OK"
    if c.action in ("list", "list-detail"):
        return [i.name for i in sw.list_ifaces() if i.name.startswith("ucli:")]
    if c.action in ("remove", "force-remove"):
        try:
            sw.remove_iface(f"ucli:{c.alias}")
        except KeyError:
            raise CmdError(f"user-client {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for user-client")


def _h_tap(app: Application, c: Command):
    sw = _ctx_switch(app, c)
    if c.action == "add":
        if "vni" not in c.params:
            raise CmdError("tap requires `vni <n>`")
        anno = _anno_dict(c.params["annotations"]) if "annotations" in c.params else None
        try:
            iface = sw.add_tap(c.alias, int(c.params["vni"]),
                               post_script=c.params.get("post-script"),
                               annotations=anno)
        except OSError as e:
            raise CmdError(str(e))
        return iface.dev
    if c.action in ("list", "list-detail"):
        return [i.name for i in sw.list_ifaces() if i.name.startswith("tap:")]
    if c.action in ("remove", "force-remove"):
        try:
            sw.remove_iface(f"tap:{c.alias}")
        except KeyError:
            raise CmdError(f"tap {c.alias!r} not found")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for tap")


def _h_ip(app: Application, c: Command):
    from ..vswitch.switch import synthetic_mac
    from ..vswitch.packets import mac_str, parse_mac
    sw, net = _ctx_vpc(app, c)
    if c.action == "add":
        ip = _parse_ip_str(c.alias)
        mac = (_parse_mac_str(c.params["mac"]) if "mac" in c.params
               else synthetic_mac(net.vni, ip))
        net.ips.add(ip, mac)
        return "OK"
    if c.action in ("list", "list-detail"):
        return [f"{format_ip(ip)} -> mac {mac_str(mac)}"
                for ip, mac in net.ips.ips().items()]
    if c.action in ("remove", "force-remove"):
        net.ips.remove(_parse_ip_str(c.alias))
        return "OK"
    raise CmdError(f"unsupported action {c.action} for ip")


def _nonneg_int(c: "Command", key: str, what: str = "") -> int:
    """Non-negative integer param; 0 is meaningful (max-sessions 0 =
    restore the default ceiling, on add and update alike)."""
    try:
        v = int(c.params[key])
    except ValueError:
        raise CmdError(f"bad {what or key}: {c.params[key]!r}")
    if v < 0:
        raise CmdError(f"{what or key} must be >= 0, got {v}")
    return v


def _pos_int(c: "Command", key: str, what: str = "") -> int:
    """Positive-integer param: `timeout 0` (or a seconds-vs-ms typo
    going negative) would turn idle sweeps into kill-everything loops."""
    try:
        v = int(c.params[key])
    except ValueError:
        raise CmdError(f"bad {what or key}: {c.params[key]!r}")
    if v <= 0:
        raise CmdError(f"{what or key} must be positive, got {v}")
    return v


def _parse_ip_str(s: str) -> bytes:
    from ..utils.ip import parse_ip as _p
    try:
        return _p(s)
    except (OSError, ValueError):
        raise CmdError(f"bad ip {s!r}")


def _parse_mac_str(s: str) -> bytes:
    from ..vswitch.packets import PacketError, parse_mac
    try:
        return parse_mac(s)
    except (PacketError, ValueError):
        raise CmdError(f"bad mac {s!r}")


def _all_lbs(app: Application) -> dict:
    out: dict = {}
    out.update(app.tcp_lbs)
    out.update(app.socks5_servers)
    return out


def _stat_target(app: Application, c: Command):
    """Resolve `in ...` chain for statistics channels."""
    if not c.contexts:
        raise CmdError(f"{c.type} requires an `in` chain")
    kind, alias = c.contexts[0]
    if kind in ("tcp-lb", "socks5-server"):
        return _need(_all_lbs(app), alias, kind)
    if kind == "server":
        if len(c.contexts) < 2 or c.contexts[1][0] != "server-group":
            raise CmdError("server stats require `in server-group`")
        sg = _need(app.server_groups, c.contexts[1][1], "server-group")
        for s in sg.servers:
            if s.name == alias:
                return s
        raise CmdError(f"server {alias!r} not found")
    raise CmdError(f"stats not supported on {kind}")


def _lb_context(app: Application, c: Command):
    if not c.contexts:
        raise CmdError(f"{c.type} requires `in tcp-lb|socks5-server <name>`")
    kind, alias = c.contexts[0]
    if kind not in ("tcp-lb", "socks5-server"):
        raise CmdError(f"{c.type} lives in tcp-lb/socks5-server")
    return _need(_all_lbs(app), alias, kind)


def _h_server_sock(app: Application, c: Command):
    """Listening sockets of a frontend (ResourceType ss): one per
    acceptor loop under REUSEPORT sharding."""
    lb = _lb_context(app, c)
    if c.action in ("list", "list-detail"):
        return [f"{ss.ip}:{ss.port} -> loop {ss.loop.name}"
                for ss in lb.server_socks]
    raise CmdError(f"unsupported action {c.action} for server-sock")


def _sessions_of(lb) -> list:
    """(desc, bytes_in, bytes_out) per live spliced session. Pump state
    is loop-confined (the lock-free native engine frees pumps on the
    owning loop thread), so each loop's stats are read ON that loop via
    call_sync — a direct cross-thread pump_stat would race pump_free."""
    out = []
    for lid, loop in list(lb._watch_loops.items()):
        def collect(lid=lid, loop=loop):
            rows = []
            for pid, ent in list(lb._pump_watch.get(lid, {}).items()):
                try:
                    a2b, b2a, _err = loop.pump_stat(pid)
                except OSError:
                    continue
                rows.append((ent[2] if len(ent) > 2 else "?", a2b, b2a))
            return rows
        try:
            out.extend(loop.call_sync(collect))
        except (OSError, RuntimeError):
            continue  # loop died mid-listing; its sessions are gone
    return out


def _h_session(app: Application, c: Command):
    """Live proxied sessions (ResourceType sess): spliced pairs with
    their byte counters; `list` returns the count."""
    lb = _lb_context(app, c)
    if c.action == "list":
        return [str(lb.active_sessions)]
    if c.action == "list-detail":
        rows = [f"{desc} bytes-in {a2b} bytes-out {b2a}"
                for desc, a2b, b2a in _sessions_of(lb)]
        other = lb.active_sessions - len(rows)
        if other > 0:  # L7 / handshaking sessions have no pump yet
            rows.append(f"({other} non-spliced sessions)")
        return rows
    raise CmdError(f"unsupported action {c.action} for session")


def _h_connection(app: Application, c: Command):
    """Live connections (ResourceType conn): both legs of each spliced
    session, frontend first (the reference lists front and back
    connections individually)."""
    lb = _lb_context(app, c)
    if c.action == "list":
        return [str(2 * lb.active_sessions)]
    if c.action == "list-detail":
        out = []
        sess = _sessions_of(lb)
        for desc, a2b, b2a in sess:
            front, _, back = desc.partition(" -> ")
            out.append(f"{front} -> {lb.bind_ip}:{lb.bind_port} "
                       f"bytes-in {a2b} bytes-out {b2a}")
            out.append(f"local -> {back} bytes-in {b2a} bytes-out {a2b}")
        other = lb.active_sessions - len(sess)
        if other > 0:
            out.append(f"({2 * other} connections of non-spliced sessions)")
        return out
    raise CmdError(f"unsupported action {c.action} for connection")


def _h_stats(app: Application, c: Command):
    t = _stat_target(app, c)
    if c.type == "bytes-in":
        return [str(getattr(t, "bytes_in", 0))]
    if c.type == "bytes-out":
        return [str(getattr(t, "bytes_out", 0))]
    if c.type == "accepted-conn-count":
        return [str(getattr(t, "accepted", 0))]
    raise CmdError(f"unsupported stat {c.type}")


def _h_eventlog(app: Application, c: Command):
    """`list event-log` — the flight-recorder ring (utils/events):
    connection lifecycle, loop stalls, classify failovers, health-check
    edges. list-detail returns the raw event dicts (what /events
    serves); list returns human-form lines. `since=`/`until=` bound the
    window in monotonic ns — the SAME clock trace spans stamp t_ns
    with, so a capture or incident window joins directly."""
    from ..utils.events import EVENT_PLANES, FlightRecorder
    plane = c.params.get("plane")
    if plane is not None and plane not in EVENT_PLANES:
        raise CmdError(f"unknown event plane {plane!r} "
                       f"(one of {', '.join(EVENT_PLANES)})")

    def _ns(key):
        v = c.params.get(key)
        if v is None:
            return None
        try:
            return int(v)
        except ValueError:
            raise CmdError(f"{key} must be an integer (monotonic ns), "
                           f"got {v!r}")

    since, until = _ns("since"), _ns("until")
    if c.action == "list":
        return FlightRecorder.get().lines(plane=plane, since=since,
                                          until=until)
    if c.action == "list-detail":
        return FlightRecorder.get().snapshot(plane=plane, since=since,
                                             until=until)
    raise CmdError(f"unsupported action {c.action} for event-log")


def _h_trace(app: Application, c: Command):
    """`list trace` — recent sampled request traces (id, span count,
    planes touched, end-to-end us); `list-detail trace` the raw trace
    summaries (what GET /trace serves). The waterfall of ONE trace is
    the bare `trace <id>` line (outside the resource grammar, like
    `drain`) — both control surfaces accept it."""
    from ..utils import trace as TR
    if c.action == "list":
        return [f"[{t['trace']}] {t['total_us']}us spans={t['spans']} "
                f"planes={','.join(t['planes'])}"
                for t in TR.summaries()]
    if c.action == "list-detail":
        return TR.summaries()
    raise CmdError(f"unsupported action {c.action} for trace")


def _h_analytics(app: Application, c: Command):
    """`list analytics` — one summary line per dimension (top entry,
    rate, update counts); `list-detail analytics` the full snapshot
    dict (what GET /analytics serves). The per-dimension table is the
    bare `top <dim>` verb."""
    from ..utils import sketch as SK
    if c.action == "list":
        st = SK.status()
        out = [f"analytics {'on' if st['enabled'] else 'off'} "
               f"window={st['window_s']:g}s k={st['k']} "
               f"cm={st['cm']['width']}x{st['cm']['depth']}"]
        for d in SK.DIMS:
            top = SK.top_table(d, 1)
            ds = st["dims"][d]
            lead = (f"#0 {top[0]['key']} count={top[0]['count']} "
                    f"{top[0]['rate']:.1f}/s" if top else "(idle)")
            out.append(f"{d}: updates={ds['updates']} "
                       f"rotations={ds['rotations']} {lead}")
        return out
    if c.action == "list-detail":
        return SK.snapshot_with_fleet()
    raise CmdError(f"unsupported action {c.action} for analytics")


def _h_policy(app: Application, c: Command):
    """`add policy <name> dim=<d> rate=<r> burst=<b>
    action=monitor|throttle|shed [tenant=<cidr|key>]` — the
    sketch-driven admission policies (policing/engine). Heavy hitters
    of `dim` get a token bucket at `rate`/s with `burst` headroom and
    `action` on over-quota; `tenant` scopes the policy and names its
    weight class for the fair-shed order (docs/robustness.md).
    Replicated + persisted like every rule resource."""
    from ..policing import engine as policing
    eng = policing.default()
    if c.action == "add":
        if any(p["name"] == c.alias for p in eng.list_policies()):
            raise CmdError(f"policy {c.alias} already exists")
        for k in ("dim", "rate", "burst", "action"):
            if k not in c.params:
                raise CmdError(f"policy requires `{k}=<value>`")
        try:
            pol = policing.Policy(
                c.alias, c.params["dim"], float(c.params["rate"]),
                float(c.params["burst"]), c.params["action"],
                tenant=c.params.get("tenant"))
        except ValueError as e:
            raise CmdError(str(e))
        eng.set_policy(pol)
        eng.tick()  # enforce against the current top-K now, not in ~1s
        return "OK"
    if c.action == "list":
        return [p["name"] for p in eng.list_policies()]
    if c.action == "list-detail":
        out = [f"{p['name']} -> dim {p['dim']} rate {p['rate']:g} "
               f"burst {p['burst']:g} action {p['action']}"
               + (f" tenant {p['tenant']}" if p["tenant"] else "")
               for p in eng.list_policies()]
        st = eng.status()
        out.append(f"policing {'on' if st['enabled'] else 'off'} "
                   f"seq {st['seq']} keys {st['keys']} "
                   f"installs {st['tables_installed_total']} "
                   f"gossip-merges {st['gossip_merges_total']} "
                   f"policed {st['policed_total']}")
        return out
    if c.action in ("remove", "force-remove"):
        if not eng.remove_policy(c.alias) and c.action == "remove":
            raise CmdError(f"policy {c.alias!r} not found")
        eng.tick()  # drop the keys (and native recs) it was policing
        return "OK"
    raise CmdError(f"unsupported action {c.action} for policy")


def _h_fault(app: Application, c: Command):
    """`add fault <site> [probability p] [count n] [match m] [seed s]`
    arms a named failpoint (utils/failpoint — the chaos-testing
    injection sites); without an explicit seed the probability coin is
    derived from VPROXY_TPU_FAILPOINT_SEED so storm/chaos runs replay;
    `remove fault <site>` disarms; `list fault` shows armed faults with
    hit counts (same view as `GET /faults`)."""
    from ..utils import failpoint
    if c.action == "add":
        try:
            failpoint.arm(
                c.alias,
                probability=float(c.params.get("probability", "1.0")),
                count=int(c.params["count"]) if "count" in c.params else None,
                match=c.params.get("match"),
                seed=int(c.params["seed"]) if "seed" in c.params else None)
        except ValueError as e:
            raise CmdError(str(e))
        return "OK"
    if c.action == "list":
        return [f["name"] for f in failpoint.active()]
    if c.action == "list-detail":
        return [f"{f['name']} -> probability {f['probability']} "
                f"count {f['count'] if f['count'] is not None else 'inf'} "
                f"match {f['match'] or '*'} hits {f['hits']}"
                for f in failpoint.active()]
    if c.action in ("remove", "force-remove"):
        if not failpoint.disarm(c.alias) and c.action == "remove":
            raise CmdError(f"fault {c.alias!r} not armed")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for fault")


def _h_cluster(app: Application, c: Command):
    """`add cluster-node <id> address <ip:port>` admits a peer into the
    membership view at runtime (the boot set comes from
    VPROXY_TPU_CLUSTER_PEERS); `remove cluster-node <id>` evicts one;
    `list[-detail] cluster-node` shows the fleet view (same data as
    `GET /cluster`)."""
    cluster = app.cluster
    if cluster is None:
        raise CmdError("cluster plane not enabled "
                       "(set VPROXY_TPU_CLUSTER_PEERS at boot)")
    if c.action == "add":
        try:
            nid = int(c.alias)
        except ValueError:
            raise CmdError(f"bad cluster-node id {c.alias!r}")
        if "address" not in c.params:
            raise CmdError("cluster-node requires `address <ip:port>`")
        ip, port = _addr(c.params["address"])
        try:
            cluster.membership.add_peer(nid, ip, port)
        except ValueError as e:
            raise CmdError(str(e))
        return "OK"
    if c.action == "list":
        return [str(p.node_id) for p in cluster.membership.peer_list()]
    if c.action == "list-detail":
        st = cluster.status()
        out = []
        for p in cluster.membership.peer_list():
            role = ("self " if p.node_id == st["self"] else "") + \
                ("leader" if p.node_id == st["leader"] else "follower")
            out.append(f"{p.node_id} -> {p.ip}:{p.port} "
                       f"repl {p.repl_port} "
                       f"{'UP' if p.up else 'DOWN'} "
                       f"generation {p.generation} "
                       f"{'stepping' if p.stepping else 'not-stepping'} "
                       f"{role}")
        out.append(f"generation {st['generation']} "
                   f"lag {st['generation_lag']} "
                   f"checksum {st['checksum']:#010x}")
        return out
    if c.action in ("remove", "force-remove"):
        try:
            cluster.membership.remove_peer(int(c.alias))
        except (ValueError, KeyError) as e:
            raise CmdError(f"cannot remove cluster-node {c.alias!r}: {e}")
        return "OK"
    raise CmdError(f"unsupported action {c.action} for cluster-node")


def _h_resolver(app: Application, c: Command):
    """The reference's resolver is a singleton named "(default)"
    (ResolverHandle.java:10-16); dns-cache lives inside it."""
    if c.action in ("list", "list-detail"):
        return ["(default)"]
    raise CmdError(f"unsupported action {c.action} for resolver")


def _h_dnscache(app: Application, c: Command):
    ctx = c.target or (c.contexts[0] if c.contexts else None)
    if ctx is not None and (ctx[0] != "resolver" or ctx[1] != "(default)"):
        raise CmdError("dns-cache lives in `resolver (default)`")
    res = app.get_resolver()
    if c.action == "list":
        return sorted({k[0] for k in res._cache})
    if c.action == "list-detail":
        import time as _t
        now = _t.monotonic()
        out = []
        for (name, qtype), (expiry, addrs) in sorted(res._cache.items()):
            from ..utils.ip import format_ip
            out.append(f"{name} -> [{','.join(format_ip(bytes(a)) for a in addrs)}]"
                       f" ttl={max(0, int(expiry - now))}")
        return out
    if c.action in ("remove", "force-remove"):
        gone = [k for k in res._cache if k[0] == c.alias]
        if not gone:
            raise CmdError(f"dns-cache {c.alias!r} not found")
        for k in gone:
            del res._cache[k]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for dns-cache")


def _h_proxy(app: Application, c: Command):
    """`add proxy <ip:port> to vpc <vni> in switch <sw> address <tgt>`
    — in-VPC user-space listener bridged to a host address
    (vswitch/ProxyHolder)."""
    from ..vswitch.proxy import VpcProxy

    sw, net = _ctx_vpc(app, c)  # validates the vpc exists in the switch
    key = (sw.alias, net.vni)
    store = app.vpc_proxies.get(key, {})
    if c.action == "add":
        if c.alias in store:
            raise CmdError(f"proxy {c.alias} already exists")
        lip, lport = _addr(c.alias)
        if "address" not in c.params:
            raise CmdError("proxy requires `address <target ip:port>`")
        tip, tport = _addr(c.params["address"])
        try:
            p = VpcProxy(sw, net.vni, lip, lport, tip, tport)
        except OSError as e:
            raise CmdError(f"proxy listen failed: {e}")
        app.vpc_proxies.setdefault(key, {})[c.alias] = p
        return "OK"
    if c.action == "list":
        return list(store.keys())
    if c.action == "list-detail":
        return [f"{p.alias} -> {p.target[0]}:{p.target[1]} "
                f"sessions={p.sessions}" for p in store.values()]
    if c.action in ("remove", "force-remove"):
        p = _need(store, c.alias, "proxy")
        p.close()
        del store[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for proxy")


def _h_respc(app: Application, c: Command):
    from .resp import RESPController
    if c.action == "add":
        if c.alias in app.resp_controllers:
            raise CmdError(f"resp-controller {c.alias} already exists")
        if "address" not in c.params:
            raise CmdError("resp-controller requires `address <ip:port>`")
        ip, port = _addr(c.params["address"])
        ctl = RESPController(app, ip, port,
                             password=c.params.get("password"))
        ctl.start()
        app.resp_controllers[c.alias] = ctl
        return "OK"
    if c.action == "list":
        return list(app.resp_controllers.keys())
    if c.action == "list-detail":
        return [f"{a} -> {ctl.bind_ip}:{ctl.bind_port}"
                for a, ctl in app.resp_controllers.items()]
    if c.action in ("remove", "force-remove"):
        ctl = _need(app.resp_controllers, c.alias, "resp-controller")
        ctl.stop()
        del app.resp_controllers[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for resp-controller")


def _h_httpc(app: Application, c: Command):
    from .http_controller import HttpController
    if c.action == "add":
        if c.alias in app.http_controllers:
            raise CmdError(f"http-controller {c.alias} already exists")
        if "address" not in c.params:
            raise CmdError("http-controller requires `address <ip:port>`")
        ip, port = _addr(c.params["address"])
        ctl = HttpController(app, ip, port)
        ctl.start()
        app.http_controllers[c.alias] = ctl
        return "OK"
    if c.action == "list":
        return list(app.http_controllers.keys())
    if c.action == "list-detail":
        return [f"{a} -> {ctl.bind_ip}:{ctl.bind_port}"
                for a, ctl in app.http_controllers.items()]
    if c.action in ("remove", "force-remove"):
        ctl = _need(app.http_controllers, c.alias, "http-controller")
        ctl.stop()
        del app.http_controllers[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for http-controller")


def _h_docker(app: Application, c: Command):
    """Docker libnetwork plugin host: unix-socket HTTP driver bridging
    docker networks onto the vswitch (DockerNetworkPluginController.java)."""
    from .docker import DockerNetworkPluginController
    if c.action == "add":
        if c.alias in app.docker_controllers:
            raise CmdError(f"docker-network-plugin-controller {c.alias} "
                           "already exists")
        if "path" not in c.params:
            raise CmdError("docker-network-plugin-controller requires "
                           "`path <uds-path>`")
        try:
            ctl = DockerNetworkPluginController(app, c.alias, c.params["path"])
        except OSError as e:
            raise CmdError(f"listen on {c.params['path']} failed: {e}")
        app.docker_controllers[c.alias] = ctl
        return "OK"
    if c.action == "list":
        return list(app.docker_controllers.keys())
    if c.action == "list-detail":
        return [f"{a} -> path {ctl.path}"
                for a, ctl in app.docker_controllers.items()]
    if c.action in ("remove", "force-remove"):
        ctl = _need(app.docker_controllers, c.alias,
                    "docker-network-plugin-controller")
        ctl.stop()
        del app.docker_controllers[c.alias]
        return "OK"
    raise CmdError(f"unsupported action {c.action} for "
                   "docker-network-plugin-controller")


_HANDLERS = {
    "fault": _h_fault,
    "event-log": _h_eventlog,
    "trace": _h_trace,
    "analytics": _h_analytics,
    "policy": _h_policy,
    "cluster-node": _h_cluster,
    "resolver": _h_resolver,
    "dns-cache": _h_dnscache,
    "proxy": _h_proxy,
    "resp-controller": _h_respc,
    "http-controller": _h_httpc,
    "docker-network-plugin-controller": _h_docker,
    "event-loop-group": _h_elg,
    "event-loop": _h_el,
    "upstream": _h_ups,
    "server-group": _h_sg,
    "server": _h_svr,
    "security-group": _h_secg,
    "security-group-rule": _h_secgr,
    "cert-key": _h_ck,
    "switch": _h_switch,
    "vpc": _h_vpc,
    "iface": _h_iface,
    "route": _h_route,
    "arp": _h_arp,
    "user": _h_user,
    "user-client": _h_ucli,
    "tap": _h_tap,
    "ip": _h_ip,
    "tcp-lb": _h_tl,
    "socks5-server": _h_socks5,
    "dns-server": _h_dns,
    "server-sock": _h_server_sock,
    "session": _h_session,
    "connection": _h_connection,
    "bytes-in": _h_stats,
    "bytes-out": _h_stats,
    "accepted-conn-count": _h_stats,
}
