"""RESPController — redis-protocol control server (works with redis-cli).

Parity: app controller/RESPController.java (+ base redis/RESPParser.java):
accepts RESP arrays or inline commands, optional AUTH password, joins
tokens into one command line and runs it through the command engine on
the control loop; replies with simple-string/bulk/array/error frames.
"""
from __future__ import annotations

from typing import Optional

from ..net.connection import Connection, Handler, ServerSock
from ..net.eventloop import SelectorEventLoop
from .app import Application
from .command import CmdError, Command


def enc_resp(result) -> bytes:
    if result is None:
        return b"+OK\r\n"
    if isinstance(result, str):
        if result == "OK":
            return b"+OK\r\n"
        data = result.encode()
        return b"$%d\r\n%s\r\n" % (len(data), data)
    if isinstance(result, list):
        out = b"*%d\r\n" % len(result)
        for item in result:
            data = str(item).encode()
            out += b"$%d\r\n%s\r\n" % (len(data), data)
        return out
    data = str(result).encode()
    return b"$%d\r\n%s\r\n" % (len(data), data)


def enc_err(msg: str) -> bytes:
    return b"-ERR %s\r\n" % msg.replace("\r", " ").replace("\n", " ").encode()


class _RespConn(Handler):
    def __init__(self, ctl: "RESPController", conn: Connection):
        self.ctl = ctl
        self.conn = conn
        self.buf = bytearray()
        self.authed = ctl.password is None
        conn.set_handler(self)

    # ------------------------------------------------------------ parsing

    def _try_parse(self) -> Optional[list[str]]:
        """One request: RESP array of bulk strings, or inline line."""
        if not self.buf:
            return None
        if self.buf[0:1] != b"*":
            nl = self.buf.find(b"\r\n")
            if nl < 0:
                nl = self.buf.find(b"\n")
                if nl < 0:
                    return None
                line = bytes(self.buf[:nl])
                del self.buf[:nl + 1]
            else:
                line = bytes(self.buf[:nl])
                del self.buf[:nl + 2]
            return line.decode("latin-1").split()
        # array of bulk strings
        pos = 0
        nl = self.buf.find(b"\r\n", pos)
        if nl < 0:
            return None
        raw_n = bytes(self.buf[1:nl])
        if not raw_n.isdigit():  # same strictness as the bulk lengths
            raise CmdError("bad RESP array header")
        n = int(raw_n)
        pos = nl + 2
        items = []
        for _ in range(n):
            if pos >= len(self.buf) or self.buf[pos:pos + 1] != b"$":
                if pos >= len(self.buf):
                    return None
                raise CmdError("expected bulk string")
            nl = self.buf.find(b"\r\n", pos)
            if nl < 0:
                return None
            raw_ln = bytes(self.buf[pos + 1:nl])
            if not raw_ln.isdigit():  # strict digits: no '+5', '1_6'
                raise CmdError("bad bulk string length")
            ln = int(raw_ln)
            start = nl + 2
            if len(self.buf) < start + ln + 2:
                return None
            items.append(bytes(self.buf[start:start + ln]).decode("latin-1"))
            pos = start + ln + 2
        del self.buf[:pos]
        return items

    # ------------------------------------------------------------- logic

    MAX_BUF = 1 << 20  # one request; a control command never nears this

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.buf += data
        if len(self.buf) > self.MAX_BUF:
            # unauthenticated clients must not balloon controller memory
            # with a huge bulk length or an endless unterminated line
            conn.write(enc_err("request too large"))
            conn.close_draining()
            return
        while True:
            try:
                toks = self._try_parse()
            except CmdError as e:
                # protocol error: no resync possible mid-stream — reply,
                # half-close, and drain (a hard close while the peer is
                # still sending turns into a RST that eats the -ERR)
                conn.write(enc_err(str(e)))
                conn.close_draining()
                return
            if toks is None:
                return
            if not toks:
                continue
            self._dispatch(conn, toks)

    def _dispatch(self, conn: Connection, toks: list[str]) -> None:
        cmd0 = toks[0].lower()
        if cmd0 == "auth":
            if len(toks) != 2:
                conn.write(enc_err("wrong number of arguments for 'auth'"))
                return
            if self.ctl.password is not None and toks[1] == self.ctl.password:
                self.authed = True
                conn.write(b"+OK\r\n")
            else:
                conn.write(enc_err("invalid password"))
            return
        if cmd0 == "ping":
            conn.write(b"+PONG\r\n")
            return
        if cmd0 == "quit":
            conn.write(b"+OK\r\n")
            conn.close()
            return
        if not self.authed:
            conn.write(enc_err("NOAUTH Authentication required"))
            return
        line = " ".join(toks)
        try:
            result = Command.execute(self.ctl.app, line)
            conn.write(enc_resp(result))
        except CmdError as e:
            conn.write(enc_err(str(e)))
        except Exception as e:  # surface internal errors to the operator
            conn.write(enc_err(f"{type(e).__name__}: {e}"))


class RESPController:
    def __init__(self, app: Application, bind_ip: str, bind_port: int,
                 password: Optional[str] = None,
                 loop: Optional[SelectorEventLoop] = None):
        self.app = app
        self.password = password
        self.loop = loop or app.control_loop
        self.bind_ip, self.bind_port = bind_ip, bind_port
        self._srv: Optional[ServerSock] = None

    def start(self) -> None:
        def mk() -> None:
            self._srv = ServerSock(self.loop, self.bind_ip, self.bind_port,
                                   self._on_accept)
            self.bind_port = self._srv.port
        self.loop.call_sync(mk)

    def _on_accept(self, fd: int, ip: str, port: int) -> None:
        _RespConn(self, Connection(self.loop, fd, (ip, port)))

    def stop(self) -> None:
        if self._srv is not None:
            srv = self._srv
            self._srv = None
            self.loop.run_on_loop(srv.close)
