"""Docker libnetwork network plugin — networks become switch VPCs,
endpoints become tap devices.

Parity: app controller/DockerNetworkPluginController.java:20-286 (the
unix-socket HTTP endpoint speaking the libnetwork remote-driver
protocol, https://github.com/moby/libnetwork/blob/master/docs/remote.md)
and controller/DockerNetworkDriverImpl.java:22-421 (the driver: a
dedicated switch "DockerNetworkDriverSW"; CreateNetwork -> VPC with the
networkId kept as an annotation + a gateway synthetic IP under the
reserved gateway mac; CreateEndpoint -> tap named tap<endpointId[:12]>
with a per-endpoint post script; Join -> writes the netns-move post
script and answers docker with the interface name + gateways).
"""
from __future__ import annotations

import json
import os
import stat
from typing import Optional

from ..lib.vserver import HttpServer, RoutingContext
from ..utils.ip import Network, format_ip, parse_ip
from ..utils.log import Logger

_log = Logger("docker")

SWITCH_NAME = "DockerNetworkDriverSW"
GATEWAY_MAC = bytes([0x02, 0x00, 0x00, 0x00, 0x00, 0x20])

ANNO_NETWORK_ID = "docker/network-id"
ANNO_ENDPOINT_ID = "docker/endpoint-id"
ANNO_ENDPOINT_IPV4 = "docker/endpoint-ipv4"
ANNO_ENDPOINT_IPV6 = "docker/endpoint-ipv6"
ANNO_ENDPOINT_MAC = "docker/endpoint-mac"

DEFAULT_SCRIPT_DIR = "/var/vproxy_tpu/docker-network-plugin/post-scripts"


class DockerError(Exception):
    """Driver-level failure reported to docker as {"Err": msg}."""


def _split_gateway(gateway: str, pool: Network, family: str) -> bytes:
    """Validate `a.b.c.d[/m]` against the pool; -> raw gateway ip."""
    ip_s, slash, mask_s = gateway.partition("/")
    if slash:
        try:
            mask = int(mask_s)
        except ValueError:
            raise DockerError(f"invalid format for {family} gateway {gateway}")
        if mask != pool.masklen:
            raise DockerError(f"the gateway mask {mask} must be the same "
                              f"as the network {pool.masklen}")
    try:
        ip = parse_ip(ip_s)
    except ValueError:
        raise DockerError(f"{family} gateway is not a valid ip address {gateway}")
    if not pool.contains_ip(ip):
        raise DockerError(f"the cidr {pool} does not contain the gateway {gateway}")
    return ip


class DockerNetworkDriver:
    """The switch-driving half (DockerNetworkDriverImpl.java)."""

    def __init__(self, app, script_dir: Optional[str] = None,
                 switch_addr: Optional[str] = None):
        self.app = app
        self.script_dir = script_dir or os.environ.get(
            "VPROXY_TPU_DOCKER_SCRIPTS", DEFAULT_SCRIPT_DIR)
        addr = switch_addr or os.environ.get(
            "VPROXY_TPU_DOCKER_SWITCH_ADDR", "127.7.7.7:7777")
        ip, _, port = addr.rpartition(":")
        self.switch_ip, self.switch_port = ip, int(port)

    # ------------------------------------------------------------- switch

    def ensure_switch(self):
        """Get or lazily create the plugin's dedicated switch
        (DockerNetworkDriverImpl.ensureSwitch :167-189)."""
        sw = self.app.switches.get(SWITCH_NAME)
        if sw is not None:
            return sw
        from ..vswitch.switch import Switch
        elg = self.app.worker_elg
        sw = Switch(SWITCH_NAME, elg.next(), self.switch_ip, self.switch_port,
                    elg=elg)
        sw.start()
        self.app.switches[SWITCH_NAME] = sw
        _log.info(f"switch {SWITCH_NAME} created")
        return sw

    def _find_network(self, sw, network_id: str):
        for net in sw.networks.values():
            if net.annotations.get(ANNO_NETWORK_ID) == network_id:
                return net
        raise DockerError(f"network {network_id} not found")

    def _find_endpoint(self, sw, endpoint_id: str):
        from ..vswitch.iface import TapIface
        for iface in sw.list_ifaces():
            if isinstance(iface, TapIface) and \
                    iface.annotations.get(ANNO_ENDPOINT_ID) == endpoint_id:
                return iface
        raise DockerError(f"endpoint {endpoint_id} not found")

    def _script_path(self, endpoint_id: str) -> str:
        return os.path.join(self.script_dir, endpoint_id)

    def _ensure_post_script(self, endpoint_id: str, content: str) -> str:
        os.makedirs(self.script_dir, exist_ok=True)
        path = self._script_path(endpoint_id)
        with open(path, "w") as f:
            f.write(content)
        os.chmod(path, os.stat(path).st_mode
                 | stat.S_IXUSR | stat.S_IXGRP | stat.S_IXOTH)
        return path

    # ------------------------------------------------------------ network

    def create_network(self, network_id: str, ipv4_data: list,
                       ipv6_data: list) -> None:
        if len(ipv4_data) > 1:
            raise DockerError("we only support at most one ipv4 cidr in one network")
        if len(ipv6_data) > 1:
            raise DockerError("we only support at most one ipv6 cidr in one network")
        if not ipv4_data:
            raise DockerError("no ipv4 network info provided")

        def check(data: dict, family: str, ver_len: int):
            if data.get("AuxAddresses"):
                raise DockerError("auxAddresses are not supported")
            try:
                pool = Network.parse(data["Pool"])
            except (ValueError, KeyError):
                raise DockerError(
                    f"{family} network is not a valid cidr {data.get('Pool')}")
            if len(pool.ip) != ver_len:
                raise DockerError(f"address {data['Pool']} is not {family} cidr")
            gw = _split_gateway(data.get("Gateway", ""), pool, family)
            return pool, gw

        v4pool, v4gw = check(ipv4_data[0], "ipv4", 4)
        v6pool = v6gw = None
        if ipv6_data:
            v6pool, v6gw = check(ipv6_data[0], "ipv6", 16)

        sw = self.ensure_switch()
        vni = max(sw.networks, default=0) + 1
        net = sw.add_network(vni, v4pool, v6pool,
                             annotations={ANNO_NETWORK_ID: network_id})
        _log.info(f"vpc added: vni={vni} v4={v4pool} v6={v6pool} "
                  f"docker:networkId={network_id}")
        net.ips.add(v4gw, GATEWAY_MAC)
        if v6gw is not None:
            net.ips.add(v6gw, GATEWAY_MAC)

    def delete_network(self, network_id: str) -> None:
        sw = self.ensure_switch()
        net = self._find_network(sw, network_id)
        sw.del_network(net.vni)
        _log.info(f"vpc deleted: vni={net.vni} docker:networkId={network_id}")

    # ----------------------------------------------------------- endpoint

    def create_endpoint(self, network_id: str, endpoint_id: str,
                        address: Optional[str], address_v6: Optional[str],
                        mac: Optional[str]) -> None:
        if not address:
            raise DockerError("ipv4 must be provided")
        sw = self.ensure_switch()
        net = self._find_network(sw, network_id)
        if address_v6 and net.v6net is None:
            raise DockerError(f"network {network_id} does not support ipv6")

        anno = {ANNO_ENDPOINT_ID: endpoint_id, ANNO_ENDPOINT_IPV4: address}
        if address_v6:
            anno[ANNO_ENDPOINT_IPV6] = address_v6
        if mac:
            anno[ANNO_ENDPOINT_MAC] = mac

        script = self._ensure_post_script(endpoint_id, "")
        name = "tap" + endpoint_id[:12]
        try:
            iface = sw.add_tap(name, net.vni, post_script=script,
                               annotations=anno)
        except OSError:
            # failed creates get no DeleteEndpoint from docker: don't
            # leave a stray script behind
            try:
                os.unlink(script)
            except OSError:
                pass
            raise
        _log.info(f"tap added: {iface.dev} vni={net.vni} "
                  f"endpointId={endpoint_id} ipv4={address} "
                  f"ipv6={address_v6} mac={mac}")

    def delete_endpoint(self, network_id: str, endpoint_id: str) -> None:
        sw = self.ensure_switch()
        self._find_network(sw, network_id)
        tap = self._find_endpoint(sw, endpoint_id)
        sw.remove_iface(f"tap:{tap.dev}")
        _log.info(f"tap deleted: {tap.dev} endpointId={endpoint_id}")
        try:
            os.unlink(self._script_path(endpoint_id))
        except OSError:
            pass

    # --------------------------------------------------------------- join

    def _gateways(self, net) -> tuple[Optional[str], Optional[str]]:
        gw4 = gw6 = None
        for ip, mac in net.ips.ips().items():
            if mac != GATEWAY_MAC:
                continue
            if len(ip) == 4:
                gw4 = format_ip(ip)
            else:
                gw6 = format_ip(ip)
        return gw4, gw6

    def join(self, network_id: str, endpoint_id: str, sandbox_key: str) -> dict:
        sw = self.ensure_switch()
        net = self._find_network(sw, network_id)
        tap = self._find_endpoint(sw, endpoint_id)
        ipv4 = tap.annotations.get(ANNO_ENDPOINT_IPV4)
        ipv6 = tap.annotations.get(ANNO_ENDPOINT_IPV6)
        mac = tap.annotations.get(ANNO_ENDPOINT_MAC)
        gw4, gw6 = self._gateways(net)
        if gw4 is None:
            raise DockerError(f"ipv4 gateway not found in network {network_id}")
        if ipv6 and gw6 is None:
            raise DockerError(f"ipv6 gateway not found in network {network_id}")

        self._ensure_post_script(
            endpoint_id, self._join_script(endpoint_id, sandbox_key,
                                           ipv4, ipv6, mac, gw4, gw6))
        resp = {
            "InterfaceName": {"SrcName": tap.dev, "DstPrefix": "eth"},
            "Gateway": gw4,
            "StaticRoutes": [],
        }
        if gw6 and ipv6:
            resp["GatewayIPv6"] = gw6
        return resp

    def _join_script(self, endpoint_id: str, sandbox_key: str,
                     ipv4: str, ipv6: Optional[str], mac: Optional[str],
                     gw4: str, gw6: Optional[str]) -> str:
        """Re-attach script run when the tap is (re)created: moves $DEV
        into the container netns, renames it to the first free ethN and
        configures addresses/routes (DockerNetworkDriverImpl.join
        :343-404). Needed so a plugin restart restores container
        connectivity; a no-op once the sandbox is gone."""
        alias = sandbox_key.rsplit("/", 1)[-1]
        lines = [
            "#!/bin/bash",
            "set -e",
            f"if [ ! -f {sandbox_key} ]; then",
            f"  rm -f {self._script_path(endpoint_id)}",
            "  exit 0",
            "fi",
            "mkdir -p /var/run/netns",
            f"[ -e /var/run/netns/{alias} ] || ln -s {sandbox_key} /var/run/netns/{alias}",
            f"ip link set $DEV netns {alias}",
            # rename to the first eth<N> not taken inside the netns
            f"used=`ip netns exec {alias} ip -o link show | awk -F': ' '{{print $2}}'`",
            "n=0",
            'while echo "$used" | grep -qx "eth$n"; do n=$((n + 1)); done',
            f'ip netns exec {alias} ip link set $DEV name "eth$n"',
            'DEV="eth$n"',
        ]
        if mac:
            lines.append(f"ip netns exec {alias} ip link set $DEV address {mac}")
        lines += [
            f"ip netns exec {alias} ip link set $DEV up",
            f"ip netns exec {alias} ip address add {ipv4} dev $DEV",
            f"ip netns exec {alias} ip route add default via {gw4} dev $DEV",
        ]
        if ipv6:
            lines += [
                f"ip netns exec {alias} sysctl -w net.ipv6.conf.$DEV.disable_ipv6=0",
                f"ip netns exec {alias} ip -6 address add {ipv6} dev $DEV",
                f"ip netns exec {alias} ip -6 route add default via {gw6} dev $DEV",
            ]
        lines.append(f"rm -f /var/run/netns/{alias}")
        return "\n".join(lines) + "\n"

    def leave(self, network_id: str, endpoint_id: str) -> None:
        self._ensure_post_script(endpoint_id, "")


class DockerNetworkPluginController:
    """The unix-socket HTTP half (DockerNetworkPluginController.java).

    Driver calls run on a dedicated serializing thread, not the control
    loop: tap post-scripts may block for seconds (netns operations) and
    must not stall RESP/HTTP control traffic. Responses complete back on
    the loop; request order is preserved (the reference serializes with
    `synchronized` driver methods)."""

    def __init__(self, app, alias: str, path: str,
                 driver: Optional[DockerNetworkDriver] = None):
        import queue
        import threading
        self.app = app
        self.alias = alias
        self.path = path
        self.driver = driver or DockerNetworkDriver(app)
        self._jobs: "queue.Queue" = queue.Queue()
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name=f"docker-driver-{alias}")
        self._worker.start()
        srv = HttpServer(app.control_loop)
        srv.post("/Plugin.Activate", self._activate)
        srv.post("/NetworkDriver.GetCapabilities", self._capabilities)
        srv.post("/NetworkDriver.CreateNetwork", self._create_network)
        srv.post("/NetworkDriver.DeleteNetwork", self._delete_network)
        srv.post("/NetworkDriver.CreateEndpoint", self._create_endpoint)
        srv.post("/NetworkDriver.EndpointOperInfo", self._oper_info)
        srv.post("/NetworkDriver.DeleteEndpoint", self._delete_endpoint)
        srv.post("/NetworkDriver.Join", self._join)
        srv.post("/NetworkDriver.Leave", self._leave)
        srv.post("/NetworkDriver.DiscoverNew", self._discover)
        srv.post("/NetworkDriver.DiscoverDelete", self._discover)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        srv.listen_unix(path)
        self.server = srv

    def stop(self) -> None:
        # synchronous: `remove` must not report OK while the socket file
        # still accepts connections
        self.server.close(sync=True)
        self._jobs.put(None)

    def _drain(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            job()

    # ----------------------------------------------------------- handlers

    @staticmethod
    def _body(rctx: RoutingContext) -> dict:
        try:
            b = rctx.req.json()
            return b if isinstance(b, dict) else {}
        except (ValueError, json.JSONDecodeError):
            return {}

    def _activate(self, rctx: RoutingContext) -> None:
        rctx.resp.end({"Implements": ["NetworkDriver"]})

    def _capabilities(self, rctx: RoutingContext) -> None:
        rctx.resp.end({"Scope": "local", "ConnectivityScope": "local"})

    def _run(self, rctx: RoutingContext, fn, ok=None) -> None:
        def job() -> None:
            try:
                out = fn()
                res = out if out is not None else (ok or {})
            except DockerError as e:
                res = {"Err": str(e)}
            except Exception as e:  # switch/tap/OS failure
                _log.alert(f"docker driver error: {e!r}")
                res = {"Err": f"{type(e).__name__}: {e}"}
            # response completion must happen on the loop that owns the conn
            self.app.control_loop.run_on_loop(lambda: rctx.resp.end(res))
        self._jobs.put(job)

    def _create_network(self, rctx: RoutingContext) -> None:
        b = self._body(rctx)
        if "NetworkID" not in b:
            rctx.resp.end({"Err": "invalid request body"})
            return
        self._run(rctx, lambda: self.driver.create_network(
            b["NetworkID"], b.get("IPv4Data") or [], b.get("IPv6Data") or []))

    def _delete_network(self, rctx: RoutingContext) -> None:
        b = self._body(rctx)
        if "NetworkID" not in b:
            rctx.resp.end({"Err": "invalid request body"})
            return
        self._run(rctx, lambda: self.driver.delete_network(b["NetworkID"]))

    def _create_endpoint(self, rctx: RoutingContext) -> None:
        b = self._body(rctx)
        if "NetworkID" not in b or "EndpointID" not in b:
            rctx.resp.end({"Err": "invalid request body"})
            return
        itf = b.get("Interface") or {}
        if not itf:
            rctx.resp.end({"Err": "we do not support auto ip allocation for now"})
            return
        self._run(rctx, lambda: self.driver.create_endpoint(
            b["NetworkID"], b["EndpointID"], itf.get("Address"),
            itf.get("AddressIPv6"), itf.get("MacAddress")))

    def _oper_info(self, rctx: RoutingContext) -> None:
        rctx.resp.end({"Value": {}})

    def _delete_endpoint(self, rctx: RoutingContext) -> None:
        b = self._body(rctx)
        if "NetworkID" not in b or "EndpointID" not in b:
            rctx.resp.end({"Err": "invalid request body"})
            return
        self._run(rctx, lambda: self.driver.delete_endpoint(
            b["NetworkID"], b["EndpointID"]))

    def _join(self, rctx: RoutingContext) -> None:
        b = self._body(rctx)
        if not all(k in b for k in ("NetworkID", "EndpointID", "SandboxKey")):
            rctx.resp.end({"Err": "invalid request body"})
            return
        self._run(rctx, lambda: self.driver.join(
            b["NetworkID"], b["EndpointID"], b["SandboxKey"]))

    def _leave(self, rctx: RoutingContext) -> None:
        b = self._body(rctx)
        if "NetworkID" not in b or "EndpointID" not in b:
            rctx.resp.end({"Err": "invalid request body"})
            return
        self._run(rctx, lambda: self.driver.leave(
            b["NetworkID"], b["EndpointID"]))

    def _discover(self, rctx: RoutingContext) -> None:
        # local-scope driver: discovery events are acknowledged, unused
        rctx.resp.end({})
