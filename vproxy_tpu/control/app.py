"""Application — the singleton resource-holder registry.

Analog of app/Application.java:16-115: one holder per resource kind plus
the default event-loop topology (a control loop, N worker loops, the
acceptor group aliased to the worker group — REUSEPORT always available
on the Linux hosts we target).
"""
from __future__ import annotations

import os
from typing import Optional

from ..components.elgroup import EventLoopGroup
from ..components.secgroup import SecurityGroup
from ..components.servergroup import ServerGroup
from ..components.socks5 import Socks5Server
from ..components.tcplb import TcpLB
from ..components.upstream import Upstream
from ..dns.server import DNSServer

DEFAULT_ACCEPTOR_ELG = "(acceptor-elg)"
DEFAULT_WORKER_ELG = "(worker-elg)"
DEFAULT_CONTROL_ELG = "(control-elg)"


class Application:
    _instance: Optional["Application"] = None

    def __init__(self, workers: Optional[int] = None):
        if workers is None:
            workers = int(os.environ.get("VPROXY_TPU_WORKERS", "0")) or (
                os.cpu_count() or 1)
        self.elgs: dict[str, EventLoopGroup] = {}
        self.upstreams: dict[str, Upstream] = {}
        self.server_groups: dict[str, ServerGroup] = {}
        self.security_groups: dict[str, SecurityGroup] = {}
        self.tcp_lbs: dict[str, TcpLB] = {}
        self.socks5_servers: dict[str, Socks5Server] = {}
        self.dns_servers: dict[str, DNSServer] = {}
        self.cert_keys: dict[str, object] = {}
        self.switches: dict[str, object] = {}
        self.resp_controllers: dict[str, object] = {}
        self.http_controllers: dict[str, object] = {}
        self.docker_controllers: dict[str, object] = {}
        # (switch alias, vni) -> {"ip:port": VpcProxy}
        self.vpc_proxies: dict[tuple, dict] = {}
        # cluster plane (vproxy_tpu/cluster ClusterNode) — None unless
        # VPROXY_TPU_CLUSTER_PEERS booted one (main.py)
        self.cluster = None
        self._resolver = None  # lazy "(default)" resolver
        # fired by request_drain (the `drain` command / SIGTERM path);
        # main.py registers its stop event here
        self.on_drain_request: list = []

        self.elgs[DEFAULT_CONTROL_ELG] = EventLoopGroup(DEFAULT_CONTROL_ELG, 1)
        worker = EventLoopGroup(DEFAULT_WORKER_ELG, workers)
        self.elgs[DEFAULT_WORKER_ELG] = worker
        # acceptor aliased to worker (Application.java:103-105, REUSEPORT)
        self.elgs[DEFAULT_ACCEPTOR_ELG] = worker

    @property
    def control_loop(self):
        return self.elgs[DEFAULT_CONTROL_ELG].loops[0]

    def get_resolver(self):
        """The "(default)" resolver singleton (AbstractResolver analog):
        TTL-cached, nameservers from /etc/resolv.conf."""
        if self._resolver is None:
            from ..dns.client import DNSClient, Resolver
            ns = []
            try:
                with open("/etc/resolv.conf") as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) >= 2 and parts[0] == "nameserver":
                            ns.append((parts[1], 53))
            except OSError:
                pass
            if not ns:
                ns = [("127.0.0.53", 53), ("8.8.8.8", 53)]
            self._resolver = Resolver(
                self.control_loop, DNSClient(self.control_loop, ns))
        return self._resolver

    @property
    def worker_elg(self) -> EventLoopGroup:
        return self.elgs[DEFAULT_WORKER_ELG]

    @property
    def acceptor_elg(self) -> EventLoopGroup:
        return self.elgs[DEFAULT_ACCEPTOR_ELG]

    # ------------------------------------------------------ graceful drain

    def sessions_in_flight(self) -> int:
        """Live client sessions across every LB surface: python-side
        active_sessions plus sessions owned by C accept lanes (real
        in-flight work the drain contract protects, invisible to the
        python counter)."""
        return sum(lb.active_sessions
                   + getattr(lb, "lane_active", lambda: 0)()
                   for lb in list(self.tcp_lbs.values())
                   + list(self.socks5_servers.values()))

    def request_drain(self) -> str:
        """Begin graceful drain (SIGTERM and the `drain` command funnel
        here): flip /healthz to draining so upstream LBs steer away,
        close every frontend listener (in-flight pumps keep running),
        and fire the drain-request callbacks (main.py registers its
        stop event there so the process exits after the drain window)."""
        from ..utils import events, lifecycle
        if not lifecycle.set_draining():
            return "already draining"
        total = self.sessions_in_flight()
        events.record("drain", f"drain requested: {total} sessions in "
                      "flight, healthz now draining", sessions=total)
        for lb in list(self.tcp_lbs.values()) \
                + list(self.socks5_servers.values()):
            lb.begin_drain()
        for cb in list(self.on_drain_request):
            cb()
        return "OK"

    def drain_wait(self, timeout_s: float, poll_s: float = 0.05,
                   settle_s: float = 0.2) -> bool:
        """Block (main thread only) until every LB session finishes or
        the drain window closes; True when fully drained. Completion
        requires the count to stay zero across a settle window:
        active_sessions counts from backend-pick onward, so connections
        still in their handshake/classify phase (socks5 greeting, TLS
        peek, http head-parse) surface a moment later — an instant zero
        must not be read as 'drained'."""
        import time as _time
        from ..utils import events
        deadline = _time.monotonic() + timeout_s
        zero_since = None
        while True:
            left = self.sessions_in_flight()
            now = _time.monotonic()
            if left <= 0:
                if zero_since is None:
                    zero_since = now
                elif now - zero_since >= settle_s:
                    events.record("drain", "drain complete: all sessions "
                                  "finished")
                    return True
            else:
                zero_since = None
            if now >= deadline:
                events.record("drain", f"drain window closed with {left} "
                              "sessions still in flight", sessions=left)
                return left <= 0
            _time.sleep(poll_s)

    @classmethod
    def create(cls, workers: Optional[int] = None) -> "Application":
        cls._instance = cls(workers)
        return cls._instance

    @classmethod
    def get(cls) -> "Application":
        if cls._instance is None:
            raise RuntimeError("Application not created")
        return cls._instance

    def close(self) -> None:
        if self.cluster is not None:
            self.cluster.close()
            self.cluster = None
        for ctl in self.docker_controllers.values():
            ctl.stop()  # unlinks the uds socket file
        for lb in list(self.tcp_lbs.values()) + list(self.socks5_servers.values()):
            lb.stop()
        for d in self.dns_servers.values():
            d.stop()
        for g in self.server_groups.values():
            g.close()
        seen = set()
        for elg in self.elgs.values():
            if id(elg) not in seen:
                seen.add(id(elg))
                elg.close()
        if Application._instance is self:
            Application._instance = None
