"""Live traffic analytics — sketch-based heavy-hitter attribution.

The PR-1/PR-12 observability layers answer "how much" (metrics) and
"where did THIS request go" (traces); this module answers the production
question in between: *which clients, routes, backends, flows and qnames
are hot RIGHT NOW*. Per-client accounting for millions of users cannot
be a hash map — it is two bounded-memory sketches per dimension:

* **Count-Min** (Cormode/Muthukrishnan) — the rate estimator: depth x
  width counter matrix, every update touches `depth` cells picked by
  independent hashes, estimate = min over rows. Never undercounts;
  overcounts by at most ~e*N/width with high probability (N = total
  stream weight), so a "hot" answer is trustworthy and a "cold" answer
  errs loudly upward, never silently downward.
* **Space-Saving** (Metwally) — the top-K identity keeper: at most K
  live counters; a new key past K evicts the minimum and inherits its
  count as its error bound. Guarantee: every true heavy hitter with
  count > N/K is IN the table, and each entry's overestimate is bounded
  by its recorded `err`.

One hash contract: FNV-1a 64 over raw key bytes — the exact
`maglev_fnv64` idiom the C planes already use (rules/maglev.py, the
flow cache, the lanes), parity-tested py==C through `vtl_hh_hash`.

Dimensions (`DIMS`): clients (peer address), backends (ip:port picked),
routes (listener/LB alias + `upstream:<name>` classify attribution),
flows (the switch flow-key), qnames (DNS). Fed from every plane where
traffic flows:

* **C accept lanes** — per-lane HH shards updated inside the poll tick
  (lane-owned, no locks); each lane's own python thread drains
  `vtl_hh_drain` (HH_REC records, `vtl_hh_rec_size`-guarded like every
  shared record) and folds the (key, count) deltas in here. Shard
  overflow is counted, never silent.
* **flow cache** — per-entry hit tallies drained via
  `vtl_hh_flow_drain` on the switch's analytics tick.
* **python accept path / DNS server / ClassifyService** — direct
  `update()` calls (one branch per site when the knob is off).

Windows: epoch-rotated current+previous pairs
(`VPROXY_TPU_ANALYTICS_WINDOW_S`, default 10s): queries merge both
windows so "current rate" covers the last 10-20s and old traffic is
forgotten two rotations later — no unbounded growth, no decay math on
the hot path. `VPROXY_TPU_ANALYTICS=0` turns the whole plane off
(python sites cost one branch; the C shards gate on one relaxed load).

Surfaces: `top [clients|backends|routes|flows|qnames]` on every command
surface, `list[-detail] analytics`, `GET /analytics` on both HTTP
servers, `vproxy_hh_count{dim,slot}` gauges, and the fleet view — each
node gossips its top-K over the membership heartbeats and any node's
`GET /analytics` renders the merged table (docs/observability.md).
"""
from __future__ import annotations

import os
import struct
import threading
import time
from typing import Dict, List, Optional

ON = os.environ.get("VPROXY_TPU_ANALYTICS", "1") != "0"
WINDOW_S = float(os.environ.get("VPROXY_TPU_ANALYTICS_WINDOW_S", "10"))
TOPK = int(os.environ.get("VPROXY_TPU_ANALYTICS_K", "32"))
CM_WIDTH = int(os.environ.get("VPROXY_TPU_ANALYTICS_CM_WIDTH", "1024"))
CM_DEPTH = int(os.environ.get("VPROXY_TPU_ANALYTICS_CM_DEPTH", "4"))

DIMS = ("clients", "backends", "routes", "flows", "qnames")
# update-plane vocabulary (closed: the vproxy_analytics_updates_total
# label set) — lane is counted in C (vtl_hh_counters), the rest here
PLANES = ("lane", "accept", "dns", "engine", "flow", "cluster")
TOP_SLOTS = 8  # vproxy_hh_count{dim,slot} exposes this many ranks

# FNV-1a 64 — THE hash contract, bit-identical to the C side's
# maglev_fnv64 (parity surface: net/vtl.hh_hash, tests/test_sketch).
# ONE python copy, shared with the tracing sampler — a contract in two
# drifting copies is no contract.
from .trace import fnv64  # noqa: E402


class CountMin:
    """depth x width counter matrix. Row i's cell for a key derives
    from TWO fnv passes (h1 over the key, h2 over the key + one salt
    byte, forced odd) as (h1 + i*h2) mod width — the standard
    double-hashing family, so every row is pairwise independent enough
    for the e*N/width bound while the contract stays "FNV over raw key
    bytes". Linear: update(key, w) == w x update(key, 1), which is what
    makes the C shard's coalesced (key, count) deltas EXACTLY
    equivalent to per-event updates (tests/test_sketch merge test)."""

    __slots__ = ("width", "depth", "rows", "total")

    def __init__(self, width: int = CM_WIDTH, depth: int = CM_DEPTH):
        self.width = width
        self.depth = depth
        self.rows = [[0] * width for _ in range(depth)]
        self.total = 0

    @staticmethod
    def _hashes(key: bytes) -> tuple:
        h1 = fnv64(key)
        h2 = fnv64(key + b"\x9e") | 1
        return h1, h2

    def update(self, key: bytes, w: int = 1) -> None:
        h1, h2 = self._hashes(key)
        for i in range(self.depth):
            self.rows[i][(h1 + i * h2) % self.width] += w
        self.total += w

    def estimate(self, key: bytes) -> int:
        h1, h2 = self._hashes(key)
        return min(self.rows[i][(h1 + i * h2) % self.width]
                   for i in range(self.depth))


class SpaceSaving:
    """At most K live (count, err) counters. A key past capacity evicts
    the current minimum and inherits its count as the error bound —
    guaranteed superset of every key with true count > total/K, each
    entry overestimated by at most its `err`."""

    __slots__ = ("k", "counts", "evictions")

    def __init__(self, k: int = TOPK):
        self.k = k
        self.counts: Dict[str, list] = {}  # key -> [count, err]
        self.evictions = 0

    def update(self, key: str, w: int = 1) -> None:
        ent = self.counts.get(key)
        if ent is not None:
            ent[0] += w
            return
        if len(self.counts) < self.k:
            self.counts[key] = [w, 0]
            return
        mk = min(self.counts, key=lambda x: self.counts[x][0])
        mc = self.counts.pop(mk)[0]
        self.counts[key] = [mc + w, mc]
        self.evictions += 1

    def top(self, n: int = 0) -> List[tuple]:
        """[(key, count, err)] descending; n=0 = all K."""
        items = sorted(((k, v[0], v[1]) for k, v in self.counts.items()),
                       key=lambda t: t[1], reverse=True)
        return items[:n] if n > 0 else items


class WindowedSketch:
    """One dimension's epoch-rotated CountMin + SpaceSaving pair.
    Rotation is lazy (checked on update/query against the monotonic
    clock — no dedicated thread): current becomes previous, previous is
    forgotten. Queries merge both windows, so an answer always covers
    between one and two window spans of traffic."""

    def __init__(self, dim: str, window_s: float = 0.0, k: int = 0,
                 width: int = 0, depth: int = 0):
        self.dim = dim
        self.window_s = window_s or WINDOW_S
        self.k = k or TOPK
        self.width = width or CM_WIDTH
        self.depth = depth or CM_DEPTH
        self.lock = threading.Lock()
        self.updates = 0
        self.rotations = 0
        now = time.monotonic()
        self._cur = (CountMin(self.width, self.depth),
                     SpaceSaving(self.k))
        self._prev = (CountMin(self.width, self.depth),
                      SpaceSaving(self.k))
        self._cur_start = now
        self._rotate_at = now + self.window_s
        # False until a previous window has actually ELAPSED (first
        # rotation; reset again by an idle-gap wipe): the rate
        # denominator must cover only real observed time, or the first
        # window's rates read up to (1 + window/elapsed)x low
        self._has_prev = False

    # caller holds self.lock
    def _maybe_rotate(self, now: float) -> None:
        if now < self._rotate_at:
            return
        if now >= self._rotate_at + self.window_s:
            # idle gap longer than a whole window: both windows are
            # stale — forget everything, start fresh (ONE rotation
            # event; the shared tail below counts it). The wiped prev
            # covers no observed time.
            self._prev = (CountMin(self.width, self.depth),
                          SpaceSaving(self.k))
            self._has_prev = False
        else:
            self._prev = self._cur
            self._has_prev = True
        self._cur = (CountMin(self.width, self.depth),
                     SpaceSaving(self.k))
        self._cur_start = now
        self._rotate_at = now + self.window_s
        self.rotations += 1

    def update(self, key: str, w: int = 1,
               now: Optional[float] = None) -> None:
        kb = key.encode() if isinstance(key, str) else bytes(key)
        if now is None:
            now = time.monotonic()
        with self.lock:
            self._maybe_rotate(now)
            cm, ss = self._cur
            cm.update(kb, w)
            ss.update(key if isinstance(key, str) else kb.decode(
                "utf-8", "replace"), w)
            self.updates += w

    def estimate(self, key: str, now: Optional[float] = None) -> int:
        kb = key.encode() if isinstance(key, str) else bytes(key)
        if now is None:
            now = time.monotonic()
        with self.lock:
            self._maybe_rotate(now)
            return self._cur[0].estimate(kb) + self._prev[0].estimate(kb)

    def top(self, n: int = 0, now: Optional[float] = None) -> List[dict]:
        """Merged cur+prev top table: [{key, count, err, rate}]
        descending by count. rate = count / covered span (between one
        and two windows)."""
        if now is None:
            now = time.monotonic()
        with self.lock:
            self._maybe_rotate(now)
            merged: Dict[str, list] = {}
            for cm_ss in (self._prev, self._cur):
                for key, cnt, err in cm_ss[1].top():
                    ent = merged.get(key)
                    if ent is None:
                        merged[key] = [cnt, err]
                    else:
                        ent[0] += cnt
                        ent[1] += err
            span = now - self._cur_start \
                + (self.window_s if self._has_prev else 0.0)
            span = max(1e-9, min(span, 2 * self.window_s))
        out = sorted(({"key": k, "count": c, "err": e,
                       "rate": round(c / span, 3)}
                      for k, (c, e) in merged.items()),
                     key=lambda d: d["count"], reverse=True)
        return out[:n] if n > 0 else out

    def stat(self) -> dict:
        with self.lock:
            cm, ss = self._cur
            return {"dim": self.dim, "window_s": self.window_s,
                    "k": self.k, "cm_width": self.width,
                    "cm_depth": self.depth, "updates": self.updates,
                    "rotations": self.rotations,
                    "window_total": cm.total + self._prev[0].total,
                    "ss_evictions": ss.evictions
                    + self._prev[1].evictions}


# ------------------------------------------------------------ the plane

_lock = threading.Lock()
_dims: Dict[str, WindowedSketch] = {}
_plane_updates = {p: 0 for p in PLANES}
# rows beyond the top table at the MOST RECENT fleet merge (a gauge,
# not a lifetime total: fleet_table runs per render, so a cumulative
# tally would grow with dashboard poll rate, not with data loss)
_merge_truncated = 0


def _sk(dim: str) -> WindowedSketch:
    sk = _dims.get(dim)
    if sk is None:
        with _lock:
            sk = _dims.get(dim)
            if sk is None:
                sk = _dims[dim] = WindowedSketch(dim)
    return sk


def enabled() -> bool:
    return ON


def configure(on: Optional[bool] = None,
              window_s: Optional[float] = None) -> None:
    """Runtime knob (bench/test hook; production uses the env). Pushes
    the on/off state into the C planes so the lane shards and the flow
    tallies flip together with the python sites."""
    global ON, WINDOW_S
    if on is not None:
        ON = bool(on)
        try:
            from ..net import vtl
            vtl.hh_set_enabled(ON)
        except Exception:
            pass  # py provider / pre-analytics .so: python sites only
    if window_s is not None:
        WINDOW_S = float(window_s)
        with _lock:
            _dims.clear()  # fresh sketches pick up the new window
            _slot_memo.clear()


def push_native_knob() -> None:
    """Push the current on/off state into the C atomic — called from
    every owner of a C-side shard at start (components/lanes.py,
    vswitch/switch.py), the trace_set_sample idiom."""
    try:
        from ..net import vtl
        vtl.hh_set_enabled(ON)
    except Exception:
        pass


# one lock per plane: concurrent updaters (lane threads, worker loops,
# the DNS thread) must not lose increments to a read-modify-write
# interleave, and unrelated planes must not serialize on one module
# lock per observation (two accept-path updates per connection)
_plane_locks = {p: threading.Lock() for p in PLANES}


def _plane_incr(plane: str, w: int) -> None:
    with _plane_locks.get(plane) or _lock:
        _plane_updates[plane] = _plane_updates.get(plane, 0) + w


def update(dim: str, key: str, w: int = 1, plane: str = "accept") -> None:
    """One traffic observation. The knob-off cost at every call site is
    this one branch."""
    if not ON:
        return
    _sk(dim).update(key, w)
    _plane_incr(plane, w)


# the C FlowKey prefix of FLOW_REC (net/vtl.py) — rendered, not
# reinterpreted: sender_ip u32, sender_port u16, vni 3s, eth_dst 6s,
# eth_type 2s, ip_src 4s, ip_dst 4s, proto B
_FLOW_KEY = struct.Struct("<IH3s6s2s4s4sB")


def _render_flow_key(kb: bytes) -> str:
    if len(kb) < _FLOW_KEY.size:
        return kb.hex()
    (snd_ip, snd_port, vni, _dst, _etype, ip_src, ip_dst,
     proto) = _FLOW_KEY.unpack_from(kb)
    vni_i = int.from_bytes(vni, "big")
    if any(ip_src):
        flow = (f"{'.'.join(map(str, ip_src))}->"
                f"{'.'.join(map(str, ip_dst))}/{proto}")
    else:  # raw-L2 flow: no parsed v4 header
        flow = f"l2:{_dst.hex()}"
    snd = ".".join(str((snd_ip >> s) & 0xFF) for s in (24, 16, 8, 0))
    return f"vni{vni_i}:{flow} via {snd}:{snd_port}"


def ingest_hh_recs(recs) -> None:
    """Fold drained C HH_REC tuples ((count, lane, dim, key) — the
    net/vtl.py hh_drain / hh_flow_drain shape) into the dimension
    sketches. Client keys arrive as raw 4/16-byte addresses and render
    through format_ip so they merge with the python accept path's
    string keys; flow keys are the 26-byte C FlowKey."""
    if not ON:
        return
    from ..net.vtl import HH_DIMS
    from .ip import format_ip
    for count, _lane, dim_i, kb in recs:
        dim = HH_DIMS[dim_i] if dim_i < len(HH_DIMS) else None
        if dim is None:
            continue
        if dim == "clients":
            try:
                key = format_ip(kb)
            except (ValueError, OSError):
                key = kb.hex()
        elif dim == "flows":
            key = _render_flow_key(kb)
            # flow tallies are not in the C shard-update counter: tally
            # them here (the lane dims ARE — vtl_hh_counters — so
            # counting their ingest too would double them)
            _plane_incr("flow", count)
        else:  # backends: a C-precompiled "ip:port" string
            key = kb.decode("utf-8", "replace")
        _sk(dim).update(key, count)


# ------------------------------------------------------------- queries

def top_table(dim: str, n: int = TOP_SLOTS) -> List[dict]:
    if dim not in DIMS:
        raise ValueError(f"unknown analytics dimension {dim!r} "
                         f"(one of {', '.join(DIMS)})")
    return _sk(dim).top(n)


# scrape memo for the per-slot gauges: {dim: ((updates, rotations),
# rows)} — a /metrics scrape reads TOP_SLOTS gauges per dim, and
# without this each one would re-merge + re-sort the same table (8x
# redundant lock traffic against the hot update path). Keyed on the
# sketch's own change counters, so a stale entry is impossible: any
# update or rotation changes the key and the next gauge recomputes.
_slot_memo: Dict[str, tuple] = {}


def top_slot(dim: str, slot: int) -> float:
    """Rank `slot`'s merged count (0 when the slot is empty) — the
    vproxy_hh_count{dim,slot} gauge reader."""
    if not ON:
        return 0.0
    sk = _sk(dim)
    # the time bucket keeps an IDLE dimension honest: with no updates
    # the change counters freeze, but rotation must still run (top()
    # rotates lazily) or the gauges would report the last burst
    # forever while /analytics shows empty tables
    key = (sk.updates, sk.rotations,
           int(time.monotonic() / sk.window_s))
    memo = _slot_memo.get(dim)
    if memo is None or memo[0] != key:
        memo = (key, sk.top(TOP_SLOTS))
        _slot_memo[dim] = memo
    rows = memo[1]
    return float(rows[slot]["count"]) if slot < len(rows) else 0.0


def plane_updates_total(plane: str) -> int:
    n = _plane_updates.get(plane, 0)
    if plane == "lane":
        # the C shard-update atomic is the authoritative lane tally
        # (ingest_hh_recs deliberately does NOT re-count those dims);
        # python-side lane credits (the routes dim) add on top
        try:
            from ..net import vtl
            n += int(vtl.hh_counters()[0])
        except Exception:
            pass
    return n


def merge_truncated_last() -> int:
    """Rows the most recent fleet merge could not fit in the top table
    — the counted form of "the fleet view is top-N, more keys exist"."""
    return _merge_truncated


def rotations_total() -> int:
    return sum(sk.rotations for sk in list(_dims.values()))


def status() -> dict:
    """`list-detail analytics` / the GET /analytics "local" object."""
    return {"enabled": ON, "window_s": WINDOW_S, "k": TOPK,
            "cm": {"width": CM_WIDTH, "depth": CM_DEPTH},
            "updates": {p: plane_updates_total(p) for p in PLANES},
            "merge_truncated": _merge_truncated,
            "dims": {d: _sk(d).stat() for d in DIMS}}


def snapshot(n: int = TOP_SLOTS) -> dict:
    """The BENCH/storm artifact hook: every dimension's merged top
    table plus the plane counters, one JSON-ready dict."""
    return {"status": status(),
            "top": {d: top_table(d, n) for d in DIMS}}


def snapshot_with_fleet(n: int = TOP_SLOTS) -> dict:
    """snapshot() plus the fleet-merged table when a cluster node is
    booted — the ONE assembly all three serving surfaces share
    (`list-detail analytics`, both HTTP servers' GET /analytics), so
    the fleet-gating rule cannot drift between them."""
    doc = snapshot(n)
    from ..cluster import ClusterNode
    node = ClusterNode._instance
    if node is not None and ON:
        doc["fleet"] = node.fleet_analytics()
    return doc


def gossip_summary(n: int = 5) -> dict:
    """The compact per-node top-K that rides the membership heartbeats:
    {dim: [[key, count], ...]} for non-empty dimensions only (an idle
    node adds ~2 bytes to its heartbeat, not 5 empty tables)."""
    if not ON:
        return {}
    out = {}
    for d in DIMS:
        t = _sk(d).top(n)
        if t:
            out[d] = [[e["key"], e["count"]] for e in t]
    return out


def fleet_table(peers: dict, n: int = TOP_SLOTS) -> dict:
    """Merge this node's top tables with the gossiped peer summaries
    ({node_id: {dim: [[key, count], ...]}}) into one fleet-wide view.
    Truncation past the top table is VISIBLE, never silent: each dim's
    truncated-row count rides the payload (`truncated`) and the gauge
    (merge_truncated_last) holds the latest merge's total."""
    global _merge_truncated
    out: dict = {"truncated": {}}
    total_truncated = 0
    for d in DIMS:
        merged: Dict[str, int] = {}
        nodes: Dict[str, int] = {}
        for e in top_table(d, 0):
            merged[e["key"]] = merged.get(e["key"], 0) + e["count"]
            nodes[e["key"]] = nodes.get(e["key"], 0) + 1
        for _nid, summ in peers.items():
            for key, count in (summ or {}).get(d, ()):
                merged[key] = merged.get(key, 0) + int(count)
                nodes[key] = nodes.get(key, 0) + 1
        rows = sorted(({"key": k, "count": c, "nodes": nodes[k]}
                       for k, c in merged.items()),
                      key=lambda r: r["count"], reverse=True)
        if len(rows) > n:
            out["truncated"][d] = len(rows) - n
            total_truncated += len(rows) - n
            rows = rows[:n]
        out[d] = rows
    _merge_truncated = total_truncated
    return out


def render_top(dim: str, rows: Optional[List[dict]] = None) -> List[str]:
    """The `top <dim>` command's text table."""
    if rows is None:
        rows = top_table(dim)
    out = [f"top {dim} (window {WINDOW_S:g}s x2, k={TOPK})"]
    if not rows:
        out.append("  (no traffic observed)")
        return out
    for i, e in enumerate(rows):
        err = f" err<={e['err']}" if e.get("err") else ""
        nodes = f" nodes={e['nodes']}" if "nodes" in e else ""
        rate = f" {e['rate']:.1f}/s" if "rate" in e else ""
        out.append(f"  #{i} {e['key']}  count={e['count']}"
                   f"{rate}{err}{nodes}")
    return out


def reset() -> None:
    """Test hook: drop every sketch (plane counters stay — process-
    lifetime totals like every other /metrics series)."""
    with _lock:
        _dims.clear()
        _slot_memo.clear()
