"""Flight recorder — fixed-size in-memory ring of data-plane events.

The black-box counterpart to utils/metrics: metrics answer "how much /
how fast", the recorder answers "what happened around second X". Event
sources (all low-rate relative to the bytes they describe):

* connection lifecycle — splice-pump sessions opening/closing with byte
  counts and the error that ended them (components/tcplb.py);
* loop stalls — any event-loop callback that held the loop thread past
  the stall threshold, the known GIL-contention p999 culprit
  (net/eventloop.py);
* classify failovers — device dispatch errors that degraded a batch to
  the host oracle (rules/service.py);
* health-check up/down edges (components/servergroup.py).

Dumped over HTTP at /events (next to /metrics, /lsof, /jstack —
utils/metrics.launch_inspection_http) and via the control-plane command
`list event-log`. The ring is process-global and bounded: recording is
a lock + deque append, safe from any thread, and never blocks on I/O.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Optional

DEFAULT_CAPACITY = 1024

# kind -> plane classification for `GET /events?plane=` and
# `list event-log plane=<p>` (the analytics drill-down: jump from a hot
# client in `top clients` to its accept-plane events without wading
# through cluster gossip). An event may carry an explicit plane= field
# to override; unmapped kinds land in "app".
EVENT_PLANES = ("accept", "lane", "engine", "cluster", "loop",
                "policing", "app")
_KIND_PLANE = {
    "conn": "accept", "conn_denied": "accept", "drain": "accept",
    "drain_shed": "accept", "overload": "accept",
    "overload_mode": "accept", "halfopen_shed": "accept",
    "retry": "accept", "eject": "accept", "eject_skipped": "accept",
    "readmit": "accept", "hc_up": "accept", "hc_down": "accept",
    "lanes": "lane",
    "classify_failover": "engine",
    "peer_up": "cluster", "peer_down": "cluster",
    "cluster_degrade": "cluster", "cluster_rejoin": "cluster",
    "cluster_steer_rebuild": "cluster",
    "generation_bump": "cluster", "generation_install": "cluster",
    "generation_reject": "cluster", "generation_discard": "cluster",
    "loop_stall": "loop",
    "policy_install": "policing", "policy_shed": "policing",
    "quarantine": "policing",
}


def _mono_ns(ev: dict) -> int:
    """An event's monotonic-ns stamp (derived from the float `mono`
    for events recorded before the field existed)."""
    ns = ev.get("mono_ns")
    return int(ns) if ns is not None else int(ev.get("mono", 0.0) * 1e9)


def plane_of(ev: dict) -> str:
    """The plane an event belongs to: its explicit plane= field when
    one was recorded, else the kind classification, else "app"."""
    p = ev.get("plane")
    if p:
        return p
    return _KIND_PLANE.get(ev.get("kind", ""), "app")


class FlightRecorder:
    _instance: Optional["FlightRecorder"] = None
    _ilock = threading.Lock()

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring: deque = deque(maxlen=capacity)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self.dropped = 0  # events evicted by ring wraparound

    @classmethod
    def get(cls) -> "FlightRecorder":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = FlightRecorder()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Test hook: drop the singleton (a new one lazily respawns)."""
        with cls._ilock:
            cls._instance = None

    def record(self, kind: str, msg: str, trace_id: int = 0,
               **fields) -> None:
        """trace_id (optional, nonzero) cross-references the event with
        a span trace (utils/trace.py): `GET /events?trace=<id>` and the
        trace waterfall join recorder events and spans instead of two
        unjoinable logs."""
        ev = {"seq": 0, "ts": time.time(), "mono": time.monotonic(),
              "mono_ns": time.monotonic_ns(), "kind": kind, "msg": msg}
        if trace_id:
            ev["trace_id"] = trace_id
        if fields:
            ev.update(fields)
        with self._lock:
            ev["seq"] = next(self._seq)
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)

    def snapshot(self, last: int = 0, trace: Optional[int] = None,
                 plane: Optional[str] = None,
                 since: Optional[int] = None,
                 until: Optional[int] = None) -> list:
        """Events oldest-first; `last` > 0 trims to the newest N;
        `trace` filters to events carrying that trace_id; `plane`
        filters by plane_of() classification; `since`/`until` are
        inclusive monotonic-ns bounds on the SAME clock trace spans
        stamp t_ns with (time.monotonic_ns) — a capture or incident
        window joins recorder events against traces directly."""
        with self._lock:
            evs = list(self._ring)
        if trace is not None:
            evs = [e for e in evs if e.get("trace_id") == trace]
        if plane is not None:
            evs = [e for e in evs if plane_of(e) == plane]
        if since is not None:
            evs = [e for e in evs if _mono_ns(e) >= since]
        if until is not None:
            evs = [e for e in evs if _mono_ns(e) <= until]
        return evs[-last:] if last > 0 else evs

    def lines(self, last: int = 0, plane: Optional[str] = None,
              since: Optional[int] = None,
              until: Optional[int] = None) -> list:
        """Human-form rendering for the command surface."""
        out = []
        for ev in self.snapshot(last, plane=plane, since=since,
                                until=until):
            extras = " ".join(
                f"{k}={ev[k]}" for k in sorted(ev)
                if k not in ("seq", "ts", "mono", "mono_ns", "kind",
                             "msg"))
            stamp = time.strftime("%H:%M:%S", time.localtime(ev["ts"]))
            out.append(f"[{ev['seq']}] {stamp} {ev['kind']}: {ev['msg']}"
                       + (f" ({extras})" if extras else ""))
        return out


def record(kind: str, msg: str, **fields) -> None:
    """Module-level convenience: FlightRecorder.get().record(...)."""
    FlightRecorder.get().record(kind, msg, **fields)
