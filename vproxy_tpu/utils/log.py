"""Leveled, colored, channel-based logging + probe channels.

Reference: base util/Logger.java:317 (leveled + colored + machine-
parsable types), probe channels gated by -Dprobe (Config.java:97-123),
and `lowLevelDebug` behind asserts. Here:

* `Logger("channel")` — per-subsystem logger; levels debug/info/warn/
  error/alert; `alert` is the reference's ALERT log type (operator-
  visible events: device failover, loop death, OOM...).
* probe channels — `VPROXY_TPU_PROBE=comma,separated,channels` enables
  targeted data-path tracing with zero cost when off (one set lookup).
  Mirrors the reference's `-Dprobe=...`.
* level filter — `VPROXY_TPU_LOG=debug|info|warn|error` (default info).
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback

_LEVELS = {"debug": 0, "info": 1, "warn": 2, "error": 3, "alert": 3}
_COLORS = {"debug": "\033[0;36m", "info": "\033[0;32m",
           "warn": "\033[0;33m", "error": "\033[0;31m",
           "alert": "\033[1;31m"}
_RESET = "\033[0m"

_lock = threading.Lock()


def _min_level() -> int:
    return _LEVELS.get(os.environ.get("VPROXY_TPU_LOG", "info"), 1)


def _probes() -> set:
    v = os.environ.get("VPROXY_TPU_PROBE", "")
    return {p.strip() for p in v.split(",") if p.strip()}


_PROBES = _probes()


def reload_probes() -> None:
    """Re-read VPROXY_TPU_PROBE (config hot-reload / tests)."""
    global _PROBES
    _PROBES = _probes()


def probe_enabled(channel: str) -> bool:
    return channel in _PROBES


def probe(channel: str, msg: str) -> None:
    """Targeted data-path trace; no-op unless the channel is enabled."""
    if channel in _PROBES:
        _emit("debug", f"probe/{channel}", msg)


def _emit(level: str, channel: str, msg: str, exc: bool = False) -> None:
    ts = time.strftime("%Y-%m-%d %H:%M:%S")
    color = _COLORS[level] if sys.stderr.isatty() else ""
    reset = _RESET if color else ""
    line = f"{color}[{ts}] [{level.upper():5s}] [{channel}] {msg}{reset}\n"
    with _lock:
        sys.stderr.write(line)
        if exc:
            traceback.print_exc(file=sys.stderr)


class Logger:
    __slots__ = ("channel",)

    def __init__(self, channel: str):
        self.channel = channel

    def debug(self, msg: str, exc: bool = False) -> None:
        if _min_level() <= 0:
            _emit("debug", self.channel, msg, exc)

    def info(self, msg: str, exc: bool = False) -> None:
        if _min_level() <= 1:
            _emit("info", self.channel, msg, exc)

    def warn(self, msg: str, exc: bool = False) -> None:
        if _min_level() <= 2:
            _emit("warn", self.channel, msg, exc)

    def error(self, msg: str, exc: bool = False) -> None:
        _emit("error", self.channel, msg, exc)

    def alert(self, msg: str, exc: bool = False) -> None:
        _emit("alert", self.channel, msg, exc)
