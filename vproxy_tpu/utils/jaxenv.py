"""JAX backend hygiene for driver entry points and tests.

Two environment hazards (both observed in round 1, see tests/conftest.py):

* The axon TPU-tunnel plugin (installed under ``~/.axon_site``) registers
  itself via sitecustomize and eagerly dials the TPU pool during backend
  discovery — even under ``JAX_PLATFORMS=cpu`` — hanging or raising
  ``Unable to initialize backend`` whenever the tunnel is busy/down.
* sitecustomize pre-imports jax at interpreter start, freezing
  ``jax_platforms`` before our env vars exist, so plain ``os.environ``
  settings are not enough; ``jax.config.update`` is required as well.

``force_cpu(n)`` applies the full hygiene (strip plugin, force the cpu
platform, request *n* virtual host devices) and is safe to call whether
or not jax is already imported, as long as no device has been touched
yet.  ``cpu_subprocess_env()`` builds a sanitized env for re-exec'ing a
script on CPU after a TPU backend failure.
"""
from __future__ import annotations

import os
import sys


def strip_axon_plugin() -> None:
    """Remove the axon TPU-tunnel plugin from module search paths."""
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    os.environ["PYTHONPATH"] = ":".join(
        p for p in os.environ.get("PYTHONPATH", "").split(":")
        if p and ".axon_site" not in p)


def _with_host_device_flag(flags: str, n_devices: int) -> str:
    """Set (or replace a differing) host-device-count flag in *flags*."""
    import re
    pat = r"--xla_force_host_platform_device_count=\d+"
    new = f"--xla_force_host_platform_device_count={n_devices}"
    if re.search(pat, flags):
        return re.sub(pat, new, flags)
    return (flags + " " + new).strip()


def _ensure_host_device_flag(n_devices: int) -> None:
    os.environ["XLA_FLAGS"] = _with_host_device_flag(
        os.environ.get("XLA_FLAGS", ""), n_devices)


def force_cpu(n_devices: int | None = None) -> None:
    """Force the CPU platform (with *n_devices* virtual devices) robustly.

    Idempotent; works whether jax is not-yet-imported, imported-but-idle,
    or pre-imported by sitecustomize with platform=axon frozen in.
    """
    strip_axon_plugin()
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        _ensure_host_device_flag(n_devices)
    if "jax" in sys.modules:
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # config frozen; detection below still applies
        # If a non-cpu backend is ALREADY live, proceeding would silently
        # dial the tunnel (the round-1 rc=124 hang) — fail loud instead.
        try:
            from jax._src import xla_bridge as _xb
            live = getattr(_xb, "_backends", {})
            if live and "cpu" not in live:
                raise RuntimeError(
                    "force_cpu() called after a non-cpu jax backend was "
                    f"initialized ({list(live)}); run in a fresh process "
                    "(see cpu_subprocess_env)")
        except ImportError:
            pass  # private layout changed; keep best-effort behavior


def cpu_subprocess_env(n_devices: int | None = None) -> dict:
    """Env for re-exec'ing a script on CPU with the plugin stripped."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The plugin (and the sitecustomize that pre-imports jax) reach the
    # interpreter solely via the PYTHONPATH entry — dropping it here is a
    # complete cure for the child process.
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if p and ".axon_site" not in p)
    if n_devices:
        env["XLA_FLAGS"] = _with_host_device_flag(
            env.get("XLA_FLAGS", ""), n_devices)
    return env
