"""vmirror — filtered traffic capture to pcap with JSON hot-reload.

Parity: /root/reference/base/src/main/java/vmirror/Mirror.java:18-89 and
doc/mirror-example.json. The reference taps chosen origins (switch
frames, SSL-plaintext ring buffers, ...) behind filters and re-emits
synthetic ethernet frames into a TAP device for wireshark. The TPU-era
redesign emits a standard pcap FILE instead (no kernel device needed;
wireshark/tcpdump read it directly); origins here:

  * "switch" — ethernet frames entering the vswitch stack (raw frames,
    no synthesis needed);
  * "ssl"    — TLS plaintext at the termination boundary, both
    directions (the only place decrypted bytes exist);
  * "proxy"  — L7 relay payload through ProcessorEngine sessions.

Config (JSON, hot-reloaded on mtime change, checked at most once per
second from the data path):

    {"enabled": true,
     "output": "/tmp/capture.pcap",
     "origins": [
        {"origin": "ssl",
         "filters": [{"network": "10.0.0.0/8", "port": 443}]},
        {"origin": "switch"}          # no filters = everything
     ]}

A filter matches when every present field matches either endpoint
(network = CIDR against src/dst ip, port against src/dst port). An
origin with no filters captures all. The process-wide instance is
Mirror.get(); VPROXY_TPU_MIRROR=<path> arms it at first use. Hot paths
gate on the plain-bool `Mirror.get().active` before building any
metadata.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Optional

from .ip import Network, parse_ip
from .log import Logger

_log = Logger("mirror")

LINKTYPE_EN10MB = 1


class PcapWriter:
    """Minimal classic-pcap writer (microsecond timestamps)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")
        if self._f.tell() == 0:
            self._f.write(struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0,
                                      65535, LINKTYPE_EN10MB))
            self._f.flush()

    def write(self, frame: bytes) -> None:
        ts = time.time()
        self._f.write(struct.pack("<IIII", int(ts), int(ts % 1 * 1e6),
                                  len(frame), len(frame)) + frame)
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class _Filter:
    def __init__(self, cfg: dict):
        self.network: Optional[Network] = None
        if cfg.get("network"):
            net = cfg["network"]
            if "/" not in net:
                raw = parse_ip(net)
                net = f"{net}/{32 if len(raw) == 4 else 128}"
            # Network.parse validates host bits — a silently-never-
            # matching filter is worse than a rejected config
            self.network = Network.parse(net)
        self.port = int(cfg["port"]) if cfg.get("port") else None

    def match(self, src_ip, dst_ip, src_port, dst_port) -> bool:
        if self.network is not None:
            ok = False
            for ip in (src_ip, dst_ip):
                if ip is not None and self.network.contains_ip(ip):
                    ok = True
            if not ok:
                return False
        if self.port is not None and self.port not in (src_port, dst_port):
            return False
        return True


def _synth_tcp_frame(src_ip: bytes, dst_ip: bytes, src_port: int,
                     dst_port: int, payload: bytes) -> bytes:
    """Fake ether+ip+tcp around a plaintext payload (Mirror.java builds
    the same shape so wireshark can dissect flows)."""
    v6 = len(src_ip) == 16 or len(dst_ip) == 16

    def pad(ip: bytes) -> bytes:
        if v6 and len(ip) == 4:
            return b"\x00" * 10 + b"\xff\xff" + ip
        return ip

    src_ip, dst_ip = pad(src_ip), pad(dst_ip)
    # synthetic locally-administered macs derived from the ip tails
    eth = (b"\x02" + (b"\x00" * 5 + dst_ip)[-5:]) + \
        (b"\x02" + (b"\x00" * 5 + src_ip)[-5:]) + \
        (b"\x86\xdd" if v6 else b"\x08\x00")
    tcp = struct.pack(">HHIIBBHHH", src_port, dst_port, 0, 0,
                      5 << 4, 0x18, 65535, 0, 0) + payload  # PSH|ACK
    if v6:
        ip = struct.pack(">IHBB", 6 << 28, len(tcp), 6, 64) + src_ip + dst_ip
    else:
        ip = struct.pack(">BBHHHBBH", 0x45, 0, 20 + len(tcp), 0, 0, 64, 6,
                         0) + src_ip + dst_ip
    return eth + ip + tcp


class Mirror:
    """Process-wide mirror registry. `active` is a plain bool so hot
    paths pay one attribute read when mirroring is off."""

    _instance: Optional["Mirror"] = None
    _ilock = threading.Lock()

    @classmethod
    def get(cls) -> "Mirror":
        inst = cls._instance
        if inst is not None:  # lock-free fast path: called per data event
            return inst
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls()
                path = os.environ.get("VPROXY_TPU_MIRROR")
                if path:
                    cls._instance.load(path)
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._ilock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.disable()

    def __init__(self):
        self.active = False
        self.hot = False  # active OR a config file is armed for reload
        self.path: Optional[str] = None
        self._mtime = 0.0
        self._next_check = 0.0
        self._origins: dict = {}
        self._writer: Optional[PcapWriter] = None
        self._lock = threading.Lock()

    # ----------------------------------------------------- configuration

    def load(self, path: str) -> None:
        """Load (or arm for hot-reload) a JSON config file. Once armed,
        `hot` stays True even while disabled so the taps keep probing
        wants() and a config edit can re-enable capture."""
        self.path = path
        try:
            self._mtime = os.stat(path).st_mtime
            with open(path) as f:
                cfg = json.load(f)
        except (OSError, ValueError) as e:
            _log.alert(f"mirror config {path}: {e!r}; disabled")
            self.set_config(None)
            return
        self.set_config(cfg)

    def set_config(self, cfg: Optional[dict]) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None
            self._origins = {}
            self.active = False
            try:
                if cfg and cfg.get("enabled", True):
                    origins = {}
                    for ent in cfg.get("origins", []):
                        origins[ent["origin"]] = [
                            _Filter(f) for f in ent.get("filters", [])]
                    out = cfg.get("output")
                    if out:
                        self._writer = PcapWriter(out)
                        self._origins = origins
                    self.active = bool(self._origins) \
                        and self._writer is not None
            except (OSError, ValueError, KeyError, TypeError) as e:
                # a malformed hot-reloaded config must never raise out
                # of the packet data path — disable and report instead
                _log.alert(f"mirror config invalid ({e!r}); disabled")
                self._origins = {}
                self.active = False
            self.hot = self.active or self.path is not None

    def disable(self) -> None:
        self.path = None
        self.set_config(None)

    def maybe_reload(self) -> None:
        """mtime-based hot reload, throttled to one stat() per second.
        Called from the data path only while a config file is armed."""
        if self.path is None:
            return
        now = time.monotonic()
        if now < self._next_check:
            return
        self._next_check = now + 1.0
        try:
            m = os.stat(self.path).st_mtime
        except OSError:
            return
        if m != self._mtime:
            self._mtime = m
            _log.info(f"mirror config changed; reloading {self.path}")
            self.load(self.path)

    # ------------------------------------------------------------- taps

    def wants(self, origin: str) -> bool:
        self.maybe_reload()
        return self.active and origin in self._origins

    def mirror(self, origin: str, payload: bytes,
               src_ip: Optional[bytes] = None, dst_ip: Optional[bytes] = None,
               src_port: int = 0, dst_port: int = 0,
               raw_ether: bool = False) -> None:
        """Capture one payload. raw_ether=True writes payload verbatim
        (already an ethernet frame — the switch origin)."""
        if not self.wants(origin):
            return
        flts = self._origins.get(origin, [])
        if flts and not any(f.match(src_ip, dst_ip, src_port, dst_port)
                            for f in flts):
            return
        if raw_ether:
            frame = payload
        else:
            frame = _synth_tcp_frame(src_ip or b"\x00" * 4,
                                     dst_ip or b"\x00" * 4,
                                     src_port, dst_port, payload)
        with self._lock:
            if self._writer is not None:
                try:
                    self._writer.write(frame)
                except OSError as e:
                    _log.alert(f"mirror write failed: {e!r}; disabled")
                    self.active = False
