"""Prometheus-style metrics registry + global inspection surface.

Parity: reference `vproxybase/prometheus/Metrics.java` (Counter / Gauge
/ GaugeF with a label set, text exposition) and `GlobalInspection.java:
24-205`: one process-global surface collecting direct-memory bytes,
per-loop thread registry, stack-trace dump and open-FD dump, exposed
over HTTP (`getPrometheusString():177`,
`GlobalInspectionHttpServerLauncher.java:9` — /metrics, /lsof, /jstack).
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Callable, Dict, List, Optional, Tuple


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Metric:
    mtype = "untyped"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})

    def value(self) -> float:
        raise NotImplementedError

    def sample_line(self) -> str:
        v = self.value()
        v_str = "%d" % v if float(v).is_integer() else repr(float(v))
        return f"{self.name}{_fmt_labels(self.labels)} {v_str}"

    def sample_lines(self) -> List[str]:
        return [self.sample_line()]


class Counter(Metric):
    mtype = "counter"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        super().__init__(name, labels)
        self._v = 0
        self._lock = threading.Lock()

    def incr(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def value(self) -> float:
        return self._v


class Gauge(Metric):
    mtype = "gauge"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        super().__init__(name, labels)
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = v

    def add(self, d: float) -> None:
        self._v += d

    def value(self) -> float:
        return self._v


class GaugeF(Metric):
    """Gauge computed by a function at scrape time."""
    mtype = "gauge"

    def __init__(self, name: str, fn: Callable[[], float],
                 labels: Optional[Dict[str, str]] = None):
        super().__init__(name, labels)
        self.fn = fn

    def value(self) -> float:
        return float(self.fn())


class Histogram(Metric):
    """Fixed log2-bucket histogram with Prometheus exposition.

    Bucket upper bounds are 1, 2, 4, ... 2**(buckets-1) in the metric's
    own unit (latencies here use microseconds, hence the `_us` naming
    convention), plus the implicit +Inf bucket. The hot path is one
    uncontended lock acquisition, a bit_length() bucket pick and three
    integer adds — no allocation, no percentile math.

    An optional reservoir (ring of the last N raw samples) makes
    percentiles() EXACT over the recent window instead of log2-bucket
    estimates; the classify latency contract (BASELINE p99 < 50us) is
    measured through it, while /metrics scrapes see the cumulative
    buckets either way.
    """
    mtype = "histogram"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 buckets: int = 27, reservoir: int = 0):
        super().__init__(name, labels)
        self._bounds = [1 << k for k in range(buckets)]
        self._counts = [0] * (buckets + 1)  # + the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        self._res_cap = reservoir
        self._res: List[float] = [0.0] * reservoir
        self._res_n = 0

    def _bucket_of(self, v: float) -> int:
        if v <= 1.0:
            return 0
        iv = int(v)
        if iv < v:
            iv += 1
        return min((iv - 1).bit_length(), len(self._bounds))

    def observe(self, v: float) -> None:
        i = self._bucket_of(v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._res_cap:
                self._res[self._res_n % self._res_cap] = v
                self._res_n += 1

    def merge(self, bucket_deltas, sum_delta: float,
              count_delta: int) -> None:
        """Fold pre-bucketed counts in (the C accept-lane stage
        histograms: native/vtl.cpp buckets with the same log2 rule and
        python merges the per-tick deltas, so lane-served connections
        land in the SAME series python-path connections populate). The
        reservoir stays sample-level-only by design — percentiles fall
        back to the bucket estimate when merged counts dominate."""
        if count_delta <= 0:
            return
        with self._lock:
            for i, d in enumerate(bucket_deltas):
                if d:
                    self._counts[i] += d
            self._sum += sum_delta
            self._count += count_delta

    def value(self) -> float:
        return self._count

    def state(self) -> Tuple[int, float, List[int]]:
        """(count, sum, [bucket counts]) snapshot — the workload-capture
        delta-window primitive (utils/workload.py)."""
        with self._lock:
            return self._count, self._sum, list(self._counts)

    def sample_lines(self) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        out = []
        cum = 0
        for bound, n in zip(self._bounds, counts):
            cum += n
            lbl = _fmt_labels({**self.labels, "le": str(bound)})
            out.append(f"{self.name}_bucket{lbl} {cum}")
        lbl = _fmt_labels({**self.labels, "le": "+Inf"})
        out.append(f"{self.name}_bucket{lbl} {total}")
        base = _fmt_labels(self.labels)
        s_str = "%d" % s if float(s).is_integer() else repr(float(s))
        out.append(f"{self.name}_sum{base} {s_str}")
        out.append(f"{self.name}_count{base} {total}")
        return out

    def percentiles(self, qs=(50.0, 99.0, 99.9)) -> Optional[Dict[str, float]]:
        """-> {"n", "p50", "p99", "p999", ...} or None when empty.
        Exact over the reservoir window when one is configured, else a
        log-linear estimate from the cumulative buckets."""
        with self._lock:
            if self._count == 0:
                return None
            if self._res_cap and self._res_n:
                n = min(self._res_n, self._res_cap)
                window = sorted(self._res[:n])
                out = {"n": self._res_n}
                for q in qs:
                    i = min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))
                    out[_q_key(q)] = float(window[i])
                return out
            counts = list(self._counts)
            total = self._count
        out = {"n": total}
        for q in qs:
            out[_q_key(q)] = _bucket_quantile(self._bounds, counts, total,
                                              q / 100.0)
        return out


def _q_key(q: float) -> str:
    return "p" + ("%g" % q).replace(".", "")


def _bucket_quantile(bounds, counts, total, q: float) -> float:
    """Log-linear interpolation inside the winning log2 bucket."""
    rank = q * total
    cum = 0
    lo = 0.0
    for bound, n in zip(bounds, counts):
        if cum + n >= rank and n > 0:
            frac = (rank - cum) / n
            return lo + frac * (bound - lo)
        cum += n
        lo = float(bound)
    return float(bounds[-1] * 2)  # landed in +Inf


class MetricsRegistry:
    def __init__(self):
        self._metrics: List[Metric] = []
        self._lock = threading.Lock()

    def add(self, m: Metric) -> Metric:
        with self._lock:
            self._metrics.append(m)
        return m

    def remove(self, m: Metric) -> None:
        with self._lock:
            if m in self._metrics:
                self._metrics.remove(m)

    def counter(self, name: str, **labels) -> Counter:
        return self.add(Counter(name, labels))  # type: ignore[return-value]

    def gauge(self, name: str, **labels) -> Gauge:
        return self.add(Gauge(name, labels))  # type: ignore[return-value]

    def gauge_f(self, name: str, fn, **labels) -> GaugeF:
        return self.add(GaugeF(name, fn, labels))  # type: ignore[return-value]

    def histogram(self, name: str, buckets: int = 27, reservoir: int = 0,
                  **labels) -> Histogram:
        return self.add(Histogram(name, labels, buckets=buckets,
                                  reservoir=reservoir))  # type: ignore[return-value]

    def prometheus_text(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        by_name: Dict[str, Tuple[str, List[Metric]]] = {}
        for m in metrics:
            by_name.setdefault(m.name, (m.mtype, []))[1].append(m)
        out = []
        for name in sorted(by_name):
            mtype, ms = by_name[name]
            out.append(f"# TYPE {name} {mtype}")
            for m in ms:
                out.extend(m.sample_lines())
        return "\n".join(out) + ("\n" if out else "")


class GlobalInspection:
    """Process-global metric + introspection surface (singleton)."""

    _instance: Optional["GlobalInspection"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self.registry = MetricsRegistry()
        self._loops: Dict[int, object] = {}  # id(loop) -> SelectorEventLoop
        self._lock = threading.Lock()
        # (name, sorted-label-items) -> Metric for get-or-create users
        self._named: Dict[tuple, Metric] = {}
        self.direct_memory_bytes = self.registry.gauge(
            "vproxy_direct_memory_bytes_current")
        self.registry.gauge_f("vproxy_event_loop_count",
                              lambda: len(self._loops))
        self.registry.gauge_f("vproxy_open_fd_count",
                              lambda: len(self._open_fds()))
        self.registry.gauge_f("vproxy_thread_count",
                              lambda: threading.active_count())
        # micro-batch classify queue (rules/service.py — the north-star
        # data plane): batching ratio = queries / dispatches
        for k in ("queries", "dispatches", "device_queries",
                  "oracle_queries", "failovers", "max_batch"):
            self.registry.gauge_f(
                f"vproxy_classify_{k}", lambda k=k: self._classify_stat(k))
        # native splice-pump counters (net/native/vtl.cpp, the hot-byte
        # black box): bytes spliced, write syscalls, short writes, TLS
        # handshakes — read through the C-ABI getter in net/vtl.py
        for i, k in enumerate(("bytes", "splice_calls", "short_writes",
                               "tls_handshakes")):
            self.registry.gauge_f(f"vproxy_pump_{k}_total",
                                  lambda i=i: self._pump_counter(i))
        # switch flow-cache counters (native/vtl.cpp flow table + the
        # zero-Python forwarding loop): probe outcomes plus native-side
        # forward/drop totals with drop REASONS preserved — no silent C
        # drops. Zeros when the provider/.so lacks the cache.
        for i, k in enumerate(("hit", "miss", "evict", "stale")):
            self.registry.gauge_f(f"vproxy_switch_flowcache_{k}_total",
                                  lambda i=i: self._flowcache_counter(i))
        self.registry.gauge_f("vproxy_switch_native_fwd_total",
                              lambda: self._flowcache_counter(4))
        try:  # the reason-index contract lives in net/vtl.py
            from ..net.vtl import FLOW_DROP_REASONS as _fc_reasons
        except Exception:  # provider import failure: labels still exist
            _fc_reasons = ("acl_deny", "same_iface", "route_miss",
                           "unknown_vni", "egress_short_write", "other")
        for j, r in enumerate(_fc_reasons):
            self.registry.gauge_f("vproxy_switch_native_drop_total",
                                  lambda j=j: self._flowcache_counter(5 + j),
                                  reason=r)
        # accept-lane counters (native/vtl.cpp accept lanes, the C
        # accept plane): accepts taken by lanes, sessions served wholly
        # in C, and punts by reason — classic (no entry / armed
        # failpoints / overload), stale (generation gate), connect_fail
        # (fed to the retry/ejection machinery). Zeros without the .so.
        self.registry.gauge_f("vproxy_lane_accepted_total",
                              lambda: self._lane_counter(0))
        self.registry.gauge_f("vproxy_lane_served_total",
                              lambda: self._lane_counter(1))
        for j, r in enumerate(("classic", "stale", "connect_fail")):
            self.registry.gauge_f("vproxy_lane_punt_total",
                                  lambda j=j: self._lane_counter(2 + j),
                                  reason=r)
        # classify-engine generation installs (rules/engine.py): total
        # published generations and the published device-table bytes
        # per matcher kind; vproxy_engine_swap_ms (install latency) is
        # get_histogram'd by the TableInstaller on first publish
        self.registry.gauge_f("vproxy_engine_generation",
                              self._engine_generation)
        for kind in ("hint", "cidr"):
            self.registry.gauge_f(
                "vproxy_engine_table_bytes",
                lambda kind=kind: self._engine_table_bytes(kind),
                matcher=kind)
        # fused-dispatch accounting (rules/engine.py note_launch): total
        # device launches on the dispatch path and how many batches rode
        # the fused one-launch program — the scrape-verifiable form of
        # the "one launch per batch" claim (docs/perf.md fused section):
        # on a fused-only load the two counters move in lockstep
        self.registry.gauge_f("vproxy_engine_dispatch_launches_total",
                              lambda: self._engine_stat(
                                  "dispatch_launches_total"))
        self.registry.gauge_f("vproxy_engine_fused_dispatches_total",
                              lambda: self._engine_stat(
                                  "fused_dispatches_total"))
        # cluster plane (vproxy_tpu/cluster): fleet membership, rule
        # generation convergence, and the step-synchronized dispatch
        # clock — all 0 until a ClusterNode boots
        for k in ("peers_up", "generation", "generation_lag",
                  "steps_total", "barrier_stalls_total"):
            self.registry.gauge_f(
                f"vproxy_cluster_{k}", lambda k=k: self._cluster_stat(k))
        # event-loop health: worst timer slip and longest single callback
        # across all live loops since the previous scrape (the known
        # GIL-contention p999 culprits); reading resets the window
        self.registry.gauge_f("vproxy_loop_timer_slip_us_max",
                              lambda: self._loop_health("slip"))
        self.registry.gauge_f("vproxy_loop_callback_us_max",
                              lambda: self._loop_health("cb"))
        # span tracing (utils/trace.py + native/vtl.cpp span rings):
        # pre-registered so a scrape shows the ZEROS before the first
        # sampled request — the PR-9 "silent drops counted" rule: a
        # span ring overflowing under storm load must show on /metrics
        # as a nonzero drop count, not as mysteriously missing spans
        self.registry.gauge_f("vproxy_trace_spans_total",
                              self._trace_c_spans, plane="lane")
        for pl in ("accept", "engine", "install", "cluster"):
            self.registry.gauge_f("vproxy_trace_spans_total",
                                  lambda pl=pl: self._trace_py_spans(pl),
                                  plane=pl)
        self.registry.gauge_f("vproxy_trace_drop_total",
                              self._trace_c_drops, ring="lane")
        self.registry.gauge_f("vproxy_trace_drop_total",
                              self._trace_py_drops, ring="py")
        # traffic-analytics plane (utils/sketch + native HH shards):
        # pre-registered with CLOSED label vocabularies (the PR-13
        # registry rule) — vproxy_hh_count{dim,slot} exposes the top-K
        # table slots per dimension, the counters account every update
        # plane and every lossy path (shard overflow, fleet-merge
        # truncation) so a scrape distinguishes "no traffic" from
        # "analytics off" from "dropped"
        from . import sketch as _sketch
        for dim in _sketch.DIMS:
            for slot in range(_sketch.TOP_SLOTS):
                self.registry.gauge_f(
                    "vproxy_hh_count",
                    lambda dim=dim, slot=slot: _sketch.top_slot(dim,
                                                                slot),
                    dim=dim, slot=str(slot))
        for pl in _sketch.PLANES:
            self.registry.gauge_f(
                "vproxy_analytics_updates_total",
                lambda pl=pl: float(_sketch.plane_updates_total(pl)),
                plane=pl)
        self.registry.gauge_f("vproxy_analytics_drop_total",
                              self._hh_overflow, reason="shard_overflow")
        # merge_truncated is the LATEST fleet merge's beyond-top-table
        # row count (a level, not a lifetime total — fleet merges run
        # per render, so a cumulative tally would track dashboard poll
        # rate instead of data loss)
        self.registry.gauge_f(
            "vproxy_analytics_drop_total",
            lambda: float(_sketch.merge_truncated_last()),
            reason="merge_truncated")
        self.registry.gauge_f(
            "vproxy_analytics_rotations_total",
            lambda: float(_sketch.rotations_total()))
        self.registry.gauge_f(
            "vproxy_analytics_enabled",
            lambda: 1.0 if _sketch.enabled() else 0.0)
        # policing plane (vproxy_tpu/policing — sketch-driven admission):
        # enforcement-table size, install/gossip counters, and policed-
        # action totals over the CLOSED action × dim grid, eagerly
        # registered so a scrape shows the zeros before the first
        # policy. The per-LB axis stays off this family (an open lb
        # vocabulary here would defeat the closed-grid registration);
        # per-LB attribution rides vproxy_lb_shed_total{reason="policed"}
        # and GET /policing.
        for k in ("keys", "tables_installed_total", "gossip_merges_total"):
            self.registry.gauge_f(f"vproxy_policy_{k}",
                                  lambda k=k: self._policing_stat(k))
        self.registry.gauge_f("vproxy_policing_enabled",
                              lambda: self._policing_stat("enabled"))
        for act in ("monitor", "throttle", "shed"):
            for dim in _sketch.DIMS:
                self.registry.gauge_f(
                    "vproxy_lb_policed_total",
                    lambda act=act, dim=dim: self._policed_total(act,
                                                                 dim),
                    action=act, dim=dim)
        # silent-drop accounting (udp_drop_incr below): created eagerly
        # so a scrape shows the zero before the first drop
        self.get_counter("vproxy_udp_drop_total")
        # maglev table-compiler accounting (rules/maglev.py): eager for
        # the same reason — a scrape shows the zeros before any build
        self.get_counter("vproxy_maglev_table_builds_total")
        self.get_gauge("vproxy_maglev_remap_fraction")
        # accept-path stage histograms (the PR-1 span family): the
        # stage vocabulary is closed, so the five series exist — at
        # zero — before the first connection. accept_stage_observe /
        # accept_stage_merge dedup onto these instances via _get_named.
        for st in ("acl", "classify", "backend_pick", "handover",
                   "total"):
            self.get_histogram("vproxy_accept_stage_us", stage=st)
        # workload-capture plane (utils/workload.py): per-plane arrival
        # inter-arrival histograms + the process-wide per-connection
        # bytes/duration series — CLOSED vocabularies, eagerly created
        # so the vlint registry pass stays green with zero new baseline
        # entries (the per-LB labeled conn series created at TcpLB
        # construction reuse these family names; the registry check is
        # name-level)
        from . import workload as _workload
        for pl in _workload.PLANES:
            self.get_histogram("vproxy_workload_interarrival_us",
                               plane=pl)
        self.get_histogram("vproxy_lb_conn_bytes")
        self.get_histogram("vproxy_lb_conn_duration_ms")
        self.registry.gauge_f(
            "vproxy_workload_capture_enabled",
            lambda: 1.0 if _workload.enabled() else 0.0)
        # install/build latency histograms: eagerly created HERE (the
        # reservoir config lives at this single site — _get_named's
        # first-creation-wins rule means the component-side
        # get_histogram calls in rules/engine.py and rules/maglev.py
        # resolve to these instances)
        self.get_histogram("vproxy_engine_swap_ms", reservoir=512)
        self.get_histogram("vproxy_maglev_build_ms", reservoir=256)

    @staticmethod
    def _classify_stat(key: str) -> float:
        from ..rules.service import ClassifyService
        svc = ClassifyService._instance
        return 0.0 if svc is None else float(getattr(svc.stats, key))

    @staticmethod
    def _engine_generation() -> float:
        import sys
        eng = sys.modules.get("vproxy_tpu.rules.engine")
        return 0.0 if eng is None else float(eng.generation_total())

    @staticmethod
    def _engine_table_bytes(kind: str) -> float:
        import sys  # scrape must not force a jax import
        eng = sys.modules.get("vproxy_tpu.rules.engine")
        return 0.0 if eng is None else float(eng.table_bytes_total(kind))

    @staticmethod
    def _engine_stat(name: str) -> float:
        import sys  # scrape must not force a jax import
        eng = sys.modules.get("vproxy_tpu.rules.engine")
        return 0.0 if eng is None else float(getattr(eng, name)())

    @staticmethod
    def _cluster_stat(key: str) -> float:
        from ..cluster import ClusterNode
        node = ClusterNode._instance
        return 0.0 if node is None else node.stat(key)

    @staticmethod
    def _pump_counter(i: int) -> float:
        from ..net import vtl
        return float(vtl.pump_counters()[i])

    @staticmethod
    def _flowcache_counter(i: int) -> float:
        from ..net import vtl
        return float(vtl.flowcache_counters()[i])

    @staticmethod
    def _lane_counter(i: int) -> float:
        from ..net import vtl
        return float(vtl.lane_counters()[i])

    @staticmethod
    def _trace_c_spans() -> float:
        from ..net import vtl
        return float(vtl.trace_counters()[0])

    @staticmethod
    def _trace_c_drops() -> float:
        from ..net import vtl
        return float(vtl.trace_counters()[1])

    @staticmethod
    def _trace_py_spans(plane: str) -> float:
        from . import trace
        return float(trace.plane_spans_total(plane))

    @staticmethod
    def _trace_py_drops() -> float:
        from . import trace
        return float(trace.py_dropped_total())

    @staticmethod
    def _policing_stat(key: str) -> float:
        import sys  # scrape must not force the policing import
        eng = sys.modules.get("vproxy_tpu.policing.engine")
        if eng is None:
            return 0.0
        return float(eng.default().status().get(key, 0))

    @staticmethod
    def _policed_total(action: str, dim: str) -> float:
        import sys  # scrape must not force the policing import
        eng = sys.modules.get("vproxy_tpu.policing.engine")
        return 0.0 if eng is None else float(
            eng.default().policed_total(action=action, dim=dim))

    @staticmethod
    def _hh_overflow() -> float:
        from ..net import vtl
        return float(vtl.hh_counters()[1])

    def _loop_health(self, key: str) -> float:
        with self._lock:
            loops = list(self._loops.values())
        worst = 0.0
        for lp in loops:
            take = getattr(lp, "take_health", None)
            if take is not None:
                worst = max(worst, take(key))
        return worst * 1e6

    def bench_snapshot(self) -> dict:
        """The BENCH-artifact view of /metrics: per-series percentiles
        for every histogram plus raw values for counters/gauges, keyed
        by exposition name with label values folded in
        (vproxy_accept_stage_us{stage="acl"} ->
        "vproxy_accept_stage_us.acl"). bench.py/bench_host.py/
        bench_switch.py merge this into the BENCH json so the latency
        contract and drop rates land in the artifact."""
        with self.registry._lock:
            metrics = list(self.registry._metrics)
        out: Dict[str, object] = {}
        for m in metrics:
            key = m.name
            if m.labels:
                key += "." + ".".join(
                    str(v) for _, v in sorted(m.labels.items()))
            try:
                if isinstance(m, Histogram):
                    pct = m.percentiles()
                    if pct is not None:
                        out[key] = {k: (round(v, 1)
                                        if isinstance(v, float) else v)
                                    for k, v in pct.items()}
                else:
                    out[key] = m.value()
            except Exception:
                pass  # a dead GaugeF fn must not sink the artifact
        return out

    # ------------------------------------------- named get-or-create

    def get_counter(self, name: str, **labels) -> Counter:
        return self._get_named(name, labels,
                               lambda: Counter(name, labels))  # type: ignore[return-value]

    def get_gauge(self, name: str, **labels) -> Gauge:
        return self._get_named(name, labels,
                               lambda: Gauge(name, labels))  # type: ignore[return-value]

    def get_histogram(self, name: str, buckets: int = 27, reservoir: int = 0,
                      **labels) -> Histogram:
        return self._get_named(
            name, labels, lambda: Histogram(name, labels, buckets=buckets,
                                            reservoir=reservoir))  # type: ignore[return-value]

    def _get_named(self, name: str, labels: dict, mk) -> Metric:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._named.get(key)
            if m is None:
                m = self._named[key] = mk()
                self.registry.add(m)
        return m

    @classmethod
    def get(cls) -> "GlobalInspection":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = GlobalInspection()
            return cls._instance

    # ----------------------------------------------------------- loops

    def register_loop(self, loop) -> None:
        with self._lock:
            self._loops[id(loop)] = loop

    def deregister_loop(self, loop) -> None:
        with self._lock:
            self._loops.pop(id(loop), None)

    # ------------------------------------------------------------ dumps

    @staticmethod
    def _open_fds() -> List[str]:
        try:
            return sorted(os.listdir("/proc/self/fd"), key=int)
        except OSError:
            return []

    def open_fd_dump(self) -> str:
        """lsof analog: fd -> target (GlobalInspection.java:196-205)."""
        lines = []
        for fd in self._open_fds():
            try:
                target = os.readlink(f"/proc/self/fd/{fd}")
            except OSError:
                target = "?"
            lines.append(f"{fd}\t{target}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def stack_trace_dump() -> str:
        """jstack analog (GlobalInspection.java:181-194)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for tid, frame in sys._current_frames().items():
            out.append(f'Thread "{names.get(tid, "?")}" id={tid}')
            out.extend(l.rstrip() for l in traceback.format_stack(frame))
            out.append("")
        return "\n".join(out)

    def prometheus_string(self) -> str:
        return self.registry.prometheus_text()


# accept-path span timers (components/tcplb.py + components/upstream.py):
# one histogram family, labeled by stage — acl (accept->ACL verdict),
# classify (hint submit->index), backend_pick (group/WRR selection),
# handover (backend connect->pump running), total (accept->pump running).
# Local memo keeps the hot path at one dict hit; a racy double-create
# resolves to the same metric through get_histogram's dedup.
_ACCEPT_STAGE_HISTS: Dict[str, Histogram] = {}

# UDP drops that used to be silent (docs/robustness.md): the BlockingUdp
# facade's queue-full drop (net/wrapfd.py) and a DNS response the kernel
# refused with EAGAIN under storm load (dns/server.py). One process
# counter; memoized so the drop path costs a dict hit, and pre-created
# at first GlobalInspection access so /metrics shows the zero.
_UDP_DROP_CTR: Optional[Counter] = None


def udp_drop_incr(n: int = 1) -> None:
    global _UDP_DROP_CTR
    if _UDP_DROP_CTR is None:
        _UDP_DROP_CTR = GlobalInspection.get().get_counter(
            "vproxy_udp_drop_total")
    _UDP_DROP_CTR.incr(n)


def accept_stage_observe(stage: str, seconds: float) -> None:
    h = _ACCEPT_STAGE_HISTS.get(stage)
    if h is None:
        h = _ACCEPT_STAGE_HISTS[stage] = GlobalInspection.get().get_histogram(
            "vproxy_accept_stage_us", stage=stage)
    h.observe(seconds * 1e6)


def accept_stage_merge(stage: str, bucket_deltas, sum_us: float,
                       count: int) -> None:
    """Fold C-side pre-bucketed stage counts (accept lanes,
    vtl_lanes_stage_stat deltas) into the SAME
    vproxy_accept_stage_us{stage=} series the python accept path
    populates — lane-served connections stop being invisible to the
    stage histograms."""
    h = _ACCEPT_STAGE_HISTS.get(stage)
    if h is None:
        h = _ACCEPT_STAGE_HISTS[stage] = GlobalInspection.get().get_histogram(
            "vproxy_accept_stage_us", stage=stage)
    h.merge(bucket_deltas, sum_us, count)


# per-connection size/duration histograms (the workload-capture
# satellite): one process-wide aggregate pair (lb=None — what the
# workload model reads) plus a labeled pair per LB. Memoized like the
# stage histograms; a racy double-create dedups through _get_named.
_CONN_HISTS: Dict[Optional[str], Tuple[Histogram, Histogram]] = {}


def conn_hists(lb: Optional[str] = None) -> Tuple[Histogram, Histogram]:
    """(bytes, duration_ms) histogram pair for one LB (or the process
    aggregate when lb is None)."""
    pair = _CONN_HISTS.get(lb)
    if pair is None:
        gi = GlobalInspection.get()
        labels = {"lb": lb} if lb else {}
        pair = _CONN_HISTS[lb] = (
            gi.get_histogram("vproxy_lb_conn_bytes", **labels),
            gi.get_histogram("vproxy_lb_conn_duration_ms", **labels))
    return pair


def conn_observe(lb: Optional[str], nbytes: float, dur_ms: float) -> None:
    """One closed python-path session's size/duration, folded into the
    per-LB series AND the process aggregate the workload model reads."""
    for target in ((None, lb) if lb else (None,)):
        hb, hd = conn_hists(target)
        hb.observe(nbytes)
        hd.observe(dur_ms)


def conn_merge(lb: Optional[str], which: str, bucket_deltas,
               sum_delta: float, count: int) -> None:
    """Fold C-side pre-bucketed per-connection counts (accept lanes,
    vtl_lanes_capture_stat deltas) into the SAME series the python
    splice path populates — lane-served connections stop being
    invisible to the conn histograms. which: "bytes" | "duration_ms"."""
    idx = 0 if which == "bytes" else 1
    for target in ((None, lb) if lb else (None,)):
        conn_hists(target)[idx].merge(bucket_deltas, sum_delta, count)


def launch_inspection_http(loop, ip: str, port: int):
    """Serve /metrics, /lsof, /jstack, /events, /healthz — the
    reference's `-Dglobal_inspection=host:port` server (Main.java:
    85-104) plus the flight-recorder dump. Returns the HttpServer
    (close() to stop)."""
    from ..lib.vserver import HttpServer
    from . import failpoint, lifecycle
    from .events import FlightRecorder

    gi = GlobalInspection.get()
    srv = HttpServer(loop)
    srv.get("/metrics", lambda ctx: ctx.resp
            .header("Content-Type", "text/plain; version=0.0.4")
            .end(gi.prometheus_string()))
    srv.get("/lsof", lambda ctx: ctx.resp
            .header("Content-Type", "text/plain").end(gi.open_fd_dump()))
    srv.get("/jstack", lambda ctx: ctx.resp
            .header("Content-Type", "text/plain").end(gi.stack_trace_dump()))

    def events(ctx) -> None:
        try:
            last = int(ctx.req.query.get("n", "0"))
        except ValueError:
            last = 0
        try:  # ?trace=<id>: only events cross-referencing that trace
            tid = int(ctx.req.query.get("trace", "0"))
        except ValueError:
            tid = 0
        # ?plane=<p>: only events of that plane (utils/events.plane_of
        # — the analytics drill-down filter)
        plane = ctx.req.query.get("plane") or None

        # ?since=&until=: monotonic-ns bounds, the SAME clock trace
        # spans stamp t_ns with — a capture window joins against
        # recorder events without clock arithmetic
        def _ns(key):
            try:
                v = int(ctx.req.query.get(key, "0"))
            except ValueError:
                v = 0
            return v or None

        ctx.resp.end(FlightRecorder.get().snapshot(
            last, trace=tid or None, plane=plane,
            since=_ns("since"), until=_ns("until")))

    srv.get("/events", events)

    def analytics(ctx) -> None:
        # the heavy-hitter plane (utils/sketch): local top tables +
        # the fleet-merged view when a cluster is booted (one shared
        # assembly across all three serving surfaces)
        from . import sketch as SK
        out = SK.snapshot_with_fleet()
        # per-node policed attribution (the enforcement half of the
        # analytics loop — what the detected heavy hitters COST them)
        from ..cluster import ClusterNode
        from ..policing import engine as PE
        node = ClusterNode._instance
        out["policing"] = (node.fleet_policing() if node is not None
                           else {"self": PE.default().policed_by_node(),
                                 "peers": {}})
        ctx.resp.end(out)

    srv.get("/analytics", analytics)

    def policing_ep(ctx) -> None:
        # the Guardian enforcement surface (vproxy_tpu/policing):
        # engine status + declared policies + the live enforcement
        # table (per-key buckets with origin/ttl — local vs gossiped)
        from ..policing import engine as PE
        eng = PE.default()
        st = eng.status()
        st["policy_list"] = eng.list_policies()
        st["table"] = eng.table_snapshot()
        st["policed_by_node"] = eng.policed_by_node()
        st["shed_receipt"] = eng.shed_receipt()
        ctx.resp.end(st)

    srv.get("/policing", policing_ep)

    def workload_ep(ctx) -> None:
        # the capture artifact (utils/workload): the current window's
        # fitted model — tools/replay.py consumes this live
        from . import workload as WL
        ctx.resp.end(WL.export_model())

    srv.get("/workload", workload_ep)

    def trace_ep(ctx) -> None:
        # GET /trace -> recent trace summaries; ?id=<trace> -> that
        # trace's spans (start-time ordered); ?n= bounds the list
        from . import trace as TR
        try:
            tid = int(ctx.req.query.get("id", "0"))
        except ValueError:
            tid = 0
        if tid:
            ctx.resp.end({"trace": tid, "spans": TR.get_trace(tid)})
            return
        try:
            last = int(ctx.req.query.get("n", "64"))
        except ValueError:
            last = 64
        ctx.resp.end({"sample_every": TR.sample_every(),
                      "traces": TR.summaries(last)})

    srv.get("/trace", trace_ep)
    srv.get("/faults", lambda ctx: ctx.resp.end(failpoint.active()))

    def cluster(ctx) -> None:
        from ..cluster import ClusterNode
        node = ClusterNode._instance
        ctx.resp.end({"enabled": False} if node is None else node.status())

    srv.get("/cluster", cluster)

    def healthz(ctx) -> None:
        # draining flips to 503 so upstream LB health probes steer away
        # while in-flight sessions finish (utils/lifecycle)
        if lifecycle.is_draining():
            ctx.resp.status(503).end(b"draining")
        else:
            ctx.resp.end(b"OK")

    srv.get("/healthz", healthz)
    srv.listen(port, ip)
    return srv
