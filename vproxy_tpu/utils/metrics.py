"""Prometheus-style metrics registry + global inspection surface.

Parity: reference `vproxybase/prometheus/Metrics.java` (Counter / Gauge
/ GaugeF with a label set, text exposition) and `GlobalInspection.java:
24-205`: one process-global surface collecting direct-memory bytes,
per-loop thread registry, stack-trace dump and open-FD dump, exposed
over HTTP (`getPrometheusString():177`,
`GlobalInspectionHttpServerLauncher.java:9` — /metrics, /lsof, /jstack).
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Callable, Dict, List, Optional, Tuple


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Metric:
    mtype = "untyped"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})

    def value(self) -> float:
        raise NotImplementedError

    def sample_line(self) -> str:
        v = self.value()
        v_str = "%d" % v if float(v).is_integer() else repr(float(v))
        return f"{self.name}{_fmt_labels(self.labels)} {v_str}"


class Counter(Metric):
    mtype = "counter"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        super().__init__(name, labels)
        self._v = 0
        self._lock = threading.Lock()

    def incr(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def value(self) -> float:
        return self._v


class Gauge(Metric):
    mtype = "gauge"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        super().__init__(name, labels)
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = v

    def add(self, d: float) -> None:
        self._v += d

    def value(self) -> float:
        return self._v


class GaugeF(Metric):
    """Gauge computed by a function at scrape time."""
    mtype = "gauge"

    def __init__(self, name: str, fn: Callable[[], float],
                 labels: Optional[Dict[str, str]] = None):
        super().__init__(name, labels)
        self.fn = fn

    def value(self) -> float:
        return float(self.fn())


class MetricsRegistry:
    def __init__(self):
        self._metrics: List[Metric] = []
        self._lock = threading.Lock()

    def add(self, m: Metric) -> Metric:
        with self._lock:
            self._metrics.append(m)
        return m

    def remove(self, m: Metric) -> None:
        with self._lock:
            if m in self._metrics:
                self._metrics.remove(m)

    def counter(self, name: str, **labels) -> Counter:
        return self.add(Counter(name, labels))  # type: ignore[return-value]

    def gauge(self, name: str, **labels) -> Gauge:
        return self.add(Gauge(name, labels))  # type: ignore[return-value]

    def gauge_f(self, name: str, fn, **labels) -> GaugeF:
        return self.add(GaugeF(name, fn, labels))  # type: ignore[return-value]

    def prometheus_text(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        by_name: Dict[str, Tuple[str, List[Metric]]] = {}
        for m in metrics:
            by_name.setdefault(m.name, (m.mtype, []))[1].append(m)
        out = []
        for name in sorted(by_name):
            mtype, ms = by_name[name]
            out.append(f"# TYPE {name} {mtype}")
            out.extend(m.sample_line() for m in ms)
        return "\n".join(out) + ("\n" if out else "")


class GlobalInspection:
    """Process-global metric + introspection surface (singleton)."""

    _instance: Optional["GlobalInspection"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self.registry = MetricsRegistry()
        self._loops: Dict[int, object] = {}  # id(loop) -> SelectorEventLoop
        self._lock = threading.Lock()
        self.direct_memory_bytes = self.registry.gauge(
            "vproxy_direct_memory_bytes_current")
        self.registry.gauge_f("vproxy_event_loop_count",
                              lambda: len(self._loops))
        self.registry.gauge_f("vproxy_open_fd_count",
                              lambda: len(self._open_fds()))
        self.registry.gauge_f("vproxy_thread_count",
                              lambda: threading.active_count())
        # micro-batch classify queue (rules/service.py — the north-star
        # data plane): batching ratio = queries / dispatches
        for k in ("queries", "dispatches", "device_queries",
                  "oracle_queries", "failovers", "max_batch"):
            self.registry.gauge_f(
                f"vproxy_classify_{k}", lambda k=k: self._classify_stat(k))

    @staticmethod
    def _classify_stat(key: str) -> float:
        from ..rules.service import ClassifyService
        svc = ClassifyService._instance
        return 0.0 if svc is None else float(getattr(svc.stats, key))

    @classmethod
    def get(cls) -> "GlobalInspection":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = GlobalInspection()
            return cls._instance

    # ----------------------------------------------------------- loops

    def register_loop(self, loop) -> None:
        with self._lock:
            self._loops[id(loop)] = loop

    def deregister_loop(self, loop) -> None:
        with self._lock:
            self._loops.pop(id(loop), None)

    # ------------------------------------------------------------ dumps

    @staticmethod
    def _open_fds() -> List[str]:
        try:
            return sorted(os.listdir("/proc/self/fd"), key=int)
        except OSError:
            return []

    def open_fd_dump(self) -> str:
        """lsof analog: fd -> target (GlobalInspection.java:196-205)."""
        lines = []
        for fd in self._open_fds():
            try:
                target = os.readlink(f"/proc/self/fd/{fd}")
            except OSError:
                target = "?"
            lines.append(f"{fd}\t{target}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def stack_trace_dump() -> str:
        """jstack analog (GlobalInspection.java:181-194)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for tid, frame in sys._current_frames().items():
            out.append(f'Thread "{names.get(tid, "?")}" id={tid}')
            out.extend(l.rstrip() for l in traceback.format_stack(frame))
            out.append("")
        return "\n".join(out)

    def prometheus_string(self) -> str:
        return self.registry.prometheus_text()


def launch_inspection_http(loop, ip: str, port: int):
    """Serve /metrics, /lsof, /jstack, /healthz — the reference's
    `-Dglobal_inspection=host:port` server (Main.java:85-104). Returns
    the HttpServer (close() to stop)."""
    from ..lib.vserver import HttpServer

    gi = GlobalInspection.get()
    srv = HttpServer(loop)
    srv.get("/metrics", lambda ctx: ctx.resp
            .header("Content-Type", "text/plain; version=0.0.4")
            .end(gi.prometheus_string()))
    srv.get("/lsof", lambda ctx: ctx.resp
            .header("Content-Type", "text/plain").end(gi.open_fd_dump()))
    srv.get("/jstack", lambda ctx: ctx.resp
            .header("Content-Type", "text/plain").end(gi.stack_trace_dump()))
    srv.get("/healthz", lambda ctx: ctx.resp.end(b"OK"))
    srv.listen(port, ip)
    return srv
