"""Workload capture — the measured-traffic model behind record-replay.

The observability planes can *see* traffic (stage histograms, span
traces, heavy-hitter sketches) but nothing could *play it back*: every
bench and storm scenario was a synthetic blast, so capacity claims
rested on guesses. This module fits a `WorkloadModel` from what the
planes already emit plus three cheap capture hooks, and serializes it
as a versioned JSON artifact that tools/replay.py can re-synthesize
deterministically (docs/replay.md):

* per-plane arrival processes — inter-arrival log2 histograms at the
  accept paths (`vproxy_workload_interarrival_us{plane=accept|lane|
  dns}`): python accepts and DNS queries observe here directly; the C
  accept lanes bucket with the SAME log2 rule in native code
  (vtl_lanes_capture_stat) and lane 0's poll tick folds the deltas in
  via arrival_merge, the accept_stage_merge idiom;
* Zipf popularity per dimension — fitted from the PR-14 Space-Saving /
  Count-Min top tables (utils/sketch): the sketch output IS the model's
  popularity parameters, error bounds included;
* per-connection size/duration — the `vproxy_lb_conn_bytes` /
  `vproxy_lb_conn_duration_ms` histograms (utils/metrics.conn_observe,
  fed by the python splice path and the lane reap fold).

Capture is windowed with `capture start|stop|export` (command surface)
or `GET /workload` (both HTTP servers): start snapshots the cumulative
histogram state, export fits the model from the deltas — when no
session is open the window is process lifetime, so a bare GET /workload
always yields a usable model. The ON knob (VPROXY_TPU_WORKLOAD=0 to
disable) gates the python hooks and pushes into the native plane
(vtl_workload_set_enabled), mirroring the analytics knob — the
capture-off A/B overhead gate in bench has a real toggle.
"""
from __future__ import annotations

import json
import math
import threading
import time
import os
from typing import Dict, List, Optional

MODEL_KIND = "vproxy-workload"
MODEL_VERSION = 1

# arrival planes with their own inter-arrival histograms (closed label
# vocabulary: vproxy_workload_interarrival_us{plane=} is pre-registered
# from GlobalInspection.__init__ for the vlint registry pass)
PLANES = ("accept", "lane", "dns")

ON = os.environ.get("VPROXY_TPU_WORKLOAD", "1") != "0"

_lock = threading.Lock()
_last_arrival: Dict[str, float] = {}  # plane -> last arrival, monotonic s
_hists: Dict[str, object] = {}        # plane -> Histogram memo
_t_boot = time.monotonic()

# capture session: idle -> recording -> stopped (export works in every
# state; start replaces any previous session)
_session: dict = {"state": "idle", "t0": 0.0, "t1": 0.0,
                  "base": None, "end": None}


def enabled() -> bool:
    return ON


def configure(on: Optional[bool] = None) -> None:
    """Runtime knob (bench/test hook; production uses the env). Pushes
    the on/off state into the C plane so the lane capture histograms
    flip together with the python sites."""
    global ON
    if on is not None:
        ON = bool(on)
        from ..net import vtl
        vtl.workload_set_enabled(ON)


def push_native_knob() -> None:
    """Re-push ON into a freshly created native Lanes plane (the knob
    is a process global in C, but the .so may load after configure)."""
    from ..net import vtl
    vtl.workload_set_enabled(ON)


def _hist(plane: str):
    h = _hists.get(plane)
    if h is None:
        from .metrics import GlobalInspection
        h = _hists[plane] = GlobalInspection.get().get_histogram(
            "vproxy_workload_interarrival_us", plane=plane)
    return h


def note_arrival(plane: str) -> None:
    """Python-path arrival hook (tcplb accept, dns query): one
    monotonic read, one dict exchange, one histogram observe. The
    first arrival on a plane only seeds the cursor."""
    if not ON:
        return
    now = time.monotonic()
    with _lock:
        prev = _last_arrival.get(plane, 0.0)
        _last_arrival[plane] = now
    if prev:
        _hist(plane).observe(max(0.0, (now - prev) * 1e6))


def arrival_merge(plane: str, bucket_deltas, sum_us: float,
                  count: int) -> None:
    """Fold C-side pre-bucketed inter-arrival counts (accept lanes,
    vtl_lanes_capture_stat deltas) into the SAME per-plane histogram
    the python paths populate — the accept_stage_merge idiom."""
    _hist(plane).merge(bucket_deltas, sum_us, count)


def reset() -> None:
    """Test hook: drop session, cursors and histogram memos."""
    global _session
    with _lock:
        _last_arrival.clear()
        _hists.clear()
        _session = {"state": "idle", "t0": 0.0, "t1": 0.0,
                    "base": None, "end": None}


# ------------------------------------------------------- capture window

def _snap() -> dict:
    """Cumulative (count, sum, buckets) state of every model source —
    the delta-window primitive."""
    from . import metrics
    hb, hd = metrics.conn_hists(None)
    return {"planes": {pl: _hist(pl).state() for pl in PLANES},
            "bytes": hb.state(), "duration_ms": hd.state()}


def _dhist(h1, h0=None) -> dict:
    """h1 - h0 as a serializable {count, sum, buckets} distribution
    (h0=None means 'since boot': h1 as-is)."""
    c1, s1, b1 = h1
    if h0 is None:
        return {"count": int(c1), "sum": float(s1),
                "buckets": [int(x) for x in b1]}
    c0, s0, b0 = h0
    return {"count": int(c1 - c0), "sum": float(s1 - s0),
            "buckets": [int(x - y) for x, y in zip(b1, b0)]}


def capture_start() -> dict:
    global _session
    with _lock:
        _session = {"state": "recording", "t0": time.monotonic(),
                    "t1": 0.0, "base": _snap(), "end": None}
    from . import events
    events.record("workload_capture", "capture started")
    return capture_status()


def capture_stop() -> dict:
    global _session
    with _lock:
        if _session["state"] != "recording":
            raise ValueError("no capture recording "
                             f"(state: {_session['state']})")
        _session["state"] = "stopped"
        _session["t1"] = time.monotonic()
        _session["end"] = _snap()
    from . import events
    events.record("workload_capture", "capture stopped",
                  window_s=round(_session["t1"] - _session["t0"], 3))
    return capture_status()


def capture_status() -> dict:
    with _lock:
        st = dict(_session)
    if st["state"] == "recording":
        window = time.monotonic() - st["t0"]
    elif st["state"] == "stopped":
        window = st["t1"] - st["t0"]
    else:
        window = time.monotonic() - _t_boot
    return {"state": st["state"], "enabled": ON,
            "window_s": round(window, 3)}


def fit_zipf_alpha(counts: List[float]) -> float:
    """Least-squares slope of log(count) vs log(rank) over a top
    table's head — the Zipf exponent the sketch measured. Clamped to
    [0, 8]; 1.0 when the head is too short to fit."""
    pts = [(math.log(i + 1), math.log(c))
           for i, c in enumerate(counts) if c > 0]
    if len(pts) < 2:
        return 1.0
    n = len(pts)
    mx = sum(x for x, _ in pts) / n
    my = sum(y for _, y in pts) / n
    sxx = sum((x - mx) ** 2 for x, _ in pts)
    if sxx <= 0:
        return 1.0
    sxy = sum((x - mx) * (y - my) for x, y in pts)
    return max(0.0, min(8.0, -(sxy / sxx)))


def _fit_popularity() -> dict:
    """Per-dimension Zipf head from the analytics top tables: the
    Space-Saving keys/counts (with their error bounds) ARE the model's
    popularity parameters."""
    from . import sketch as SK
    out = {}
    for dim in SK.DIMS:
        try:
            rows = SK.top_table(dim, SK.TOPK)
        except Exception:
            rows = []
        top = [[r["key"], int(r["count"]), int(r.get("err", 0))]
               for r in rows if int(r.get("count", 0)) > 0]
        out[dim] = {"alpha": round(fit_zipf_alpha([c for _, c, _ in top]),
                                   4),
                    "top": top}
    return out


def export_model(seed: Optional[int] = None) -> dict:
    """Fit the WorkloadModel from the current capture window (stopped
    session > live session > process lifetime) — the `capture export`
    verb and the GET /workload body."""
    with _lock:
        st = dict(_session)
    if st["state"] == "stopped":
        base, end, secs = st["base"], st["end"], st["t1"] - st["t0"]
    elif st["state"] == "recording":
        base, end, secs = st["base"], _snap(), time.monotonic() - st["t0"]
    else:
        base, end, secs = None, _snap(), time.monotonic() - _t_boot
    secs = max(secs, 1e-9)
    planes = {}
    for pl in PLANES:
        d = _dhist(end["planes"][pl],
                   base["planes"][pl] if base else None)
        planes[pl] = {"arrivals": d["count"],
                      "rate_hz": round(d["count"] / secs, 6),
                      "interarrival_us": d}
    model = {
        "kind": MODEL_KIND, "version": MODEL_VERSION,
        "seed": seed, "captured_at": time.time(),
        "window_s": round(secs, 6),
        "planes": planes,
        "conn": {"bytes": _dhist(end["bytes"],
                                 base["bytes"] if base else None),
                 "duration_ms": _dhist(end["duration_ms"],
                                       base["duration_ms"] if base
                                       else None)},
        "popularity": _fit_popularity(),
    }
    return model


def capture(verb: str, seed: Optional[int] = None) -> dict:
    """The command-surface dispatcher: capture start|stop|export|status."""
    if verb == "start":
        return capture_start()
    if verb == "stop":
        return capture_stop()
    if verb == "export":
        return export_model(seed=seed)
    if verb == "status":
        return capture_status()
    raise ValueError(f"unknown capture verb {verb!r} "
                     "(one of: start, stop, export, status)")


# --------------------------------------------------------- model object

class WorkloadModel:
    """The versioned capture artifact: a thin validator/serializer over
    the model dict (replay.py loads these from files or a live
    GET /workload)."""

    def __init__(self, data: dict):
        self.data = data

    @property
    def seed(self) -> Optional[int]:
        return self.data.get("seed")

    def plane_rate(self, plane: str) -> float:
        return float(self.data["planes"].get(plane, {}).get("rate_hz",
                                                            0.0))

    def to_json(self) -> str:
        # canonical form: sorted keys, no whitespace — two exports of
        # the same state are byte-identical, so artifacts diff cleanly
        return json.dumps(self.data, sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def fit(cls, seed: Optional[int] = None) -> "WorkloadModel":
        return cls(export_model(seed=seed))

    @classmethod
    def from_json(cls, text: str) -> "WorkloadModel":
        data = json.loads(text)
        if data.get("kind") != MODEL_KIND:
            raise ValueError(f"not a workload model (kind="
                             f"{data.get('kind')!r})")
        ver = int(data.get("version", 0))
        if ver < 1 or ver > MODEL_VERSION:
            raise ValueError(f"workload model version {ver} outside "
                             f"supported range [1, {MODEL_VERSION}]")
        for field in ("planes", "conn", "popularity", "window_s"):
            if field not in data:
                raise ValueError(f"workload model missing {field!r}")
        return cls(data)


def sample_from_hist(rng, dhist: dict) -> float:
    """One draw from a {count, sum, buckets} log2 distribution: pick a
    bucket by cumulative weight, then uniform within its bounds (the
    +Inf tail draws in (2**26, 2**27]). Pure function of (rng state,
    dhist) — the seeded-determinism contract replay schedules build on."""
    buckets = dhist.get("buckets") or []
    total = sum(buckets)
    if total <= 0:
        return 0.0
    x = rng.randrange(total)
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if x < cum:
            lo = 0.0 if i == 0 else float(1 << (i - 1))
            hi = float(1 << i) if i < 27 else float(1 << 27)
            return lo + (hi - lo) * rng.random()
    return 0.0
