"""IP address / network value types.

Semantics modeled on the reference's vfd/IP.java and
vproxybase/util/Network.java (see /root/reference): addresses are raw
big-endian byte strings (4 bytes for v4, 16 for v6); a Network keeps its
mask as a byte string whose length is 4 when masklen <= 32 else 16, and
`contains` implements the mixed v4/v6 cases of Network.maskMatch
(Network.java:183-278) including IPv4-compatible (::a.b.c.d) and
IPv4-mapped (::ffff:a.b.c.d) v6 addresses matching v4 rules.
"""
from __future__ import annotations

import socket
from dataclasses import dataclass


def parse_ip(s: str) -> bytes:
    """Parse an IPv4 or IPv6 literal into raw bytes. Raises ValueError."""
    s = s.strip()
    if s.startswith("[") and s.endswith("]"):
        s = s[1:-1]
    try:
        return socket.inet_aton(s) if ("." in s and ":" not in s) else socket.inet_pton(socket.AF_INET6, s)
    except OSError as e:
        raise ValueError(f"invalid ip literal: {s!r}") from e


def is_ip_literal(s: str) -> bool:
    try:
        parse_ip(s)
        return True
    except ValueError:
        return False


def is_ipv6_literal(s: str) -> bool:
    return is_ip_literal(s) and len(parse_ip(s)) == 16


def format_ip(b: bytes) -> str:
    if len(b) == 4:
        return socket.inet_ntoa(b)
    if len(b) == 16:
        return socket.inet_ntop(socket.AF_INET6, b)
    raise ValueError(f"bad address length {len(b)}")


def to16(b: bytes) -> bytes:
    """Canonicalize to 16 bytes (v4 -> low 4 bytes, high 12 zero)."""
    if len(b) == 16:
        return b
    if len(b) == 4:
        return b"\x00" * 12 + b
    raise ValueError(f"bad address length {len(b)}")


def _low_bits_v6_v4(ip: bytes, last_low: int, second_last: int) -> bool:
    # Utils.lowBitsV6V4 (reference base/.../util/Utils.java:122-133)
    for i in range(second_last):
        if ip[i] != 0:
            return False
    if ip[last_low] == 0:
        return ip[second_last] == 0
    if ip[last_low] == 0xFF:
        return ip[second_last] == 0xFF
    return False


def mask_bytes(masklen: int) -> bytes:
    """Network.parseMask: 4 bytes when masklen <= 32, else 16."""
    if masklen > 128 or masklen < 0:
        raise ValueError(f"unknown mask {masklen}")
    n = 16 if masklen > 32 else 4
    out = bytearray(n)
    m = masklen
    for i in range(n):
        ones = 8 if m > 8 else max(m, 0)
        out[i] = (0xFF << (8 - ones)) & 0xFF if ones > 0 else 0
        m -= 8
    return bytes(out)


def mask_match(inp: bytes, rule: bytes, mask: bytes) -> bool:
    """Network.maskMatch's five mixed-length cases (Network.java:183-278)."""
    if len(inp) == len(rule) and len(rule) > len(mask):
        # v6 input, v6 rule, mask <= 32: compare first 4 bytes
        return all((inp[i] & mask[i]) == rule[i] for i in range(len(mask)))
    if len(inp) < len(rule) and len(rule) > len(mask):
        # v4 input, v6 rule, mask <= 32
        return False
    if len(inp) < len(rule) and len(rule) == len(mask):
        # v4 input, v6 rule, mask > 32: compare low 4 bytes + rule-high check
        off = len(rule) - len(inp)
        for i in range(len(inp)):
            if (inp[i] & mask[i + off]) != rule[i + off]:
                return False
        return _low_bits_v6_v4(rule, off - 1, off - 2)
    # cases 4 (v6 input, v4 rule) and 5 (same length): compare from the end
    n = min(len(inp), len(rule), len(mask))
    for i in range(n):
        if (inp[-1 - i] & mask[-1 - i]) != rule[-1 - i]:
            return False
    if len(inp) > len(rule):
        off = len(inp) - len(rule)
        return _low_bits_v6_v4(inp, off - 1, off - 2)
    return True


@dataclass(frozen=True)
class Network:
    """A CIDR network; `ip` is already in network form (host bits zero)."""

    ip: bytes
    mask: bytes

    @staticmethod
    def parse(net: str) -> "Network":
        if "/" not in net:
            raise ValueError(f"invalid network {net!r}")
        ip_s, _, m_s = net.rpartition("/")
        masklen = int(m_s)
        ip = parse_ip(ip_s)
        mask = mask_bytes(masklen)
        if len(ip) < len(mask):
            raise ValueError(f"invalid network {net!r}: v4 address with mask > 32")
        for i in range(len(mask)):
            if (ip[i] & mask[i]) != ip[i]:
                raise ValueError(f"invalid network {net!r}: host bits set")
        for i in range(len(mask), len(ip)):
            if ip[i] != 0:
                raise ValueError(f"invalid network {net!r}: host bits set")
        return Network(ip, mask)

    @property
    def masklen(self) -> int:
        zeros = 0
        for b in reversed(self.mask):
            if b == 0:
                zeros += 8
            else:
                while not (b & 1):
                    zeros += 1
                    b >>= 1
                break
        return len(self.mask) * 8 - zeros

    def contains_ip(self, addr: bytes) -> bool:
        return mask_match(addr, self.ip, self.mask)

    def contains_net(self, other: "Network") -> bool:
        # Network.contains(Network): strict (mask must be narrower)
        return self.contains_ip(other.ip) and self.masklen < other.masklen

    def __str__(self) -> str:
        return f"{format_ip(self.ip)}/{self.masklen}"


@dataclass(frozen=True)
class IPPort:
    ip: bytes
    port: int

    @staticmethod
    def parse(s: str) -> "IPPort":
        host, _, port = s.rpartition(":")
        return IPPort(parse_ip(host), int(port))

    def __str__(self) -> str:
        ip = format_ip(self.ip)
        return f"[{ip}]:{self.port}" if len(self.ip) == 16 else f"{ip}:{self.port}"
