"""OOM survival: a pre-allocated reserve released on MemoryError so the
crash can still be logged and exit cleanly.

Parity: app/OOMHandler.java:60 — the reference pre-allocates a 2MB
buffer and frees it when an OutOfMemoryError surfaces, buying the
logger enough headroom to record the failure before the process dies
(the Daemon supervisor then restarts it). Python raises MemoryError
with the heap similarly wedged; releasing the reserve gives the
excepthook room to format and flush the alert.
"""
from __future__ import annotations

import os
import sys
import threading

from .log import Logger

_log = Logger("oom")
_reserve: list = []
_installed = False
_lock = threading.Lock()


def install(reserve_mb: int = 2) -> None:
    """Idempotent. Wraps sys.excepthook (and threading.excepthook) so an
    uncaught MemoryError releases the reserve, logs, and exits 137 —
    matching the reference's log-then-die contract; a wedged allocator
    must not linger half-alive."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
        _reserve.append(bytearray(reserve_mb << 20))

    prev = sys.excepthook
    prev_thread = threading.excepthook

    def hook(tp, val, tb):
        if issubclass(tp, MemoryError):
            _die(val)
        prev(tp, val, tb)

    def thread_hook(args):
        if args.exc_type is not None and \
                issubclass(args.exc_type, MemoryError):
            _die(args.exc_value)
        prev_thread(args)

    sys.excepthook = hook
    threading.excepthook = thread_hook


def _die(val) -> None:
    _reserve.clear()  # give the logger headroom
    try:
        _log.alert(f"out of memory: {val!r}; exiting for supervisor restart")
        sys.stderr.flush()
    finally:
        os._exit(137)


def installed() -> bool:
    return _installed
