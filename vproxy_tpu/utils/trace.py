"""Request tracing — span-level latency attribution across every plane.

The PR-1 observability layer (utils/metrics histograms + the
utils/events flight recorder) answers "how much / how fast" and "what
happened around second X"; this module answers "where did THIS request
spend its time". A sampled request carries a **trace context** — a
nonzero u64 trace id — through every plane it touches, and each plane
records `(trace_id, plane, span, t_start_ns, dur_ns, fields)` into one
process-wide bounded buffer:

* **lane**    — the C accept plane (native/vtl.cpp): each lane thread
  writes fixed binary TraceRec records into a lock-free SPSC span ring
  (accept → route_pick → connect → splice → close for lane-served
  connections; accept → punt for punted ones, with the trace id riding
  the widened LanePunt so the python path CONTINUES the same trace).
  components/lanes.py drains the rings through `vtl_trace_drain` into
  this buffer. Ring overflow is counted, never silent
  (`vproxy_trace_drop_total{ring="lane"}`).
* **accept**  — the python accept path (components/tcplb.py): acl,
  backend_pick, connect, splice, close, total.
* **engine**  — classify dispatch (rules/service.py + rules/engine.py):
  queue_wait, dispatch, launch markers (fused vs unfused
  distinguishable), d2h_sync, classify_inline / host_index fallbacks.
* **install** — the TableInstaller (rules/engine.py): every standby
  generation install traced as compile / upload / swap spans.
* **cluster** — the step-synchronized submit loop (cluster/submit.py):
  barrier, collective, barrier_stall, host_index — a degraded query's
  trace shows WHICH phase ate the time on the node that served it.

Sampling: `VPROXY_TPU_TRACE_SAMPLE` = N samples 1-in-N (0 = off, the
default). Knob-off cost is one branch per site. Two deciders:

* `maybe_sample()` — deterministic counter-based 1-in-N (the accept
  paths; every Nth request).
* `sampled_key(key)` — seeded hash decision, value-stable across
  processes (FNV-1a 64 over `VPROXY_TPU_TRACE_SEED` + key — the
  VPROXY_TPU_FAILPOINT_SEED idiom: the same key samples identically on
  every host, so a fleet traces the same request end to end).

Trace ids: python allocates ODD ids, the C lane plane allocates EVEN
ids (one atomic each) — no coordination, no collisions. Timestamps are
CLOCK_MONOTONIC nanoseconds on both sides (time.monotonic_ns() and
clock_gettime share the clock on linux), so cross-plane spans in one
trace order consistently.

Surfaces: `GET /trace` (inspection server + HTTP controller),
`list[-detail] trace` and the bare `trace <id>` line on every command
surface, `tools/traceview.py` for offline artifacts, and the
`bench.py --trace` stage committing the per-stage attribution table.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

SAMPLE = int(os.environ.get("VPROXY_TPU_TRACE_SAMPLE", "0") or 0)
SEED = os.environ.get("VPROXY_TPU_TRACE_SEED", "")
# bounded: at most this many live traces; evicting a trace counts its
# spans as dropped (ring="py") — bounded memory, never silent loss
MAX_TRACES = int(os.environ.get("VPROXY_TPU_TRACE_BUF", "512"))
MAX_SPANS_PER_TRACE = 256

PLANES = ("lane", "accept", "engine", "install", "cluster")

_lock = threading.Lock()
_traces: "OrderedDict[int, list]" = OrderedDict()
_plane_spans = {p: 0 for p in PLANES}
_py_dropped = 0
_id_seq = itertools.count(0)
_sample_seq = itertools.count(0)
_tls = threading.local()


def sample_every() -> int:
    return SAMPLE


def enabled() -> bool:
    return SAMPLE > 0


def configure(n: int) -> None:
    """Set the sampling knob at runtime (bench/test hook; production
    uses the env). Pushes the knob into the C lane plane too, so C
    sampling and python sampling flip together."""
    global SAMPLE
    SAMPLE = int(n)
    try:
        from ..net import vtl
        if hasattr(vtl, "trace_set_sample"):
            vtl.trace_set_sample(SAMPLE)
    except Exception:
        pass  # py provider / pre-trace .so: python-plane tracing only


def fnv64(data: bytes) -> int:
    """FNV-1a 64 (the maglev/flow-cache hash idiom) — value-stable
    across processes, unlike PYTHONHASHSEED-randomized hash()."""
    h = 14695981039346656037
    for b in data:
        h ^= b
        h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


def sampled_key(key) -> bool:
    """Seeded, value-stable 1-in-N decision for `key` (bytes or str):
    the same (seed, key) decides identically in every process — the
    VPROXY_TPU_FAILPOINT_SEED reproducibility contract, trace form."""
    if SAMPLE <= 0:
        return False
    if SAMPLE == 1:
        return True
    kb = key if isinstance(key, (bytes, bytearray)) else str(key).encode()
    return fnv64(SEED.encode() + b"\x00" + bytes(kb)) % SAMPLE == 0


def new_trace_id() -> int:
    """Fresh python-plane trace id (odd; the C lane plane allocates
    even ids from its own atomic — disjoint by construction)."""
    return (next(_id_seq) << 1) | 1


def maybe_sample() -> int:
    """Deterministic counter-based 1-in-N: a fresh trace id for every
    Nth call, 0 otherwise. The accept paths' decider."""
    if SAMPLE <= 0:
        return 0
    if next(_sample_seq) % SAMPLE:
        return 0
    return new_trace_id()


# ------------------------------------------------------------- context

class bind:
    """Context manager pushing `tid` as the current trace context for
    this thread (no-op for tid=0): spans recorded by downstream code
    (engine launch markers, installer phases) attach to the request
    that triggered them."""

    __slots__ = ("tid",)

    def __init__(self, tid: int):
        self.tid = tid

    def __enter__(self):
        if self.tid:
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self.tid)
        return self.tid

    def __exit__(self, *exc):
        if self.tid:
            _tls.stack.pop()
        return False


def current_id() -> int:
    """The calling thread's active trace id, 0 when none (one getattr
    + a truthiness check when tracing never bound on this thread)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else 0


# -------------------------------------------------------------- buffer

def record_span(trace_id: int, plane: str, span: str, t_start_ns: int,
                dur_ns: int, **fields) -> None:
    """Append one span (any thread). Bounded: trace eviction and
    per-trace span caps count into the py drop tally, never block."""
    global _py_dropped
    if not trace_id:
        return
    ev = {"trace": trace_id, "plane": plane, "span": span,
          "t_ns": int(t_start_ns), "dur_ns": int(dur_ns)}
    if fields:
        ev.update(fields)
    with _lock:
        spans = _traces.get(trace_id)
        if spans is None:
            if len(_traces) >= MAX_TRACES:
                _, evicted = _traces.popitem(last=False)
                _py_dropped += len(evicted)
            spans = _traces[trace_id] = []
        if len(spans) >= MAX_SPANS_PER_TRACE:
            _py_dropped += 1
            return
        spans.append(ev)
        _plane_spans[plane] = _plane_spans.get(plane, 0) + 1


def ingest_lane_recs(recs) -> None:
    """Fold drained C TraceRecs ((trace_id, t_start_ns, dur_ns, aux,
    lane, span, flags, err) tuples, net/vtl.py trace_drain shape) into
    the buffer. Called from the lane threads (components/lanes.py)."""
    from ..net.vtl import TRACE_SPANS
    for tid, t_ns, dur_ns, aux, lane, span, flags, err in recs:
        name = TRACE_SPANS[span] if span < len(TRACE_SPANS) \
            else f"span{span}"
        fields = {"lane": lane}
        if name == "splice":
            fields["bytes"] = aux
        elif name == "punt":
            fields["kind"] = "connect_fail" if aux else "classic"
        if err:
            fields["err"] = err
        record_span(tid, "lane", name, t_ns, dur_ns, **fields)


def plane_spans_total(plane: str) -> int:
    return _plane_spans.get(plane, 0)


def py_dropped_total() -> int:
    return _py_dropped


def reset() -> None:
    """Test hook: drop every buffered trace (counters stay — they are
    process-lifetime totals, like every other /metrics series)."""
    with _lock:
        _traces.clear()


# ------------------------------------------------------------- queries

def get_trace(trace_id: int) -> list:
    """All spans of one trace, start-time ordered ([] when unknown)."""
    with _lock:
        spans = list(_traces.get(trace_id, ()))
    return sorted(spans, key=lambda s: (s["t_ns"], s["dur_ns"]))


def trace_ids(last: int = 0) -> list:
    with _lock:
        ids = list(_traces.keys())
    return ids[-last:] if last > 0 else ids


def summaries(last: int = 64) -> list:
    """Newest-last trace summaries: id, span count, planes touched,
    end-to-end ns (max span end - min span start)."""
    out = []
    with _lock:
        items = list(_traces.items())[-last:] if last > 0 \
            else list(_traces.items())
    for tid, spans in items:
        if not spans:
            continue
        t0 = min(s["t_ns"] for s in spans)
        t1 = max(s["t_ns"] + s["dur_ns"] for s in spans)
        out.append({"trace": tid, "spans": len(spans),
                    "planes": sorted({s["plane"] for s in spans}),
                    "total_us": round((t1 - t0) / 1000.0, 1)})
    return out


def waterfall(trace_id: int, width: int = 48) -> list:
    """Text waterfall for one trace (the `trace <id>` command): one bar
    per span, offset/scaled to the trace's own [t0, t1] window."""
    spans = get_trace(trace_id)
    if not spans:
        return [f"trace {trace_id}: not found (evicted or never sampled)"]
    return render_spans(trace_id, spans, width)


def render_spans(trace_id, spans: list, width: int = 48) -> list:
    """Waterfall renderer over raw span dicts — shared by the live
    `trace <id>` command and tools/traceview.py (offline artifacts)."""
    spans = sorted(spans, key=lambda s: (s["t_ns"], s["dur_ns"]))
    t0 = min(s["t_ns"] for s in spans)
    t1 = max(s["t_ns"] + s["dur_ns"] for s in spans)
    total = max(1, t1 - t0)
    out = [f"trace {trace_id}  total {total / 1000.0:.1f}us  "
           f"spans {len(spans)}"]
    for s in spans:
        off = int((s["t_ns"] - t0) * width / total)
        w = max(1, int(s["dur_ns"] * width / total))
        w = min(w, width - off) if off < width else 1
        bar = " " * min(off, width - 1) + "#" * w
        extras = " ".join(
            f"{k}={s[k]}" for k in sorted(s)
            if k not in ("trace", "plane", "span", "t_ns", "dur_ns"))
        out.append(f"  [{bar:<{width}}] {s['plane']:>7}/{s['span']:<14} "
                   f"+{(s['t_ns'] - t0) / 1000.0:9.1f}us "
                   f"{s['dur_ns'] / 1000.0:9.1f}us"
                   + (f"  {extras}" if extras else ""))
    return out


def slowest(n: int = 8) -> list:
    """The n slowest buffered traces, spans attached — the worst-trace
    dump shape shared by the bench --trace stage, storm and chaos
    reports (docs/observability.md)."""
    worst = sorted(summaries(last=0), key=lambda t: t["total_us"],
                   reverse=True)[:n]
    return [dict(t, spans=get_trace(t["trace"])) for t in worst]


def stage_table(span_filter=None) -> dict:
    """Per-(plane, span) duration percentiles over every buffered
    trace — the bench attribution table's source. -> {"plane/span":
    {"n", "p50_us", "p99_us"}}."""
    by: dict[str, list] = {}
    with _lock:
        all_spans = [s for spans in _traces.values() for s in spans]
    for s in all_spans:
        key = f"{s['plane']}/{s['span']}"
        if span_filter is not None and not span_filter(s):
            continue
        by.setdefault(key, []).append(s["dur_ns"] / 1000.0)
    out = {}
    for key, durs in sorted(by.items()):
        durs.sort()
        n = len(durs)
        out[key] = {"n": n,
                    "p50_us": round(durs[n // 2], 1),
                    "p99_us": round(durs[min(n - 1, (n * 99) // 100)], 1)}
    return out
