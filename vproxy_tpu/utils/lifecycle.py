"""Process lifecycle state — the graceful-drain flag.

One process-global tri-state consulted by every surface that must agree
during shutdown:

* `/healthz` (utils/metrics inspection server AND the HttpController)
  flips from `ok` to `draining` with a 503 so upstream LBs steer away;
* TcpLB/Socks5 accept paths shed raced-in accepts once draining;
* main.py's SIGTERM path and the `drain` operator command both funnel
  through Application.request_drain(), which sets this.

Kept in utils (not control/) because the data plane and the metrics
surface must read it without importing the control plane.
"""
from __future__ import annotations

import threading
import time

STATE_OK = "ok"
STATE_DRAINING = "draining"

_lock = threading.Lock()
_state = STATE_OK
_drain_started_mono: float = 0.0


def state() -> str:
    return _state


def is_draining() -> bool:
    return _state == STATE_DRAINING


def set_draining() -> bool:
    """Flip to draining; returns False if already draining (idempotent —
    SIGTERM and the `drain` command may race)."""
    global _state, _drain_started_mono
    with _lock:
        if _state == STATE_DRAINING:
            return False
        _state = STATE_DRAINING
        _drain_started_mono = time.monotonic()
    return True


def drain_age_s() -> float:
    """Seconds since drain started (0.0 when not draining)."""
    if not is_draining():
        return 0.0
    return time.monotonic() - _drain_started_mono


def reset() -> None:
    """Test hook: back to ok (a real process never un-drains)."""
    global _state, _drain_started_mono
    with _lock:
        _state = STATE_OK
        _drain_started_mono = 0.0
