"""Failpoint — deterministic, named fault-injection sites.

The failure-containment layer (backend retry, passive outlier ejection,
graceful drain, overload shed) is only trustworthy if every behavior is
provable in tier-1 tests without ad-hoc socket monkeypatching. This
module gives the data plane named injection sites that tests (and
operators, via `add fault` / `remove fault` and `GET /faults`) can arm:

    backend.connect.refuse   Connection.connect raises ECONNREFUSED
    backend.connect.hang     the nonblocking connect never completes
                             (and never errors) — exercises timeouts
    device.dispatch.error    ClassifyService device batches raise,
                             driving the host-oracle failover path
    hc.force_down            health-check probes report failure
    pump.abort               a just-registered splice pump is killed
    pool.handover.dead       a validated warm-pool connection dies at
                             pump handover (the stale-socket race),
                             driving the fresh-connect fallback
    cluster.peer.drop        inbound membership heartbeats are dropped
                             (ctx "from=<id> <addr>"), driving the
                             peer-DOWN hysteresis edge
    cluster.replicate.torn   the leader cuts a replication frame
                             mid-transfer; followers must reject it at
                             the framing layer (no partial install)
    cluster.step.stall       a step dispatch wedges past the barrier
                             deadline, degrading the host to the
                             inline host-index path
    switch.flowcache.stale   ONE flow-cache generation bump is
                             suppressed (ctx = switch alias): proves
                             the generation gate is what prevents the
                             native flow table forwarding through a
                             stale action after a rule mutation
    engine.swap.stall        the background standby-table compile
                             (rules/engine.py TableInstaller) sleeps
                             VPROXY_TPU_SWAP_STALL_S before publishing:
                             proves dispatch keeps answering the OLD
                             generation through a slow install and
                             flips atomically after

Each armed fault carries three independent gates, all optional:

* probability p in (0, 1]  — fire on a coin flip (default 1.0). The
  coin is a per-fault `random.Random(seed)` so a seeded arm replays the
  same hit sequence — "deterministic" is the design goal, not a vibe.
  When no explicit seed is given, the seed is derived from
  `VPROXY_TPU_FAILPOINT_SEED` (read at arm time) combined with the site
  name: one process-level seed makes EVERY probability arm in a
  chaos/storm run reproducible, and the harnesses (`tools/chaos.py
  --seed`, `tools/storm.py --seed`) echo it into their report/BENCH
  artifact so a failed SLO gate can be replayed exactly.
* count n                  — fire at most n times, then auto-disarm.
* match m                  — fire only when the site's context string
  (e.g. the backend "ip:port") contains m.

The hot-path cost when nothing is armed is one module-global bool read
(`_armed` flips with registry size); sites call `failpoint.hit(name,
ctx)` unconditionally.

Env bootstrap (mirrors the VPROXY_TPU_* knob layer): arm faults at
import with `VPROXY_TPU_FAILPOINTS=name[:p[:n]][@match][,...]`, e.g.

    VPROXY_TPU_FAILPOINTS=backend.connect.refuse:0.5@:8080,pump.abort::3
"""
from __future__ import annotations

import os
import random
import threading
from typing import Optional

# the catalog of wired sites — arming anything else is a typo, and the
# command surface must reject typos loudly (a fault that never fires
# "passes" every chaos run)
SITES = (
    "backend.connect.refuse",
    "backend.connect.hang",
    "device.dispatch.error",
    "hc.force_down",
    "pump.abort",
    "pool.handover.dead",
    "cluster.peer.drop",
    "cluster.replicate.torn",
    "cluster.step.stall",
    "switch.flowcache.stale",
    "engine.swap.stall",
    "lane.entry.stale",
    # policing/engine.check consults this FIRST: a hit pins the verdict
    # to shed (ctx "<dim>:<key>", so match= selects specific keys) —
    # tests prove enforcement wiring without traffic shaping. Arming it
    # punts lane accepts to the python mirror (any_armed_excluding),
    # which is where the forced verdict applies.
    "policing.decision.force",
)

# fired (no args) after any arm/disarm/clear/auto-disarm edge — the
# accept lanes subscribe so armed faults force the classic accept path
# in C (vtl_lanes_set_punt_all) without a per-accept ctypes crossing
on_change: list = []


def _fire_change() -> None:
    for cb in list(on_change):
        try:
            cb()
        except Exception:
            pass

_lock = threading.Lock()
_registry: dict[str, "Fault"] = {}
_armed = False  # lock-free fast-path gate, true iff _registry non-empty


class Fault:
    __slots__ = ("name", "probability", "count", "match", "hits", "_rng")

    def __init__(self, name: str, probability: float = 1.0,
                 count: Optional[int] = None, match: Optional[str] = None,
                 seed: Optional[int] = None):
        if name not in SITES:
            raise ValueError(f"unknown failpoint {name!r} "
                             f"(known: {', '.join(SITES)})")
        if not 0.0 < probability <= 1.0:
            raise ValueError(f"probability must be in (0, 1], got {probability}")
        if count is not None and count <= 0:
            raise ValueError(f"count must be positive, got {count}")
        self.name = name
        self.probability = probability
        self.count = count  # remaining fires; None = unlimited
        self.match = match
        self.hits = 0
        if seed is None:
            # string seeds hash by VALUE (sha512 path), not by the
            # PYTHONHASHSEED-randomized hash — stable across processes,
            # so --seed replays the same arm sequence everywhere
            seed = f"{os.environ.get('VPROXY_TPU_FAILPOINT_SEED', '')}:{name}"
        self._rng = random.Random(seed)

    def describe(self) -> dict:
        return {"name": self.name, "probability": self.probability,
                "count": self.count, "match": self.match, "hits": self.hits}


def arm(name: str, probability: float = 1.0, count: Optional[int] = None,
        match: Optional[str] = None, seed: Optional[int] = None) -> None:
    """Arm (or re-arm, replacing) a fault site."""
    global _armed
    f = Fault(name, probability, count, match, seed)
    with _lock:
        _registry[name] = f
        _armed = True
    _fire_change()


def disarm(name: str) -> bool:
    """Disarm; returns False when the fault wasn't armed."""
    global _armed
    with _lock:
        gone = _registry.pop(name, None) is not None
        _armed = bool(_registry)
    if gone:
        _fire_change()
    return gone


def clear() -> None:
    """Test hook: drop every armed fault."""
    global _armed
    with _lock:
        _registry.clear()
        _armed = False
    _fire_change()


def active() -> list[dict]:
    """Snapshot for `GET /faults` / `list fault`."""
    with _lock:
        return [f.describe() for f in _registry.values()]


def any_armed() -> bool:
    """Any fault armed at all (the lock-free fast-path gate). The accept
    fast lane (C-side connect+pump, tcplb._fast_splice) bypasses the
    python connect path whose code hosts the backend.connect.* sites, so
    it defers to the classic path whenever faults are armed — failpoint
    semantics stay exact under test."""
    return _armed


def any_armed_excluding(prefix: str) -> bool:
    """any_armed() minus sites under `prefix` — the accept lanes force
    the classic path for every armed fault EXCEPT the lane.* sites
    themselves (lane.entry.stale suppresses a generation bump; forcing
    punts on it would make the gate untestable)."""
    with _lock:
        return any(not n.startswith(prefix) for n in _registry)


def hit(name: str, ctx: str = "") -> bool:
    """Ask a site whether its fault fires for this event. Decrements a
    count arm on fire and auto-disarms at zero. Safe from any thread."""
    global _armed
    if not _armed:
        return False
    with _lock:
        f = _registry.get(name)
        if f is None:
            return False
        if f.match is not None and f.match not in ctx:
            return False
        if f.probability < 1.0 and f._rng.random() >= f.probability:
            return False
        f.hits += 1
        auto_disarmed = False
        if f.count is not None:
            f.count -= 1
            if f.count <= 0:
                del _registry[name]
                _armed = bool(_registry)
                auto_disarmed = True
    if auto_disarmed:
        _fire_change()  # a count arm draining re-enables the lanes
    from . import events
    events.record("fault_injected", f"failpoint {name} fired",
                  failpoint=name, ctx=ctx)
    return True


def _bootstrap_env() -> None:
    """VPROXY_TPU_FAILPOINTS=name[:p[:n]][@match],... at import."""
    spec = os.environ.get("VPROXY_TPU_FAILPOINTS", "")
    for part in filter(None, (p.strip() for p in spec.split(","))):
        body, _, match = part.partition("@")
        fields = body.split(":")
        try:
            name = fields[0]
            p = float(fields[1]) if len(fields) > 1 and fields[1] else 1.0
            n = int(fields[2]) if len(fields) > 2 and fields[2] else None
            arm(name, p, n, match or None)
        except ValueError as e:
            import sys
            print(f"VPROXY_TPU_FAILPOINTS: skipping {part!r}: {e}",
                  file=sys.stderr)


_bootstrap_env()
