"""TcpLB — the TCP/HTTP load balancer resource.

Reference: component/app/TcpLB.java — per-acceptor-loop server socks
(:201-250), per-connection classify = securityGroup.allow then
backend.next(clientAddr, hint) (:166-180), worker round-robin (:182-199).

TPU-first data path: accept and classification decisions run in Python
(ACL + hint through the device matchers). protocol="tcp" splices
immediately through the native pump (C++, net/native/vtl.cpp) and never
touches the interpreter again; protocol="http-splice" parses only the
first request head for a Host/URI hint before dropping into the same
pump; any other protocol name resolves through the processor registry
(processors/base.py — http/http1/h2/dubbo/framed-int32) and runs the
full per-request/per-stream L7 engine (components/l7.py).
"""
from __future__ import annotations

import time
from typing import Optional

from ..net import vtl
from ..net.connection import Connection, Handler, ServerSock
from ..processors import base as processors
from ..processors.http1 import HeadParser
from ..rules.ir import Proto
from ..utils import events
from ..utils.ip import parse_ip
from ..utils.log import Logger
from ..utils.metrics import accept_stage_observe
from .elgroup import EventLoopGroup
from .l7 import L7Engine
from .secgroup import SecurityGroup
from .servergroup import Connector
from .upstream import Upstream

_log = Logger("tcp-lb")


class _SpliceBack(Handler):
    """Backend-connect handler for the splice path — ONE shared class
    (defining it per accept showed up as __build_class__ on the
    short-connection profile)."""

    __slots__ = ("lb", "loop", "front_fd", "target", "head", "front",
                 "_pid", "tls_ctx", "t_acc", "t_back")

    def __init__(self, lb, loop, front_fd: int, target: Connector,
                 head: bytes, front: str, tls_ctx: int = 0,
                 t_acc: Optional[float] = None):
        self.lb = lb
        self.loop = loop
        self.front_fd = front_fd
        self.target = target
        self.head = head
        self.front = front
        self._pid = None
        self.tls_ctx = tls_ctx  # nonzero: TLS-terminating pump
        self.t_acc = t_acc         # accept timestamp (span timers)
        self.t_back = time.monotonic()  # backend chosen -> handover span

    def on_connected(self, conn: Connection) -> None:
        # do NOT consume early backend bytes (100-continue, early
        # errors): leave them queued in the kernel for the pump
        conn.pause_reading()
        if self.head:
            conn.write(self.head)
        if conn.out:
            # wait for drain before pump handover
            return
        self._handover(conn)

    def on_drained(self, conn: Connection) -> None:
        self._handover(conn)

    def _handover(self, conn: Connection) -> None:
        if conn.detached or conn.closed:
            return
        bfd = conn.detach()
        vtl.set_nodelay(self.front_fd)
        vtl.set_nodelay(bfd)
        if self.tls_ctx:
            pid = self.loop.pump_tls(self.front_fd, bfd, self.tls_ctx,
                                     self.lb.in_buffer_size, self._done)
        else:
            pid = self.loop.pump(self.front_fd, bfd,
                                 self.lb.in_buffer_size, self._done)
        self._pid = pid
        now = time.monotonic()
        self.lb._watch_pump(
            self.loop, pid,
            f"{self.front} -> {self.target.ip}:{self.target.port}")
        # span observations AFTER the watch registration: the native pump
        # moves bytes without the GIL, so a session-listing racing these
        # (lock-taking) calls must already see the pump as spliced
        accept_stage_observe("handover", now - self.t_back)
        if self.t_acc is not None:
            accept_stage_observe("total", now - self.t_acc)

    def _done(self, a2b: int, b2a: int, err: int) -> None:
        lb, svr = self.lb, self.target.svr
        lb._unwatch_pump(self.loop, self._pid)
        lb.bytes_in += a2b
        lb.bytes_out += b2a
        svr.bytes_in += a2b
        svr.bytes_out += b2a
        svr.conn_count -= 1
        lb.active_sessions -= 1
        events.record(
            "conn", f"{self.front} -> {self.target.ip}:{self.target.port} "
            "closed", lb=lb.alias, bytes_in=a2b, bytes_out=b2a, err=err)

    def on_closed(self, conn: Connection, err: int) -> None:
        self.target.svr.conn_count -= 1
        self.lb.active_sessions -= 1
        vtl.close(self.front_fd)
        events.record(
            "conn", f"{self.front} -> {self.target.ip}:{self.target.port} "
            "backend connect failed", lb=self.lb.alias, err=err)


class TcpLB:
    def __init__(self, alias: str, acceptor: EventLoopGroup,
                 worker: EventLoopGroup, bind_ip: str, bind_port: int,
                 backend: Upstream, protocol: str = "tcp",
                 security_group: Optional[SecurityGroup] = None,
                 in_buffer_size: int = 65536, timeout_ms: int = 900_000,
                 cert_keys: Optional[list] = None):
        if protocol not in ("tcp", "http-splice") \
                and processors.get(protocol) is None:
            raise ValueError(f"unsupported protocol {protocol}")
        self.holder = None
        self.cert_keys = cert_keys or []
        self.protocol = protocol
        if cert_keys:
            self.set_cert_keys(cert_keys)
        self.alias = alias
        self.acceptor = acceptor
        self.worker = worker
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.backend = backend
        self.security_group = security_group or SecurityGroup.allow_all()
        self.in_buffer_size = in_buffer_size
        self.timeout_ms = timeout_ms
        self.server_socks: list[ServerSock] = []
        self.started = False
        # stats (cmd/ResourceType accepted-conn-count / bytes-in / bytes-out)
        self.accepted = 0
        self.active_sessions = 0
        self.bytes_in = 0
        self.bytes_out = 0
        # id(loop) -> {pid: (total, ts, desc)}; loops kept by id so the
        # session listing can marshal stat reads onto the OWNING loop
        self._pump_watch: dict[int, dict] = {}
        self._watch_loops: dict[int, object] = {}
        self._sweep_armed: set[int] = set()
        self._sweep_timers: dict[int, object] = {}  # id(loop) -> TimerEvent

    # ------------------------------------------------------------ control

    def on_loop_death(self, group, lp) -> None:
        """LBAttach semantics (TcpLB.java:45-66): an acceptor loop died —
        forget its listener (the dying loop already closed the fd) and
        bind a replacement on a surviving loop so capacity recovers."""
        if group is not self.acceptor or not self.started:
            return
        dead = [ss for ss in self.server_socks if ss.loop is lp]
        if not dead:
            return
        self.server_socks = [ss for ss in self.server_socks
                             if ss.loop is not lp]
        if not group.loops:
            return  # nowhere to re-home; stop() semantics apply
        try:
            nlp = group.next()

            def mk() -> None:
                if not self.started:  # raced a concurrent stop()
                    return
                self.server_socks.append(ServerSock(
                    nlp, self.bind_ip, self.bind_port,
                    lambda fd, ip, port, lp=nlp: self._on_accept(
                        lp, fd, ip, port),
                    reuseport=True))
            nlp.call_sync(mk)
            if not self.started:  # stop() raced the re-home: undo
                for ss in self.server_socks:
                    ss.loop.run_on_loop(ss.close)
                self.server_socks = []
        except OSError as e:
            _log.alert(f"tcp-lb {self.alias}: re-home bind failed: {e!r}")

    def start(self) -> None:
        if self.started:
            return
        self.started = True
        self.acceptor.attach(self)
        loops = self.acceptor.loops
        # bind loops one at a time so an ephemeral port (bind_port=0) is
        # resolved once and the remaining loops share it via REUSEPORT
        try:
            for lp in loops:
                def mk(lp=lp) -> None:
                    ss = ServerSock(
                        lp, self.bind_ip, self.bind_port,
                        lambda fd, ip, port, lp=lp: self._on_accept(lp, fd, ip, port),
                        reuseport=len(loops) > 1)
                    self.server_socks.append(ss)
                    if self.bind_port == 0:
                        self.bind_port = ss.port
                lp.call_sync(mk)
        except OSError as e:
            self.stop()
            self.started = False
            raise OSError(
                f"tcp-lb {self.alias}: bind failed on "
                f"{self.bind_ip}:{self.bind_port}: {e}") from e

    def stop(self) -> None:
        if not self.started:
            return
        self.started = False
        self.acceptor.detach(self)
        for ss in self.server_socks:
            ss.loop.run_on_loop(ss.close)
        self.server_socks = []

    # --------------------------------------------------------- data plane

    def _on_accept(self, loop, cfd: int, ip: str, port: int) -> None:
        self.accepted += 1
        t_acc = time.monotonic()

        # ACL gate (SecurityGroup.allow — TcpLB.java:168-171); the lookup
        # rides the ClassifyService micro-batch queue, coalescing with
        # other in-flight accepts across connections/loops
        def on_verdict(ok: bool) -> None:
            accept_stage_observe("acl", time.monotonic() - t_acc)
            if not ok or not self.started:
                if not ok:
                    events.record("conn_denied",
                                  f"{ip}:{port} denied by ACL",
                                  lb=self.alias)
                vtl.close(cfd)
                return
            if self.worker is not self.acceptor:
                wl = self.worker.next()
                if not wl.run_on_loop(
                        lambda: self._serve(wl, cfd, ip, port, t_acc)):
                    vtl.close(cfd)  # worker loop died; don't leak the fd
            else:
                self._serve(loop, cfd, ip, port, t_acc)

        try:
            self.security_group.allow_async(Proto.TCP, parse_ip(ip),
                                            self.bind_port, on_verdict, loop)
        except Exception:
            vtl.close(cfd)  # classify queue unavailable: refuse, not leak
            raise

    def _serve(self, loop, cfd: int, ip: str, port: int,
               t_acc: Optional[float] = None) -> None:
        """Owns cfd: every branch either hands it off or closes it exactly
        once — including when `loop` died while the accept's ACL verdict
        was in flight (the verdict then runs on the dispatcher thread, or
        via the closed loop's promised-task drain)."""
        if self.holder is not None:
            self._serve_tls(loop, cfd, ip, port, t_acc)
        elif self.protocol == "tcp":
            t0 = time.monotonic()
            conn = self.backend.next(parse_ip(ip))
            accept_stage_observe("backend_pick", time.monotonic() - t0)
            if conn is None:
                vtl.close(cfd)
                return
            self._splice(loop, cfd, conn, b"", front=f"{ip}:{port}",
                         t_acc=t_acc)
        elif self.protocol == "http-splice":
            self._http_classify(loop, cfd, ip, port, t_acc)
        else:
            try:
                L7Engine(self, loop, cfd, ip, port,
                         processors.get(self.protocol))
            except Exception:
                pass  # L7Engine closes cfd on its failure paths

    def _serve_tls(self, loop, cfd: int, ip: str, port: int,
                   t_acc: Optional[float] = None) -> None:
        """TLS termination. protocol=tcp on the native provider takes
        the C-side path: MSG_PEEK the ClientHello for SNI (cert choice +
        classify hint), then hand the untouched socket to the OpenSSL
        splice pump — handshake and record layer run in C, TLS bytes
        never enter Python (the reference's engine-speed SSL rings,
        SSLWrapRingBuffer.java:23/SSLUnwrapRingBuffer.java:28). L7
        protocols (and the pure-python provider, or mirror taps wanting
        plaintext) keep the MemoryBIO path through the L7 engine."""
        import os as _os
        if (self.protocol == "tcp" and vtl.PROVIDER == "native"
                and _os.environ.get("VPROXY_TPU_NATIVE_TLS", "1") != "0"
                and vtl.tls_available() and not self._mirror_wants_tls()):
            self._serve_tls_native(loop, cfd, ip, port, t_acc)
            return
        from ..net.tls import TlsSocket
        from ..processors.base import TcpRelaySession
        from ..rules.ir import Hint
        try:
            conn = Connection(loop, cfd, (ip, port))
        except OSError:
            vtl.close(cfd)
            return
        tls = TlsSocket(conn, self.holder.front_context)
        if self.protocol == "tcp":
            def factory(eng, addr):
                return TcpRelaySession(
                    eng, addr,
                    hint_fn=lambda: Hint.of_host(tls.sni) if tls.sni else None)
        else:
            name = "http1" if self.protocol == "http-splice" else self.protocol
            factory = processors.get(name)
        L7Engine(self, loop, cfd, ip, port, factory, front=tls)

    def _mirror_wants_tls(self) -> bool:
        """Plaintext mirror taps need the python TLS path (the native
        pump's plaintext never surfaces to the mirror)."""
        from ..utils.mirror import Mirror
        m = Mirror.get()
        return m.hot and m.wants("ssl")  # net/tls.py's mirror origin

    def _serve_tls_native(self, loop, cfd: int, ip: str, port: int,
                          t_acc: Optional[float] = None) -> None:
        """Peek the ClientHello (bytes stay queued), choose the cert and
        classify by SNI, connect the backend, then run the C-side
        TLS-terminating splice pump on the untouched client socket."""
        from ..net.sniff import MAX_HELLO, parse_client_hello_sni
        from ..rules.ir import Hint
        lb = self
        # the timeout abort gets the deadline list so it clears
        # deadline[0]: the parked-hello rearm timer guards on that, and
        # without it a post-timeout rearm could re-enable reads on a
        # RECYCLED fd number owned by an unrelated connection
        deadline: list = [None]
        deadline[0] = loop.delay(
            self.timeout_ms,
            lambda: self._peek_abort(loop, cfd, deadline))

        def on_ev(fd: int, ev: int) -> None:
            if ev & vtl.EV_ERROR:
                self._peek_abort(loop, cfd, deadline)
                return
            try:
                data = vtl.recv_peek(cfd, MAX_HELLO)
            except OSError:
                self._peek_abort(loop, cfd, deadline)
                return
            if data is None:
                return  # spurious wakeup
            if not data:
                self._peek_abort(loop, cfd, deadline)  # EOF before hello
                return
            sni, complete = parse_client_hello_sni(data)
            if not complete:
                # MSG_PEEK leaves the fd readable: a level-triggered
                # re-arm here would busy-spin until the hello completes.
                # Park interest and re-check shortly (deadline still
                # bounds the total wait).
                try:
                    loop.modify(cfd, 0)

                    def rearm() -> None:
                        if deadline[0] is None:  # aborted meanwhile
                            return
                        try:
                            if loop.registered(cfd):
                                loop.modify(cfd, vtl.EV_READ)
                        except Exception:
                            pass
                    loop.delay(20, rearm)
                except Exception:
                    self._peek_abort(loop, cfd, deadline)
                return  # wait for more ClientHello bytes
            if deadline[0] is not None:
                deadline[0].cancel()
                deadline[0] = None
            loop.remove(cfd)
            ck = self.holder.choose_cert_key(sni)
            ctx = ck.native_ctx()
            if ctx is None:
                # libssl vanished / cert unreadable: python TLS fallback
                self._serve_tls_python_fallback(loop, cfd, ip, port)
                return
            hint = Hint.of_host(sni) if sni else None

            def on_back(back) -> None:
                if back is None:
                    vtl.close(cfd)
                    return
                self._splice_tls(loop, cfd, back, ctx,
                                 front=f"{ip}:{port}", t_acc=t_acc)

            lb.backend.next_async(parse_ip(ip), hint, on_back, loop=loop)

        try:
            loop.add(cfd, vtl.EV_READ, on_ev)
        except OSError:
            if deadline[0] is not None:  # the timer must not fire on a
                deadline[0].cancel()     # closed (reusable) fd number
                deadline[0] = None
            vtl.close(cfd)

    def _peek_abort(self, loop, cfd: int, deadline=None) -> None:
        if deadline and deadline[0] is not None:
            deadline[0].cancel()
            deadline[0] = None
        try:
            if loop.registered(cfd):
                loop.remove(cfd)
        except Exception:
            pass
        vtl.close(cfd)

    def _serve_tls_python_fallback(self, loop, cfd: int, ip: str,
                                   port: int) -> None:
        from ..net.tls import TlsSocket
        from ..processors.base import TcpRelaySession
        from ..rules.ir import Hint
        try:
            conn = Connection(loop, cfd, (ip, port))
        except OSError:
            vtl.close(cfd)
            return
        tls = TlsSocket(conn, self.holder.front_context)

        def factory(eng, addr):
            return TcpRelaySession(
                eng, addr,
                hint_fn=lambda: Hint.of_host(tls.sni) if tls.sni else None)

        L7Engine(self, loop, cfd, ip, port, factory, front=tls)

    def _splice_tls(self, loop, front_fd: int, target: Connector,
                    ctx: int, front: str = "?",
                    t_acc: Optional[float] = None) -> None:
        """Like _splice, but the handover runs the TLS-terminating pump
        (client side TLS in C, backend plaintext)."""
        svr = target.svr
        svr.conn_count += 1
        self.active_sessions += 1
        try:
            back = Connection.connect(loop, target.ip, target.port)
        except OSError:
            svr.conn_count -= 1
            self.active_sessions -= 1
            vtl.close(front_fd)
            return
        back.set_handler(_SpliceBack(self, loop, front_fd, target, b"",
                                     f"tls {front}", tls_ctx=ctx,
                                     t_acc=t_acc))

    # ------------------------------------------------------ idle timeout

    # ------------------------------------------------- hot-settable knobs

    def set_cert_keys(self, cert_keys: list) -> None:
        """Swap the served certs without restart ("modifiable when
        running", TcpLB.java:294-320): the holder is built FIRST so a
        bad cert file leaves the old holder and cert list untouched;
        new accepts use the new holder, in-flight sessions keep theirs."""
        from .certkey import CertKeyHolder
        proc = processors.get(self.protocol)
        alpn = list(proc.alpn) if proc is not None and proc.alpn else None
        holder = CertKeyHolder(cert_keys, alpn=alpn)  # may raise: no change
        self.cert_keys = cert_keys
        self.holder = holder

    def set_timeout(self, timeout_ms: int) -> None:
        """Hot-set the idle timeout AND re-arm the per-loop idle sweeps:
        an armed sweep waits timeout/4, so lowering the timeout without
        re-arming would only bite after the OLD interval elapsed."""
        self.timeout_ms = timeout_ms
        for lid, lp in list(self._watch_loops.items()):
            def rearm(lid=lid, lp=lp) -> None:
                t = self._sweep_timers.pop(lid, None)
                if t is not None:
                    t.cancel()
                self._sweep_armed.discard(lid)
                if self._pump_watch.get(lid):
                    self._arm_sweep(lp)
            lp.run_on_loop(rearm)

    def _watch_pump(self, loop, pid: int, desc: str = "") -> None:
        """Track spliced-session activity; kill sessions idle > timeout_ms
        (the reference's tcpTimeout, Config.java:20 — default 15 min).
        `desc` ("front -> back") feeds the session/connection listing
        resources (cmd/ResourceType sess/conn)."""
        st = self._pump_watch.setdefault(id(loop), {})
        self._watch_loops[id(loop)] = loop  # session listing needs the obj
        st[pid] = (0, loop.now, desc)
        if len(st) == 1:
            self._arm_sweep(loop)

    def _unwatch_pump(self, loop, pid) -> None:
        self._pump_watch.get(id(loop), {}).pop(pid, None)

    def _arm_sweep(self, loop) -> None:
        def sweep() -> None:
            st = self._pump_watch.get(id(loop), {})
            if not st or not self.started:
                self._sweep_armed.discard(id(loop))
                self._sweep_timers.pop(id(loop), None)
                return
            for pid, (last_total, last_ts, desc) in list(st.items()):
                try:
                    a2b, b2a, _err = loop.pump_stat(pid)
                except OSError:
                    st.pop(pid, None)
                    continue
                total = a2b + b2a
                if total != last_total:
                    st[pid] = (total, loop.now, desc)
                elif (loop.now - last_ts) * 1000 >= self.timeout_ms:
                    st.pop(pid, None)
                    loop.pump_close(pid)
            if st:  # interval re-read so hot-set timeouts take effect
                self._sweep_timers[id(loop)] = loop.delay(
                    max(self.timeout_ms // 4, 1000), sweep)
            else:
                self._sweep_armed.discard(id(loop))
                self._sweep_timers.pop(id(loop), None)

        if id(loop) not in self._sweep_armed:
            self._sweep_armed.add(id(loop))
            self._sweep_timers[id(loop)] = loop.delay(
                max(self.timeout_ms // 4, 1000), sweep)

    def _http_classify(self, loop, cfd: int, ip: str, port: int,
                       t_acc: Optional[float] = None) -> None:
        lb = self
        parser = HeadParser()
        try:
            front = Connection(loop, cfd, (ip, port))
        except OSError:
            vtl.close(cfd)
            return
        # a client that never completes its head is dropped at the timeout
        def head_timeout() -> None:
            if not front.closed and not front.detached:
                front.close()
        loop.delay(lb.timeout_ms, head_timeout)

        class Front(Handler):
            def on_data(self, conn: Connection, data: bytes) -> None:
                parser.feed(data)
                if parser.error:
                    conn.close()
                    return
                if parser.done:
                    conn.pause_reading()
                    hint = parser.hint()

                    # classify via the cross-connection micro-batch queue
                    def on_back(back) -> None:
                        if conn.closed or conn.detached:
                            return
                        if back is None:
                            conn.write(b"HTTP/1.1 503 Service Unavailable\r\n"
                                       b"content-length: 0\r\nconnection: close\r\n\r\n")
                            loop.delay(50, conn.close)
                            return
                        buffered = bytes(parser.buf)
                        ffd = conn.detach()
                        lb._splice(loop, ffd, back, buffered,
                                   front=f"{ip}:{port}", t_acc=t_acc)

                    lb.backend.next_async(parse_ip(ip), hint, on_back,
                                          loop=loop)

            def on_eof(self, conn: Connection) -> None:
                conn.close()

        front.set_handler(Front())

    def _splice(self, loop, front_fd: int, target: Connector,
                head: bytes, front: str = "?",
                t_acc: Optional[float] = None) -> None:
        svr = target.svr
        svr.conn_count += 1
        self.active_sessions += 1
        try:
            back = Connection.connect(loop, target.ip, target.port)
        except OSError:
            svr.conn_count -= 1
            self.active_sessions -= 1
            vtl.close(front_fd)
            return
        back.set_handler(_SpliceBack(self, loop, front_fd, target, head,
                                     front, t_acc=t_acc))
