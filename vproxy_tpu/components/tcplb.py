"""TcpLB — the TCP/HTTP load balancer resource.

Reference: component/app/TcpLB.java — per-acceptor-loop server socks
(:201-250), per-connection classify = securityGroup.allow then
backend.next(clientAddr, hint) (:166-180), worker round-robin (:182-199).

TPU-first data path: accept and classification decisions run in Python
(ACL + hint through the device matchers). protocol="tcp" splices
immediately through the native pump (C++, net/native/vtl.cpp) and never
touches the interpreter again; protocol="http-splice" parses only the
first request head for a Host/URI hint before dropping into the same
pump; any other protocol name resolves through the processor registry
(processors/base.py — http/http1/h2/dubbo/framed-int32) and runs the
full per-request/per-stream L7 engine (components/l7.py).
"""
from __future__ import annotations

import errno
import os
import threading
import time
from typing import Optional

from ..net import vtl
from ..net.connection import Connection, Handler, ServerSock
from ..policing import engine as policing
from ..processors import base as processors
from ..processors.http1 import HeadParser
from ..rules.ir import Proto
from ..utils import events, failpoint, sketch, trace, workload
from ..utils.ip import parse_ip
from ..utils.log import Logger
from ..utils.metrics import accept_stage_observe, conn_observe
from .elgroup import EventLoopGroup
from .l7 import L7Engine
from .lanes import LANES, AcceptLanes
from .pool import ConnectionPool, PoolHandler
from .secgroup import SecurityGroup
from .servergroup import Connector
from .upstream import Upstream

_log = Logger("tcp-lb")

# failure-containment knobs (docs/robustness.md)
CONNECT_RETRIES = int(os.environ.get("VPROXY_TPU_CONNECT_RETRIES", "2"))
RETRY_BUDGET_RATIO = float(os.environ.get("VPROXY_TPU_RETRY_BUDGET", "0.2"))
MAX_SESSIONS = int(os.environ.get("VPROXY_TPU_MAX_SESSIONS", "1000000"))
CONNECT_TIMEOUT_MS = int(os.environ.get("VPROXY_TPU_CONNECT_TIMEOUT_MS",
                                        "3000"))
# slowloris defense (docs/robustness.md): every pre-handover phase a
# client can stall — the TLS ClientHello peek, the http-splice head
# parse — is bounded by this deadline instead of the (minutes-long)
# idle timeout, so a half-open flood cannot pin fds/parser state for
# timeout_ms per connection. Expired sessions are RST-killed and
# counted vproxy_lb_shed_total{reason=halfopen}. 0 disables (the
# pre-r10 behavior: the idle timeout governs).
HANDSHAKE_MS = int(os.environ.get("VPROXY_TPU_HANDSHAKE_MS", "10000"))
# accept-fast-lane knobs (docs/perf.md): pre-connected idle sockets per
# (worker loop, backend) so short connections skip the backend-connect
# round trip entirely. 0 = off (the default: pooling assumes the backend
# tolerates idle warm connections).
POOL_SIZE = int(os.environ.get("VPROXY_TPU_POOL_SIZE", "0"))
POOL_IDLE_S = float(os.environ.get("VPROXY_TPU_POOL_IDLE_S", "30"))
# sockets warmed within this window skip the MSG_PEEK liveness check at
# handover (a socket this young is as trustworthy as a fresh connect;
# RSTs are reaped by EV_ERROR, clean FINs by the peek once it ages past
# the window, and the residual race by the handover-failure fallback)
POOL_VALIDATE_S = float(os.environ.get("VPROXY_TPU_POOL_VALIDATE_S", "1"))


def _tspan(tid: int, span: str, t0: float, t1: float, **fields) -> None:
    """Accept-plane span helper: time.monotonic() floats -> ns (same
    CLOCK_MONOTONIC the C lane spans stamp). One branch when the
    request is unsampled."""
    if tid:
        trace.record_span(tid, "accept", span, int(t0 * 1e9),
                          int((t1 - t0) * 1e9), **fields)


class RetryBudget:
    """Sliding-window retry budget: retries ≤ ratio × accepts (+ a small
    burst floor so a quiet LB's first failure can still fail over). A
    dead cluster must not double its own connect load via retries, so
    the budget is enforced per LB over a two-bucket rolling window."""

    __slots__ = ("ratio", "burst", "window_s", "_lock",
                 "_t0", "_accepts", "_retries", "_p_accepts", "_p_retries")

    def __init__(self, ratio: float = RETRY_BUDGET_RATIO, burst: int = 5,
                 window_s: float = 10.0):
        self.ratio = ratio
        self.burst = burst
        self.window_s = window_s
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._accepts = 0
        self._retries = 0
        self._p_accepts = 0  # previous bucket (smooths the window edge)
        self._p_retries = 0

    def _roll(self, now: float) -> None:
        age = now - self._t0
        if age < self.window_s:
            return
        if age < 2 * self.window_s:
            self._p_accepts, self._p_retries = self._accepts, self._retries
        else:
            self._p_accepts = self._p_retries = 0
        self._accepts = self._retries = 0
        self._t0 = now

    def on_accept(self) -> None:
        with self._lock:
            self._roll(time.monotonic())
            self._accepts += 1

    def on_accepts(self, n: int) -> None:
        """Bulk credit — the C accept lanes sync their accepted counter
        in batches (per lane-poll tick): lane traffic must fund the
        budget its own connect-fail punts spend."""
        if n <= 0:
            return
        with self._lock:
            self._roll(time.monotonic())
            self._accepts += n

    def try_take(self) -> bool:
        """Reserve one retry; False when the budget is exhausted."""
        with self._lock:
            self._roll(time.monotonic())
            accepts = self._accepts + self._p_accepts
            retries = self._retries + self._p_retries
            if retries + 1 > self.ratio * accepts + self.burst:
                return False
            self._retries += 1
            return True


class _LBPoolHandler(PoolHandler):
    """How TcpLB's warm pool dials one backend: a plain data-plane
    connect (failpoint-gated like any other, bounded by the LB's connect
    timeout). No keepalive traffic — protocol=tcp can't speak for the
    backend's protocol — so staleness is bounded by idle expiry plus the
    MSG_PEEK validation at handover. Refill successes report_success:
    a pool fill is a real connect, and pooled traffic must keep clearing
    the backend's passive-ejection streak the way classic connects do."""

    __slots__ = ("svr", "group", "ip", "port", "timeout_ms")

    def __init__(self, target: Connector, timeout_ms: int):
        self.svr = target.svr
        self.group = target.group
        self.ip = target.ip
        self.port = target.port
        self.timeout_ms = timeout_ms

    def connect(self, loop) -> Connection:
        return Connection.connect(loop, self.ip, self.port,
                                  timeout_ms=self.timeout_ms)

    def on_warm(self, conn: Connection) -> None:
        self.group.report_success(self.svr)


class _SpliceBack(Handler):
    """Backend-connect handler for the splice path — ONE shared class
    (defining it per accept showed up as __build_class__ on the
    short-connection profile)."""

    __slots__ = ("lb", "loop", "front_fd", "target", "head", "front",
                 "_pid", "tls_ctx", "t_acc", "t_back", "connected",
                 "src_ip", "tried", "hint", "pooled", "tid", "t_hand")

    def __init__(self, lb, loop, front_fd: int, target: Connector,
                 head: bytes, front: str, tls_ctx: int = 0,
                 t_acc: Optional[float] = None, src_ip: bytes = b"",
                 tried: Optional[set] = None, hint=None,
                 pooled: bool = False, tid: int = 0):
        self.lb = lb
        self.loop = loop
        self.front_fd = front_fd
        self.target = target
        self.head = head
        self.front = front
        self._pid = None
        self.tls_ctx = tls_ctx  # nonzero: TLS-terminating pump
        self.t_acc = t_acc         # accept timestamp (span timers)
        self.t_back = time.monotonic()  # backend chosen -> handover span
        self.connected = False     # flips in on_connected: phase evidence
        self.src_ip = src_ip       # client addr bytes (retry re-balance)
        self.tried = tried if tried is not None else set()
        self.hint = hint           # classify hint: retries re-run the
                                   # original selection, not plain WRR
        self.pooled = pooled       # adopted a warmed pool connection
        self.tid = tid             # trace id (0 = unsampled request)
        self.t_hand = 0.0          # handover stamp (splice span start)

    def on_connected(self, conn: Connection) -> None:
        self.connected = True
        self.target.group.report_success(self.target.svr)
        if self.tried:  # a retry attempt landed
            self.lb._retries_total("success").incr()
        # do NOT consume early backend bytes (100-continue, early
        # errors): leave them queued in the kernel for the pump
        conn.pause_reading()
        if self.head:
            conn.write(self.head)
        if conn.out:
            # wait for drain before pump handover
            return
        self._handover(conn)

    def on_drained(self, conn: Connection) -> None:
        self._handover(conn)

    def _handover(self, conn: Connection) -> None:
        if conn.detached or conn.closed:
            return
        if self.pooled and self.tried:
            # the retried session is now truly served (classic connects
            # count this edge in on_connected; pooled ones count here)
            self.lb._retries_total("success").incr()
        bfd = conn.detach()
        if not vtl.pump_sets_nodelay():
            # prebuilt pre-r6 .so: its pump setup lacks pump_set_nodelay,
            # so the explicit calls stay (r6+ does it in C — two fewer
            # ctypes crossings per session)
            vtl.set_nodelay(self.front_fd)
            vtl.set_nodelay(bfd)
        if self.tls_ctx:
            pid = self.loop.pump_tls(self.front_fd, bfd, self.tls_ctx,
                                     self.lb.in_buffer_size, self._done)
        else:
            pid = self.loop.pump(self.front_fd, bfd,
                                 self.lb.in_buffer_size, self._done)
        self._pid = pid
        now = time.monotonic()
        self.lb._watch_pump(
            self.loop, pid,
            f"{self.front} -> {self.target.ip}:{self.target.port}")
        # span observations AFTER the watch registration: the native pump
        # moves bytes without the GIL, so a session-listing racing these
        # (lock-taking) calls must already see the pump as spliced
        accept_stage_observe("handover", now - self.t_back)
        self.t_hand = now
        _tspan(self.tid, "connect", self.t_back, now,
               backend=f"{self.target.ip}:{self.target.port}",
               pooled=self.pooled)
        if self.t_acc is not None:
            accept_stage_observe("total", now - self.t_acc)
            self.lb._observe_accept(now - self.t_acc)

    def _done(self, a2b: int, b2a: int, err: int) -> None:
        lb, svr = self.lb, self.target.svr
        lb._unwatch_pump(self.loop, self._pid)
        lb.bytes_in += a2b
        lb.bytes_out += b2a
        svr.bytes_in += a2b
        svr.bytes_out += b2a
        svr.conn_count -= 1
        lb._sessions_delta(-1)
        # workload capture: the python splice path's per-connection
        # size/duration (lane-served sessions fold in from C deltas)
        if workload.ON:
            t0 = self.t_acc if self.t_acc is not None else self.t_hand
            dur_ms = (time.monotonic() - t0) * 1e3 if t0 else 0.0
            conn_observe(lb.alias, a2b + b2a, dur_ms)
        if self.tid:
            now = time.monotonic()
            _tspan(self.tid, "splice", self.t_hand or now, now,
                   bytes=a2b + b2a)
            _tspan(self.tid, "close", now, now, err=err)
        events.record(
            "conn", f"{self.front} -> {self.target.ip}:{self.target.port} "
            "closed", lb=lb.alias, bytes_in=a2b, bytes_out=b2a, err=err,
            trace_id=self.tid)

    def on_closed(self, conn: Connection, err: int) -> None:
        self.target.svr.conn_count -= 1
        errno_ = -err if err < 0 else err  # close(-err) carries the errno
        if not self.connected:
            # backend refused/unreachable pre-handshake: the retry layer
            # owns the front fd from here (closes it if no retry starts).
            # This attempt's session count is released AFTER the retry
            # decision so a mid-retry drain_wait never sees a false zero.
            self.lb._backend_connect_failed(
                self.loop, self.front_fd, self.target, self.head,
                self.front, self.t_acc, self.src_ip, self.tls_ctx,
                self.tried, errno_, hint=self.hint, tid=self.tid)
            self.lb._sessions_delta(-1)
            return
        if self.pooled and self._pid is None:
            # a warmed connection died between validation and pump
            # handover: counts as a connect failure (ejection streak) and
            # falls back to a fresh connect under the retry budget
            self.lb._pooled_handover_failed(
                self.loop, self.front_fd, self.target, self.head,
                self.front, self.t_acc, self.src_ip, self.tls_ctx,
                self.tried, errno_, hint=self.hint, tid=self.tid)
            self.lb._sessions_delta(-1)
            return
        self.lb._sessions_delta(-1)
        # the backend connected and then died before pump handover — a
        # different failure domain than a refused connect, and the event
        # must say so (it used to claim "backend connect failed" here)
        vtl.close(self.front_fd)
        events.record(
            "conn", f"{self.front} -> {self.target.ip}:{self.target.port} "
            "backend closed before handover", lb=self.lb.alias, err=errno_,
            phase="pre_handover_close")


class TcpLB:
    def __init__(self, alias: str, acceptor: EventLoopGroup,
                 worker: EventLoopGroup, bind_ip: str, bind_port: int,
                 backend: Upstream, protocol: str = "tcp",
                 security_group: Optional[SecurityGroup] = None,
                 in_buffer_size: int = 65536, timeout_ms: int = 900_000,
                 cert_keys: Optional[list] = None,
                 max_sessions: int = 0, pool_size: int = -1,
                 lanes: int = -1, overload: str = ""):
        if protocol not in ("tcp", "http-splice") \
                and processors.get(protocol) is None:
            raise ValueError(f"unsupported protocol {protocol}")
        self.holder = None
        self.cert_keys = cert_keys or []
        self.protocol = protocol
        if cert_keys:
            self.set_cert_keys(cert_keys)
        self.alias = alias
        self.acceptor = acceptor
        self.worker = worker
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.backend = backend
        self.security_group = security_group or SecurityGroup.allow_all()
        self.in_buffer_size = in_buffer_size
        self.timeout_ms = timeout_ms
        self.server_socks: list[ServerSock] = []
        self.started = False
        # failure containment: bounded connect retries under a per-LB
        # budget, accept shedding past max_sessions, graceful drain
        self.max_sessions = max_sessions if max_sessions > 0 else MAX_SESSIONS
        self.connect_retries = CONNECT_RETRIES
        self.connect_timeout_ms = CONNECT_TIMEOUT_MS
        self.draining = False
        # overload mode (docs/robustness.md): static = the PR-2 fixed
        # ceiling; adaptive attaches the AIMD controller
        # (components/overload.py) moving an effective ceiling on loop
        # stall + accept latency, shedding with RST in both planes
        from .overload import MODE, AdaptiveOverload
        mode = overload or MODE
        if mode not in ("static", "adaptive"):
            raise ValueError(f"overload mode {mode!r}: "
                             "expected 'static' or 'adaptive'")
        self.overload_mode = mode
        self._overguard: Optional[AdaptiveOverload] = (
            AdaptiveOverload(self) if mode == "adaptive" else None)
        # sessions mutate from every worker loop and the counter now
        # gates behavior (overload shed, drain completion): the +=/-=
        # must not lose updates to GIL interleaving
        self._sess_lock = threading.Lock()
        self._retry_budget = RetryBudget()
        self._retry_ctrs: dict[str, object] = {}
        self._overload_ctr = None
        self._shed_ctrs: dict[str, object] = {}
        # warm backend pool (accept fast lane): per-(worker loop, backend)
        # pre-connected idle sockets, lazily spawned on first use,
        # drained on backend DOWN edges (hc or passive ejection)
        self.pool_size = POOL_SIZE if pool_size < 0 else pool_size
        # C accept lanes (docs/perf.md): when eligible, N native lane
        # threads own every listener and run short connections without
        # touching Python; self.lanes is the AcceptLanes manager or None
        self.lanes_n = LANES if lanes < 0 else lanes
        self.lanes: Optional[AcceptLanes] = None
        self._pools: dict[tuple, ConnectionPool] = {}
        self._pool_lock = threading.Lock()
        self._pool_groups: set = set()   # groups with our health listener
        self._pool_ctrs: dict[str, object] = {}
        # stats (cmd/ResourceType accepted-conn-count / bytes-in / bytes-out)
        self.accepted = 0
        self.active_sessions = 0
        self.bytes_in = 0
        self.bytes_out = 0
        # id(loop) -> {pid: (total, ts, desc)}; loops kept by id so the
        # session listing can marshal stat reads onto the OWNING loop
        self._pump_watch: dict[int, dict] = {}
        self._watch_loops: dict[int, object] = {}
        self._sweep_armed: set[int] = set()
        self._sweep_timers: dict[int, object] = {}  # id(loop) -> TimerEvent

    # ------------------------------------------------------------ control

    def on_loop_death(self, group, lp) -> None:
        """LBAttach semantics (TcpLB.java:45-66): an acceptor loop died —
        forget its listener (the dying loop already closed the fd) and
        bind a replacement on a surviving loop so capacity recovers."""
        if group is not self.acceptor or not self.started or self.draining:
            return
        dead = [ss for ss in self.server_socks if ss.loop is lp]
        if not dead:
            return
        self.server_socks = [ss for ss in self.server_socks
                             if ss.loop is not lp]
        if not group.loops:
            return  # nowhere to re-home; stop() semantics apply
        try:
            nlp = group.next()

            def mk() -> None:
                if not self.started:  # raced a concurrent stop()
                    return
                self.server_socks.append(ServerSock(
                    nlp, self.bind_ip, self.bind_port,
                    lambda fd, ip, port, lp=nlp: self._on_accept(
                        lp, fd, ip, port),
                    reuseport=True))
            nlp.call_sync(mk)
            if not self.started:  # stop() raced the re-home: undo
                for ss in self.server_socks:
                    ss.loop.run_on_loop(ss.close)
                self.server_socks = []
        except OSError as e:
            _log.alert(f"tcp-lb {self.alias}: re-home bind failed: {e!r}")

    # subclasses that wrap the byte stream in their own handshake
    # (Socks5Server passes protocol="tcp" but speaks RFC 1928 first)
    # MUST NOT let the C lanes raw-splice their clients
    lanes_capable = True

    def _lanes_eligible(self) -> bool:
        return (self.lanes_capable and self.lanes_n > 0
                and self.protocol == "tcp"
                and self.holder is None and vtl.lanes_supported()
                and bool(self.worker.loops))

    def start(self) -> None:
        if self.started:
            return
        self.started = True
        self.acceptor.attach(self)
        # C accept lanes: when eligible they own ALL the listeners (the
        # whole point is the accept edge never entering Python); punts
        # reach the classic path through the lane threads, so no python
        # listener is needed. Bind failure falls back to python accepts.
        if self._lanes_eligible():
            try:
                lanes = AcceptLanes(self, self.lanes_n)
                lanes.start()  # resolves bind_port when 0
                self.lanes = lanes
                if self._overguard is not None:
                    self._overguard.start()  # also flips C RST shed on
                return
            except OSError as e:
                _log.warn(f"tcp-lb {self.alias}: accept lanes failed "
                          f"({e}); falling back to python accepts")
        loops = self.acceptor.loops
        # bind loops one at a time so an ephemeral port (bind_port=0) is
        # resolved once and the remaining loops share it via REUSEPORT
        try:
            for lp in loops:
                def mk(lp=lp) -> None:
                    ss = ServerSock(
                        lp, self.bind_ip, self.bind_port,
                        lambda fd, ip, port, lp=lp: self._on_accept(lp, fd, ip, port),
                        reuseport=len(loops) > 1)
                    self.server_socks.append(ss)
                    if self.bind_port == 0:
                        self.bind_port = ss.port
                lp.call_sync(mk)
        except OSError as e:
            self.stop()
            self.started = False
            raise OSError(
                f"tcp-lb {self.alias}: bind failed on "
                f"{self.bind_ip}:{self.bind_port}: {e}") from e
        if self._overguard is not None:
            self._overguard.start()

    def stop(self) -> None:
        if not self.started:
            return
        self.started = False
        if self._overguard is not None:
            self._overguard.stop()
        self.acceptor.detach(self)
        if self.lanes is not None:
            self.lanes.shutdown()
            self.lanes = None
        for ss in self.server_socks:
            ss.loop.run_on_loop(ss.close)
        self.server_socks = []
        self._drain_pools()
        with self._pool_lock:
            groups, self._pool_groups = self._pool_groups, set()
        for g in groups:
            g.off_health_change(self._on_pool_backend_health)

    def begin_drain(self) -> None:
        """Graceful drain: close the listeners so no new connections
        arrive (upstream LBs see RSTs / healthz says draining and steer
        away) while live pumps run to completion. Raced-in accepts are
        shed in _on_accept. Idempotent; stop() still tears down fully."""
        if self.draining:
            return
        self.draining = True
        events.record("drain",
                      f"lb {self.alias} draining: listeners closing, "
                      f"{self.active_sessions} sessions in flight",
                      lb=self.alias, sessions=self.active_sessions)
        if self.started:
            if self.lanes is not None:
                # lanes stop accepting; live lane pumps run to completion
                self.lanes.close_listeners()
            for ss in self.server_socks:
                ss.loop.run_on_loop(ss.close)
            self.server_socks = []
        # warm sockets are not in-flight work: release them immediately
        # (the drain contract only protects established client sessions)
        self._drain_pools()

    # ------------------------------------------------- failure containment

    def _sessions_delta(self, d: int) -> None:
        with self._sess_lock:
            self.active_sessions += d
        self._push_lane_limit()

    def effective_max_sessions(self) -> int:
        """The live admission ceiling: max_sessions in static mode, the
        adaptive controller's current ceiling otherwise."""
        g = self._overguard
        return g.ceiling if g is not None else self.max_sessions

    def _push_lane_limit(self) -> None:
        """Forward the remaining session budget to the C lanes: the
        ceiling (static OR the adaptive controller's moving one) is
        SHARED across both admission planes — the C side admits only
        the remainder, so python-held sessions (punts) can never stack
        a second ceiling on top of the lane ones."""
        lanes = self.lanes
        if lanes is not None:
            lanes.set_limit(max(0, self.effective_max_sessions()
                                - self.active_sessions))

    def _retries_total(self, result: str):
        c = self._retry_ctrs.get(result)
        if c is None:
            from ..utils.metrics import GlobalInspection
            c = self._retry_ctrs[result] = GlobalInspection.get().get_counter(
                "vproxy_lb_retries_total", lb=self.alias, result=result)
        return c

    def _overload_total(self):
        if self._overload_ctr is None:
            from ..utils.metrics import GlobalInspection
            self._overload_ctr = GlobalInspection.get().get_counter(
                "vproxy_lb_overload_total", lb=self.alias)
        return self._overload_ctr

    def _shed_total(self, reason: str):
        """vproxy_lb_shed_total{lb,reason} — reason ∈ {static, adaptive,
        halfopen, policed}: what WAS silent (which guard refused, and
        whether the slowloris deadline fired) is now countable per
        cause."""
        c = self._shed_ctrs.get(reason)
        if c is None:
            from ..utils.metrics import GlobalInspection
            c = self._shed_ctrs[reason] = GlobalInspection.get().get_counter(
                "vproxy_lb_shed_total", lb=self.alias, reason=reason)
        return c

    def _policed_shed(self, n: int = 1) -> None:
        """Policed refusals (python mirror verdicts + lane-0's C shed
        fold). The per-action attribution lives in
        vproxy_lb_policed_total (the engine accounts it); HERE the
        legacy families move too — the PR-9 rule: a policed shed is
        still a shed, and the pre-r19 dashboards alerting on
        vproxy_lb_shed_total / vproxy_lb_overload_total must see it."""
        self._shed_total("policed").incr(n)
        self._overload_total().incr(n)

    def _observe_accept(self, seconds: float) -> None:
        g = self._overguard
        if g is not None:
            g.observe_accept(seconds)

    def _handshake_ms(self) -> int:
        """Pre-handover phase deadline: the module-level HANDSHAKE_MS
        (read per call so tests/ops can retune), never beyond the idle
        timeout; 0 disables (falls back to timeout_ms)."""
        hs = HANDSHAKE_MS
        return min(self.timeout_ms, hs) if hs > 0 else self.timeout_ms

    def _halfopen_count(self, desc: str) -> None:
        """One half-open release: the shed accounting shared by every
        pre-handover deadline path (TLS hello peek, http head parse) —
        one site, so the metric semantics cannot fork between them."""
        self._overload_total().incr()
        self._shed_total("halfopen").incr()
        events.record("halfopen_shed", desc, lb=self.alias)

    def _halfopen_kill(self, conn) -> None:
        """A pre-handover phase blew the handshake deadline: RST the
        client (no TIME_WAIT for flood sheds) and count it."""
        vtl.set_linger0(conn.fd)
        # count BEFORE close: the RST is the client-visible edge, so
        # the shed must already be on the counters when it lands
        self._halfopen_count(f"{conn.remote[0]}:{conn.remote[1]} shed: "
                             "handshake deadline")
        conn.close(errno.ETIMEDOUT)

    # ------------------------------------------------- warm backend pool

    def _pool_total(self, result: str):
        c = self._pool_ctrs.get(result)
        if c is None:
            from ..utils.metrics import GlobalInspection
            c = self._pool_ctrs[result] = GlobalInspection.get().get_counter(
                "vproxy_lb_pool_total", lb=self.alias, result=result)
        return c

    def set_pool_size(self, n: int) -> None:
        """Hot-set the per-(loop, backend) warm-pool capacity (0 = off).
        Existing pools are drained and lazily respawn at the new size on
        the next accept that wants one."""
        self.pool_size = max(0, n)
        self._drain_pools()

    def _drain_pools(self, svr=None) -> None:
        """Close (and forget) pools — all of them, or one backend's
        (DOWN edge / pooled-handover failure: its parked sockets are
        presumed dead and must not be handed to more clients)."""
        with self._pool_lock:
            if svr is None:
                doomed = list(self._pools.values())
                self._pools = {}
            else:
                doomed = [p for k, p in self._pools.items() if k[1] is svr]
                self._pools = {k: p for k, p in self._pools.items()
                               if k[1] is not svr}
        for p in doomed:
            p.close()

    def _on_pool_backend_health(self, svr, up: bool) -> None:
        # ejection and hc-down take the same edge (ServerGroup._notify):
        # either way the backend's warm sockets are no longer trustworthy
        if not up:
            self._drain_pools(svr)

    def _pool_for(self, loop, target: Connector) -> Optional[ConnectionPool]:
        if self.pool_size <= 0 or self.draining or not self.started:
            return None
        key = (id(loop), target.svr)
        pool = self._pools.get(key)
        if pool is None:
            if not target.svr.healthy:
                # a selection that raced the DOWN edge must not respawn
                # a pool the edge just drained — no new DOWN will arrive
                # to drain it while the backend stays down
                return None
            with self._pool_lock:
                # re-check EVERYTHING under the lock: an accept racing
                # stop()/begin_drain()/hot-set-0/the DOWN edge must not
                # recreate a pool (and re-register the health listener)
                # after the drain
                if (self.pool_size <= 0 or self.draining
                        or not self.started or not target.svr.healthy):
                    return None
                pool = self._pools.get(key)
                if pool is None:
                    # keepalive tick doubles as the idle-expiry sweep, so
                    # it must run a few times per expiry window
                    ka_ms = max(250, min(int(POOL_IDLE_S * 250), 15000))
                    pool = self._pools[key] = ConnectionPool(
                        loop, _LBPoolHandler(target,
                                             self.connect_timeout_ms),
                        self.pool_size, keepalive_ms=ka_ms,
                        park_reads=True,
                        idle_expire_ms=int(POOL_IDLE_S * 1000))
                if target.group not in self._pool_groups:
                    self._pool_groups.add(target.group)
                    target.group.on_health_change(
                        self._on_pool_backend_health)
        return pool

    def _pool_take(self, loop, target: Connector) -> Optional[Connection]:
        """One validated warm connection, or None (pool off/empty). Must
        run on the owning loop thread (it does: every _splice caller is
        loop-confined)."""
        pool = self._pool_for(loop, target)
        if pool is None:
            return None
        while True:
            conn = pool.get()
            if conn is None:
                self._pool_total("miss").incr()
                return None
            if self._pool_validate(conn):
                self._pool_total("hit").incr()
                return conn
            self._pool_total("stale").incr()
            conn.close()

    @staticmethod
    def _pool_validate(conn: Connection) -> bool:
        """Parked sockets don't watch for EOF (reads are off so early
        backend bytes survive for the pump) — so check liveness HERE,
        with a MSG_PEEK: b'' means the peer already closed. Queued bytes
        (server-first banner) are fine; they stay queued. Sockets still
        inside the POOL_VALIDATE_S warm window skip the peek syscall."""
        if conn.closed or conn.detached or conn.eof_seen:
            return False
        if (time.monotonic() - getattr(conn, "_pooled_at", 0.0)
                < POOL_VALIDATE_S):
            return True
        if vtl.PROVIDER != "native":
            # pure-python provider has no MSG_PEEK surface (recv_peek is
            # native-only, like the SNI sniffer's gate): rely on the
            # closed/eof checks above + the handover-failure fallback
            return True
        try:
            data = vtl.recv_peek(conn.fd, 1)
        except OSError:
            return False
        return data != b""  # None (nothing queued, alive) or bytes: ok

    def _take_retry_slot(self, tried: set, what: str, pick):
        """THE retry gate, shared by the splice/TLS path, Socks5 and the
        L7 engine: attempt cap -> budget -> re-selection via `pick()`
        (a callable returning Connector | None — callers bind their own
        selection semantics, e.g. hint-seek vs WRR). Returns the next
        Connector or None; every outcome lands in
        vproxy_lb_retries_total{result=} and the flight recorder.
        Retries stay allowed while draining: an accepted connection IS
        in-flight work the drain contract protects."""
        if not self.started:
            return None
        if len(tried) > self.connect_retries:
            self._retries_total("exhausted").incr()
            events.record("retry",
                          f"{what}: retries exhausted after "
                          f"{len(tried)} attempts",
                          lb=self.alias, result="exhausted")
            return None
        target = pick()
        if target is None:
            # selection BEFORE the budget take: a no-alternative outcome
            # generates zero connect load and must not burn the budget
            # other sessions need for real retries
            self._retries_total("no_backend").incr()
            events.record("retry", f"{what}: no alternative backend",
                          lb=self.alias, result="no_backend")
            return None
        if not self._retry_budget.try_take():
            self._retries_total("budget_exhausted").incr()
            events.record("retry", f"{what}: retry budget exhausted",
                          lb=self.alias, result="budget_exhausted")
            return None
        events.record("retry",
                      f"{what} retry {len(tried)} -> "
                      f"{target.ip}:{target.port}",
                      lb=self.alias, attempt=len(tried))
        return target

    def _backend_connect_failed(self, loop, front_fd: int, target: Connector,
                                head: bytes, front: str,
                                t_acc: Optional[float], src_ip: bytes,
                                tls_ctx: int, tried: set, err: int,
                                hint=None, tid: int = 0) -> None:
        """A pre-handover backend connect failed (sync raise or async
        finish_connect error). Owns front_fd: either a retry attempt
        takes it over or it is closed here. Session counters for the
        failed attempt are already released by the caller. The retry
        re-runs the ORIGINAL selection semantics (hint group first, then
        the same WRR fallback the initial classify uses when the hint
        group is empty) minus the tried set — a retry is never MORE
        willing to leave the hint group than the first pick was."""
        svr = target.svr
        tried.add(svr)
        if tid:
            now = time.monotonic()
            _tspan(tid, "connect_failed", now, now,
                   backend=f"{target.ip}:{target.port}", err=err,
                   attempt=len(tried))
        events.record(
            "conn", f"{front} -> {target.ip}:{target.port} connect failed",
            lb=self.alias, err=err, phase="connect_failed",
            attempt=len(tried), trace_id=tid)
        target.group.report_failure(svr, err)
        nxt = self._take_retry_slot(
            tried, front,
            lambda: self.backend.next_host(src_ip, hint, exclude=tried))
        if nxt is None:
            vtl.close(front_fd)
            return
        self._splice(loop, front_fd, nxt, head, front, t_acc,
                     src_ip=src_ip, tls_ctx=tls_ctx, tried=tried, hint=hint,
                     tid=tid)

    def _pooled_handover_failed(self, loop, front_fd: int, target: Connector,
                                head: bytes, front: str,
                                t_acc: Optional[float], src_ip: bytes,
                                tls_ctx: int, tried: set, err: int,
                                hint=None, tid: int = 0) -> None:
        """A warmed pool connection died at handover (post-validation).
        One stale socket says little about the backend beyond this
        session — but from the session's point of view it IS a failed
        connect: report it (feeding the passive-ejection streak), drop
        this backend's pools (its siblings were parked the same way and
        are presumed equally stale), and retry with a FRESH connect
        under the existing retry budget — same backend first while it is
        still healthy (a restarted backend accepts new connects fine;
        excluding it would strand single-backend groups), the normal
        re-selection otherwise. The backend is NOT added to `tried`
        here: if the fresh connect also fails, the ordinary
        connect-failed path excludes it then."""
        svr = target.svr
        events.record(
            "conn", f"{front} -> {target.ip}:{target.port} pooled "
            "handover failed", lb=self.alias, err=err,
            phase="pooled_handover_failed")
        target.group.report_failure(svr, err)
        self._drain_pools(svr)

        def pick():
            if svr.healthy and not svr.logic_delete:
                return Connector(svr, target.group)
            return self.backend.next_host(src_ip, hint,
                                          exclude=set(tried) | {svr})

        nxt = self._take_retry_slot(tried, front, pick)
        if nxt is None:
            vtl.close(front_fd)
            return
        self._splice(loop, front_fd, nxt, head, front, t_acc,
                     src_ip=src_ip, tls_ctx=tls_ctx, tried=tried,
                     hint=hint, fresh=True, tid=tid)

    # --------------------------------------------------------- data plane

    def _on_accept(self, loop, cfd: int, ip: str, port: int,
                   tid: int = 0, hh_counted: bool = False) -> None:
        """tid: a nonzero trace id CONTINUES a trace begun in the C
        accept plane (a sampled lane punt); 0 lets this path make its
        own 1-in-N sampling decision (utils/trace). hh_counted: the C
        lane plane already tallied this accept's analytics dims (a
        connect-fail punt whose backend vanished falls through here —
        re-counting would double its client/route)."""
        if self.draining:
            # listener close raced an in-flight accept: shed it; the
            # drain contract only protects established sessions
            events.record("drain_shed", f"{ip}:{port} shed: draining",
                          lb=self.alias)
            vtl.close(cfd)
            return
        # admission policing (vproxy_tpu/policing): the python mirror
        # of the C lane probe — same table, same integer bucket law, so
        # a punted (or lanes-off) accept reaches the verdict the lane
        # probe would have. One branch when the knob is off.
        if policing.ON:
            policing.maybe_tick()
            verdict = policing.check("clients", ip, lb=self.alias,
                                     trace_id=tid)
            if verdict == "shed" or (
                    verdict == "throttle"
                    and self.active_sessions + self.lane_active()
                    >= self.effective_max_sessions()):
                # a throttle verdict defers to the ceiling (sheds only
                # when the LB is already at its limit); shed refuses
                # outright. Account BEFORE the RST lands — the engine
                # attributed the verdict, this folds the legacy
                # families — and sample the rejection as a police span.
                self._policed_shed(1)
                if tid == 0:
                    tid = trace.maybe_sample()
                if tid:
                    now = time.monotonic()
                    _tspan(tid, "police", now, now, action=verdict)
                vtl.close_rst(cfd)
                return
        eff = self.effective_max_sessions()
        if (self.active_sessions + self.lane_active() >= eff
                and not policing.overload_spare(ip, lb=self.alias)):
            # overload guard: close-on-accept beats queueing unboundedly.
            # The policing spare above implements the weighted-fair shed
            # order: an in-quota classed tenant draws on its
            # deficit-round-robin budget (refilled per policing tick in
            # proportion to its declared rate, capped at one burst — so
            # the elasticity past the ceiling is bounded) while
            # over-quota and unclassed arrivals shed here first.
            # Lane-owned sessions count against the same budget — the C
            # side bounds itself at the shared ceiling and punts (or
            # RST-sheds, adaptive mode) past it, and this check stops
            # those punts from doubling the ceiling. Adaptive sheds RST
            # (a crowd big enough to move the ceiling would park one
            # TIME_WAIT per FIN-shed); static keeps the clean close.
            # account BEFORE closing: the close is the client-visible
            # edge, so counters/events must already be readable when a
            # shed client observes it (the probe-then-assert race)
            self._overload_total().incr()
            self._shed_total(
                "adaptive" if self._overguard is not None else
                "static").incr()
            events.record(
                "overload", f"{ip}:{port} shed: {self.active_sessions} "
                f"sessions at ceiling {eff} (max {self.max_sessions})",
                lb=self.alias, mode=self.overload_mode)
            if self._overguard is not None:
                vtl.close_rst(cfd)
            else:
                vtl.close(cfd)
            return
        self.accepted += 1
        self._retry_budget.on_accept()
        # workload capture (utils/workload): the accept-plane arrival
        # process — one branch per accept when VPROXY_TPU_WORKLOAD=0
        workload.note_arrival("accept")
        # analytics (utils/sketch): who is hot right now — one branch
        # per site when VPROXY_TPU_ANALYTICS=0
        if not hh_counted:
            sketch.update("clients", ip)
            sketch.update("routes", self.alias)
        t_acc = time.monotonic()
        if tid == 0:
            tid = trace.maybe_sample()  # one branch when the knob is off

        # ACL gate (SecurityGroup.allow — TcpLB.java:168-171); the lookup
        # rides the ClassifyService micro-batch queue, coalescing with
        # other in-flight accepts across connections/loops
        def on_verdict(ok: bool) -> None:
            now = time.monotonic()
            accept_stage_observe("acl", now - t_acc)
            _tspan(tid, "acl", t_acc, now, allow=ok)
            if not ok or not self.started:
                if not ok:
                    events.record("conn_denied",
                                  f"{ip}:{port} denied by ACL",
                                  lb=self.alias, trace_id=tid)
                vtl.close(cfd)
                return
            if self.worker is not self.acceptor:
                wl = self.worker.next()
                if not wl.run_on_loop(
                        lambda: self._serve(wl, cfd, ip, port, t_acc,
                                            tid=tid)):
                    vtl.close(cfd)  # worker loop died; don't leak the fd
            else:
                self._serve(loop, cfd, ip, port, t_acc, tid=tid)

        try:
            # the submit rides the trace context so the classify plane
            # (queue wait / dispatch / launch markers) attaches its
            # spans to THIS request's trace
            with trace.bind(tid):
                self.security_group.allow_async(Proto.TCP, parse_ip(ip),
                                                self.bind_port, on_verdict,
                                                loop)
        except Exception:
            vtl.close(cfd)  # classify queue unavailable: refuse, not leak
            raise

    def _serve(self, loop, cfd: int, ip: str, port: int,
               t_acc: Optional[float] = None, tid: int = 0) -> None:
        """Owns cfd: every branch either hands it off or closes it exactly
        once — including when `loop` died while the accept's ACL verdict
        was in flight (the verdict then runs on the dispatcher thread, or
        via the closed loop's promised-task drain)."""
        if self.holder is not None:
            self._serve_tls(loop, cfd, ip, port, t_acc)
        elif self.protocol == "tcp":
            t0 = time.monotonic()
            src_ip = parse_ip(ip)
            with trace.bind(tid):  # classify spans attach to the trace
                conn = self.backend.next(src_ip)
            now = time.monotonic()
            accept_stage_observe("backend_pick", now - t0)
            _tspan(tid, "backend_pick", t0, now)
            if conn is None:
                vtl.close(cfd)
                return
            self._splice(loop, cfd, conn, b"", front=f"{ip}:{port}",
                         t_acc=t_acc, src_ip=src_ip, tid=tid)
        elif self.protocol == "http-splice":
            self._http_classify(loop, cfd, ip, port, t_acc, tid=tid)
        else:
            try:
                L7Engine(self, loop, cfd, ip, port,
                         processors.get(self.protocol))
            except Exception:
                pass  # L7Engine closes cfd on its failure paths

    def _serve_tls(self, loop, cfd: int, ip: str, port: int,
                   t_acc: Optional[float] = None) -> None:
        """TLS termination. protocol=tcp on the native provider takes
        the C-side path: MSG_PEEK the ClientHello for SNI (cert choice +
        classify hint), then hand the untouched socket to the OpenSSL
        splice pump — handshake and record layer run in C, TLS bytes
        never enter Python (the reference's engine-speed SSL rings,
        SSLWrapRingBuffer.java:23/SSLUnwrapRingBuffer.java:28). L7
        protocols (and the pure-python provider, or mirror taps wanting
        plaintext) keep the MemoryBIO path through the L7 engine."""
        import os as _os
        if (self.protocol == "tcp" and vtl.PROVIDER == "native"
                and _os.environ.get("VPROXY_TPU_NATIVE_TLS", "1") != "0"
                and vtl.tls_available() and not self._mirror_wants_tls()):
            self._serve_tls_native(loop, cfd, ip, port, t_acc)
            return
        from ..net.tls import TlsSocket
        from ..processors.base import TcpRelaySession
        from ..rules.ir import Hint
        try:
            conn = Connection(loop, cfd, (ip, port))
        except OSError:
            vtl.close(cfd)
            return
        tls = TlsSocket(conn, self.holder.front_context)
        if self.protocol == "tcp":
            def factory(eng, addr):
                return TcpRelaySession(
                    eng, addr,
                    hint_fn=lambda: Hint.of_host(tls.sni) if tls.sni else None)
        else:
            name = "http1" if self.protocol == "http-splice" else self.protocol
            factory = processors.get(name)
        L7Engine(self, loop, cfd, ip, port, factory, front=tls)

    def _mirror_wants_tls(self) -> bool:
        """Plaintext mirror taps need the python TLS path (the native
        pump's plaintext never surfaces to the mirror)."""
        from ..utils.mirror import Mirror
        m = Mirror.get()
        return m.hot and m.wants("ssl")  # net/tls.py's mirror origin

    def _serve_tls_native(self, loop, cfd: int, ip: str, port: int,
                          t_acc: Optional[float] = None) -> None:
        """Peek the ClientHello (bytes stay queued), choose the cert and
        classify by SNI, connect the backend, then run the C-side
        TLS-terminating splice pump on the untouched client socket."""
        from ..net.sniff import MAX_HELLO, parse_client_hello_sni
        from ..rules.ir import Hint
        lb = self
        # the timeout abort gets the deadline list so it clears
        # deadline[0]: the parked-hello rearm timer guards on that, and
        # without it a post-timeout rearm could re-enable reads on a
        # RECYCLED fd number owned by an unrelated connection
        deadline: list = [None]
        # the hello peek is a pre-handover phase: bounded by the
        # handshake deadline (slowloris defense), not the idle timeout;
        # with the deadline disabled (HANDSHAKE_MS=0) expiry keeps the
        # pre-r10 plain-close semantics, not the RST + halfopen count
        deadline[0] = loop.delay(
            self._handshake_ms(),
            lambda: self._peek_abort(loop, cfd, deadline,
                                     halfopen=HANDSHAKE_MS > 0))

        def on_ev(fd: int, ev: int) -> None:
            if ev & vtl.EV_ERROR:
                self._peek_abort(loop, cfd, deadline)
                return
            try:
                data = vtl.recv_peek(cfd, MAX_HELLO)
            except OSError:
                self._peek_abort(loop, cfd, deadline)
                return
            if data is None:
                return  # spurious wakeup
            if not data:
                self._peek_abort(loop, cfd, deadline)  # EOF before hello
                return
            sni, complete = parse_client_hello_sni(data)
            if not complete:
                # MSG_PEEK leaves the fd readable: a level-triggered
                # re-arm here would busy-spin until the hello completes.
                # Park interest and re-check shortly (deadline still
                # bounds the total wait).
                try:
                    loop.modify(cfd, 0)

                    def rearm() -> None:
                        if deadline[0] is None:  # aborted meanwhile
                            return
                        try:
                            if loop.registered(cfd):
                                loop.modify(cfd, vtl.EV_READ)
                        except Exception:
                            pass
                    loop.delay(20, rearm)
                except Exception:
                    self._peek_abort(loop, cfd, deadline)
                return  # wait for more ClientHello bytes
            if deadline[0] is not None:
                deadline[0].cancel()
                deadline[0] = None
            loop.remove(cfd)
            ck = self.holder.choose_cert_key(sni)
            ctx = ck.native_ctx()
            if ctx is None:
                # libssl vanished / cert unreadable: python TLS fallback
                self._serve_tls_python_fallback(loop, cfd, ip, port)
                return
            hint = Hint.of_host(sni) if sni else None

            src_ip = parse_ip(ip)

            def on_back(back) -> None:
                if back is None:
                    vtl.close(cfd)
                    return
                self._splice_tls(loop, cfd, back, ctx,
                                 front=f"{ip}:{port}", t_acc=t_acc,
                                 src_ip=src_ip, hint=hint)

            lb.backend.next_async(src_ip, hint, on_back, loop=loop)

        try:
            loop.add(cfd, vtl.EV_READ, on_ev)
        except OSError:
            if deadline[0] is not None:  # the timer must not fire on a
                deadline[0].cancel()     # closed (reusable) fd number
                deadline[0] = None
            vtl.close(cfd)

    def _peek_abort(self, loop, cfd: int, deadline=None,
                    halfopen: bool = False) -> None:
        if deadline and deadline[0] is not None:
            deadline[0].cancel()
            deadline[0] = None
        try:
            if loop.registered(cfd):
                loop.remove(cfd)
        except Exception:
            pass
        if halfopen:
            # the handshake deadline fired with the hello still
            # incomplete: a slowloris/half-open client — RST (no
            # TIME_WAIT for flood sheds) and count the release
            self._halfopen_count("tls hello never completed: "
                                 "handshake deadline")
            vtl.close_rst(cfd)
            return
        vtl.close(cfd)

    def _serve_tls_python_fallback(self, loop, cfd: int, ip: str,
                                   port: int) -> None:
        from ..net.tls import TlsSocket
        from ..processors.base import TcpRelaySession
        from ..rules.ir import Hint
        try:
            conn = Connection(loop, cfd, (ip, port))
        except OSError:
            vtl.close(cfd)
            return
        tls = TlsSocket(conn, self.holder.front_context)

        def factory(eng, addr):
            return TcpRelaySession(
                eng, addr,
                hint_fn=lambda: Hint.of_host(tls.sni) if tls.sni else None)

        L7Engine(self, loop, cfd, ip, port, factory, front=tls)

    def _splice_tls(self, loop, front_fd: int, target: Connector,
                    ctx: int, front: str = "?",
                    t_acc: Optional[float] = None,
                    src_ip: bytes = b"", hint=None) -> None:
        """Like _splice, but the handover runs the TLS-terminating pump
        (client side TLS in C, backend plaintext)."""
        self._splice(loop, front_fd, target, b"", f"tls {front}",
                     t_acc=t_acc, src_ip=src_ip, tls_ctx=ctx, hint=hint)

    # ------------------------------------------------------ idle timeout

    # ------------------------------------------------- hot-settable knobs

    def set_cert_keys(self, cert_keys: list) -> None:
        """Swap the served certs without restart ("modifiable when
        running", TcpLB.java:294-320): the holder is built FIRST so a
        bad cert file leaves the old holder and cert list untouched;
        new accepts use the new holder, in-flight sessions keep theirs."""
        from .certkey import CertKeyHolder
        proc = processors.get(self.protocol)
        alpn = list(proc.alpn) if proc is not None and proc.alpn else None
        holder = CertKeyHolder(cert_keys, alpn=alpn)  # may raise: no change
        self.cert_keys = cert_keys
        self.holder = holder
        if getattr(self, "lanes", None) is not None:  # ctor calls this
            # lanes route plaintext in C — they cannot terminate TLS.
            # A hot cert install on a running lanes LB tears the lanes
            # down and rebinds python listeners on the same port.
            _log.warn(f"tcp-lb {self.alias}: TLS certs installed; "
                      "disabling C accept lanes")
            lanes, self.lanes = self.lanes, None
            lanes.shutdown()
            if self.started:
                for lp in self.acceptor.loops:
                    def mk(lp=lp) -> None:
                        self.server_socks.append(ServerSock(
                            lp, self.bind_ip, self.bind_port,
                            lambda fd, ip, port, lp=lp: self._on_accept(
                                lp, fd, ip, port),
                            reuseport=len(self.acceptor.loops) > 1))
                    lp.call_sync(mk)

    def set_security_group(self, sg: SecurityGroup) -> None:
        """Hot-swap the ACL group; a lanes LB moves its mutation hook to
        the new group and recompiles (the old entry is gen-gated out)."""
        old = self.security_group
        self.security_group = sg
        if self.lanes is not None:
            old.remove_listener(self.lanes._on_mutation)
            sg.add_listener(self.lanes._on_mutation)
            self.lanes._on_mutation()

    def lane_active(self) -> int:
        """Live lane-owned sessions (drain accounting: these are real
        in-flight client sessions invisible to active_sessions)."""
        return self.lanes.active() if self.lanes is not None else 0

    def maglev_stat(self) -> dict:
        """`list-detail tcp-lb` / HTTP detail `maglev` object: every
        consistent-hash table this LB routes through — the C lane
        route's (when the pick mode is maglev) and each source-method
        group's python table — with size, generation and the last
        resize's remap fraction (docs/perf.md)."""
        d: dict = {"lanes": None, "groups": []}
        lanes = self.lanes
        if lanes is not None:
            st = lanes.stat()
            if st.get("on") and st.get("pick") == "maglev":
                d["lanes"] = dict(st.get("maglev") or {}, gen=st["gen"])
        for gh in list(self.backend.handles):
            if gh.group.method == "source":
                info = gh.group.maglev_info()
                if info.get("on"):
                    d["groups"].append(dict(info, group=gh.group.alias))
        return d

    def set_max_sessions(self, n: int) -> None:
        """Hot-set the overload ceiling for BOTH admission paths: the
        python accept check and the C lanes' active bound. In adaptive
        mode this moves the controller's UPPER bound; the effective
        ceiling re-clamps on its next tick."""
        self.max_sessions = n if n > 0 else MAX_SESSIONS
        g = self._overguard
        if g is not None:
            g.ceiling = min(max(g.ceiling, g.floor), self.max_sessions)
        self._push_lane_limit()

    def set_overload_mode(self, mode: str) -> None:
        """Hot-flip static <-> adaptive (`update tcp-lb ... overload`).
        Leaving adaptive restores the full max_sessions bound (and the
        lanes' punt-style shed); entering it starts the controller at
        the current ceiling."""
        if mode not in ("static", "adaptive"):
            raise ValueError(f"overload mode {mode!r}: "
                             "expected 'static' or 'adaptive'")
        if mode == self.overload_mode:
            return
        from .overload import AdaptiveOverload
        if mode == "adaptive":
            self._overguard = AdaptiveOverload(self)
            if self.started:
                self._overguard.start()
        else:
            g, self._overguard = self._overguard, None
            if g is not None:
                g.stop()  # also flips the C lanes' RST shed off
        self.overload_mode = mode
        self._push_lane_limit()
        events.record("overload_mode",
                      f"lb {self.alias} overload mode -> {mode}",
                      lb=self.alias, mode=mode)

    def overload_stat(self) -> dict:
        """list-detail / HTTP detail payload: the live admission state
        (mode, bounds, controller EWMAs when adaptive)."""
        g = self._overguard
        if g is None:
            return {"mode": "static", "maxSessions": self.max_sessions,
                    "ceiling": self.max_sessions}
        return g.stat()

    def set_timeout(self, timeout_ms: int) -> None:
        """Hot-set the idle timeout AND re-arm the per-loop idle sweeps:
        an armed sweep waits timeout/4, so lowering the timeout without
        re-arming would only bite after the OLD interval elapsed. Lane
        sweeps read the C-side value per pass — forwarded here."""
        lanes = self.lanes
        if lanes is not None:
            lanes.set_timeout(timeout_ms)
        self.timeout_ms = timeout_ms
        for lid, lp in list(self._watch_loops.items()):
            def rearm(lid=lid, lp=lp) -> None:
                t = self._sweep_timers.pop(lid, None)
                if t is not None:
                    t.cancel()
                self._sweep_armed.discard(lid)
                if self._pump_watch.get(lid):
                    self._arm_sweep(lp)
            lp.run_on_loop(rearm)

    def _watch_pump(self, loop, pid: int, desc: str = "") -> None:
        """Track spliced-session activity; kill sessions idle > timeout_ms
        (the reference's tcpTimeout, Config.java:20 — default 15 min).
        `desc` ("front -> back") feeds the session/connection listing
        resources (cmd/ResourceType sess/conn)."""
        st = self._pump_watch.setdefault(id(loop), {})
        self._watch_loops[id(loop)] = loop  # session listing needs the obj
        st[pid] = (0, loop.now, desc)
        if failpoint.hit("pump.abort", desc):
            # kill the just-registered pump on the owning loop; the DONE
            # callback runs the normal cleanup path
            loop.next_tick(lambda: loop.pump_close(pid))
        if len(st) == 1:
            self._arm_sweep(loop)

    def _unwatch_pump(self, loop, pid) -> None:
        self._pump_watch.get(id(loop), {}).pop(pid, None)

    def _arm_sweep(self, loop) -> None:
        def sweep() -> None:
            st = self._pump_watch.get(id(loop), {})
            if not st or not self.started:
                self._sweep_armed.discard(id(loop))
                self._sweep_timers.pop(id(loop), None)
                return
            for pid, (last_total, last_ts, desc) in list(st.items()):
                try:
                    a2b, b2a, _err = loop.pump_stat(pid)
                except OSError:
                    st.pop(pid, None)
                    continue
                total = a2b + b2a
                if total != last_total:
                    st[pid] = (total, loop.now, desc)
                elif (loop.now - last_ts) * 1000 >= self.timeout_ms:
                    st.pop(pid, None)
                    loop.pump_close(pid)
            if st:  # interval re-read so hot-set timeouts take effect
                self._sweep_timers[id(loop)] = loop.delay(
                    max(self.timeout_ms // 4, 1000), sweep)
            else:
                self._sweep_armed.discard(id(loop))
                self._sweep_timers.pop(id(loop), None)

        if id(loop) not in self._sweep_armed:
            self._sweep_armed.add(id(loop))
            self._sweep_timers[id(loop)] = loop.delay(
                max(self.timeout_ms // 4, 1000), sweep)

    def _http_classify(self, loop, cfd: int, ip: str, port: int,
                       t_acc: Optional[float] = None,
                       tid: int = 0) -> None:
        lb = self
        parser = HeadParser()
        try:
            front = Connection(loop, cfd, (ip, port))
        except OSError:
            vtl.close(cfd)
            return
        # a client that never completes its head is a half-open
        # (slowloris) session: dropped at the HANDSHAKE deadline — not
        # the minutes-long idle timeout — with an RST, and counted, so
        # a flood can neither pin parser state nor stack TIME_WAITs.
        # The deadline bounds the CLIENT's phase only: it is cancelled
        # the moment the head completes, so a slow classify/backend
        # connect (bounded by its own timeouts) can never get a
        # well-behaved client RST-killed as "halfopen"
        head_deadline: list = [None]

        def head_timeout() -> None:
            head_deadline[0] = None
            if not front.closed and not front.detached:
                if HANDSHAKE_MS > 0:
                    lb._halfopen_kill(front)
                else:  # deadline disabled: the pre-r10 idle-expiry close
                    front.close()
        head_deadline[0] = loop.delay(lb._handshake_ms(), head_timeout)

        class Front(Handler):
            def on_data(self, conn: Connection, data: bytes) -> None:
                parser.feed(data)
                if parser.error:
                    conn.close()
                    return
                if parser.done:
                    if head_deadline[0] is not None:
                        head_deadline[0].cancel()
                        head_deadline[0] = None
                    conn.pause_reading()
                    hint = parser.hint()
                    t_cls = time.monotonic()

                    # classify via the cross-connection micro-batch queue
                    def on_back(back) -> None:
                        now = time.monotonic()
                        _tspan(tid, "classify", t_cls, now)
                        if conn.closed or conn.detached:
                            return
                        if back is None:
                            conn.write(b"HTTP/1.1 503 Service Unavailable\r\n"
                                       b"content-length: 0\r\nconnection: close\r\n\r\n")
                            loop.delay(50, conn.close)
                            return
                        buffered = bytes(parser.buf)
                        ffd = conn.detach()
                        lb._splice(loop, ffd, back, buffered,
                                   front=f"{ip}:{port}", t_acc=t_acc,
                                   src_ip=parse_ip(ip), hint=hint,
                                   tid=tid)

                    with trace.bind(tid):  # classify-plane spans attach
                        lb.backend.next_async(parse_ip(ip), hint, on_back,
                                              loop=loop)

            def on_eof(self, conn: Connection) -> None:
                conn.close()

        front.set_handler(Front())

    def _splice(self, loop, front_fd: int, target: Connector,
                head: bytes, front: str = "?",
                t_acc: Optional[float] = None, src_ip: bytes = b"",
                tls_ctx: int = 0, tried: Optional[set] = None,
                hint=None, fresh: bool = False, tid: int = 0) -> None:
        """fresh=True bypasses the warm pool (the pooled-handover retry
        path: it just drained this backend's pools and must dial a real
        connect, not fish another parked socket)."""
        if tried is None:
            tried = set()
        svr = target.svr
        # analytics: backend attribution for every python-path handover
        # (plain, pooled, fast-lane; lane-served sessions tally in C).
        # The knob gate wraps the key build too — knob-off must not pay
        # a string format per handover
        if sketch.ON:
            sketch.update("backends", f"{target.ip}:{target.port}")
        if not fresh:
            conn = self._pool_take(loop, target)
            if conn is not None:
                self._adopt_pooled(loop, front_fd, target, conn, head,
                                   front, t_acc, src_ip, tls_ctx, tried,
                                   hint, tid=tid)
                return
        # C fast lane: plain splice sessions (no head bytes, no TLS)
        # ride vtl_pump_connect — ONE native call replaces the whole
        # connect/register/nodelay/handover chain (~8 crossings).
        # Armed failpoints force the classic path: the backend.connect.*
        # injection sites live in Connection.connect.
        if (not head and not tls_ctx and not failpoint.any_armed()
                and self._fast_splice(loop, front_fd, target, front,
                                      t_acc, src_ip, tried, hint,
                                      tid=tid)):
            return
        svr.conn_count += 1
        self._sessions_delta(1)
        try:
            # the timeout turns a SYN-blackholed backend into the same
            # on_closed(-ETIMEDOUT) -> retry path a refusal takes
            back = Connection.connect(loop, target.ip, target.port,
                                      timeout_ms=self.connect_timeout_ms)
        except OSError as e:
            svr.conn_count -= 1
            # retry first, release after: active_sessions must not dip
            # to 0 mid-retry (drain_wait reads it as "drained")
            self._backend_connect_failed(loop, front_fd, target, head,
                                         front, t_acc, src_ip, tls_ctx,
                                         tried, e.errno or 1, hint=hint,
                                         tid=tid)
            self._sessions_delta(-1)
            return
        back.set_handler(_SpliceBack(self, loop, front_fd, target, head,
                                     front, tls_ctx=tls_ctx, t_acc=t_acc,
                                     src_ip=src_ip, tried=tried, hint=hint,
                                     tid=tid))

    def _fast_splice(self, loop, front_fd: int, target: Connector,
                     front: str, t_acc: Optional[float], src_ip: bytes,
                     tried: set, hint, tid: int = 0) -> bool:
        """One-crossing backend connect + pump handover in the C loop
        (net/eventloop.pump_connect). The connect resolves natively; a
        refused/unreachable/timed-out backend comes back as a
        connect_failed DONE with the client fd intact, feeding the SAME
        retry/ejection machinery the python path uses. False = fast lane
        unavailable (py provider / old .so) — caller takes the classic
        path."""
        pc = getattr(loop, "pump_connect", None)
        if pc is None:
            return False
        lb = self
        svr = target.svr
        t_back = time.monotonic()
        desc = f"{front} -> {target.ip}:{target.port}"
        pid_box = [0]
        reported = [False]  # connect success noted (streak reset) once

        def _report_ok() -> None:
            # the classic path clears the ejection streak one RTT after
            # dialing (on_connected). The fast lane hears back at DONE
            # (short sessions) or at the connect-deadline check the loop
            # runs for still-open sessions (long streams) — a bounded
            # delay of at most connect_timeout_ms, never hours.
            if not reported[0]:
                reported[0] = True
                target.group.report_success(svr)
                if tried:  # a retry attempt landed through the fast lane
                    lb._retries_total("success").incr()

        def done(a2b: int, b2a: int, err: int, flags: int = 0,
                 connect_us: int = 0) -> None:
            lb._unwatch_pump(loop, pid_box[0])
            if flags & 1:  # backend never came up: retry machinery
                # front_fd is still open (pump_fail_connect keeps it):
                # same ownership contract as a python connect failure
                svr.conn_count -= 1
                lb._backend_connect_failed(
                    loop, front_fd, target, b"", front, t_acc, src_ip,
                    0, tried, err, hint=hint, tid=tid)
                lb._sessions_delta(-1)
                return
            if flags & 2:
                # torn down while STILL mid-connect (client RST'd the
                # front fd first): says nothing about the backend —
                # neither success (a report_success here would keep
                # resetting a blackholed backend's ejection streak on
                # every impatient client) nor failure. Plain teardown.
                svr.conn_count -= 1
                lb._sessions_delta(-1)
                events.record("conn", f"{desc} client abort mid-connect",
                              lb=lb.alias, err=err,
                              phase="client_abort_connecting")
                return
            _report_ok()
            # span semantics match the classic path (_handover observes
            # once the backend is up): registration cost + the REAL
            # connect duration the C side measured — observed late, at
            # DONE, but histograms only care about the value
            accept_stage_observe("handover",
                                 reg_s + connect_us / 1e6)
            if t_acc is not None:
                accept_stage_observe(
                    "total", (t_reg - t_acc) + connect_us / 1e6)
                lb._observe_accept((t_reg - t_acc) + connect_us / 1e6)
            if tid:
                # the fast lane hears everything back at DONE: spans
                # reconstructed from the C-measured connect duration +
                # the registration stamp — values exact, observed late
                t_conn1 = t_reg + connect_us / 1e6
                _tspan(tid, "connect", t_back, t_conn1,
                       backend=f"{target.ip}:{target.port}", fast=True)
                now = time.monotonic()
                _tspan(tid, "splice", t_conn1, now, bytes=a2b + b2a)
                _tspan(tid, "close", now, now, err=err)
            lb.bytes_in += a2b
            lb.bytes_out += b2a
            svr.bytes_in += a2b
            svr.bytes_out += b2a
            svr.conn_count -= 1
            lb._sessions_delta(-1)
            # workload capture: fast-lane sessions land in the same
            # per-connection histograms as the classic splice path
            if workload.ON:
                t0 = t_acc if t_acc is not None else t_reg
                conn_observe(lb.alias, a2b + b2a,
                             (time.monotonic() - t0) * 1e3)
            events.record("conn", f"{desc} closed", lb=lb.alias,
                          bytes_in=a2b, bytes_out=b2a, err=err,
                          trace_id=tid)

        pid = pc(front_fd, target.ip, target.port, self.in_buffer_size,
                 done, timeout_ms=self.connect_timeout_ms,
                 on_connected=_report_ok)
        if not pid:
            return False  # registration failed: classic path retries
        pid_box[0] = pid
        t_reg = time.monotonic()
        reg_s = t_reg - t_back
        svr.conn_count += 1
        self._sessions_delta(1)
        self._watch_pump(loop, pid, desc)
        return True

    def _adopt_pooled(self, loop, front_fd: int, target: Connector,
                      conn: Connection, head: bytes, front: str,
                      t_acc: Optional[float], src_ip: bytes, tls_ctx: int,
                      tried: set, hint, tid: int = 0) -> None:
        """Hand a validated warm connection straight to the pump: the
        accept path skips the whole backend-connect round trip (syscalls
        + a loop iteration waiting for writability). Reads are already
        parked, so a server-first backend's early bytes are still queued
        in the kernel for the pump to deliver."""
        svr = target.svr
        svr.conn_count += 1
        self._sessions_delta(1)
        sb = _SpliceBack(self, loop, front_fd, target, head, front,
                         tls_ctx=tls_ctx, t_acc=t_acc, src_ip=src_ip,
                         tried=tried, hint=hint, pooled=True, tid=tid)
        sb.connected = True
        conn.set_handler(sb)
        # NOTE: a retried session landing on a pooled socket counts its
        # retries_total{success} in _handover, once the pump is actually
        # registered — counting here would double-count when the pooled
        # socket dies at handover and the fresh-connect fallback succeeds
        if failpoint.hit("pool.handover.dead", f"{target.ip}:{target.port}"):
            # deterministic stale-at-handover: exercises the pooled
            # failure -> fresh-connect fallback (tests/test_pool_wiring)
            conn.close(errno.ECONNRESET)
            return
        if head:
            conn.write(head)  # a dead socket closes here -> on_closed
            if conn.closed:   # handles the fallback; nothing more to do
                return
        if conn.out:
            return  # _handover on drain, like a fresh connect
        sb._handover(conn)
