"""L7Engine — drives a Processor session over real connections.

Parity: core component/proxy/ProcessorConnectionHandler.java:16 (the L7
data pump behind every `protocol=<processor>` TcpLB): owns the frontend
connection plus up to MAX_BACKENDS backend connections, funnels bytes
into the ProtoSession, executes its backend selections through
`Upstream.next` (the classify engine), and applies byte/connection
accounting and backpressure. The reference pumps through ring buffers
with TODO instructions; here the session pushes into Connection out
buffers and the engine pauses reading a source whenever a sink's out
buffer passes the high-water mark (the writable-ET analog).
"""
from __future__ import annotations

import itertools
from typing import Optional

from ..net.connection import Connection, Handler
from ..processors.base import Processor, ProcessorEngine
from ..rules.ir import Hint
from ..utils.ip import parse_ip

MAX_BACKENDS = 1024  # ProcessorConnectionHandler.java:27
HIGH_WATER = 1 * 1024 * 1024


class _Sel:
    """Opaque backend selection handed back to the session; key identifies
    the concrete backend server so sessions can pool/reuse connections.
    The hint that produced the selection rides along: connect retries
    must re-run the SAME classify, not the global WRR."""

    __slots__ = ("connector", "key", "hint")

    def __init__(self, connector, hint=None):
        self.connector = connector
        self.key = (connector.ip, connector.port)
        self.hint = hint


class L7Engine(ProcessorEngine):
    def __init__(self, lb, loop, cfd: int, ip: str, port: int,
                 processor, front=None):
        """processor: a Processor, or a session factory
        callable(engine, addr) -> ProtoSession. front: a pre-built
        Connection-like (e.g. TlsSocket); when None, cfd is wrapped."""
        self.lb = lb
        self.loop = loop
        self.client_ip = parse_ip(ip)
        self.closed = False
        self.backs: dict[int, Connection] = {}
        self.back_sels: dict[int, object] = {}   # conn_id -> Connector
        self._tried: dict[int, set] = {}         # conn_id -> retried svrs
        self._hints: dict[int, object] = {}      # conn_id -> selection hint
        self._ids = itertools.count(1)
        self._front_paused = False
        self._back_paused: set[int] = set()
        lb._sessions_delta(1)
        if front is not None:
            self.front = front
        else:
            try:
                self.front = Connection(loop, cfd, (ip, port))
            except BaseException:
                lb._sessions_delta(-1)
                from ..net import vtl
                vtl.close(cfd)
                raise
        self.front.set_handler(_FrontHandler(self))
        make = processor.session if isinstance(processor, Processor) \
            else processor
        try:
            self.session = make(self, (ip, port))
        except Exception:
            self.close()
            raise

    # ----------------------------------------------------- engine interface

    def select(self, hint: Optional[Hint]) -> _Sel:
        c = self.lb.backend.next(self.client_ip, hint)
        if c is None:
            raise OSError("no healthy backend for hint")
        return _Sel(c, hint)

    def open(self, sel: _Sel) -> int:
        if self.closed:
            raise OSError("session closed")
        if len(self.backs) >= MAX_BACKENDS:
            raise OSError("too many backend connections")
        tried: set = set()
        connector = sel.connector
        while True:
            try:
                conn = Connection.connect(
                    self.loop, connector.ip, connector.port,
                    timeout_ms=self.lb.connect_timeout_ms)
                break
            except OSError as e:
                # sync connect failure: report and re-enter selection
                # excluding everything tried (shared retry knobs/budget)
                tried.add(connector.svr)
                connector.group.report_failure(connector.svr,
                                               e.errno or 0)
                connector = self._next_retry(tried, sel.hint)
                if connector is None:
                    raise OSError("backend connect failed "
                                  "(retries exhausted)")
        conn_id = next(self._ids)
        self.backs[conn_id] = conn
        self.back_sels[conn_id] = connector
        self._tried[conn_id] = tried
        self._hints[conn_id] = sel.hint
        connector.svr.conn_count += 1
        conn.set_handler(_BackHandler(self, conn_id))
        return conn_id

    def _next_retry(self, tried: set, hint):
        """One retry-gated re-selection through the shared TcpLB gate,
        re-running the SAME hint classify select() ran (hint group
        first, then the initial pick's own WRR fallback); None when out
        of attempts."""
        lb = self.lb
        return lb._take_retry_slot(
            tried, "l7",
            lambda: lb.backend.next_host(self.client_ip, hint,
                                         exclude=tried))

    def _reconnect_back(self, conn_id: int, dead: Connection,
                        err: int = 0) -> bool:
        """A backend conn died before completing its connect: swap in a
        fresh connection to another backend under the SAME conn_id,
        carrying over any bytes the session already wrote (still sitting
        in the dead conn's out buffer — nothing reached the wire).
        Transparent to the ProtoSession. True when the swap happened."""
        if self.closed:
            return False
        tried = self._tried.setdefault(conn_id, set())
        hint = self._hints.get(conn_id)
        connector = self.back_sels.get(conn_id)
        if connector is not None:
            tried.add(connector.svr)
            connector.group.report_failure(connector.svr,
                                           -err if err < 0 else err)
        pending = bytes(dead.out)
        while True:
            nxt = self._next_retry(tried, hint)
            if nxt is None:
                return False
            try:
                newc = Connection.connect(
                    self.loop, nxt.ip, nxt.port,
                    timeout_ms=self.lb.connect_timeout_ms)
                break
            except OSError as e:
                tried.add(nxt.svr)
                nxt.group.report_failure(nxt.svr, e.errno or 0)
        self._release_back(conn_id, dead)  # pops the tried/hint state too
        self.backs[conn_id] = newc
        self.back_sels[conn_id] = nxt
        self._tried[conn_id] = tried
        self._hints[conn_id] = hint
        nxt.svr.conn_count += 1
        # handler FIRST: write() can close synchronously (late async
        # connect refusal, out-buffer blowout) and that close must reach
        # _BackHandler, not the default no-op Handler
        newc.set_handler(_BackHandler(self, conn_id))
        if pending:
            newc.write(pending)
        return True

    def send_front(self, data: bytes) -> None:
        if not self.closed:
            self.front.write(data)
            self._check_pressure()

    def send_back(self, conn_id: int, data: bytes) -> None:
        conn = self.backs.get(conn_id)
        if conn is not None:
            conn.write(data)
            self._check_pressure()

    def close_back(self, conn_id: int) -> None:
        conn = self.backs.pop(conn_id, None)
        self._back_paused.discard(conn_id)
        if conn is not None:
            self._release_back(conn_id, conn)
            conn.set_handler(Handler())  # drop session callbacks
            conn.close_graceful()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.lb._sessions_delta(-1)
        self.lb.bytes_in += self.front.bytes_in
        self.lb.bytes_out += self.front.bytes_out
        self.front.set_handler(Handler())
        self.front.close_graceful()
        for conn_id, conn in list(self.backs.items()):
            self._release_back(conn_id, conn)
            conn.set_handler(Handler())
            conn.close_graceful()
        self.backs.clear()

    def pause_front(self) -> None:
        self._front_paused = True
        self.front.pause_reading()

    def resume_front(self) -> None:
        self._front_paused = False
        self.front.resume_reading()

    def pause_back(self, conn_id: int) -> None:
        conn = self.backs.get(conn_id)
        if conn is not None:
            self._back_paused.add(conn_id)
            conn.pause_reading()

    def resume_back(self, conn_id: int) -> None:
        conn = self.backs.get(conn_id)
        if conn is not None:
            self._back_paused.discard(conn_id)
            conn.resume_reading()

    # ----------------------------------------------------------- internals

    def _release_back(self, conn_id: int, conn: Connection) -> None:
        sel = self.back_sels.pop(conn_id, None)
        self._tried.pop(conn_id, None)
        self._hints.pop(conn_id, None)
        if sel is not None:
            svr = sel.svr
            svr.conn_count -= 1
            svr.bytes_in += conn.bytes_out  # bytes we pushed toward the server
            svr.bytes_out += conn.bytes_in

    def _check_pressure(self) -> None:
        """Sink out-buffer past high water -> pause all sources feeding it;
        resumed from the drain callbacks."""
        if self.closed:
            return
        if len(self.front.out) > HIGH_WATER:
            for conn_id, conn in self.backs.items():
                if conn_id not in self._back_paused:
                    conn.pause_reading()
        if any(len(c.out) > HIGH_WATER for c in self.backs.values()):
            if not self._front_paused:
                self.front.pause_reading()

    def _front_drained(self) -> None:
        for conn_id, conn in self.backs.items():
            if conn_id not in self._back_paused:
                conn.resume_reading()
        self.session.on_front_drained()

    def _back_drained(self, conn_id: int) -> None:
        if not self._front_paused and \
                all(len(c.out) <= HIGH_WATER for c in self.backs.values()):
            self.front.resume_reading()
        self.session.on_back_drained(conn_id)


class _FrontHandler(Handler):
    def __init__(self, eng: L7Engine):
        self.eng = eng

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.eng.session.on_front_data(data)

    def on_eof(self, conn: Connection) -> None:
        self.eng.session.on_front_eof()

    def on_closed(self, conn: Connection, err: int) -> None:
        self.eng.close()

    def on_drained(self, conn: Connection) -> None:
        self.eng._front_drained()


class _BackHandler(Handler):
    def __init__(self, eng: L7Engine, conn_id: int):
        self.eng = eng
        self.conn_id = conn_id
        self.connected = False

    def on_connected(self, conn: Connection) -> None:
        self.connected = True
        eng = self.eng
        connector = eng.back_sels.get(self.conn_id)
        if connector is not None:
            connector.group.report_success(connector.svr)
            if eng._tried.get(self.conn_id):  # a retry attempt landed
                eng.lb._retries_total("success").incr()
        eng.session.on_back_connected(self.conn_id)

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.eng.session.on_back_data(self.conn_id, data)

    def on_eof(self, conn: Connection) -> None:
        self.eng.session.on_back_eof(self.conn_id)

    def on_closed(self, conn: Connection, err: int) -> None:
        eng = self.eng
        if not self.connected and not eng.closed \
                and eng.backs.get(self.conn_id) is conn:
            # pre-connect death: transparently swap in another backend
            # (the session never learns; its written bytes carry over)
            if eng._reconnect_back(self.conn_id, conn, err):
                return
        conn2 = eng.backs.pop(self.conn_id, None)
        if conn2 is not None:
            eng._release_back(self.conn_id, conn2)
        if eng.closed:
            return
        if not eng.session.on_back_closed(self.conn_id, err):
            eng.close()

    def on_drained(self, conn: Connection) -> None:
        self.eng._back_drained(self.conn_id)
