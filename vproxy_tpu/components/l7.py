"""L7Engine — drives a Processor session over real connections.

Parity: core component/proxy/ProcessorConnectionHandler.java:16 (the L7
data pump behind every `protocol=<processor>` TcpLB): owns the frontend
connection plus up to MAX_BACKENDS backend connections, funnels bytes
into the ProtoSession, executes its backend selections through
`Upstream.next` (the classify engine), and applies byte/connection
accounting and backpressure. The reference pumps through ring buffers
with TODO instructions; here the session pushes into Connection out
buffers and the engine pauses reading a source whenever a sink's out
buffer passes the high-water mark (the writable-ET analog).
"""
from __future__ import annotations

import itertools
from typing import Optional

from ..net.connection import Connection, Handler
from ..processors.base import Processor, ProcessorEngine
from ..rules.ir import Hint
from ..utils.ip import parse_ip

MAX_BACKENDS = 1024  # ProcessorConnectionHandler.java:27
HIGH_WATER = 1 * 1024 * 1024


class _Sel:
    """Opaque backend selection handed back to the session; key identifies
    the concrete backend server so sessions can pool/reuse connections."""

    __slots__ = ("connector", "key")

    def __init__(self, connector):
        self.connector = connector
        self.key = (connector.ip, connector.port)


class L7Engine(ProcessorEngine):
    def __init__(self, lb, loop, cfd: int, ip: str, port: int,
                 processor, front=None):
        """processor: a Processor, or a session factory
        callable(engine, addr) -> ProtoSession. front: a pre-built
        Connection-like (e.g. TlsSocket); when None, cfd is wrapped."""
        self.lb = lb
        self.loop = loop
        self.client_ip = parse_ip(ip)
        self.closed = False
        self.backs: dict[int, Connection] = {}
        self.back_svrs: dict[int, object] = {}
        self._ids = itertools.count(1)
        self._front_paused = False
        self._back_paused: set[int] = set()
        lb.active_sessions += 1
        if front is not None:
            self.front = front
        else:
            try:
                self.front = Connection(loop, cfd, (ip, port))
            except BaseException:
                lb.active_sessions -= 1
                from ..net import vtl
                vtl.close(cfd)
                raise
        self.front.set_handler(_FrontHandler(self))
        make = processor.session if isinstance(processor, Processor) \
            else processor
        try:
            self.session = make(self, (ip, port))
        except Exception:
            self.close()
            raise

    # ----------------------------------------------------- engine interface

    def select(self, hint: Optional[Hint]) -> _Sel:
        c = self.lb.backend.next(self.client_ip, hint)
        if c is None:
            raise OSError("no healthy backend for hint")
        return _Sel(c)

    def open(self, sel: _Sel) -> int:
        if self.closed:
            raise OSError("session closed")
        if len(self.backs) >= MAX_BACKENDS:
            raise OSError("too many backend connections")
        conn = Connection.connect(self.loop, sel.connector.ip,
                                  sel.connector.port)
        conn_id = next(self._ids)
        self.backs[conn_id] = conn
        svr = sel.connector.svr
        self.back_svrs[conn_id] = svr
        svr.conn_count += 1
        conn.set_handler(_BackHandler(self, conn_id))
        return conn_id

    def send_front(self, data: bytes) -> None:
        if not self.closed:
            self.front.write(data)
            self._check_pressure()

    def send_back(self, conn_id: int, data: bytes) -> None:
        conn = self.backs.get(conn_id)
        if conn is not None:
            conn.write(data)
            self._check_pressure()

    def close_back(self, conn_id: int) -> None:
        conn = self.backs.pop(conn_id, None)
        self._back_paused.discard(conn_id)
        if conn is not None:
            self._release_back(conn_id, conn)
            conn.set_handler(Handler())  # drop session callbacks
            conn.close_graceful()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.lb.active_sessions -= 1
        self.lb.bytes_in += self.front.bytes_in
        self.lb.bytes_out += self.front.bytes_out
        self.front.set_handler(Handler())
        self.front.close_graceful()
        for conn_id, conn in list(self.backs.items()):
            self._release_back(conn_id, conn)
            conn.set_handler(Handler())
            conn.close_graceful()
        self.backs.clear()

    def pause_front(self) -> None:
        self._front_paused = True
        self.front.pause_reading()

    def resume_front(self) -> None:
        self._front_paused = False
        self.front.resume_reading()

    def pause_back(self, conn_id: int) -> None:
        conn = self.backs.get(conn_id)
        if conn is not None:
            self._back_paused.add(conn_id)
            conn.pause_reading()

    def resume_back(self, conn_id: int) -> None:
        conn = self.backs.get(conn_id)
        if conn is not None:
            self._back_paused.discard(conn_id)
            conn.resume_reading()

    # ----------------------------------------------------------- internals

    def _release_back(self, conn_id: int, conn: Connection) -> None:
        svr = self.back_svrs.pop(conn_id, None)
        if svr is not None:
            svr.conn_count -= 1
            svr.bytes_in += conn.bytes_out  # bytes we pushed toward the server
            svr.bytes_out += conn.bytes_in

    def _check_pressure(self) -> None:
        """Sink out-buffer past high water -> pause all sources feeding it;
        resumed from the drain callbacks."""
        if self.closed:
            return
        if len(self.front.out) > HIGH_WATER:
            for conn_id, conn in self.backs.items():
                if conn_id not in self._back_paused:
                    conn.pause_reading()
        if any(len(c.out) > HIGH_WATER for c in self.backs.values()):
            if not self._front_paused:
                self.front.pause_reading()

    def _front_drained(self) -> None:
        for conn_id, conn in self.backs.items():
            if conn_id not in self._back_paused:
                conn.resume_reading()
        self.session.on_front_drained()

    def _back_drained(self, conn_id: int) -> None:
        if not self._front_paused and \
                all(len(c.out) <= HIGH_WATER for c in self.backs.values()):
            self.front.resume_reading()
        self.session.on_back_drained(conn_id)


class _FrontHandler(Handler):
    def __init__(self, eng: L7Engine):
        self.eng = eng

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.eng.session.on_front_data(data)

    def on_eof(self, conn: Connection) -> None:
        self.eng.session.on_front_eof()

    def on_closed(self, conn: Connection, err: int) -> None:
        self.eng.close()

    def on_drained(self, conn: Connection) -> None:
        self.eng._front_drained()


class _BackHandler(Handler):
    def __init__(self, eng: L7Engine, conn_id: int):
        self.eng = eng
        self.conn_id = conn_id

    def on_connected(self, conn: Connection) -> None:
        self.eng.session.on_back_connected(self.conn_id)

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.eng.session.on_back_data(self.conn_id, data)

    def on_eof(self, conn: Connection) -> None:
        self.eng.session.on_back_eof(self.conn_id)

    def on_closed(self, conn: Connection, err: int) -> None:
        eng = self.eng
        conn2 = eng.backs.pop(self.conn_id, None)
        if conn2 is not None:
            eng._release_back(self.conn_id, conn2)
        if eng.closed:
            return
        if not eng.session.on_back_closed(self.conn_id, err):
            eng.close()

    def on_drained(self, conn: Connection) -> None:
        self.eng._back_drained(self.conn_id)
