"""Adaptive overload control — degrade-rather-than-fail under flash
crowds (docs/robustness.md).

The PR-2 overload guard is a static `max_sessions` ceiling: correct for
a known capacity, wrong for the real failure mode, where the proxy's
capacity MOVES (classify load on the same cores, a slow disk, a noisy
neighbor) and a flash crowd overwhelms the event loops long before any
fixed session count is reached. Ananta and Envoy both survive overload
the same way: observe the symptoms, shed early, keep the sessions you
do admit fast.

`VPROXY_TPU_OVERLOAD=adaptive` (or `overload adaptive` on
add/update tcp-lb) attaches this controller to a TcpLB. It runs AIMD
over an *effective* session ceiling between a floor and max_sessions:

* **signals** — (1) event-loop stall rate: each loop accumulates
  `stall_total_s` (callback time beyond 1ms + timer slip, PR-1's
  health machinery); the controller diffs it per tick into
  milliseconds-stalled-per-second and takes the worst loop. (2)
  accept-path latency: TcpLB feeds every completed accept→handover
  span in; the per-tick mean (0 when idle — no stale-high memory).
  Both are EWMA-smoothed (`VPROXY_TPU_OVERLOAD_ALPHA`).
* **law** — hot (either EWMA above its threshold): multiplicative
  decrease, `ceiling = max(floor, 0.75 × min(ceiling, active))` —
  anchored at the live session count so shedding starts immediately
  instead of waiting for the old ceiling to drain down. Calm (both
  EWMAs under half their thresholds): additive-ish increase of 1/8 per
  tick back toward max_sessions. In between: hold (hysteresis).
* **shed mechanics** — over-ceiling accepts are closed with an RST
  (SO_LINGER {1,0}; `net/vtl.py close_rst`) instead of a FIN: a crowd
  big enough to trip the controller would otherwise park one TIME_WAIT
  per shed and exhaust the table. Counted
  `vproxy_lb_shed_total{lb,reason=adaptive}`.
* **both planes** — the live bound is forwarded to the C accept lanes
  (`vtl_lanes_set_limit`, as `ceiling − python-held sessions`) and the
  lanes flip into C-side RST shed (`vtl_lanes_set_shed`): over-limit
  lane accepts never cross into Python. The controller folds the C
  shed counter into the same metric.

The controller runs on its OWN daemon thread, never on an event loop:
a controller scheduled on the loop it is supposed to police could not
observe that loop stalling.

Knobs: VPROXY_TPU_OVERLOAD (static|adaptive), VPROXY_TPU_OVERLOAD_FLOOR
(64), VPROXY_TPU_OVERLOAD_TICK_MS (100), VPROXY_TPU_OVERLOAD_STALL_MS
(50 — ms of loop stall per second of wall time), and
VPROXY_TPU_OVERLOAD_ACCEPT_MS (50 — mean accept→handover span).
"""
from __future__ import annotations

import os
import threading
import time

from ..utils.log import Logger

_log = Logger("overload")

MODE = os.environ.get("VPROXY_TPU_OVERLOAD", "static")
FLOOR = int(os.environ.get("VPROXY_TPU_OVERLOAD_FLOOR", "64"))
TICK_MS = int(os.environ.get("VPROXY_TPU_OVERLOAD_TICK_MS", "100"))
STALL_HI_MS = float(os.environ.get("VPROXY_TPU_OVERLOAD_STALL_MS", "50"))
ACCEPT_HI_MS = float(os.environ.get("VPROXY_TPU_OVERLOAD_ACCEPT_MS", "50"))
ALPHA = float(os.environ.get("VPROXY_TPU_OVERLOAD_ALPHA", "0.3"))


class AdaptiveOverload:
    """One per adaptive-mode TcpLB; owns the ceiling and the ticker."""

    def __init__(self, lb, floor: int = 0, tick_ms: int = 0,
                 stall_hi_ms: float = 0.0, accept_hi_ms: float = 0.0,
                 alpha: float = 0.0):
        self.lb = lb
        self.floor = floor or FLOOR
        self.tick_ms = tick_ms or TICK_MS
        self.stall_hi_ms = stall_hi_ms or STALL_HI_MS
        self.accept_hi_ms = accept_hi_ms or ACCEPT_HI_MS
        self.alpha = alpha or ALPHA
        # start wide open AT the configured max — never above it: a
        # floor beyond a small max_sessions must not admit 2x the
        # operator's ceiling until the first tick's clamp runs
        self.ceiling = lb.max_sessions
        self.stall_ewma_ms = 0.0
        self.accept_ewma_ms = 0.0
        self.lane_ewma_ms = 0.0  # last tick's C-plane accept EWMA
        self.ticks = 0
        self._calm_streak = 0  # raises need SUSTAINED calm (see tick)
        self._acc_lock = threading.Lock()
        self._acc_sum = 0.0
        self._acc_n = 0
        self._prev_stall: dict[int, float] = {}  # id(loop) -> last total
        # baseline at the CURRENT cumulative C counter: a mode hot-flip
        # (static -> adaptive) builds a fresh controller against lanes
        # whose shed history is already in the metric — starting at 0
        # would re-fold it all on the first tick
        lanes = getattr(lb, "lanes", None)
        self._lane_shed_seen = lanes.shed_count() if lanes is not None else 0
        self._last_tick = time.monotonic()
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"overload-{self.lb.alias}", daemon=True)
        self._thread.start()
        lanes = self.lb.lanes
        if lanes is not None:
            lanes.set_shed(True)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(2)
        lanes = self.lb.lanes
        if lanes is not None:
            lanes.set_shed(False)

    def _run(self) -> None:
        errors = 0
        while not self._stop.wait(self.tick_ms / 1000.0):
            try:
                self.tick_once()
                errors = 0
            except Exception:
                # the controller must outlive any one bad sample — but a
                # SYSTEMATIC failure (every tick raising) would freeze
                # the ceiling wherever it last was, invisibly; log the
                # first of a streak (and every 600th: ~1/min at the
                # default tick) instead of swallowing forever
                errors += 1
                if errors == 1 or errors % 600 == 0:
                    _log.error(
                        f"overload-{self.lb.alias}: controller tick "
                        f"failed ({errors} consecutive; ceiling frozen "
                        f"at {self.ceiling})", exc=True)

    # ------------------------------------------------------------ signals

    def observe_accept(self, seconds: float) -> None:
        """One completed accept→handover span (TcpLB feeds this from the
        same sites as the `total` stage histogram)."""
        with self._acc_lock:
            self._acc_sum += seconds
            self._acc_n += 1

    def _loops(self) -> list:
        seen: set = set()
        out = []
        for grp in (self.lb.acceptor, self.lb.worker):
            for lp in list(grp.loops):
                if id(lp) not in seen:
                    seen.add(id(lp))
                    out.append(lp)
        return out

    # ------------------------------------------------------------ the law

    def tick_once(self, now: float = None) -> int:  # type: ignore[assignment]
        """One controller step; returns the (possibly moved) ceiling.
        Exposed for deterministic tests — feed observe_accept / loop
        stall state, then call this directly."""
        lb = self.lb
        if now is None:
            now = time.monotonic()
        dt = max(1e-3, now - self._last_tick)
        self._last_tick = now
        self.ticks += 1
        # worst loop's stalled-ms per second of wall time this tick
        worst = 0.0
        cur: dict[int, float] = {}
        for lp in self._loops():
            tot = getattr(lp, "stall_total_s", 0.0)
            prev = self._prev_stall.get(id(lp), tot)
            cur[id(lp)] = tot
            if tot > prev:
                worst = max(worst, (tot - prev) / dt)
        self._prev_stall = cur  # dead loops forgotten
        stall_ms = worst * 1000.0
        with self._acc_lock:
            s, n = self._acc_sum, self._acc_n
            self._acc_sum, self._acc_n = 0.0, 0
        acc_ms = (s / n * 1000.0) if n else 0.0
        # lane-aware signal (r11): the C accept plane serves whole
        # sessions without ever calling observe_accept, so a lanes-heavy
        # LB used to look idle to this controller exactly when it was
        # busiest. The lanes export their own accept->backend-connected
        # EWMA (lanes_stat field 12); take the worse of the two planes
        # as this tick's sample — one law, both admission paths.
        lanes = getattr(lb, "lanes", None)
        self.lane_ewma_ms = (lanes.accept_latency_ms()
                             if lanes is not None else 0.0)
        acc_ms = max(acc_ms, self.lane_ewma_ms)
        a = self.alpha
        self.stall_ewma_ms += a * (stall_ms - self.stall_ewma_ms)
        self.accept_ewma_ms += a * (acc_ms - self.accept_ewma_ms)
        hot = (self.stall_ewma_ms > self.stall_hi_ms
               or self.accept_ewma_ms > self.accept_hi_ms)
        calm = (self.stall_ewma_ms < self.stall_hi_ms / 2
                and self.accept_ewma_ms < self.accept_hi_ms / 2)
        if hot:
            self._calm_streak = 0
            active = lb.active_sessions + lb.lane_active()
            base = min(self.ceiling, max(active, self.floor))
            self.ceiling = max(self.floor, int(base * 0.75))
        elif calm:
            # raises wait for SUSTAINED calm: a single quiet tick inside
            # a storm would over-admit a batch whose sessions become the
            # p99 tail — the sawtooth's top is where SLOs go to die
            self._calm_streak += 1
            if (self._calm_streak >= 3
                    and self.ceiling < lb.max_sessions):
                self.ceiling = min(lb.max_sessions,
                                   self.ceiling + max(1, self.ceiling >> 3))
        else:
            self._calm_streak = 0
        self.ceiling = min(self.ceiling, lb.max_sessions)  # hot-set clamp
        lb._push_lane_limit()
        self._fold_lane_sheds()
        return self.ceiling

    def _fold_lane_sheds(self) -> None:
        lanes = self.lb.lanes
        if lanes is None:
            return
        shed = lanes.shed_count()
        if shed > self._lane_shed_seen:
            d = shed - self._lane_shed_seen
            # BOTH counters, like every python-side shed path: the
            # legacy vproxy_lb_overload_total is the one pre-r10
            # dashboards alert on — C-plane sheds must not be invisible
            # to it
            self.lb._shed_total("adaptive").incr(d)
            self.lb._overload_total().incr(d)
            self._lane_shed_seen = shed

    # ------------------------------------------------------------ surfaces

    def stat(self) -> dict:
        return {"mode": "adaptive", "maxSessions": self.lb.max_sessions,
                "ceiling": self.ceiling, "floor": self.floor,
                "stallEwmaMs": round(self.stall_ewma_ms, 2),
                "acceptEwmaMs": round(self.accept_ewma_ms, 2),
                "laneAcceptEwmaMs": round(self.lane_ewma_ms, 2),
                "ticks": self.ticks}
