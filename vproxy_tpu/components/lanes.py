"""AcceptLanes — Python as the lane-entry COMPILER for TcpLB's C accept
plane (native/vtl.cpp "accept lanes").

The PR-5 flow-cache division of labor applied to TCP accept: N lane
threads park inside `vtl_lane_poll` (ctypes releases the GIL) while C
runs the whole short-connection lifetime — accept4 batch, route lookup
against the installed lane entry, backend connect, splice, close. This
module owns everything that must stay in Python:

* **compile + install** — flatten the Upstream's (group weight x server
  weight) healthy-backend set into LANE_REC records plus the
  subtract-sum WRR sequence, stamped with the generation read BEFORE
  the compile began (`vtl_lane_install` rejects a raced stamp with
  -EAGAIN and we recompile against current state);
* **generation hooks** — every upstream mutation (Upstream listeners),
  ACL edit (SecurityGroup listeners) and backend membership/health
  change (ServerGroup.on_change) bumps the one C atomic
  (`vtl_lane_gen_bump`) and schedules a recompile. A lane entry whose
  stamp mismatches is a forced punt: zero stale routing by
  construction. The `lane.entry.stale` failpoint suppresses exactly one
  bump (tests/test_lanes.py proves the gate is what prevents stale
  forwards);
* **failpoint discipline** — any armed fault outside the lane.* sites
  flips the C punt_all flag, forcing the classic path so the
  backend.connect.* / pump.abort injection sites keep exact semantics
  (the PR-3 `_fast_splice` rule, enforced once per arm edge instead of
  per accept);
* **punt dispatch** — classic punts land in `TcpLB._on_accept` on a
  worker loop (ACL, overload shed, drain shed, accounting all apply);
  connect-failure punts resolve the backend handle and feed
  `report_failure` + the bounded retry machinery with the client fd
  intact, exactly like `vtl_pump_connect`'s connect_failed DONE.

Knobs: VPROXY_TPU_ACCEPT_LANES (lane thread count, 0 = off, the
default), VPROXY_TPU_ACCEPT_LANES_URING (allow the io_uring engine when
the runtime probe passes; the epoll engine is the fallback and the only
engine on pre-5.1 kernels like this container's).
"""
from __future__ import annotations

import math
import os
import threading
from typing import Optional

from ..net import vtl
from ..policing import engine as policing
from ..rules.ir import Proto
from ..utils import events, failpoint, sketch, trace, workload
from ..utils.ip import parse_ip
from ..utils.log import Logger
from ..utils.metrics import accept_stage_merge, conn_merge
from .servergroup import Connector

_log = Logger("accept-lanes")

LANES = int(os.environ.get("VPROXY_TPU_ACCEPT_LANES", "0"))
LANES_URING = os.environ.get("VPROXY_TPU_ACCEPT_LANES_URING", "1") != "0"
# backend-pick mode for wrr-method upstreams: "wrr" (default — the
# configured round-robin semantics) or "maglev" (consistent hashing:
# per-connection spread via the 5-tuple hash, resize moves ~1/N of
# flows; the bench A/B lever). method=source groups ALWAYS compile the
# maglev table — that IS their semantic (docs/perf.md).
LANE_PICK = os.environ.get("VPROXY_TPU_LANE_PICK", "wrr")
_SEQ_CAP = 4096  # WRR sequence bound (weights renormalized past it)


def _wrr_seq(weights: list) -> list:
    """The reference's subtract-sum sequence over backend indexes
    (ServerGroup._wrr_compute semantics), gcd-reduced and capped so a
    pathological weight set cannot inflate the C-side table. Equal
    weights (the common fleet) short-circuit to plain round-robin —
    the subtract-sum loop is O(picks x n) and the compiler runs on
    every health edge, so big fleets must not pay it."""
    if not weights:
        return []
    if len(set(weights)) == 1:
        return list(range(len(weights)))
    g = 0
    for w in weights:
        g = math.gcd(g, w)
    if g > 1:
        weights = [w // g for w in weights]
    total = sum(weights)
    if total > _SEQ_CAP:
        weights = [max(1, (w * _SEQ_CAP) // total) for w in weights]
        total = sum(weights)
    if total > _SEQ_CAP:
        # the max(1,..) floor can't shrink below one slot per backend:
        # a fleet larger than the cap degrades to fair round-robin
        # (O(n) compile, every backend picked) instead of an O(n*total)
        # subtract-sum that would pin the compiler on each health edge
        return list(range(len(weights)))
    cur = list(weights)
    seq: list = []
    while True:
        idx = max(range(len(cur)), key=lambda i: (cur[i], -i))
        seq.append(idx)
        cur[idx] -= total
        if all(w == 0 for w in cur):
            return seq
        for i in range(len(cur)):
            cur[i] += weights[i]


class AcceptLanes:
    """One per lanes-enabled TcpLB; owns the C handle, the lane threads
    and every registered mutation hook."""

    def __init__(self, lb, n: int, uring: bool = LANES_URING):
        self.lb = lb
        self.n = n
        self.uring = uring
        self.handle = 0
        self.threads: list[threading.Thread] = []
        self._compiler: Optional[threading.Thread] = None
        self._dirty = threading.Event()
        self._stop = False
        self._groups: set = set()  # groups holding our on_change hook
        self._hook_lock = threading.Lock()
        # pick-structure state for the detail surface (compiler thread
        # writes, readers tolerate a torn mid-compile view)
        self.pick_mode = "empty"      # "wrr" | "maglev" | "empty"
        self.maglev_m = 0
        self.maglev_last_remap = 0.0
        self._maglev_prev = None      # (table, names) of the last compile
        # serializes vtl_lanes_free against cross-thread stat()/active()
        # readers (list-detail, HTTP detail, drain polling): the C
        # object must not be freed mid-read
        self._handle_lock = threading.Lock()
        # cumulative C stage-histogram snapshot (lane 0's poll tick
        # merges the deltas into vproxy_accept_stage_us)
        self._stage_last = [(0, 0.0) for _ in vtl.LANE_STAGES]
        self._stage_bkt_last = [[0] * vtl.LANE_STAGE_BUCKETS
                                for _ in vtl.LANE_STAGES]
        # cumulative C workload-capture snapshot (same fold, r16):
        # lane-plane inter-arrival + per-connection bytes/duration
        self._cap_last = [(0, 0.0) for _ in vtl.LANE_CAPTURES]
        self._cap_bkt_last = [[0] * vtl.LANE_STAGE_BUCKETS
                              for _ in vtl.LANE_CAPTURES]
        # policing plane (r19): the last POLICE_REC table the engine
        # compiled (re-stamped after every route recompile — a gen bump
        # stales the C police table too) + the cumulative C counter
        # snapshot lane 0 folds deltas from
        self._police_recs: list = []
        self._pol_last = (0, 0, 0, 0, 0)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Bind the lane listeners (resolving an ephemeral bind_port),
        install the first lane entry, register every generation hook and
        launch the lane + compiler threads. Raises OSError on bind
        failure — the caller falls back to the python accept path."""
        lb = self.lb
        # the sampling knob is one process-wide C atomic: push the
        # current python-side value so C lanes and python flip together
        # (trace.configure() pushes on later changes)
        vtl.trace_set_sample(trace.sample_every())
        # same idiom for the analytics knob (the lane HH shards gate
        # their per-accept work on one C atomic)
        sketch.push_native_knob()
        # ...and the workload-capture knob (lane inter-arrival +
        # per-connection histograms gate on one C atomic too)
        workload.push_native_knob()
        # ...and the policing knob (the lane admission probe gates on
        # one relaxed C atomic — the knob-off contract)
        policing.push_native_knob()
        self.handle = vtl.lanes_new(
            lb.bind_ip, lb.bind_port, 512, self.n, lb.in_buffer_size,
            self.uring, lb.timeout_ms, lb.connect_timeout_ms)
        if lb.bind_port == 0:
            lb.bind_port = vtl.lanes_port(self.handle)
        vtl.lanes_set_limit(self.handle,
                            max(0, lb.effective_max_sessions()
                                - lb.active_sessions))
        if getattr(lb, "_overguard", None) is not None:
            vtl.lanes_set_shed(self.handle, True)  # adaptive: RST in C
        lb.backend.add_listener(self._on_mutation)
        lb.security_group.add_listener(self._on_mutation)
        failpoint.on_change.append(self._on_failpoints)
        self._on_failpoints()  # pick up faults armed before start
        # enforcement-table installer: the decision plane pushes every
        # recompiled POLICE_REC set through here; seed with the current
        # table so a lane brought up mid-storm enforces immediately
        policing.default().on_install.append(self._install_police)
        self._install_police(policing.default().compile_recs())
        self._compile_install()
        self._compiler = threading.Thread(
            target=self._compile_loop, name=f"lane-compile-{lb.alias}",
            daemon=True)
        self._compiler.start()
        for i in range(self.n):
            t = threading.Thread(target=self._lane_loop, args=(i,),
                                 name=f"lane-{lb.alias}-{i}", daemon=True)
            t.start()
            self.threads.append(t)
        events.record(
            "lanes", f"lb {lb.alias}: {self.n} accept lanes on "
            f"{lb.bind_ip}:{lb.bind_port} engine={self.engine()}",
            lb=lb.alias, lanes=self.n, engine=self.engine())

    def close_listeners(self) -> None:
        """Drain: lanes stop accepting (each lane closes its own
        listener at the next tick); live spliced sessions run on."""
        if self.handle:
            vtl.lanes_close_listeners(self.handle)

    def shutdown(self) -> None:
        """Stop: close listeners, give in-flight pumps a short grace,
        then tear down threads, hooks and the native object."""
        lb = self.lb
        self._stop = True
        self._dirty.set()
        if self.handle:
            vtl.lanes_shutdown(self.handle, 500)
        for t in self.threads:
            t.join(3)
        if self._compiler is not None:
            self._compiler.join(3)
        lb.backend.remove_listener(self._on_mutation)
        lb.security_group.remove_listener(self._on_mutation)
        try:
            failpoint.on_change.remove(self._on_failpoints)
        except ValueError:
            pass
        try:
            policing.default().on_install.remove(self._install_police)
        except ValueError:
            pass
        with self._hook_lock:
            groups, self._groups = self._groups, set()
        for g in groups:
            g.off_change(self._on_mutation)
        alive = [t for t in self.threads if t.is_alive()]
        if self._compiler is not None and self._compiler.is_alive():
            alive.append(self._compiler)  # mid-compile: it holds handle
        if alive:
            # a wedged lane/compiler thread still owns the native
            # object: freeing under it would be a use-after-free — leak
            # instead. self.handle stays nonzero ON PURPOSE: the wedged
            # thread keeps using its live (leaked) object, never NULL.
            _log.alert(f"lanes {lb.alias}: {len(alive)} thread(s) did "
                       "not exit; leaking native lanes")
            return
        with self._handle_lock:  # no stat()/active() mid-free
            h, self.handle = self.handle, 0
        vtl.lanes_free(h)

    # ------------------------------------------------------------ state

    def engine(self) -> str:
        with self._handle_lock:  # like every cross-thread reader
            return vtl.lanes_engine(self.handle) if self.handle else "off"

    def stat(self) -> dict:
        """list-detail / HTTP detail payload. Reads under the handle
        lock so a concurrent shutdown cannot free the C object mid-
        read."""
        with self._handle_lock:
            if not self.handle:
                return {"on": False}
            st = vtl.lanes_stat(self.handle)
        (accepted, served, active, p_classic, p_stale, p_fail,
         nbytes, gen, engine, port, killed) = st[:11]
        shed = st[11] if len(st) > 11 else 0  # pre-r10 .so: no C shed
        lat_us = st[12] if len(st) > 12 else 0  # pre-r11 .so: no EWMA
        punts = p_classic + p_stale + p_fail
        return {"on": True, "lanes": self.n,
                "engine": "uring" if engine else "epoll",
                "uring_probe": vtl.uring_probe_fields(),
                "gen": gen, "accepted": accepted, "served": served,
                "active": active, "punts": punts,
                "punt_stale": p_stale, "punt_connect_fail": p_fail,
                "killed": killed, "shed": shed, "bytes": nbytes,
                "hit_rate": round(
                    (served + killed) / max(1, served + killed + punts),
                    4),
                "accept_ewma_ms": round(lat_us / 1000.0, 3),
                "pick": self.pick_mode,
                "maglev": ({"m": self.maglev_m,
                            "last_remap": round(self.maglev_last_remap, 4)}
                           if self.pick_mode == "maglev" else None),
                "port": port}

    def active(self) -> int:
        """Live lane-owned sessions (drain accounting + the per-accept
        overload check): one atomic load under the handle lock."""
        with self._handle_lock:
            if not self.handle:
                return 0
            return vtl.lanes_active(self.handle)

    def set_timeout(self, timeout_ms: int) -> None:
        """Hot-set the lane idle timeout — under the handle lock (a
        hot-update racing remove/stop must not reach a freed Lanes*)."""
        with self._handle_lock:
            if self.handle:
                vtl.lanes_set_timeout(self.handle, timeout_ms)

    def set_limit(self, n: int) -> None:
        """Hot-set the lane active-session bound (same locking)."""
        with self._handle_lock:
            if self.handle:
                vtl.lanes_set_limit(self.handle, n)

    def set_shed(self, on: bool) -> None:
        """Adaptive-overload RST shed inside C for over-limit accepts
        (components/overload.py flips this with the controller mode)."""
        with self._handle_lock:
            if self.handle:
                vtl.lanes_set_shed(self.handle, on)

    def shed_count(self) -> int:
        """Cumulative C-side RST sheds (the guard tick diffs this into
        vproxy_lb_shed_total{reason=adaptive})."""
        with self._handle_lock:
            if not self.handle:
                return 0
            st = vtl.lanes_stat(self.handle)
        return st[11] if len(st) > 11 else 0

    def accept_latency_ms(self) -> float:
        """The C-plane accept->backend-connected EWMA (ms) — the signal
        the adaptive overload controller folds in so lane-served load
        is no longer invisible to its accept-latency input (pre-r11 the
        python EWMA only ever saw punts). 0.0 on a pre-r11 .so."""
        with self._handle_lock:
            if not self.handle:
                return 0.0
            st = vtl.lanes_stat(self.handle)
        return (st[12] / 1000.0) if len(st) > 12 else 0.0

    # ------------------------------------------------------------ hooks

    def _on_mutation(self) -> None:
        """ANY routing-relevant mutation lands here (upstream recalc,
        ACL edit, group membership/health edge). Bump first — the gate
        must close before the new state is even readable — then defer
        the recompile to the compiler thread (callers may hold group
        locks; the compile takes none but must not run under them)."""
        if failpoint.hit("lane.entry.stale", self.lb.alias):
            # suppress exactly ONE bump: the stale lane entry stays
            # serveable, proving the generation gate (not timing) is
            # what prevents stale routing — tests/test_lanes.py
            return
        if self.handle:
            vtl.lane_gen_bump(self.handle)
        self._dirty.set()

    def _on_failpoints(self) -> None:
        """Armed faults (outside lane.*) force every accept down the
        classic path so injection-site semantics stay exact."""
        if self.handle:
            vtl.lanes_set_punt_all(
                self.handle, failpoint.any_armed_excluding("lane."))

    # ------------------------------------------------------------ policing

    def _install_police(self, recs: list) -> bool:
        """The decision plane's installer hook: remember the table (the
        route compiler re-stamps it after every gen bump) and push it
        into C now."""
        self._police_recs = list(recs)
        return self._police_install()

    def _police_install(self) -> bool:
        """Generation-stamped POLICE_REC install, retried while bumps
        race it — same contract as the route entry, except a losing
        stamp fails OPEN (consult-miss = admit) instead of punting."""
        if not vtl.police_supported():
            return False
        with self._handle_lock:
            if not self.handle:
                return False
            for _ in range(8):
                gen = vtl.lane_gen(self.handle)
                r = vtl.police_install(
                    self.handle, b"".join(self._police_recs),
                    len(self._police_recs), gen)
                if r >= 0:
                    return True
        return False

    def _merge_police(self, handle) -> None:
        """Fold the C police-counter deltas into the decision plane's
        attribution — and the legacy shed/overload families via the LB
        (the PR-9 rule: policed refusals must move the counters pre-r19
        dashboards alert on). shed/monitor deltas only: a C throttle
        verdict PUNTS, so the python mirror counts it exactly once;
        stale deltas are a diagnostic, not an action."""
        if not vtl.police_supported():
            return
        try:
            cur = vtl.police_counters(handle)
        except OSError:
            return
        _c, shed, _t, mon, _s = cur
        _lc, lshed, _lt, lmon, _ls = self._pol_last
        if shed > lshed:
            d = shed - lshed
            policing.account_native(self.lb.alias, "shed", "clients", d)
            self.lb._policed_shed(d)
        if mon > lmon:
            policing.account_native(self.lb.alias, "monitor", "clients",
                                    mon - lmon)
        self._pol_last = cur

    # ------------------------------------------------------------ compile

    def _compile_loop(self) -> None:
        while not self._stop:
            self._dirty.wait(timeout=1.0)
            if self._stop:
                return
            if not self._dirty.is_set():
                continue
            self._dirty.clear()
            try:
                self._compile_install()
            except Exception as e:  # never kill the compiler thread
                _log.alert(f"lanes {self.lb.alias}: compile failed: {e!r}")

    def _compile_install(self) -> None:
        """Snapshot -> LANE_RECs + pick structure (WRR seq or maglev
        table) -> vtl_lane_install / vtl_lane_maglev_install, retried
        while mutations race the compile (bounded; the gate keeps
        correctness either way — worst case the entry stays empty and
        every accept punts)."""
        lb = self.lb
        for _ in range(8):
            gen = vtl.lane_gen(self.handle)
            mode, recs, aux, hash_port = self._compile()
            if mode == "maglev":
                r = vtl.lane_maglev_install(self.handle, b"".join(recs),
                                            len(recs), aux, hash_port, gen)
            else:
                r = vtl.lane_install(self.handle, b"".join(recs),
                                     len(recs), aux, gen)
            if r >= 0:
                self.pick_mode = mode if recs else "empty"
                # the gen bump that forced this recompile staled the
                # police table too (same stamp): re-install it so
                # enforcement resumes — until then mismatched stamps
                # fail OPEN (admit), never closed
                self._police_install()
                return
            # -EAGAIN: a bump landed mid-compile; go again vs new state
        _log.warn(f"lanes {lb.alias}: install kept racing mutations; "
                  "entry left stale-gated (all accepts punt)")

    def _compile(self):
        """Flatten the upstream into (backend, combined-weight) records
        plus the pick structure. -> (mode, recs, seq_or_table,
        hash_port): mode "wrr" installs the subtract-sum sequence,
        "maglev" the consistent-hash slot table. Non-trivial ACLs and
        TLS holders compile to an EMPTY entry — every accept punts to
        the python path that owns those checks. Also (re)subscribes
        group change hooks for the current group set."""
        lb = self.lb
        handles = list(lb.backend.handles)
        groups = {gh.group for gh in handles}
        with self._hook_lock:
            for g in groups - self._groups:
                g.on_change(self._on_mutation)
            for g in self._groups - groups:
                g.off_change(self._on_mutation)
            self._groups = groups
        if (lb.holder is not None or lb.draining
                or not lb.security_group.trivial_allow(Proto.TCP)):
            return "wrr", [], [], True
        methods = {gh.group.method for gh in handles}
        if "wlc" in methods:
            # least-connections needs live python-side conn counts:
            # compile EMPTY, python keeps the semantics
            return "wrr", [], [], True
        if "source" in methods:
            weighted = [gh for gh in handles
                        if gh.weight > 0 and gh.group.method == "source"]
            if (methods != {"source"} or len(weighted) != 1
                    or not vtl.maglev_supported()):
                # mixed methods / multi-group source keep the python
                # path's two-level semantics; an old .so without the
                # maglev ABI punts too (never guess in C)
                return "wrr", [], [], True
            # source affinity IS a maglev table (hash_port=0: one
            # backend per client address). The SAME identities, weights
            # and M as ServerGroup._maglev_state, so the C pick and the
            # python punt-path pick agree at every generation —
            # tests/test_maglev.py proves it.
            return self._compile_maglev([weighted[0]], hash_port=False)
        if LANE_PICK == "maglev" and vtl.maglev_supported():
            return self._compile_maglev(
                [gh for gh in handles if gh.weight > 0], hash_port=True)
        # two-level pick, exactly like the classic path (group-level
        # WRR, then THAT group's own server WRR): flattening
        # gh.weight*s.weight would skew multi-group proportions by
        # server count. Emit the outer group sequence with each slot
        # resolved through the group's rotating server sequence.
        recs, group_seqs = [], []
        for gh in handles:
            if gh.weight <= 0:
                continue
            sidx, sweights = [], []
            for s in list(gh.group.servers):
                if not s.healthy or s.logic_delete or s.weight <= 0:
                    continue
                sidx.append(len(recs))
                sweights.append(s.weight)
                recs.append(vtl.LANE_REC.pack(
                    s.ip.encode(), s.port, 1 if ":" in s.ip else 0,
                    min(255, s.weight)))
            if sidx:
                group_seqs.append(
                    (gh.weight, [sidx[i] for i in _wrr_seq(sweights)]))
        if not group_seqs:
            return "wrr", recs, [], True
        outer = _wrr_seq([w for w, _ in group_seqs])
        # close EVERY group's rotation: lcm of the inner sequence
        # lengths (max alone leaves shorter rotations mid-cycle at the
        # wrap point — a persistent intra-group weight skew). The cap
        # bounds pathological lcm blowups; a capped sequence wraps with
        # at most one inner-cycle misalignment per seqlen picks.
        reps = 1
        for _, sq in group_seqs:
            reps = math.lcm(reps, len(sq))
        reps = min(reps, max(1, _SEQ_CAP // max(1, len(outer))))
        order, cursors = [], [0] * len(group_seqs)
        for _ in range(reps):
            for gi in outer:
                sq = group_seqs[gi][1]
                order.append(sq[cursors[gi] % len(sq)])
                cursors[gi] += 1
        return "wrr", recs, order, True

    def _compile_maglev(self, weighted, hash_port: bool):
        """Compile the maglev route: MAGLEV_REC backends + the slot
        table (rules/maglev.build_table).

        Single source group (hash_port=False): the group's OWN table
        snapshot — identical identities/weights/M to the python pick
        path, so a punted connection routes exactly where the lane
        would have. Multi-group wrr (hash_port=True): flattened with
        gh.weight x s.weight scaled by the group's weight sum, so
        group-level proportions survive regardless of server count.
        Tracks the rebuild's slot churn for the detail surface."""
        from ..rules import maglev as MG
        recs, entries = [], []
        table = None
        if not hash_port:
            # the group's own snapshot: build_table is deterministic on
            # (identities, weights, M), so reusing the group's table IS
            # the parity guarantee (and skips a redundant build)
            g = weighted[0].group
            servers, table = g.maglev_table()
            for s in servers:
                entries.append((g.maglev_identity(s), s.weight))
                recs.append(vtl.MAGLEV_REC.pack(
                    s.ip.encode(), s.port, 1 if ":" in s.ip else 0,
                    min(255, s.weight)))
        else:
            for gh in weighted:
                eligible = [s for s in list(gh.group.servers)
                            if s.healthy and not s.logic_delete
                            and s.weight > 0]
                sw = sum(s.weight for s in eligible)
                for s in eligible:
                    w = max(1, round(gh.weight * s.weight * 64 / sw))
                    entries.append(
                        (f"{gh.group.alias}|{s.ip}:{s.port}", w))
                    recs.append(vtl.MAGLEV_REC.pack(
                        s.ip.encode(), s.port, 1 if ":" in s.ip else 0,
                        min(255, s.weight)))
        if not entries:
            return "maglev", [], [], hash_port
        if table is None:
            table = MG.build_table(entries, MG.GROUP_M)
        prev = self._maglev_prev
        names = [n for n, _ in entries]
        self.maglev_last_remap = MG.remap_fraction(
            prev[0] if prev else None, table,
            prev[1] if prev else None, names)
        self._maglev_prev = (table, names)
        self.maglev_m = len(table)
        return "maglev", recs, table, hash_port

    # ------------------------------------------------------------ punts

    def _lane_loop(self, idx: int) -> None:
        # snapshot the handle: shutdown() zeroes self.handle after the
        # join window, and a late (wedged-then-recovered) thread must
        # keep polling the real — possibly leaked — C object, never 0
        handle = self.handle
        last_accepted = 0
        last_routed = 0  # routes-dim analytics credit (lane 0 only)
        while True:
            try:
                punts = vtl.lane_poll(handle, idx, 1000)
            except OSError as e:
                _log.alert(f"lane {self.lb.alias}/{idx} poll: {e!r}")
                return
            if trace.enabled() and vtl.trace_supported():
                # drain THIS lane's span ring into the process buffer
                # (SPSC: this thread is the one consumer) — until dry:
                # a lane that stayed inside C for a whole poll window
                # under load has a multi-chunk backlog. Knob-off cost
                # is the enabled() branch alone.
                try:
                    while True:
                        recs = vtl.trace_drain(handle, idx)
                        if recs:
                            trace.ingest_lane_recs(recs)
                        if len(recs) < vtl._TRACE_DRAIN_MAX:
                            break
                except OSError:
                    pass
            if sketch.enabled() and vtl.hh_supported():
                # drain THIS lane's analytics shard (same OS thread as
                # the in-C producer — no concurrency by construction)
                # until dry; knob-off cost is the enabled() branch
                try:
                    while True:
                        recs = vtl.hh_drain(handle, idx)
                        if recs:
                            sketch.ingest_hh_recs(recs)
                        if len(recs) < vtl._HH_DRAIN_MAX:
                            break
                except OSError:
                    pass
            if idx == 0:
                self._merge_stage_hists(handle)
                self._merge_capture_hists(handle)
                self._merge_police(handle)
                # the decision plane's lazy tick rides the lane-0 poll
                # cadence (the sketch-rotation idiom: no extra thread)
                policing.maybe_tick()
            if idx == 0:
                # retry-budget denominator: lane-SERVED accepts never
                # pass through _on_accept, but their connect-fail punts
                # SPEND the budget — credit them in batches (per poll
                # tick, lane 0 only). Classic/stale punts are excluded:
                # those land in _on_accept, which credits them itself
                # (double-crediting would double the retry allowance
                # exactly in degraded punt-heavy states).
                try:
                    st = vtl.lanes_stat(handle)
                    acc = st[0] - st[3] - st[4]  # - classic - stale
                    if len(st) > 11:
                        # C RST-sheds never generate connect load and
                        # must not fund the budget (the python shed path
                        # returns before on_accept for the same reason)
                        acc -= st[11]
                except OSError:
                    acc = last_accepted
                if acc > last_accepted:
                    self.lb._retry_budget.on_accepts(acc - last_accepted)
                    last_accepted = acc
                if acc > last_routed:
                    # routes-dim credit for lane-owned traffic: the LB
                    # alias keyed by the SAME punt/shed-adjusted delta
                    # the retry budget uses — classic/stale punts land
                    # in _on_accept (which credits the route itself,
                    # so raw accepted would double-count them) and RST
                    # sheds were never routed anywhere. The cursor
                    # advances even with analytics OFF: a later enable
                    # must not replay the whole off-period into one
                    # window as a phantom rate spike.
                    if sketch.enabled():
                        sketch.update("routes", self.lb.alias,
                                      acc - last_routed, plane="lane")
                    last_routed = acc
            if punts is None:
                return  # lanes_shutdown drained this lane
            for p in punts:
                try:
                    self._dispatch(p)
                except Exception:
                    vtl.close(p[0])

    def _merge_stage_hists(self, handle) -> None:
        """Fold the C stage-histogram deltas into the process-wide
        vproxy_accept_stage_us series (satellite of the tracing PR:
        lane-served connections used to be invisible to the stage
        histograms python-path connections populate). Lane 0's poll
        tick only; one ctypes call per stage per tick."""
        if not hasattr(vtl.LIB, "vtl_lanes_stage_stat"):
            return
        for si, stage in enumerate(vtl.LANE_STAGES):
            try:
                count, sum_us, bkt = vtl.lanes_stage_stat(handle, si)
            except OSError:
                return
            lc, ls = self._stage_last[si]
            if count <= lc:
                continue
            deltas = [b - p for b, p in
                      zip(bkt, self._stage_bkt_last[si])]
            accept_stage_merge(stage, deltas, float(sum_us - ls),
                               count - lc)
            self._stage_last[si] = (count, float(sum_us))
            self._stage_bkt_last[si] = bkt

    def _merge_capture_hists(self, handle) -> None:
        """Fold the C workload-capture deltas into the python-side
        series (utils/workload satellite): lane-plane inter-arrival
        into vproxy_workload_interarrival_us{plane=lane}, per-connection
        bytes/duration into the vproxy_lb_conn_* histograms (process
        aggregate + this LB). Lane 0's poll tick only — the same
        delta-fold discipline as _merge_stage_hists."""
        if not hasattr(vtl.LIB, "vtl_lanes_capture_stat"):
            return
        for ci, cap in enumerate(vtl.LANE_CAPTURES):
            try:
                count, total, bkt = vtl.lanes_capture_stat(handle, ci)
            except OSError:
                return
            lc, ls = self._cap_last[ci]
            if count <= lc:
                continue
            deltas = [b - p for b, p in zip(bkt, self._cap_bkt_last[ci])]
            if cap == "interarrival_us":
                workload.arrival_merge("lane", deltas, float(total - ls),
                                       count - lc)
            else:
                conn_merge(self.lb.alias,
                           "bytes" if cap == "conn_bytes"
                           else "duration_ms",
                           deltas, float(total - ls), count - lc)
            self._cap_last[ci] = (count, float(total))
            self._cap_bkt_last[ci] = bkt

    def _dispatch(self, punt) -> None:
        fd, kind, err, cip, cport, bip, bport, tid = punt
        lb = self.lb
        try:
            wl = lb.worker.next()
        except Exception:
            vtl.close(fd)
            return
        if kind == vtl.LANE_PUNT_CONNECT_FAIL:
            target = self._find_backend(bip, bport)
            if target is not None:
                src = parse_ip(cip) if cip else b""

                def run(wl=wl, target=target):
                    # same ownership contract as a python connect
                    # failure: report_failure feeds the ejection streak
                    # and the bounded retry either re-dials or closes
                    # (a sampled punt's trace id rides along: the retry
                    # continues the C-side trace)
                    lb._backend_connect_failed(
                        wl, fd, target, b"", f"{cip}:{cport}", None, src,
                        0, set(), err, hint=None, tid=tid)

                if not wl.run_on_loop(run):
                    vtl.close(fd)
                return
            # backend vanished from the tables since the entry compiled:
            # fall through — the classic path re-decides from scratch
            # (its analytics dims were already tallied in C at pick
            # time AND by lane 0's routes credit, so _on_accept must
            # not count them again)
            if not wl.run_on_loop(
                    lambda: lb._on_accept(wl, fd, cip, cport, tid=tid,
                                          hh_counted=True)):
                vtl.close(fd)
            return
        if not wl.run_on_loop(
                lambda: lb._on_accept(wl, fd, cip, cport, tid=tid)):
            vtl.close(fd)

    def _find_backend(self, ip: str, port: int) -> Optional[Connector]:
        for gh in list(self.lb.backend.handles):
            for s in list(gh.group.servers):
                if s.ip == ip and s.port == port and not s.logic_delete:
                    return Connector(s, gh.group)
        return None
