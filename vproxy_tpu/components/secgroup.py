"""SecurityGroup — L4 ACL on the classify engine.

Reference: component/secure/SecurityGroup.java (per-protocol ordered
first-match lists, default allow/deny) and SecurityGroupRule.java. The
per-rule linear scan becomes a CidrMatcher table query.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

from ..rules.engine import CidrMatcher
from ..rules.ir import AclRule, Proto
from ..utils.ip import Network


class SecurityGroup:
    DEFAULT_NAME = "(allow-all)"

    def __init__(self, alias: str, default_allow: bool = True,
                 backend: Optional[str] = None):
        self.alias = alias
        self.default_allow = default_allow
        self._rules: list[AclRule] = []
        self._backend = backend
        # proto -> (matcher, rules) published atomically; matchers are
        # immutable once published (a recalc builds a NEW one) so a data-
        # plane allow() never sees a half-updated table/rule-list pair
        self._tables: dict[Proto, tuple[CidrMatcher, list[AclRule]]] = {}
        self._lock = threading.Lock()
        # mutation listeners (fired AFTER the new table publishes, lock
        # released): the switch flow cache registers its generation bump
        # here so an ACL edit invalidates native entries immediately
        self._listeners: list = []

    def add_listener(self, cb) -> None:
        self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        try:
            self._listeners.remove(cb)
        except ValueError:
            pass

    def _fire(self) -> None:
        for cb in list(self._listeners):
            cb()

    @classmethod
    def allow_all(cls) -> "SecurityGroup":
        return cls(cls.DEFAULT_NAME, True)

    @property
    def rules(self) -> list[AclRule]:
        return list(self._rules)

    def add_rule(self, rule: AclRule) -> None:
        with self._lock:
            if any(r.alias == rule.alias for r in self._rules):
                raise ValueError(f"rule {rule.alias} already exists in {self.alias}")
            for r in self._rules:
                if (r.network == rule.network and r.protocol == rule.protocol
                        and r.min_port == rule.min_port and r.max_port == rule.max_port):
                    raise ValueError(f"equivalent rule {r.alias} already exists")
            self._rules.append(rule)
            self._recalc(rule.protocol)
        self._fire()

    def extend_rules(self, rules: Sequence[AclRule]) -> None:
        """Bulk add: one table recompile per touched protocol instead of
        per rule (a 5k-rule group would otherwise pay 5k recompiles)."""
        with self._lock:
            seen = {r.alias for r in self._rules}
            eq = {(r.network, r.protocol, r.min_port, r.max_port)
                  for r in self._rules}
            for r in rules:
                if r.alias in seen:
                    raise ValueError(f"rule {r.alias} already exists in {self.alias}")
                k = (r.network, r.protocol, r.min_port, r.max_port)
                if k in eq:
                    raise ValueError(f"equivalent rule for {r.alias} already exists")
                seen.add(r.alias)
                eq.add(k)
            self._rules.extend(rules)
            for proto in {r.protocol for r in rules}:
                self._recalc(proto)
        self._fire()

    def remove_rule(self, alias: str) -> None:
        with self._lock:
            for i, r in enumerate(self._rules):
                if r.alias == alias:
                    del self._rules[i]
                    self._recalc(r.protocol)
                    break
            else:
                raise KeyError(alias)
        self._fire()

    def _recalc(self, proto: Proto) -> None:
        sub = [r for r in self._rules if r.protocol == proto]
        if not sub:
            self._tables.pop(proto, None)
            return
        m = CidrMatcher([r.network for r in sub], backend=self._backend,
                        acl=sub, payload=sub)
        self._tables[proto] = (m, sub)  # atomic publish

    def trivial_allow(self, proto: Proto) -> bool:
        """True when allow() can only ever answer True for `proto` (no
        rules for it + default allow) — the accept lanes serve in C only
        under a trivially-allowing group; anything else punts every
        connection to the python ACL path."""
        return self.default_allow and self._tables.get(proto) is None

    def allow(self, proto: Proto, addr: bytes, port: int) -> bool:
        ent = self._tables.get(proto)
        if ent is None:
            return self.default_allow
        m, sub = ent
        idx = m.match_one(addr, port)
        return sub[idx].allow if idx >= 0 else self.default_allow

    def allow_async(self, proto: Proto, addr: bytes, port: int, cb,
                    loop=None) -> None:
        """Async allow(): the CIDR+port lookup rides the ClassifyService
        micro-batch queue; cb(bool) fires on *loop*. Empty rule sets
        short-circuit synchronously (the common allow-all group costs
        nothing)."""
        ent = self._tables.get(proto)
        if ent is None:
            cb(self.default_allow)
            return
        from ..rules.service import ClassifyService
        m, _ = ent

        def on_idx(idx: int, sub) -> None:
            cb(sub[idx].allow if sub and idx >= 0 else self.default_allow)

        ClassifyService.get().submit_cidr(m, addr, port, on_idx, loop)

    def allow_batch(self, proto: Proto, addrs: Sequence[bytes],
                    ports: Sequence[int]) -> list[bool]:
        ent = self._tables.get(proto)
        if ent is None:
            return [self.default_allow] * len(addrs)
        m, sub = ent
        return [sub[i].allow if i >= 0 else self.default_allow
                for i in m.match(addrs, ports)]
