"""Pre-warmed idle connection pool.

Parity: reference `core/.../pool/ConnectionPool.java:14` + `PoolCallback`:
a fixed-capacity set of established idle connections to one destination,
kept alive by a pluggable keepalive hook, handed out ready-to-use
(used by the reference for conn-transfer / WebSocks "holding"
connections). A connection that dies while pooled is replaced after a
short retry delay. All state is loop-thread-confined.

Two accept-fast-lane options (TcpLB's warm backend pool rides both):

* park_reads=True — pooled connections drop read interest while idle,
  so early backend bytes (server-first protocols: the banner a backend
  sends on connect) stay queued in the kernel and reach the client
  through the splice pump after handover instead of being consumed and
  dropped by on_pooled_data. The cost: a peer's clean FIN while parked
  goes unnoticed until the taker validates (MSG_PEEK) at handover.
* idle_expire_ms>0 — connections pooled longer than this are closed on
  the keepalive sweep and replaced, bounding how stale a parked socket
  can get (backends commonly reap idle connections server-side; expiry
  keeps the pool ahead of their reaper).
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..net.connection import Connection, Handler
from ..net.eventloop import SelectorEventLoop

RETRY_MS = 1000
KEEPALIVE_MS = 15000


class PoolHandler:
    """How the pool establishes and maintains connections."""

    def connect(self, loop: SelectorEventLoop) -> Connection:
        """Create a connecting Connection (raise OSError on failure)."""
        raise NotImplementedError

    def on_warm(self, conn: Connection) -> None:
        """A pool connect completed (the socket is now idle-warm).
        TcpLB reports backend connect success here — a refill IS a
        fresh data-plane connect, and the passive-ejection failure
        streak must clear on it like on any other successful dial."""

    def keepalive(self, conn: Connection) -> None:
        """Called periodically on each idle pooled connection."""

    def on_pooled_data(self, conn: Connection, data: bytes) -> None:
        """Data arriving while pooled (keepalive replies). Default: drop."""


class ConnectionPool:
    def __init__(self, loop: SelectorEventLoop, handler: PoolHandler,
                 capacity: int, keepalive_ms: int = KEEPALIVE_MS,
                 park_reads: bool = False, idle_expire_ms: int = 0):
        self.loop = loop
        self.handler = handler
        self.capacity = capacity
        self.keepalive_ms = keepalive_ms
        self.park_reads = park_reads
        self.idle_expire_ms = idle_expire_ms
        self._idle: List[Connection] = []   # connected, ready to hand out
        self._connecting = 0
        self.expired = 0                    # idle-expiry closures (stats)
        self.closed = False
        self._ka = None

        def boot() -> None:
            self._ka = loop.period(keepalive_ms, self._keepalive_all)
            self._fill()
        loop.run_on_loop(boot)

    # ------------------------------------------------------------- intern

    def _fill(self) -> None:
        if self.closed:
            return
        while len(self._idle) + self._connecting < self.capacity:
            try:
                conn = self.handler.connect(self.loop)
            except OSError:
                self.loop.delay(RETRY_MS, self._fill)
                return
            self._connecting += 1
            conn.set_handler(_PooledHandler(self, conn))

    def _on_up(self, conn: Connection) -> None:
        self._connecting -= 1
        if self.closed:
            conn.close()
            return
        if self.park_reads:
            # early backend bytes stay in the kernel for the pump; the
            # taker validates liveness with a MSG_PEEK at handover
            conn.pause_reading()
        conn._pooled_at = time.monotonic()
        self._idle.append(conn)
        self.handler.on_warm(conn)

    def _on_dead(self, conn: Connection, connected: bool) -> None:
        if connected:
            if conn in self._idle:
                self._idle.remove(conn)
        else:
            self._connecting -= 1
        if not self.closed:
            self.loop.delay(RETRY_MS, self._fill)

    def _keepalive_all(self) -> None:
        now = time.monotonic()
        for c in list(self._idle):
            if (self.idle_expire_ms > 0
                    and (now - getattr(c, "_pooled_at", now)) * 1000
                    >= self.idle_expire_ms):
                self.expired += 1
                c.close()  # _on_dead removes it and schedules a refill
                continue
            self.handler.keepalive(c)

    # ------------------------------------------------------------- public

    def get(self) -> Optional[Connection]:
        """Hand out one warmed connection. None if the pool is empty right
        now. Must be called on the loop thread, and the caller must
        set_handler before yielding back to the loop (no events can fire
        in between — the loop is single-threaded)."""
        if self.closed or not self._idle:
            return None
        conn = self._idle.pop(0)
        self.loop.next_tick(self._fill)
        return conn

    @property
    def count(self) -> int:
        return len(self._idle)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True

        def run() -> None:
            if self._ka is not None:
                self._ka.cancel()
            # copy: conn.close() reenters _on_dead which mutates _idle
            for c in list(self._idle):
                c.close()
            self._idle.clear()
        self.loop.run_on_loop(run)


class _PooledHandler(Handler):
    def __init__(self, pool: ConnectionPool, conn: Connection):
        self.pool = pool
        self.connected = False

    def on_connected(self, conn: Connection) -> None:
        self.connected = True
        self.pool._on_up(conn)

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.pool.handler.on_pooled_data(conn, data)

    def on_eof(self, conn: Connection) -> None:
        conn.close()

    def on_closed(self, conn: Connection, err: int) -> None:
        self.pool._on_dead(conn, self.connected)
