"""ServerAddressUpdater — periodic re-resolve of hostname backends.

Parity: reference `app/ServerAddressUpdater.java:171`: servers added by
hostname keep their `host_name`; this updater re-resolves each name on
a period and swaps the server's IP in place (ServerGroup.replace_ip)
when DNS moved it — health checks restart against the new address.
Resolution happens on a dedicated thread (getaddrinfo blocks); the
swap itself is the group's own thread-safe admin call.
"""
from __future__ import annotations

import socket
import threading
from typing import Callable, Dict, Iterable, Optional

DEFAULT_PERIOD_S = 60.0


def _resolve_all(host: str, want_v6: bool) -> list[str]:
    try:
        fam = socket.AF_INET6 if want_v6 else socket.AF_INET
        infos = socket.getaddrinfo(host, None, fam, socket.SOCK_STREAM)
    except OSError:
        return []
    return [i[4][0] for i in infos]


class ServerAddressUpdater:
    """groups: callable returning the live ServerGroup iterable (so the
    updater always sees the current resource graph)."""

    def __init__(self, groups: Callable[[], Iterable],
                 period_s: float = DEFAULT_PERIOD_S):
        self.groups = groups
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="server-address-updater",
                                        daemon=True)
        self._thread.start()

    def check_once(self) -> Dict[str, str]:
        """One pass; returns {group/server: new_ip} for every swap."""
        changed: Dict[str, str] = {}
        for g in list(self.groups()):
            for s in list(g.servers):
                if not s.host_name:
                    continue
                # swap only when the current IP left the record set —
                # multi-A round-robin answers must not flap the server
                ips = _resolve_all(s.host_name, ":" in s.ip)
                if ips and s.ip not in ips:
                    new_ip = ips[0]
                    try:
                        g.replace_ip(s.name, new_ip)
                        changed[f"{g.alias}/{s.name}"] = new_ip
                    except KeyError:
                        pass  # removed concurrently
        return changed

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.check_once()

    def close(self) -> None:
        self._stop.set()
