"""Upstream — groups-of-groups with hint-based selection on the classify
engine.

Reference: component/svrgroup/Upstream.java — weighted-RR across
ServerGroups (seq :68-116), hint selection via searchForGroup (:187-198).
THE difference: the linear annotation scan is replaced by the device
HintMatcher (vproxy_tpu/rules/engine.py) — the rule table lives in HBM
and single queries or micro-batches go through the same compiled kernel.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from ..rules.engine import HintMatcher
from ..rules.ir import Hint, HintRule
from ..utils.metrics import accept_stage_observe
from .servergroup import Connector, ServerGroup


class GroupHandle:
    def __init__(self, group: ServerGroup, weight: int,
                 annotations: Optional[HintRule] = None):
        self.alias = group.alias
        self.group = group
        self.weight = weight
        self.annotations = annotations or HintRule()

    def merged_rule(self) -> HintRule:
        """Handle annotations take precedence over the group's own
        (Hint.matchLevel merges in that order, Hint.java:104-117)."""
        g = self.group.annotations
        return HintRule(
            host=self.annotations.host if self.annotations.host is not None else g.host,
            port=self.annotations.port if self.annotations.port != 0 else g.port,
            uri=self.annotations.uri if self.annotations.uri is not None else g.uri,
        )


class Upstream:
    def __init__(self, alias: str, backend: Optional[str] = None):
        self.alias = alias
        self.handles: list[GroupHandle] = []
        self._matcher = HintMatcher([], backend=backend)
        # analytics attribution: the ClassifyService credits device
        # launches/batch occupancy to this upstream by this name
        self._matcher.owner_alias = alias
        self._wrr_seq: list[int] = []
        self._wrr_groups: list[GroupHandle] = []
        self._wrr_cursor = 0
        self._lock = threading.Lock()
        # mutation listeners, fired AFTER a recalc publishes (lock
        # released): the accept lanes register their generation bump +
        # lane-entry recompile here so add/remove/annotation edits
        # invalidate the C-resident route table immediately
        self._listeners: list = []

    def add_listener(self, cb) -> None:
        self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        try:
            self._listeners.remove(cb)
        except ValueError:
            pass

    def _fire(self) -> None:
        for cb in list(self._listeners):
            try:
                cb()
            except Exception:
                pass

    # ------------------------------------------------------------- admin

    def add(self, group: ServerGroup, weight: int = 10,
            annotations: Optional[HintRule] = None) -> GroupHandle:
        with self._lock:
            if any(h.group is group for h in self.handles):
                raise ValueError(f"group {group.alias} already in upstream {self.alias}")
            h = GroupHandle(group, weight, annotations)
            self.handles.append(h)
            self._recalc()
        self._fire()
        return h

    def remove(self, group: ServerGroup) -> None:
        with self._lock:
            for i, h in enumerate(self.handles):
                if h.group is group:
                    del self.handles[i]
                    self._recalc()
                    break
            else:
                raise KeyError(group.alias)
        self._fire()

    def set_annotations(self, group: ServerGroup, annotations: HintRule) -> None:
        with self._lock:
            for h in self.handles:
                if h.group is group:
                    h.annotations = annotations
                    self._recalc()
                    break
            else:
                raise KeyError(group.alias)
        self._fire()

    def _recalc(self) -> None:
        # the handle list is the rules' payload: published atomically
        # with the compiled table so async classify results map their
        # index through the SAME generation (see HintMatcher._pub)
        self._matcher.set_rules([h.merged_rule() for h in self.handles],
                                payload=list(self.handles))
        groups = [h for h in self.handles if h.weight > 0]
        self._wrr_groups = groups
        self._wrr_seq = ServerGroup._wrr_compute(groups) if groups else []
        self._wrr_cursor = 0

    # ------------------------------------------------------------- data

    def search_for_group(self, hint: Hint) -> Optional[GroupHandle]:
        """Sync hint search against ONE matcher generation: the index
        is interpreted through the SNAPSHOT's payload (the handle list
        registered with those rules), never `self.handles` — a standby
        install publishes seconds after add/remove mutated the live
        list, and a published-generation index into the mutated list
        would route wrong (or past the end). Served from the exact
        O(probes) host index, same winner as the oracle/device."""
        m = self._matcher
        snap = m.snapshot()
        idx = m.index_snap(snap, hint)
        handles = m.snap_payload(snap)
        if handles is None:  # pre-first-publish: the live list
            handles = self.handles
        return handles[idx] if 0 <= idx < len(handles) else None

    def search_batch(self, hints: Sequence[Hint]) -> list[Optional[GroupHandle]]:
        m = self._matcher
        snap = m.snapshot()  # one generation for every answer
        handles = m.snap_payload(snap)
        if handles is None:
            handles = self.handles
        out = []
        for h in hints:
            i = m.index_snap(snap, h)
            out.append(handles[i] if 0 <= i < len(handles) else None)
        return out

    def seek(self, source_ip: bytes, hint: Hint,
             fam: Optional[str] = None,
             exclude: Optional[set] = None) -> Optional[Connector]:
        h = self.search_for_group(hint)
        if h is not None:
            return h.group.next(source_ip, fam, exclude)
        return None

    # --------------------------------------- host-only (retry) selection

    def _search_host(self, hint: Hint) -> Optional[GroupHandle]:
        """search_for_group on the HOST index only (exact oracle parity,
        O(probes), ~µs — rules/index.py): the connect-retry path runs
        inside event-loop failure callbacks and must never eat a
        synchronous device dispatch, least of all during a backend
        outage when retries spike."""
        m = self._matcher
        snap = m.snapshot()
        idx = m.index_snap(snap, hint)
        payload = m.snap_payload(snap)
        handles = payload if payload is not None else self.handles
        return handles[idx] if 0 <= idx < len(handles) else None

    def next_host(self, source_ip: bytes, hint: Optional[Hint] = None,
                  fam: Optional[str] = None,
                  exclude: Optional[set] = None) -> Optional[Connector]:
        """`next` semantics (hint group first, WRR fallback) with the
        classify served from the host index."""
        if hint is not None:
            h = self._search_host(hint)
            if h is not None:
                c = h.group.next(source_ip, fam, exclude)
                if c is not None:
                    return c
        return self._wrr_next(source_ip, fam, exclude)

    def seek_host(self, source_ip: bytes, hint: Hint,
                  fam: Optional[str] = None,
                  exclude: Optional[set] = None) -> Optional[Connector]:
        """`seek` semantics (hint-only, no WRR fallback), host index."""
        h = self._search_host(hint)
        if h is not None:
            return h.group.next(source_ip, fam, exclude)
        return None

    def next(self, source_ip: bytes, hint: Optional[Hint] = None,
             fam: Optional[str] = None,
             exclude: Optional[set] = None) -> Optional[Connector]:
        """exclude: ServerHandles a connect-retry must skip (the
        failure-containment layer re-enters this loop after a backend
        refused, excluding everything already tried)."""
        if hint is not None:
            c = self.seek(source_ip, hint, fam, exclude)
            if c is not None:
                return c
        return self._wrr_next(source_ip, fam, exclude)

    def _wrr_next(self, source_ip: bytes, fam: Optional[str],
                  exclude: Optional[set] = None) -> Optional[Connector]:
        with self._lock:
            seq, groups = self._wrr_seq, self._wrr_groups
            for _ in range(len(seq) + 1):
                if not seq:
                    return None
                idx = self._wrr_cursor % len(seq)
                self._wrr_cursor = idx + 1
                c = groups[seq[idx]].group.next(source_ip, fam, exclude)
                if c is not None:
                    return c
            return None

    # ------------------------------------------------- batched data plane

    def search_for_group_async(self, hint: Hint, cb, loop=None) -> None:
        """Async search_for_group via the ClassifyService micro-batch
        queue; cb(GroupHandle | None) fires on *loop*. The handle list
        arrives as the matcher generation's payload, so the index is
        always interpreted against the same add/remove generation that
        the device table encoded."""
        if not self.handles:
            cb(None)
            return
        from ..rules.service import ClassifyService

        def on_idx(idx: int, handles) -> None:
            cb(handles[idx] if handles and 0 <= idx < len(handles) else None)

        ClassifyService.get().submit_hint(self._matcher, hint, on_idx, loop)

    def next_async(self, source_ip: bytes, hint: Optional[Hint], cb,
                   fam: Optional[str] = None, loop=None) -> None:
        """Async `next`: the hint classify rides the ClassifyService
        micro-batch queue (rules/service.py) instead of a per-connection
        device dispatch; cb(Connector | None) fires on *loop*.

        This is the replacement for the reference's per-connection scan
        in Upstream.searchForGroup (Upstream.java:187-198).

        Span timers: the hint classify (submit->index) lands in the
        `classify` accept-stage histogram, the group/WRR selection in
        `backend_pick` (utils/metrics accept_stage_observe)."""
        if hint is None or not self.handles:
            t0 = time.monotonic()
            c = self._wrr_next(source_ip, fam)
            accept_stage_observe("backend_pick", time.monotonic() - t0)
            cb(c)
            return
        from ..rules.service import ClassifyService
        t_sub = time.monotonic()

        def on_idx(idx: int, handles) -> None:
            t_idx = time.monotonic()
            accept_stage_observe("classify", t_idx - t_sub)
            if handles and 0 <= idx < len(handles):
                c = handles[idx].group.next(source_ip, fam)
                if c is not None:
                    accept_stage_observe("backend_pick",
                                         time.monotonic() - t_idx)
                    cb(c)
                    return
            c = self._wrr_next(source_ip, fam)
            accept_stage_observe("backend_pick", time.monotonic() - t_idx)
            cb(c)

        ClassifyService.get().submit_hint(self._matcher, hint, on_idx, loop)

    def seek_async(self, source_ip: bytes, hint: Hint, cb,
                   fam: Optional[str] = None, loop=None) -> None:
        """Async `seek` (hint-only, no WRR fallback); cb(Connector|None)."""
        if not self.handles:
            cb(None)
            return
        from ..rules.service import ClassifyService

        def on_idx(idx: int, handles) -> None:
            if handles and 0 <= idx < len(handles):
                cb(handles[idx].group.next(source_ip, fam))
            else:
                cb(None)

        ClassifyService.get().submit_hint(self._matcher, hint, on_idx, loop)
