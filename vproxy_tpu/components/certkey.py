"""CertKey resource + SNI-dispatching context holder.

Parity: the reference cert-key resource (component/ssl/CertKey.java) and
ringbuffer/ssl/SSLContextHolder.java — choose(sni) scans each cert's
DNS names with exact then wildcard (`*.x`) matching (:50-66, :172
wildcard) with a quick sni->ctx cache (:27); the first cert-key is the
default when nothing matches. DNS names come from the certificate's SAN
list plus subject CN (parsed with `cryptography`).
"""
from __future__ import annotations

import ssl
from typing import Optional

from ..net.tls import install_sni_chooser


def _cert_dns_names(cert_path: str) -> list[str]:
    from cryptography import x509
    from cryptography.x509.oid import ExtensionOID, NameOID

    with open(cert_path, "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    names: list[str] = []
    try:
        san = cert.extensions.get_extension_for_oid(
            ExtensionOID.SUBJECT_ALTERNATIVE_NAME)
        names += san.value.get_values_for_type(x509.DNSName)
    except x509.ExtensionNotFound:
        pass
    for attr in cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME):
        v = attr.value
        if isinstance(v, bytes):
            v = v.decode("latin-1")
        if v not in names:
            names.append(v)
    return names


class CertKey:
    def __init__(self, alias: str, cert_path: str, key_path: str):
        self.alias = alias
        self.cert_path = cert_path
        self.key_path = key_path
        self.dns_names = [n.lower() for n in _cert_dns_names(cert_path)]
        import threading
        self._native = None  # lazy native SSL_CTX handle (int) or False
        self._native_lock = threading.Lock()
        self.make_ctx()  # validate cert/key pair up front

    def native_ctx(self):
        """Native OpenSSL SSL_CTX handle for the C-side TLS splice pump
        (net/vtl.py tls_ctx_new), or None when native TLS is
        unavailable. Lazy and cached for the CertKey's lifetime —
        in-flight SSL sessions refcount the ctx, so the handle staying
        alive with the resource is the simple safe ownership."""
        with self._native_lock:
            if self._native is None:
                from ..net import vtl
                try:
                    if vtl.tls_available():
                        self._native = vtl.tls_ctx_new(self.cert_path,
                                                       self.key_path)
                    else:
                        self._native = False
                except OSError:
                    self._native = False
            return self._native or None

    def close_native(self) -> None:
        """Release the native SSL_CTX (cert-key removal / rotation).
        In-flight TLS sessions hold their own refs (OpenSSL refcounts
        the ctx via SSL_new), so freeing here never kills live splices;
        new handshakes on this CertKey become impossible — which is the
        point of removing it."""
        with self._native_lock:
            h, self._native = self._native, False
        if h:
            from ..net import vtl
            vtl.tls_ctx_free(h)

    def make_ctx(self) -> ssl.SSLContext:
        """Fresh server context; each holder (LB) builds its own so ALPN
        and SNI dispatch never leak between resources sharing a cert."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        return ctx

    def matches(self, sni: str) -> bool:
        """Exact or wildcard DNS-name match (SSLContextHolder.java:50-66)."""
        sni = sni.lower()
        for name in self.dns_names:
            if name == sni:
                return True
            if name.startswith("*.") and "." in sni and \
                    sni.split(".", 1)[1] == name[2:]:
                return True
        return False


class CertKeyHolder:
    """VSSLContext analog: ordered cert-keys, SNI choose with cache."""

    def __init__(self, cert_keys: list[CertKey],
                 alpn: Optional[list[str]] = None):
        if not cert_keys:
            raise ValueError("at least one cert-key required")
        self.cert_keys = list(cert_keys)
        self._ctxs = [ck.make_ctx() for ck in self.cert_keys]
        self._quick: dict[str, ssl.SSLContext] = {}  # quickAccess cache
        if alpn:
            for ctx in self._ctxs:
                ctx.set_alpn_protocols(alpn)
        self.front_context = self._ctxs[0]
        install_sni_chooser(self.front_context, self.choose)

    def choose_cert_key(self, sni: Optional[str]) -> "CertKey":
        """The CertKey serving `sni` (exact -> wildcard -> default)."""
        if sni:
            for ck in self.cert_keys:
                if ck.matches(sni):
                    return ck
        return self.cert_keys[0]

    def choose(self, sni: Optional[str]) -> Optional[ssl.SSLContext]:
        if not sni:
            return None  # no SNI: default (first) cert
        hit = self._quick.get(sni)
        if hit is not None:
            return hit
        for ck, ctx in zip(self.cert_keys, self._ctxs):
            if ck.matches(sni):
                self._quick[sni] = ctx
                return ctx
        return None  # unmatched SNI falls back to the default cert
