"""ServerGroup — weighted backends with health checks and 3 balancing
methods.

Semantics from the reference (svrgroup/ServerGroup.java): WRR with the
subtract-sum max-index sequence (:692-741) and a random start offset
(:721-737); WLC least-connection with the C(Sm)*W(Si) > C(Si)*W(Sm)
integer comparison (:527-560); `source` sdbm hash of the client address
with linear probe past unhealthy servers (:389-398, :479-490); v4/v6
filtered variants of each (nextIPv4/nextIPv6); health checks with up/down
edge thresholds (check/HealthCheckClient.java:100-137).
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..net import vtl
from ..net.eventloop import SelectorEventLoop
from ..rules import maglev as _maglev
from ..rules.ir import HintRule
from ..utils import failpoint
from .elgroup import EventLoopGroup

# passive outlier ejection (report_failure): N consecutive data-plane
# connect failures eject the backend immediately — detection latency is
# one RTT instead of the health checker's interval*down (~seconds)
EJECT_FAILURES = int(os.environ.get("VPROXY_TPU_EJECT_FAILURES", "3"))
EJECT_BASE_S = float(os.environ.get("VPROXY_TPU_EJECT_BASE_S", "5"))
EJECT_CAP_S = float(os.environ.get("VPROXY_TPU_EJECT_CAP_S", "300"))

# proxy-local connect failures (fd/port/buffer exhaustion on OUR side):
# not evidence against the backend — they must not feed its ejection
# streak, or an overloaded proxy ejects its whole healthy pool
import errno as _errno
LOCAL_ERRNOS = frozenset({
    _errno.EMFILE, _errno.ENFILE, _errno.EADDRNOTAVAIL,
    _errno.EADDRINUSE, _errno.ENOBUFS, _errno.ENOMEM,
})


@dataclass
class HealthCheckConfig:
    """check/HealthCheckConfig + the hc annotations of AnnotatedHcConfig
    (ConnectClient.java:166-290): http checks GET a url and accept the
    configured status classes (default 1xx-4xx), dns checks resolve a
    domain against the backend as nameserver."""
    timeout_ms: int = 2000
    period_ms: int = 5000
    up: int = 2
    down: int = 3
    protocol: str = "tcp"  # none | tcp | tcpDelay | dns | http
    http_method: str = "GET"
    http_url: str = "/"
    http_host: Optional[str] = None
    http_status: tuple = (1, 2, 3, 4)  # accepted status/100 classes
    dns_domain: str = "example.com"


@dataclass(eq=False)  # identity eq/hash: handles live in exclude-sets
class ServerHandle:
    name: str
    ip: str
    port: int
    weight: int
    healthy: bool = False
    conn_count: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    logic_delete: bool = False
    host_name: Optional[str] = None
    check_cost_ms: float = -1.0  # tcpDelay: last successful connect cost
    _up_cnt: int = 0
    _down_cnt: int = 0
    # passive outlier-ejection state (ServerGroup.report_failure)
    _consec_fails: int = 0       # consecutive data-plane connect failures
    ejected: bool = False        # down via passive ejection (not hc edge)
    _eject_backoff_s: float = 0.0  # last applied backoff (doubles per eject)
    _eject_until: float = 0.0    # monotonic re-admission gate

    @property
    def is_v4(self) -> bool:
        return ":" not in self.ip


class _HealthChecker:
    """Periodic nonblocking connect on the group's event loop; edge-triggered
    up/down transitions after N consecutive successes/failures."""

    def __init__(self, loop: SelectorEventLoop, group: "ServerGroup",
                 svr: ServerHandle):
        self.loop = loop
        self.group = group
        self.svr = svr
        self.stopped = False
        self._periodic = None
        loop.run_on_loop(self._start)

    def _start(self) -> None:
        if self.stopped:
            return
        cfg = self.group.hc
        if cfg.protocol == "none":
            self._result(True)
            self._periodic = self.loop.period(cfg.period_ms, lambda: self._result(True))
            return
        self._periodic = self.loop.period(cfg.period_ms, self._check_once)
        self._check_once()

    def _check_once(self) -> None:
        if self.stopped:
            return
        if failpoint.hit("hc.force_down",
                         f"{self.group.alias}/{self.svr.name} "
                         f"{self.svr.ip}:{self.svr.port}"):
            self._result(False)
            return
        cfg = self.group.hc
        if cfg.protocol == "http":
            self._check_http(cfg)
        elif cfg.protocol == "dns":
            self._check_dns(cfg)
        else:
            self._check_tcp(cfg)

    def _check_tcp(self, cfg: HealthCheckConfig) -> None:
        import time as _time
        try:
            fd = vtl.tcp_connect(self.svr.ip, self.svr.port)
        except OSError:
            self._result(False)
            return
        state = {"done": False}
        t0 = _time.monotonic()

        def finish(ok: bool) -> None:
            if state["done"]:
                return
            state["done"] = True
            if self.loop.registered(fd):
                self.loop.remove(fd)
            vtl.close(fd)
            if ok and cfg.protocol == "tcpDelay":
                self.svr.check_cost_ms = (_time.monotonic() - t0) * 1000.0
            self._result(ok)

        def on_ev(_fd: int, ev: int) -> None:
            finish(vtl.finish_connect(fd) == 0)

        self.loop.add(fd, vtl.EV_WRITE, on_ev)
        self.loop.delay(cfg.timeout_ms, lambda: finish(False))

    def _check_http(self, cfg: HealthCheckConfig) -> None:
        """connect, send one request, parse the status line; up iff the
        status class is in cfg.http_status (ConnectClient.java:166-215)."""
        from ..net.connection import Connection, Handler

        state = {"done": False, "buf": b"", "conn": None}

        def finish(ok: bool) -> None:
            if state["done"]:
                return
            state["done"] = True
            if state["conn"] is not None:
                state["conn"].close()
            self._result(ok)

        host = cfg.http_host or self.svr.host_name or self.svr.ip

        class H(Handler):
            def on_connected(_s, conn) -> None:
                conn.write((f"{cfg.http_method} {cfg.http_url} HTTP/1.1\r\n"
                            f"Host: {host}\r\nConnection: close\r\n\r\n"
                            ).encode())

            def on_data(_s, conn, data) -> None:
                state["buf"] += data
                if b"\r\n" not in state["buf"]:
                    if len(state["buf"]) > 4096:
                        finish(False)
                    return
                line = state["buf"].split(b"\r\n", 1)[0].split()
                if len(line) < 2 or not line[0].startswith(b"HTTP/"):
                    finish(False)
                    return
                try:
                    status = int(line[1])
                except ValueError:
                    finish(False)
                    return
                finish(100 <= status < 600 and
                       status // 100 in cfg.http_status)

            def on_eof(_s, conn) -> None:
                finish(False)

            def on_closed(_s, conn, err) -> None:
                finish(False)

        def start() -> None:
            try:
                # failpoints=False: the probe must not consume the data
                # plane's count-armed backend.connect.* faults (probes
                # have their own site, hc.force_down)
                c = Connection.connect(self.loop, self.svr.ip,
                                       self.svr.port, failpoints=False)
            except OSError:
                finish(False)
                return
            state["conn"] = c
            c.set_handler(H())
            self.loop.delay(cfg.timeout_ms, lambda: finish(False))
        start()

    def _check_dns(self, cfg: HealthCheckConfig) -> None:
        """resolve cfg.dns_domain with the backend as the nameserver; up
        iff a well-formed answer comes back (ConnectClient.java:286-290)."""
        from ..dns import packet as P
        from ..dns.client import DNSClient

        state = {"done": False}
        client = DNSClient(self.loop, [(self.svr.ip, self.svr.port)],
                           timeout_ms=cfg.timeout_ms, max_retry=1)

        def cb(resp, err) -> None:
            if state["done"]:
                return
            state["done"] = True
            # cb runs inside the client's recvfrom loop: closing the fd
            # here would make that loop read a dead (or reused) fd
            self.loop.next_tick(client.close)
            self._result(err is None and resp is not None)

        client.query(cfg.dns_domain, P.A, cb)

    def _result(self, ok: bool) -> None:
        if self.stopped:
            return
        s = self.svr
        cfg = self.group.hc
        if ok:
            s._up_cnt += 1
            s._down_cnt = 0
            if not s.healthy and s._up_cnt >= cfg.up:
                if s.ejected:
                    # passively ejected: each passing active probe halves
                    # the remaining backoff; the healthy flip waits for
                    # the (shrinking) re-admission gate to expire
                    now = time.monotonic()
                    if now < s._eject_until:
                        s._eject_until = now + (s._eject_until - now) / 2.0
                        return
                    self.group._readmit(s)
                    return
                s.healthy = True
                # fresh UP edge starts a fresh ejection streak: stale
                # pre-downtime failures must not let one post-recovery
                # blip eject the server
                s._consec_fails = 0
                self.group._notify(s, True)
        else:
            s._down_cnt += 1
            s._up_cnt = 0
            if s.healthy and s._down_cnt >= cfg.down:
                s.healthy = False
                self.group._notify(s, False)
            elif not s.healthy and s._down_cnt == cfg.down:
                self.group._notify(s, False)

    def stop(self) -> None:
        self.stopped = True
        if self._periodic is not None:
            self.loop.run_on_loop(self._periodic.cancel)


class Connector:
    """How to reach a chosen backend (SvrHandleConnector analog)."""

    def __init__(self, svr: ServerHandle, group: "ServerGroup"):
        self.svr = svr
        self.group = group
        self.ip = svr.ip
        self.port = svr.port


class ServerGroup:
    METHODS = ("wrr", "wlc", "source")

    def __init__(self, alias: str, elg: EventLoopGroup,
                 hc: Optional[HealthCheckConfig] = None, method: str = "wrr",
                 annotations: Optional[HintRule] = None):
        if method not in self.METHODS:
            raise ValueError(f"unsupported method {method}")
        self.alias = alias
        self.elg = elg
        self.hc = hc or HealthCheckConfig()
        self.method = method
        self.annotations = annotations or HintRule()
        self.servers: list[ServerHandle] = []
        self._checkers: dict[str, _HealthChecker] = {}
        self._listeners: list[Callable[[ServerHandle, bool], None]] = []
        # generic change listeners: fired on EVERY health edge AND every
        # membership/weight recalc (the superset of on_health_change).
        # The accept lanes subscribe their generation bump here so any
        # mutation of the routable set invalidates the C lane entry.
        # Callbacks may run under the group lock (recalc paths) and must
        # not take group locks themselves — bump-and-defer only.
        self._change_listeners: list = []
        # bumped on every health edge and membership/weight recalc: a
        # cheap staleness token for answer caches (dns/server.py) that
        # must never serve a backend past its DOWN edge
        self.health_version = 0
        self._lock = threading.Lock()
        self._wrr_seq: list[int] = []
        self._wrr_servers: list[ServerHandle] = []
        self._wrr_cursor = 0
        self._wrr_cache: dict[str, tuple] = {}
        # maglev state for method=source (rules/maglev.py): table per
        # family over the HEALTHY member set, rebuilt lazily when the
        # health_version token moves — identity-keyed permutations mean
        # a membership/health edge moves only the affected backend's
        # slots, never reshuffles the group
        self._maglev_prev: dict = {}   # cache key -> (table, names)
        # flow_hash(ip) is pure in the address bytes, so the memo
        # survives rebuilds (slot = h % m is re-derived per pick); it
        # is what keeps the maglev pick at WRR cost on the accept path
        self._maglev_hash: dict = {}   # ip bytes -> flow_hash
        # one-slot (fam, hv, servers, tlist, m) view of _maglev_state:
        # the pick hot path allocates NOTHING reading it (a per-call
        # cache-key tuple doubles gen0 GC pressure vs the wrr path —
        # that was the measured p99 tail, not the lookup itself)
        self._maglev_fast: Optional[tuple] = None
        self.maglev_last_remap = 0.0   # last rebuild's churn fraction

    # ------------------------------------------------------------- admin

    def add(self, name: str, ip: str, port: int, weight: int = 10) -> ServerHandle:
        with self._lock:
            if any(s.name == name for s in self.servers):
                raise ValueError(f"server {name} already exists in {self.alias}")
            s = ServerHandle(name=name, ip=ip, port=port, weight=weight)
            self.servers.append(s)
            self._recalc()
            self._checkers[name] = _HealthChecker(self.elg.next(), self, s)
        return s

    def remove(self, name: str) -> None:
        removed = None
        with self._lock:
            for i, s in enumerate(self.servers):
                if s.name == name:
                    del self.servers[i]
                    self._recalc()
                    chk = self._checkers.pop(name, None)
                    if chk:
                        chk.stop()
                    removed = s
                    break
            else:
                raise KeyError(name)
        # removal IS a DOWN edge for listeners (outside the lock, like
        # every notify): a TcpLB's warm pools for the decommissioned
        # backend must drain now, not keep redialing its address forever
        self._notify(removed, False)

    def replace_ip(self, name: str, new_ip: str) -> None:
        """Swap a server's address in place (ServerGroup.replaceIp
        :811-950): health state resets and the checker re-targets; used
        by the address updater when a hostname re-resolves."""
        swapped = None
        with self._lock:
            for s in self.servers:
                if s.name == name:
                    if s.ip == new_ip:
                        return
                    s.ip = new_ip
                    was_healthy, s.healthy = s.healthy, False
                    s._up_cnt = s._down_cnt = 0
                    # a new address is a new failure domain: drop any
                    # passive-eject state along with the hc counters
                    s.ejected = False
                    s._consec_fails = 0
                    s._eject_backoff_s = s._eject_until = 0.0
                    self._recalc()
                    # swap the checker under the lock: racing remove()
                    # must not resurrect a checker for a gone server
                    chk = self._checkers.pop(name, None)
                    if chk:
                        chk.stop()
                    self._checkers[name] = _HealthChecker(
                        self.elg.next(), self, s)
                    swapped = s if was_healthy else None
                    break
            else:
                raise KeyError(name)
        # down transition notifies like every health-checker edge does —
        # outside the lock, listeners may re-enter the group
        if swapped is not None:
            self._notify(swapped, False)

    def set_weight(self, name: str, weight: int) -> None:
        with self._lock:
            for s in self.servers:
                if s.name == name:
                    s.weight = weight
                    self._recalc()
                    return
        raise KeyError(name)

    def on_change(self, cb: Callable[[], None]) -> None:
        self._change_listeners.append(cb)

    def off_change(self, cb: Callable[[], None]) -> None:
        try:
            self._change_listeners.remove(cb)
        except ValueError:
            pass

    def _fire_change(self) -> None:
        for cb in list(self._change_listeners):
            try:
                cb()
            except Exception:
                pass

    def on_health_change(self, cb: Callable[[ServerHandle, bool], None]) -> None:
        self._listeners.append(cb)

    def off_health_change(self, cb: Callable[[ServerHandle, bool], None]) -> None:
        """Unregister (idempotent): a stopped TcpLB's pool-drain listener
        must not keep firing — or keep the LB alive — forever."""
        try:
            self._listeners.remove(cb)
        except ValueError:
            pass

    def _notify(self, svr: ServerHandle, up: bool) -> None:
        from ..utils import events
        self.health_version += 1
        events.record("hc_up" if up else "hc_down",
                      f"{self.alias}/{svr.name} {svr.ip}:{svr.port} "
                      + ("UP" if up else "DOWN"),
                      group=self.alias, server=svr.name)
        for cb in self._listeners:
            cb(svr, up)
        self._fire_change()

    # ---------------------------------------- passive outlier ejection

    def report_failure(self, svr: ServerHandle, err: int = 0) -> None:
        """Data-plane connect failure/timeout against svr. N consecutive
        failures ejects it immediately — the same DOWN edge the health
        checker drives, but at one-RTT detection latency — with
        exponential backoff re-admission (base EJECT_BASE_S, doubling to
        EJECT_CAP_S; passing active probes halve the remaining wait).
        `err` (errno, when the caller has it) filters out proxy-local
        failures that say nothing about the backend."""
        if err in LOCAL_ERRNOS:
            return
        from ..utils import events
        eject = False
        with self._lock:
            svr._consec_fails += 1
            if svr._consec_fails >= EJECT_FAILURES and svr.healthy:
                # ejection floor: never empty the pool. With no other
                # healthy backend, a possibly-flaky server beats a
                # guaranteed full-group blackout (the hc still owns the
                # hard-down edge for genuinely dead backends).
                if not any(s.healthy and s.weight > 0 and s is not svr
                           for s in self.servers):
                    if svr._consec_fails == EJECT_FAILURES:
                        events.record(
                            "eject_skipped",
                            f"{self.alias}/{svr.name} over the failure "
                            "threshold but is the last healthy backend",
                            group=self.alias, server=svr.name)
                    return
                svr.healthy = False
                svr.ejected = True
                svr._up_cnt = svr._down_cnt = 0
                backoff = (EJECT_BASE_S if svr._eject_backoff_s <= 0
                           else min(svr._eject_backoff_s * 2, EJECT_CAP_S))
                svr._eject_backoff_s = backoff
                svr._eject_until = time.monotonic() + backoff
                eject = True
        if eject:
            self._eject_counter().incr()
            events.record(
                "eject", f"{self.alias}/{svr.name} {svr.ip}:{svr.port} "
                f"EJECTED after {svr._consec_fails} connect failures, "
                f"backoff {svr._eject_backoff_s:.0f}s",
                group=self.alias, server=svr.name,
                fails=svr._consec_fails, backoff_s=svr._eject_backoff_s)
            self._notify(svr, False)

    def report_success(self, svr: ServerHandle) -> None:
        """Data-plane connect success against svr: clears the consecutive
        failure streak and decays the eject backoff back to base so the
        next ejection doesn't inherit a stale doubled penalty."""
        with self._lock:
            svr._consec_fails = 0
            if not svr.ejected:
                svr._eject_backoff_s = 0.0

    def _readmit(self, svr: ServerHandle) -> None:
        """Re-admission edge (health checker, backoff expired + up
        threshold met): same UP notify path as an hc edge."""
        from ..utils import events
        with self._lock:
            if not svr.ejected:
                return
            svr.ejected = False
            svr.healthy = True
            svr._consec_fails = 0
            svr._eject_until = 0.0
        events.record(
            "readmit", f"{self.alias}/{svr.name} {svr.ip}:{svr.port} "
            "re-admitted after eject backoff",
            group=self.alias, server=svr.name)
        self._notify(svr, True)

    def _eject_counter(self):
        from ..utils.metrics import GlobalInspection
        return GlobalInspection.get().get_counter(
            "vproxy_group_ejections_total", group=self.alias)

    def close(self) -> None:
        for chk in self._checkers.values():
            chk.stop()
        self._checkers.clear()

    # --------------------------------------------------------- balancing

    def _recalc(self) -> None:
        self.health_version += 1  # membership/weight change
        self._wrr_cache.clear()
        self._fire_change()  # lane-entry invalidation (bump-and-defer)

    @staticmethod
    def _wrr_compute(servers: list[ServerHandle]) -> list[int]:
        """The reference's subtract-sum sequence: repeatedly pick max-weight
        index, subtract the total, re-add originals until all zero."""
        if not servers:
            return []
        weights = [s.weight for s in servers]
        original = list(weights)
        total = sum(weights)
        seq: list[int] = []
        while True:
            idx = max(range(len(weights)), key=lambda i: (weights[i], -i))
            seq.append(idx)
            weights[idx] -= total
            if all(w == 0 for w in weights):
                break
            for i in range(len(weights)):
                weights[i] += original[i]
            total = sum(weights)
        # random rotation so multiple identical instances don't sync
        start = random.randrange(len(seq))
        return seq[start:] + seq[:start]

    def _subset(self, fam: Optional[str]) -> list[ServerHandle]:
        out = [s for s in self.servers if s.weight > 0]
        if fam == "v4":
            out = [s for s in out if s.is_v4]
        elif fam == "v6":
            out = [s for s in out if not s.is_v4]
        return out

    def _wrr_state(self, fam: Optional[str]):
        key = fam or "all"
        st = self._wrr_cache.get(key)
        if st is None:
            servers = self._subset(fam)
            st = {"servers": servers, "seq": self._wrr_compute(servers),
                  "cursor": 0}
            self._wrr_cache[key] = st
        return st

    def next(self, source_ip: Optional[bytes] = None,
             fam: Optional[str] = None,
             exclude: Optional[set] = None) -> Optional[Connector]:
        """exclude: ServerHandles already tried this session (connect
        retry must not re-dial the backend that just refused)."""
        if self.method == "wlc":
            return self._wlc_next(fam, exclude)
        if self.method == "source":
            return self._source_next(source_ip or b"", fam, exclude)
        return self._wrr_next(fam, exclude)

    def _wrr_next(self, fam, exclude=None) -> Optional[Connector]:
        with self._lock:
            st = self._wrr_state(fam)
            seq, servers = st["seq"], st["servers"]
            for _ in range(len(seq) + 1):
                if not seq:
                    return None
                idx = st["cursor"] % len(seq)
                st["cursor"] = idx + 1
                s = servers[seq[idx]]
                if s.healthy and not (exclude and s in exclude):
                    return Connector(s, self)
            return None

    def _wlc_next(self, fam, exclude=None) -> Optional[Connector]:
        with self._lock:
            servers = [s for s in self._subset(fam)
                       if s.healthy and not (exclude and s in exclude)]
            if not servers:
                return None
            m = servers[0]
            for s in servers[1:]:
                if m.conn_count * s.weight > s.conn_count * m.weight:
                    m = s
            return Connector(m, self)

    @staticmethod
    def _sdbm(data: bytes) -> int:
        """The reference's sdbm source hash — kept for provenance; the
        source method now rides the Maglev table (_source_next), whose
        consistency bound sdbm%N lacks entirely (one membership change
        under sdbm remaps (N-1)/N of clients; Maglev moves only the
        changed backend's share)."""
        h = 0
        for b in data:
            sb = b - 256 if b > 127 else b  # signed byte like Java
            h = (sb + (h << 6) + (h << 16) - h) & 0xFFFFFFFF
        if h & 0x80000000:
            h = (~h + 1) & 0xFFFFFFFF  # abs in int32 space
            if h & 0x80000000:  # Integer.MIN_VALUE edge
                h = 0
        return h

    def maglev_identity(self, s: ServerHandle) -> str:
        """The backend's stable maglev identity: the SAME string the
        lane compiler hashes (components/lanes.py), so the C-plane pick
        and this python pick agree bit-for-bit at a given generation."""
        return f"{self.alias}|{s.ip}:{s.port}"

    def _maglev_state(self, fam) -> dict:
        """Per-family maglev table over the healthy, weighted, live
        members — rebuilt when health_version moves (a dead backend's
        slots fall to survivors; everyone else keeps their backend) and
        dropped wholesale by _recalc's cache clear on membership
        edits. Caller holds the group lock."""
        key = ("maglev", fam or "all")
        st = self._wrr_cache.get(key)
        if st is not None and st["hv"] == self.health_version:
            return st
        MG = _maglev
        servers = [s for s in self._subset(fam)
                   if s.healthy and not s.logic_delete]
        names = [self.maglev_identity(s) for s in servers]
        tab = MG.build_table(list(zip(names, (s.weight for s in servers))),
                             MG.GROUP_M)
        prev = self._maglev_prev.get(key)
        self.maglev_last_remap = MG.remap_fraction(
            prev[0] if prev else None, tab,
            prev[1] if prev else None, names)
        self._maglev_prev[key] = (tab, names)
        # tlist: plain-int list view of the table — numpy scalar indexing
        # is ~5x a list load and next_source is the accept hot path
        st = {"hv": self.health_version, "servers": servers, "table": tab,
              "tlist": tab.tolist()}
        self._wrr_cache[key] = st
        return st

    def maglev_info(self) -> dict:
        """Detail-surface view (list-detail tcp-lb / HTTP detail)."""
        if self.method != "source":
            return {"on": False}
        with self._lock:
            st = self._maglev_state(None)
        return {"on": True, "m": int(len(st["table"])),
                "backends": len(st["servers"]),
                "last_remap": round(self.maglev_last_remap, 4)}

    def maglev_table(self, fam=None):
        """(servers, table) snapshot for the current health generation
        — the lane compiler and the parity tests read this."""
        with self._lock:
            st = self._maglev_state(fam)
            return list(st["servers"]), st["table"]

    def _source_next(self, source_ip: bytes, fam,
                     exclude=None) -> Optional[Connector]:
        """Source affinity via the Maglev table: one FNV over the client
        address + one slot load (the table already holds only healthy
        members, so the probe loop only runs for retry excludes). A
        resize moves ~weight-share of clients instead of sdbm%N's
        near-total reshuffle; the same hash/table contract as the C
        accept lanes (tests/test_maglev.py parity)."""
        with self._lock:
            fast = self._maglev_fast
            if (fast is None or fast[0] != fam
                    or fast[1] != self.health_version):
                st = self._maglev_state(fam)
                fast = self._maglev_fast = (fam, st["hv"], st["servers"],
                                            st["tlist"], len(st["tlist"]))
            _fam, _hv, servers, tab, m = fast
            if not servers:
                return None
            hc = self._maglev_hash
            h = hc.get(source_ip)
            if h is None:
                if len(hc) >= 16384:  # bounded: clear beats LRU churn
                    hc.clear()
                h = hc[source_ip] = _maglev.flow_hash(source_ip)
            slot = h % m
            idx = tab[slot]
            if idx >= 0:  # the hot path: one hash + one slot load
                s = servers[idx]
                if s.healthy and not (exclude and s in exclude):
                    return Connector(s, self)
            # probe forward (retry excludes / a health edge racing the
            # rebuild): next slots' owners, dedup'd, bounded
            tried = {idx} if idx >= 0 else set()
            for k in range(1, m):
                idx = tab[(slot + k) % m]
                if idx < 0 or idx in tried:
                    continue
                s = servers[idx]
                if s.healthy and not (exclude and s in exclude):
                    return Connector(s, self)
                tried.add(idx)
                if len(tried) >= len(servers):
                    return None
            return None
