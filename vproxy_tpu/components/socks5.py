"""Socks5Server — SOCKS5 proxy with upstream-backed target selection.

Parity: component/app/Socks5Server.java — domain CONNECTs become
Hint.ofHostPort lookups into the upstream (:63-66); IP CONNECTs are
matched against the backend server list (:73-82); unmatched targets are
only honored when allow_non_backend is set (direct connect). Handshake
is the RFC 1928 no-auth flow (socks/Socks5ProxyProtocolHandler.java).
After the reply, the session drops into the native splice pump.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from ..net import vtl
from ..net.connection import Connection, Handler, ServerSock
from ..rules.ir import Hint, Proto
from ..utils.ip import format_ip, is_ip_literal, parse_ip
from .elgroup import EventLoopGroup
from .secgroup import SecurityGroup
from .servergroup import Connector
from .tcplb import TcpLB
from .upstream import Upstream

VER = 5
CMD_CONNECT = 1
ATYP_V4, ATYP_DOMAIN, ATYP_V6 = 1, 3, 4
REP_OK, REP_FAIL, REP_NOT_ALLOWED, REP_HOST_UNREACH, REP_CMD_UNSUP = 0, 1, 2, 4, 7


class Socks5Server(TcpLB):
    """Same resource shape as TcpLB (bind, elgroups, upstream, secgroup)
    with the SOCKS5 handshake instead of http/tcp classify."""

    # protocol reads "tcp" but every client speaks RFC 1928 first: the
    # C accept lanes must never raw-splice a SOCKS5 connection
    lanes_capable = False

    def __init__(self, alias: str, acceptor: EventLoopGroup,
                 worker: EventLoopGroup, bind_ip: str, bind_port: int,
                 backend: Upstream,
                 security_group: Optional[SecurityGroup] = None,
                 allow_non_backend: bool = False,
                 in_buffer_size: int = 65536, timeout_ms: int = 900_000):
        super().__init__(alias, acceptor, worker, bind_ip, bind_port, backend,
                         protocol="tcp", security_group=security_group,
                         in_buffer_size=in_buffer_size, timeout_ms=timeout_ms)
        self.allow_non_backend = allow_non_backend

    # override: every accepted conn goes through the handshake
    def _serve(self, loop, cfd: int, ip: str, port: int,
               t_acc=None, tid: int = 0) -> None:
        # tid: the accept path's trace context (unused here — the RFC
        # 1928 session has no span instrumentation yet)
        _Socks5Session(self, loop, cfd, ip, port)

    # ---------------------------------------------------------- selection

    def pick_target_async(self, client_ip: bytes, atyp: int, addr, port: int,
                          cb, loop=None) -> None:
        """Async pick_target: the domain classify rides the
        ClassifyService micro-batch queue; cb(connector, direct_addr)."""
        if atyp == ATYP_DOMAIN:
            def on_conn(c) -> None:
                if c is not None:
                    cb(c, None)
                elif self.allow_non_backend:
                    cb(None, (addr, port))
                else:
                    cb(None, None)
            self.backend.seek_async(client_ip, Hint.of_host_port(addr, port),
                                    on_conn, loop=loop)
            return
        cb(*self._pick_literal(addr, port))

    def pick_target(self, client_ip: bytes, atyp: int, addr, port: int
                    ) -> tuple[Optional[Connector], Optional[tuple[str, int]]]:
        """-> (connector, direct_addr). Only one is non-None on success."""
        if atyp == ATYP_DOMAIN:
            c = self.backend.seek(client_ip, Hint.of_host_port(addr, port))
            if c is not None:
                return c, None
            if self.allow_non_backend:
                return None, (addr, port)
            return None, None
        return self._pick_literal(addr, port)

    def _pick_literal(self, addr, port: int
                      ) -> tuple[Optional[Connector], Optional[tuple[str, int]]]:
        ip_str = format_ip(addr)
        # match the literal ip:port against known backend servers
        for h in self.backend.handles:
            for s in h.group.servers:
                if s.port == port and s.ip == ip_str and s.healthy:
                    return Connector(s, h.group), None
        if self.allow_non_backend:
            return None, (ip_str, port)
        return None, None


class _Socks5Session(Handler):
    ST_GREETING, ST_REQUEST, ST_DONE = range(3)

    def __init__(self, server: Socks5Server, loop, cfd: int, ip: str, port: int):
        self.server = server
        self.loop = loop
        self.client_ip = ip
        self.buf = bytearray()
        self.state = self.ST_GREETING
        self.conn = Connection(loop, cfd, (ip, port))
        self.conn.set_handler(self)

    def on_data(self, conn: Connection, data: bytes) -> None:
        self.buf += data
        if self.state == self.ST_GREETING:
            self._try_greeting(conn)
        if self.state == self.ST_REQUEST:
            self._try_request(conn)

    def _try_greeting(self, conn: Connection) -> None:
        if len(self.buf) < 2:
            return
        ver, n = self.buf[0], self.buf[1]
        if ver != VER:
            conn.close()
            return
        if len(self.buf) < 2 + n:
            return
        methods = self.buf[2: 2 + n]
        del self.buf[: 2 + n]
        if 0 not in methods:  # only no-auth supported
            conn.write(b"\x05\xff")
            self.loop.delay(20, conn.close)
            self.state = self.ST_DONE
            return
        conn.write(b"\x05\x00")
        self.state = self.ST_REQUEST

    def _try_request(self, conn: Connection) -> None:
        if len(self.buf) < 4:
            return
        ver, cmd, _rsv, atyp = self.buf[:4]
        if ver != VER:
            conn.close()
            return
        if atyp == ATYP_V4:
            need = 4 + 4 + 2
        elif atyp == ATYP_V6:
            need = 4 + 16 + 2
        elif atyp == ATYP_DOMAIN:
            if len(self.buf) < 5:
                return
            need = 4 + 1 + self.buf[4] + 2
        else:
            self._reply(conn, REP_FAIL)
            return
        if len(self.buf) < need:
            return
        if cmd != CMD_CONNECT:
            self._reply(conn, REP_CMD_UNSUP)
            return
        if atyp == ATYP_DOMAIN:
            dlen = self.buf[4]
            addr = bytes(self.buf[5:5 + dlen]).decode("latin-1")
            port = struct.unpack(">H", self.buf[5 + dlen:7 + dlen])[0]
        else:
            alen = 4 if atyp == ATYP_V4 else 16
            addr = bytes(self.buf[4:4 + alen])
            port = struct.unpack(">H", self.buf[4 + alen:6 + alen])[0]
        del self.buf[:need]
        self.state = self.ST_DONE

        # retries re-run THIS selection (hint-only seek) minus tried —
        # a CONNECT to db.example:5432 must never fail over to a backend
        # of some other service
        hint = (Hint.of_host_port(addr, port) if atyp == ATYP_DOMAIN
                else None)

        def picked(connector, direct) -> None:
            if conn.closed:
                return
            if connector is None and direct is None:
                self._reply(conn, REP_NOT_ALLOWED)
                return
            target = (connector.ip, connector.port) if connector else direct
            self._connect_and_splice(conn, connector, target, set(), hint)

        self.server.pick_target_async(
            parse_ip(self.client_ip), atyp, addr, port, picked, self.loop)

    def _reply(self, conn: Connection, rep: int) -> None:
        conn.write(b"\x05" + bytes([rep]) + b"\x00\x01\x00\x00\x00\x00\x00\x00")
        if rep != REP_OK:
            self.loop.delay(20, conn.close)

    def _connect_and_splice(self, conn: Connection, connector, target,
                            tried=None, hint=None) -> None:
        svr = connector.svr if connector else None
        if svr is not None:
            svr.conn_count += 1
        self.server._sessions_delta(1)
        # stop pulling client bytes into python: whatever is already in
        # session.buf is flushed to the backend at handover; everything
        # later stays in the kernel buffer for the pump
        conn.pause_reading()
        host, port = target
        if is_ip_literal(host):
            self._do_connect(conn, svr, host, port, self._mk_release(svr),
                             connector=connector, tried=tried, hint=hint)
            return
        # direct (allow_non_backend) domain target: resolve off-loop, then
        # continue on the loop (Socks5Server.java resolves via Resolver)
        release = self._mk_release(svr)

        def resolve() -> None:
            try:
                infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
                ip = infos[0][4][0]
            except OSError:
                ip = None
            self.loop.run_on_loop(lambda: cont(ip))

        def cont(ip: Optional[str]) -> None:
            if conn.closed:
                release()
                return
            if ip is None:
                release()
                self._reply(conn, REP_HOST_UNREACH)
                return
            self._do_connect(conn, svr, ip, port, release)

        threading.Thread(target=resolve, name="socks5-resolve", daemon=True).start()

    def _mk_release(self, svr):
        lb = self.server
        released = [False]

        def release() -> None:
            if released[0]:
                return
            released[0] = True
            if svr is not None:
                svr.conn_count -= 1
            lb._sessions_delta(-1)
        return release

    def _retry_backend(self, conn: Connection, tried: set, hint) -> bool:
        """Pre-reply backend connect failed: re-run the ORIGINAL
        hint-only selection (never the global WRR — the client named a
        target) minus tried, under the shared TcpLB retry gate. Literal
        ip:port targets have no hint and therefore no alternatives; they
        don't retry. True when a new attempt owns the session."""
        lb = self.server
        if conn.closed or conn.detached or hint is None:
            return False
        src_ip = parse_ip(self.client_ip)
        c = lb._take_retry_slot(
            tried, f"socks5 {self.client_ip}",
            lambda: lb.backend.seek_host(src_ip, hint, exclude=tried))
        if c is None:
            return False
        self._connect_and_splice(conn, c, (c.ip, c.port), tried, hint)
        return True

    def _do_connect(self, conn: Connection, svr, ip: str, port: int,
                    release, connector=None, tried=None,
                    hint=None) -> None:
        lb = self.server
        session = self
        group = connector.group if connector is not None else None
        try:
            # bounded connect for BACKEND targets only: a SYN blackhole
            # times out into the same on_closed retry path a refusal
            # takes. Direct (allow-non-backend) targets are arbitrary
            # internet hosts with no retry alternative — they keep the
            # kernel's own connect deadline.
            back = Connection.connect(
                self.loop, ip, port,
                timeout_ms=(lb.connect_timeout_ms
                            if connector is not None else 0))
        except OSError as e:
            retried = False
            if group is not None and tried is not None:
                tried.add(svr)
                group.report_failure(svr, e.errno or 0)
                retried = self._retry_backend(conn, tried, hint)
            # release AFTER the retry decision: the new attempt's
            # increment keeps active_sessions from dipping to 0, which
            # drain_wait would misread as "drained"
            release()
            if not retried:
                self._reply(conn, REP_HOST_UNREACH)
            return
        class Back(Handler):
            connected = False

            def on_connected(self, bconn: Connection) -> None:
                self.connected = True
                if group is not None:
                    group.report_success(svr)
                    if tried:  # a retry attempt landed
                        lb._retries_total("success").incr()
                # keep early backend bytes in the kernel buffer for the pump
                bconn.pause_reading()
                session._reply(conn, REP_OK)
                leftover = bytes(session.buf)
                if leftover:
                    bconn.write(leftover)
                if bconn.out:
                    return
                self._handover(bconn)

            def on_drained(self, bconn: Connection) -> None:
                self._handover(bconn)

            def _handover(self, bconn: Connection) -> None:
                if bconn.detached or bconn.closed:
                    return
                if conn.closed:
                    # client went away before handover: drop the backend
                    # (on_closed below releases the counters)
                    bconn.close()
                    return
                front_desc = (f"{conn.remote[0]}:{conn.remote[1]}"
                              if conn.remote else "?")
                ffd = conn.detach()
                bfd = bconn.detach()
                if not vtl.pump_sets_nodelay():  # pre-r6 .so only
                    vtl.set_nodelay(ffd)
                    vtl.set_nodelay(bfd)
                pid = session.loop.pump(ffd, bfd, lb.in_buffer_size,
                                        self._done)
                self._pid = pid
                # session/connection listing + the idle-timeout sweep
                # (the reference's tcpTimeout covers socks5 sessions too)
                lb._watch_pump(session.loop, pid,
                               f"{front_desc} -> {ip}:{port}")

            def _done(self, a2b: int, b2a: int, err: int) -> None:
                lb._unwatch_pump(session.loop, getattr(self, "_pid", None))
                lb.bytes_in += a2b
                lb.bytes_out += b2a
                if svr is not None:
                    svr.bytes_in += a2b
                    svr.bytes_out += b2a
                release()

            def on_closed(self, bconn: Connection, err: int) -> None:
                retried = False
                if not (conn.closed or conn.detached) \
                        and not self.connected and group is not None \
                        and tried is not None:
                    # nonblocking connect failed asynchronously: same
                    # retry re-entry as the sync raise above
                    tried.add(svr)
                    group.report_failure(svr, -err if err < 0 else err)
                    retried = session._retry_backend(conn, tried, hint)
                release()  # after the retry decision: no count dip
                if retried or conn.closed or conn.detached:
                    return
                session._reply(conn, REP_HOST_UNREACH)

        back.set_handler(Back())

    def on_eof(self, conn: Connection) -> None:
        conn.close()
