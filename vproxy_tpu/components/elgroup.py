"""EventLoopGroup — named set of worker loops with round-robin next().

Analog of component/elgroup/EventLoopGroup.java (round-robin next()
:188-207, attach/detach resource lifecycle, named event-loop add/remove).
Worker topology follows app/Application.java:83-114: one control loop +
N worker loops.
"""
from __future__ import annotations

import itertools
import traceback
from typing import Optional

from ..net.eventloop import SelectorEventLoop


class EventLoopGroup:
    def __init__(self, name: str, n_loops: int = 1):
        self.name = name
        self._loops: dict[str, SelectorEventLoop] = {}
        self._rr = itertools.count()
        self._closed = False
        self._resources: list = []
        for i in range(n_loops):
            self.add_loop(f"{name}-{i}")

    @property
    def loops(self) -> list[SelectorEventLoop]:
        return list(self._loops.values())

    def loop_names(self) -> list[str]:
        return list(self._loops.keys())

    def add_loop(self, name: str) -> SelectorEventLoop:
        if name in self._loops:
            raise ValueError(f"event-loop {name} already exists in {self.name}")
        lp = SelectorEventLoop(name)
        lp.on_death.append(self._loop_died)
        lp.loop_thread()
        self._loops[name] = lp
        return lp

    def _loop_died(self, lp: SelectorEventLoop) -> None:
        """A member loop stopped (crash or close). Unless the whole group
        is shutting down, attached resources re-home their bindings —
        the reference's LBAttach / DNSServer EventLoopAttach semantics
        (TcpLB.java:45-66, DNSServer.java:89-106)."""
        if self._closed:
            return
        for k, v in list(self._loops.items()):
            if v is lp:
                del self._loops[k]
        for r in list(self._resources):
            cb = getattr(r, "on_loop_death", None)
            if cb is None:
                continue
            try:
                cb(self, lp)
            except Exception:
                traceback.print_exc()

    def remove_loop(self, name: str) -> None:
        lp = self._loops.pop(name, None)
        if lp is None:
            raise KeyError(name)
        lp.close()

    def get_loop(self, name: str) -> Optional[SelectorEventLoop]:
        return self._loops.get(name)

    def next(self) -> SelectorEventLoop:
        loops = self.loops
        if not loops:
            raise RuntimeError(f"event loop group {self.name} is empty")
        return loops[next(self._rr) % len(loops)]

    def attach(self, resource) -> None:
        if resource not in self._resources:
            self._resources.append(resource)

    def detach(self, resource) -> None:
        if resource in self._resources:
            self._resources.remove(resource)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for r in list(self._resources):
            closer = getattr(r, "on_group_close", None)
            if closer:
                closer()
        for lp in self.loops:
            lp.close()
        self._loops.clear()
