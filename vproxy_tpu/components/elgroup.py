"""EventLoopGroup — named set of worker loops with round-robin next().

Analog of component/elgroup/EventLoopGroup.java (round-robin next()
:188-207, attach/detach resource lifecycle). Worker topology follows
app/Application.java:83-114: one control loop + N worker loops.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, Optional

from ..net.eventloop import SelectorEventLoop


class EventLoopGroup:
    def __init__(self, name: str, n_loops: int = 1):
        self.name = name
        self.loops: list[SelectorEventLoop] = []
        self._rr = itertools.count()
        self._closed = False
        self._resources: list = []
        for i in range(n_loops):
            lp = SelectorEventLoop(f"{name}-{i}")
            lp.loop_thread()
            self.loops.append(lp)

    def next(self) -> SelectorEventLoop:
        if not self.loops:
            raise RuntimeError(f"event loop group {self.name} is empty")
        return self.loops[next(self._rr) % len(self.loops)]

    def attach(self, resource) -> None:
        self._resources.append(resource)

    def detach(self, resource) -> None:
        if resource in self._resources:
            self._resources.remove(resource)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for r in list(self._resources):
            closer = getattr(r, "on_group_close", None)
            if closer:
                closer()
        for lp in self.loops:
            lp.close()
