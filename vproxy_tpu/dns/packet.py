"""DNS wire codec (RFC 1035 subset).

Parity target: the reference's dns/Formatter.java + DNSPacket/rdata/*
(A/AAAA/SRV/CNAME/TXT — base dns, SURVEY.md §2.1). Parsing handles
name-compression pointers; encoding writes uncompressed names (legal,
simpler, and responses stay under typical EDNS sizes for our record
counts).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

from ..utils.ip import format_ip, parse_ip

# qtype / type codes
A, NS, CNAME, SOA, PTR, TXT, AAAA, SRV, OPT, ANY = (
    1, 2, 5, 6, 12, 16, 28, 33, 41, 255)
CLASS_IN = 1


class DNSFormatError(Exception):
    pass


def _encode_name(name: str) -> bytes:
    if name and not name.endswith("."):
        name += "."
    out = bytearray()
    for label in name.split("."):
        if not label:
            continue
        raw = label.encode()
        if len(raw) > 63:
            raise DNSFormatError(f"label too long: {label!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    return bytes(out)


def _decode_name(data: bytes, off: int) -> tuple[str, int]:
    labels = []
    jumps = 0
    end = -1
    while True:
        if off >= len(data):
            raise DNSFormatError("truncated name")
        ln = data[off]
        if ln == 0:
            if end < 0:
                end = off + 1
            break
        if ln & 0xC0 == 0xC0:
            if off + 1 >= len(data):
                raise DNSFormatError("truncated pointer")
            ptr = ((ln & 0x3F) << 8) | data[off + 1]
            if end < 0:
                end = off + 2
            off = ptr
            jumps += 1
            if jumps > 64:
                raise DNSFormatError("compression loop")
            continue
        if off + 1 + ln > len(data):
            raise DNSFormatError("truncated label")
        labels.append(data[off + 1: off + 1 + ln].decode("latin-1"))
        off += 1 + ln
    return ".".join(labels) + ".", end


@dataclass
class Question:
    qname: str  # always with trailing dot
    qtype: int
    qclass: int = CLASS_IN


@dataclass
class Record:
    name: str
    rtype: int
    rclass: int = CLASS_IN
    ttl: int = 0
    # interpreted rdata (by rtype): A/AAAA -> ip bytes; CNAME/PTR -> str;
    # SRV -> (prio, weight, port, target); TXT -> list[bytes]; else raw bytes
    rdata: object = b""

    def _encode_rdata(self) -> bytes:
        if self.rtype in (A, AAAA):
            return bytes(self.rdata)
        if self.rtype in (CNAME, PTR, NS):
            return _encode_name(self.rdata)
        if self.rtype == SRV:
            prio, weight, port, target = self.rdata
            return struct.pack(">HHH", prio, weight, port) + _encode_name(target)
        if self.rtype == TXT:
            out = bytearray()
            for chunk in self.rdata:
                out.append(len(chunk))
                out += chunk
            return bytes(out)
        return bytes(self.rdata)


@dataclass
class Packet:
    id: int = 0
    is_resp: bool = False
    opcode: int = 0
    aa: bool = False
    tc: bool = False
    rd: bool = True
    ra: bool = False
    rcode: int = 0
    questions: list = field(default_factory=list)
    answers: list = field(default_factory=list)
    authorities: list = field(default_factory=list)
    additionals: list = field(default_factory=list)

    def encode(self) -> bytes:
        flags = 0
        if self.is_resp:
            flags |= 0x8000
        flags |= (self.opcode & 0xF) << 11
        if self.aa:
            flags |= 0x0400
        if self.tc:
            flags |= 0x0200
        if self.rd:
            flags |= 0x0100
        if self.ra:
            flags |= 0x0080
        flags |= self.rcode & 0xF
        out = bytearray(struct.pack(
            ">HHHHHH", self.id, flags, len(self.questions), len(self.answers),
            len(self.authorities), len(self.additionals)))
        for q in self.questions:
            out += _encode_name(q.qname) + struct.pack(">HH", q.qtype, q.qclass)
        for r in self.answers + self.authorities + self.additionals:
            rd = r._encode_rdata()
            out += _encode_name(r.name)
            out += struct.pack(">HHIH", r.rtype, r.rclass, r.ttl, len(rd))
            out += rd
        return bytes(out)


def _parse_record(data: bytes, off: int) -> tuple[Record, int]:
    name, off = _decode_name(data, off)
    if off + 10 > len(data):
        raise DNSFormatError("truncated record")
    rtype, rclass, ttl, rdlen = struct.unpack(">HHIH", data[off: off + 10])
    off += 10
    if off + rdlen > len(data):
        raise DNSFormatError("truncated rdata")
    raw = data[off: off + rdlen]
    rdata: object = raw
    if rtype in (A, AAAA):
        rdata = raw
    elif rtype in (CNAME, PTR, NS):
        rdata, _ = _decode_name(data, off)
    elif rtype == SRV:
        if rdlen < 6:
            raise DNSFormatError("truncated SRV rdata")
        prio, weight, port = struct.unpack(">HHH", raw[:6])
        target, _ = _decode_name(data, off + 6)
        rdata = (prio, weight, port, target)
    elif rtype == TXT:
        chunks = []
        i = 0
        while i < len(raw):
            ln = raw[i]
            chunks.append(raw[i + 1: i + 1 + ln])
            i += 1 + ln
        rdata = chunks
    off += rdlen
    return Record(name=name, rtype=rtype, rclass=rclass, ttl=ttl, rdata=rdata), off


def parse(data: bytes) -> Packet:
    if len(data) < 12:
        raise DNSFormatError("short packet")
    pid, flags, nq, nan, nau, nad = struct.unpack(">HHHHHH", data[:12])
    p = Packet(
        id=pid,
        is_resp=bool(flags & 0x8000),
        opcode=(flags >> 11) & 0xF,
        aa=bool(flags & 0x0400),
        tc=bool(flags & 0x0200),
        rd=bool(flags & 0x0100),
        ra=bool(flags & 0x0080),
        rcode=flags & 0xF,
    )
    off = 12
    for _ in range(nq):
        qname, off = _decode_name(data, off)
        if off + 4 > len(data):
            raise DNSFormatError("truncated question")
        qtype, qclass = struct.unpack(">HH", data[off: off + 4])
        off += 4
        p.questions.append(Question(qname, qtype, qclass))
    for n, lst in ((nan, p.answers), (nau, p.authorities), (nad, p.additionals)):
        for _ in range(n):
            rec, off = _parse_record(data, off)
            lst.append(rec)
    return p
