"""DHCP DNS-server discovery.

Parity: base dhcp/DHCPClientHelper.java:27-180 + DHCPPacket/options —
the reference broadcasts a DHCPDISCOVER carrying a parameter-request
list asking for option 6 (domain name servers) and collects the servers
from the OFFER/ACK replies; `system-property dns discover-by-dhcp` uses
it to seed the resolver.

This implementation keeps the same wire behavior (BOOTP/DHCP codec,
DISCOVER with PRL=[6], option-6 harvesting, xid matching) on the
framework's event loop. Tests (and non-root use) point it at an
explicit server address/port instead of the 255.255.255.255:67
broadcast.
"""
from __future__ import annotations

import os
import struct
import time
from typing import Callable, Optional

from ..net.eventloop import SelectorEventLoop
from ..net.udp import UdpSock
from ..utils.log import Logger

_log = Logger("dhcp")

DHCP_SERVER_PORT = 67
DHCP_CLIENT_PORT = 68
MAGIC = b"\x63\x82\x53\x63"
OPT_MSG_TYPE = 53
OPT_PRL = 55
OPT_DNS = 6
OPT_END = 255
DISCOVER = 1
OFFER = 2
ACK = 5


def build_discover(xid: int, mac: bytes = b"\x02\x00\x00\x00\x00\x01") -> bytes:
    """BOOTREQUEST + DHCPDISCOVER asking for option 6 (DNS servers)."""
    head = struct.pack(">BBBBIHH", 1, 1, 6, 0, xid, 0, 0x8000)  # broadcast
    head += b"\x00" * 16  # ciaddr/yiaddr/siaddr/giaddr
    head += mac.ljust(16, b"\x00")
    head += b"\x00" * (64 + 128)  # sname + file
    opts = bytes([OPT_MSG_TYPE, 1, DISCOVER,
                  OPT_PRL, 1, OPT_DNS,
                  OPT_END])
    return head + MAGIC + opts


def parse_reply(data: bytes, xid: int) -> Optional[list]:
    """-> list of DNS server IPv4 bytes from an OFFER/ACK matching xid,
    None if not ours / not a DHCP reply."""
    if len(data) < 240 or data[0] != 2:  # BOOTREPLY
        return None
    (got_xid,) = struct.unpack(">I", data[4:8])
    if got_xid != xid or data[236:240] != MAGIC:
        return None
    i = 240
    msg_type = None
    dns: list = []
    while i + 1 < len(data):
        opt = data[i]
        if opt == OPT_END:
            break
        if opt == 0:  # pad
            i += 1
            continue
        # clamp to the actual remaining bytes: a hostile length must not
        # yield truncated "server" entries
        ln = min(data[i + 1], len(data) - i - 2)
        body = data[i + 2: i + 2 + ln]
        if opt == OPT_MSG_TYPE and ln == 1:
            msg_type = body[0]
        elif opt == OPT_DNS:
            dns += [bytes(body[j: j + 4])
                    for j in range(0, len(body) // 4 * 4, 4)]
        i += 2 + ln
    if msg_type not in (OFFER, ACK):
        return None
    return dns


def get_dns_servers(loop: SelectorEventLoop,
                    cb: Callable[[set, Optional[Exception]], None],
                    server: tuple = ("255.255.255.255", DHCP_SERVER_PORT),
                    bind_ip: str = "", bind_port: Optional[int] = None,
                    timeout_ms: int = 2000, retries: int = 2) -> None:
    """Broadcast (or unicast, for tests) a DHCPDISCOVER and collect DNS
    servers from every OFFER/ACK until the timeout; cb(set[bytes], err)
    on the loop. The set may aggregate multiple responding servers,
    like the reference's per-NIC collection."""
    xid = int.from_bytes(os.urandom(4), "big")
    found: set = set()
    state = {"done": False, "sock": None, "tries": 0}

    def finish(err: Optional[Exception]) -> None:
        if state["done"]:
            return
        state["done"] = True
        if state["sock"] is not None:
            state["sock"].close()
        if found:
            cb(set(found), None)
        else:
            cb(set(), err or TimeoutError("no DHCP reply"))

    def on_packet(data: bytes, ip: str, port: int) -> None:
        dns = parse_reply(data, xid)
        if dns is None:
            return
        found.update(dns)

    def send() -> None:
        if state["done"]:
            return
        state["tries"] += 1
        try:
            state["sock"].send(build_discover(xid), server[0], server[1])
        except OSError as e:
            finish(e)
            return
        if state["tries"] <= retries:
            loop.delay(timeout_ms // (retries + 1), send)

    def mk() -> None:
        broadcast = server[0].endswith(".255") or \
            server[0] == "255.255.255.255"
        # broadcast replies target 255.255.255.255:68 (the DISCOVER sets
        # the broadcast flag) — an ephemeral bind would never hear them
        port = bind_port if bind_port is not None else (
            DHCP_CLIENT_PORT if broadcast else 0)
        sock = None
        try:
            sock = UdpSock(loop, bind_ip or "0.0.0.0", port, on_packet)
            if broadcast:
                import socket as pysock
                tmp = pysock.socket(fileno=os.dup(sock.fd))
                tmp.setsockopt(pysock.SOL_SOCKET, pysock.SO_BROADCAST, 1)
                tmp.close()
        except OSError as e:
            if sock is not None:
                sock.close()
            cb(set(), e)
            return
        state["sock"] = sock
        send()
        loop.delay(timeout_ms, lambda: finish(None))

    if not loop.run_on_loop(mk):
        # loop is gone: the callback must still fire (per run_on_loop's
        # cleanup contract), or waiters hang with no diagnostic
        cb(set(), OSError("event loop is closed"))
