"""DNSServer — authoritative + recursive DNS over UDP, with DNS-as-LB.

Parity: core dns/DNSServer.java. Lookup order per qname
(DNSServer.java:116-195): hosts map -> rrsets (an Upstream searched with
Hint.ofHost(domain) — the classify engine) -> IP-literal echo ->
`*.vproxy.local` introspection (DNSServer.java:150-157 + runInternal
:339-349: who.am.i answers the requester's own address, who.are.you the
server's local address facing them; plus the resource extension below)
-> recursive upstream via DNSClient. A/AAAA answers pick a HEALTHY
backend via the matched group's nextIPv4/nextIPv6 (DNS answers
load-balance); SRV lists all healthy server handles with weights.
Queries are gated by a SecurityGroup (UDP protocol).

Resource introspection extension: `resource_resolver` (installed by the
control plane, control/command.py) maps the sub-domain left of
`.vproxy.local` to a live resource address — e.g. `web.tcp-lb
.vproxy.local` answers tcp-lb `web`'s bind address from the running
Application state.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from ..components.secgroup import SecurityGroup
from ..components.upstream import Upstream
from ..net import vtl
from ..net.eventloop import SelectorEventLoop
from ..policing import engine as policing
from ..rules.ir import Hint, Proto
from ..utils import sketch, workload
from ..utils.ip import is_ip_literal, parse_ip
from ..utils.log import Logger
from . import packet as P
from .client import DNSClient

_log = Logger("dns-server")


class DNSServer:
    def __init__(self, alias: str, loop: SelectorEventLoop, bind_ip: str,
                 bind_port: int, rrsets: Upstream, ttl: int = 0,
                 security_group: Optional[SecurityGroup] = None,
                 recursive_client: Optional[DNSClient] = None,
                 hosts: Optional[dict[str, bytes]] = None, elg=None,
                 resource_resolver=None):
        self.alias = alias
        self.loop = loop
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.rrsets = rrsets
        self.ttl = ttl
        self.security_group = security_group or SecurityGroup.allow_all()
        self.recursive = recursive_client
        self.hosts = hosts or {}
        # optional `(subdomain) -> Optional[bytes addr]` hook answering
        # `<subdomain>.vproxy.local` from live resource state
        self.resource_resolver = resource_resolver
        self._fd: Optional[int] = None
        self.elg = elg  # attach target for loop-death re-homing
        self.started = False
        self.queries = 0
        # hot-path answer cache: packed response bytes per (qname,
        # qtype, rd) for single-question group-backed queries — without
        # it every repeat query re-walks the group and re-packs records.
        # Entries pin the tokens they were built under (the rrsets
        # matcher snapshot = rule generation, the group + its
        # health_version = backend health edges) and die the instant
        # either moves; a short TTL bounds how long the DNS-as-LB
        # rotation is frozen on one backend. VPROXY_TPU_DNS_CACHE_MS=0
        # disables.
        self._cache_ms = int(os.environ.get("VPROXY_TPU_DNS_CACHE_MS",
                                            "1000"))
        self._ans_cache: dict = {}  # key -> (expires, token, resp bytes)
        self.cache_hits = 0
        self.drops = 0  # responses the kernel refused (EAGAIN) — counted
        # qname quarantine (vproxy_tpu/policing): a quarantined qname
        # answers REFUSED from this packed-response cache — the flood
        # never re-walks the group or re-packs records
        self._ref_cache: dict = {}  # key -> (expires, packed REFUSED)
        self.quarantines = 0

    def _send(self, data: bytes, ip: str, port: int) -> None:
        """One response datagram; an EAGAIN under storm load is a DROP
        and must be counted (vproxy_udp_drop_total), never silent —
        the client's retry is the recovery, the counter is the evidence.
        A raised OSError is a real send failure (EBADF, ENETUNREACH…),
        not backpressure: logged, never reclassified as a storm drop —
        an outage must not read as benign overload on /metrics."""
        if self._fd is None:
            return
        try:
            r = vtl.sendto(self._fd, data, ip, port)
        except OSError:
            _log.error(f"dns response sendto {ip}:{port} failed",
                       exc=True)
            return
        if r == vtl.AGAIN:
            self.drops += 1
            from ..utils.metrics import udp_drop_incr
            udp_drop_incr()

    # ------------------------------------------------------------ control

    def start(self) -> None:
        if self.started:
            return
        self._bind(self.loop)
        if self.elg is not None:
            self.elg.attach(self)
        self.started = True

    def _bind(self, loop) -> None:
        def mk() -> None:
            self._fd = vtl.udp_bind(self.bind_ip, self.bind_port)
            if self.bind_port == 0:
                _, self.bind_port = vtl.sock_name(self._fd)
            loop.add(self._fd, vtl.EV_READ, self._on_readable)
        try:
            loop.call_sync(mk)
        except OSError as e:
            raise OSError(f"dns-server {self.alias}: bind failed: {e}") from e

    def on_loop_death(self, group, lp) -> None:
        """DNSServer.java:89-106: when the hosting loop dies, re-home
        the UDP bind onto a surviving loop of the attached group (death
        callbacks fire after the dead loop released our fd)."""
        if lp is not self.loop or not self.started:
            return
        self._fd = None
        if not group.loops:
            self.started = False
            group.detach(self)
            return
        self.loop = group.next()
        try:
            self._bind(self.loop)
        except OSError as e:
            _log.alert(f"dns-server {self.alias}: re-home bind failed: "
                       f"{e!r}; server is down")
            self.started = False
            group.detach(self)
            return
        if not self.started:  # raced a concurrent stop(): undo the bind
            fd, self._fd = self._fd, None
            lp2 = self.loop

            def rm() -> None:
                if fd is not None:
                    lp2.remove(fd)
                    vtl.close(fd)
            lp2.run_on_loop(rm)

    def stop(self) -> None:
        if not self.started:
            return
        self.started = False
        if self.elg is not None:
            self.elg.detach(self)
        fd = self._fd
        self._fd = None

        def rm() -> None:
            if fd is not None:
                self.loop.remove(fd)
                vtl.close(fd)
        self.loop.run_on_loop(rm)

    # --------------------------------------------------------- data plane

    def _on_readable(self, fd: int, ev: int) -> None:
        # drain the socket; every datagram's ACL gate is submitted to the
        # ClassifyService immediately, so a burst of queries coalesces
        # into one device batch (the DNS arm of the north-star queue)
        while self._fd is not None:
            r = vtl.recvfrom(fd)
            if r is None:
                return
            data, ip, port = r
            self.queries += 1

            def gated(ok: bool, data=data, ip=ip, port=port) -> None:
                if not ok or self._fd is None:
                    return
                try:
                    req = P.parse(data)
                except P.DNSFormatError:
                    return
                self._handle(req, ip, port)

            self.security_group.allow_async(Proto.UDP, parse_ip(ip),
                                            self.bind_port, gated, self.loop)

    def _respond(self, req: P.Packet, ip: str, port: int,
                 answers: list, rcode: int = 0) -> None:
        resp = P.Packet(id=req.id, is_resp=True, aa=rcode == 0, rd=req.rd,
                        ra=self.recursive is not None, rcode=rcode,
                        questions=list(req.questions), answers=answers)
        data = resp.encode()
        ck = getattr(req, "_cache_key", None)
        if ck is not None and rcode == 0:
            if len(self._ans_cache) > 4096:
                self._ans_cache.clear()
            self._ans_cache[ck] = (
                time.monotonic() + self._cache_ms / 1000.0,
                req._cache_token, data)
        self._send(data, ip, port)

    def _cache_lookup(self, req: P.Packet, q) -> Optional[bytes]:
        """-> a fresh cached response (id already patched) or None."""
        key = (q.qname, q.qtype, req.rd)
        ent = self._ans_cache.get(key)
        if ent is None:
            return None
        expires, (gh, hv, snap), data = ent
        if (time.monotonic() >= expires
                or gh.group.health_version != hv
                or self.rrsets._matcher.snapshot() is not snap):
            del self._ans_cache[key]
            return None
        out = bytearray(data)
        out[0:2] = req.id.to_bytes(2, "big")
        return bytes(out)

    def _handle(self, req: P.Packet, ip: str, port: int) -> None:
        if not req.questions:
            self._respond(req, ip, port, [], rcode=1)
            return
        qs = list(req.questions)
        # workload capture: the dns-plane arrival process (one query =
        # one arrival, cache hits included — the offered load is what
        # the capacity model wants, not the miss rate)
        workload.note_arrival("dns")
        # analytics: which qnames are hot (covers cache hits too — the
        # whole point is seeing the crowd, cached or not)
        if sketch.ON:
            for q in qs:
                sketch.update("qnames", q.qname, plane="dns")
        # qname-flood quarantine: the policing verdict comes BEFORE the
        # answer cache (a quarantined name must not serve stale answers
        # from a pre-quarantine fill) and the REFUSED bytes come from
        # their own packed cache. One branch when the knob is off.
        if policing.ON:
            policing.maybe_tick()
            if self._quarantine_refuse(req, ip, port, qs):
                return
        if len(qs) == 1 and self._cache_ms > 0:
            hit = self._cache_lookup(req, qs[0])
            if hit is not None:
                self.cache_hits += 1
                self._send(hit, ip, port)
                return
        # continuation pipeline over the questions: each rrsets lookup
        # rides the ClassifyService queue (DNSServer.java:136's scan),
        # coalescing with other in-flight queries across datagrams
        self._handle_q(req, ip, port, qs, 0, [])

    def _quarantine_refuse(self, req: P.Packet, ip: str, port: int,
                           qs: Optional[list] = None) -> bool:
        """True = a quarantined qname answered REFUSED (rcode 5) from
        the packed cache (id patched per query) — the group walk, the
        record packing and the classify submit never run."""
        if qs is None:
            qs = list(req.questions)
        hit = None
        for q in qs:
            if policing.quarantined(q.qname, lb=self.alias):
                hit = q
                break
        if hit is None:
            return False
        self.quarantines += 1
        key = (hit.qname, hit.qtype, req.rd)
        now = time.monotonic()
        ent = self._ref_cache.get(key)
        if ent is not None and now < ent[0]:
            out = bytearray(ent[1])
            out[0:2] = req.id.to_bytes(2, "big")
            self._send(bytes(out), ip, port)
            return True
        resp = P.Packet(id=req.id, is_resp=True, aa=False, rd=req.rd,
                        ra=self.recursive is not None, rcode=5,
                        questions=list(req.questions), answers=[])
        data = resp.encode()
        if len(self._ref_cache) > 1024:
            self._ref_cache.clear()
        # the verdict is re-checked per query (quarantine lifting takes
        # effect immediately); the cache only skips the re-pack
        self._ref_cache[key] = (now + 1.0, data)
        self._send(data, ip, port)
        return True

    def _handle_q(self, req: P.Packet, ip: str, port: int, qs: list,
                  i: int, answers: list) -> None:
        while i < len(qs):
            q = qs[i]
            if q.qtype not in (P.A, P.AAAA, P.SRV, P.ANY):
                self._run_recursive(req, ip, port)
                return
            domain = q.qname.rstrip(".")
            host_hit = self.hosts.get(domain)
            if host_hit is not None:
                answers.append(self._addr_record(q.qname, host_hit))
                i += 1
                continue

            def found(gh, q=q, i=i, domain=domain) -> None:
                if gh is None:
                    if is_ip_literal(domain):
                        addr = parse_ip(domain)
                        if ((q.qtype == P.A and len(addr) == 4)
                                or (q.qtype == P.AAAA and len(addr) == 16)
                                or q.qtype == P.SRV):
                            answers.append(self._addr_record(q.qname, addr))
                        self._handle_q(req, ip, port, qs, i + 1, answers)
                        return
                    if domain.endswith(".vproxy.local"):
                        # DNSServer.java:150-157: answered from internal
                        # state, never recursed out; family gated by the
                        # question type like the IP-literal arm above
                        for a in self._run_internal(
                                domain[: -len(".vproxy.local")], ip):
                            if ((q.qtype == P.A and len(a) == 4)
                                    or (q.qtype == P.AAAA and len(a) == 16)
                                    or q.qtype in (P.SRV, P.ANY)):
                                answers.append(
                                    self._addr_record(q.qname, a))
                        self._handle_q(req, ip, port, qs, i + 1, answers)
                        return
                    self._run_recursive(req, ip, port)
                    return
                # single-question group answer: cacheable — pin the
                # tokens whose movement must invalidate it. Per-client
                # picks (source hash, live-connection wlc) must NOT be
                # cached: one client's backend would serve everyone.
                # SRV lists all healthy servers, so it is always safe.
                if len(qs) == 1 and self._cache_ms > 0 and (
                        q.qtype == P.SRV or gh.group.method == "wrr"):
                    req._cache_key = (q.qname, q.qtype, req.rd)
                    req._cache_token = (gh, gh.group.health_version,
                                        self.rrsets._matcher.snapshot())
                self._answer_group(q, gh, ip, answers)
                self._handle_q(req, ip, port, qs, i + 1, answers)

            self.rrsets.search_for_group_async(Hint.of_host(domain), found,
                                               self.loop)
            return
        self._respond(req, ip, port, answers)

    def _answer_group(self, q, gh, ip: str, answers: list) -> None:
        if q.qtype == P.SRV:
            for svr in gh.group.servers:
                if not svr.healthy:
                    continue
                answers.append(P.Record(
                    name=q.qname, rtype=P.SRV, ttl=self.ttl,
                    rdata=(0, svr.weight, svr.port,
                           (svr.host_name or svr.ip) + ".")))
        else:
            fam = "v4" if q.qtype == P.A else ("v6" if q.qtype == P.AAAA else None)
            conn = gh.group.next(parse_ip(ip), fam)
            if conn is not None:  # no healthy server: empty answer section
                answers.append(self._addr_record(q.qname, parse_ip(conn.ip)))

    def _run_internal(self, sub: str, ip: str) -> list[bytes]:
        """`<sub>.vproxy.local` answers (DNSServer.runInternal
        :339-349): who.am.i = the requester's address; who.are.you =
        this server's local address facing them; the cluster service
        name = the UP cluster peers (DNS-as-LB across the fleet,
        cluster/membership.py — healthy-only, but never an empty set:
        this node itself is the floor); anything else consults the
        control plane's resource resolver."""
        if sub == "who.am.i":
            return [parse_ip(ip)]
        from ..cluster import cluster_service_name, dns_peer_addrs
        if sub == cluster_service_name():
            # maglev-steered by the requester's address: the picked
            # peer answers FIRST, so one client keeps one peer across
            # repeat queries and a fleet resize moves only ~1/N of
            # client affinities (cluster/membership.steer_addrs)
            try:
                client = parse_ip(ip)
            except (OSError, ValueError):
                client = None
            addrs = dns_peer_addrs(client)
            if addrs is not None:
                return addrs
        if sub == "who.are.you":
            local = self.bind_ip
            if local in ("0.0.0.0", "::"):
                import socket
                try:  # routed local address toward the requester
                    s = socket.socket(socket.AF_INET6 if ":" in ip
                                      else socket.AF_INET,
                                      socket.SOCK_DGRAM)
                    s.connect((ip, 53))
                    local = s.getsockname()[0]
                    s.close()
                except OSError:
                    return []
            return [parse_ip(local)]
        if self.resource_resolver is not None:
            a = self.resource_resolver(sub)
            if a is not None:
                return [a]
        return []

    def _addr_record(self, qname: str, addr: bytes) -> P.Record:
        return P.Record(name=qname, rtype=P.A if len(addr) == 4 else P.AAAA,
                        ttl=self.ttl, rdata=addr)

    def _run_recursive(self, req: P.Packet, ip: str, port: int) -> None:
        if self.recursive is None or not req.questions:
            self._respond(req, ip, port, [], rcode=3)  # NXDOMAIN
            return
        q = req.questions[0]

        def on_resp(resp: Optional[P.Packet], err) -> None:
            if resp is None:
                self._respond(req, ip, port, [], rcode=2)  # SERVFAIL
                return
            resp.id = req.id
            resp.is_resp = True
            resp.ra = True
            self._send(resp.encode(), ip, port)

        self.recursive.query(q.qname, q.qtype, on_resp)
