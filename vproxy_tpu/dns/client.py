"""DNSClient + caching resolver.

Parity: base dns/DNSClient.java (UDP-only queries, timeout + maxRetry
rotation across nameservers, :34-52,156-181) and AbstractResolver.java
(async TTL cache). Runs on a SelectorEventLoop; callbacks fire on the
loop thread.
"""
from __future__ import annotations

import itertools
import time
from typing import Callable, Optional

from ..net import vtl
from ..net.eventloop import SelectorEventLoop
from . import packet as P


class DNSClient:
    def __init__(self, loop: SelectorEventLoop, nameservers: list[tuple[str, int]],
                 timeout_ms: int = 1500, max_retry: int = 2):
        self.loop = loop
        self.nameservers = list(nameservers)
        self.timeout_ms = timeout_ms
        self.max_retry = max_retry
        self._idgen = itertools.count(1)
        self._inflight: dict[int, dict] = {}
        self._fd: Optional[int] = None

    def _ensure_sock(self) -> int:
        if self._fd is None:
            self._fd = vtl.udp_bind("0.0.0.0", 0)
            self.loop.add(self._fd, vtl.EV_READ, self._on_readable)
        return self._fd

    def _on_readable(self, fd: int, ev: int) -> None:
        while True:
            r = vtl.recvfrom(fd)
            if r is None:
                return
            data, ip, port = r
            try:
                resp = P.parse(data)
            except P.DNSFormatError:
                continue
            st = self._inflight.pop(resp.id & 0xFFFF, None)
            if st is None:
                continue
            st["timer"].cancel()
            st["cb"](resp, None)

    def query(self, qname: str, qtype: int,
              cb: Callable[[Optional[P.Packet], Optional[Exception]], None]) -> None:
        """Send a query; cb(resp, err) on the loop thread."""
        qid = next(self._idgen) & 0xFFFF or 1
        pkt = P.Packet(id=qid, rd=True,
                       questions=[P.Question(qname, qtype)])
        data = pkt.encode()
        st = {"cb": cb, "attempt": 0, "data": data}
        self._inflight[qid] = st

        def send_attempt() -> None:
            ns = self.nameservers[st["attempt"] % len(self.nameservers)]
            try:
                vtl.sendto(self._ensure_sock(), data, ns[0], ns[1])
            except OSError:
                pass
            st["timer"] = self.loop.delay(self.timeout_ms, on_timeout)

        def on_timeout() -> None:
            st["attempt"] += 1
            if st["attempt"] >= self.max_retry * len(self.nameservers):
                self._inflight.pop(qid, None)
                cb(None, TimeoutError(f"dns query {qname} timed out"))
                return
            send_attempt()

        send_attempt()

    def close(self) -> None:
        if self._fd is not None:
            self.loop.remove(self._fd)
            vtl.close(self._fd)
            self._fd = None


class Resolver:
    """TTL-cached async resolver (AbstractResolver/VResolver analog)."""

    def __init__(self, loop: SelectorEventLoop, client: DNSClient,
                 hosts: Optional[dict[str, bytes]] = None):
        self.loop = loop
        self.client = client
        self.hosts = hosts or {}
        self._cache: dict[tuple[str, int], tuple[float, list[bytes]]] = {}

    def resolve(self, name: str, cb: Callable[[Optional[list[bytes]], Optional[Exception]], None],
                qtype: int = P.A) -> None:
        key = name.rstrip(".")
        if key in self.hosts:
            cb([self.hosts[key]], None)
            return
        ent = self._cache.get((key, qtype))
        now = time.monotonic()
        if ent and ent[0] > now:
            cb(list(ent[1]), None)
            return

        def on_resp(resp, err):
            if err is not None or resp is None:
                cb(None, err or OSError("no response"))
                return
            addrs = [r.rdata for r in resp.answers
                     if r.rtype == qtype and isinstance(r.rdata, (bytes, bytearray))]
            ttl = min((r.ttl for r in resp.answers), default=60) or 60
            if addrs:
                self._cache[(key, qtype)] = (now + ttl, addrs)
                cb(addrs, None)
            else:
                cb(None, OSError(f"no {qtype} records for {name}"))

        self.client.query(key + ".", qtype, on_resp)
