"""Device-mesh sharding for the classify engine.

The scaling story (SURVEY.md §5 "distributed communication backend"):
rule tables live in HBM sharded over the mesh's "rules" axis (the
tensor-parallel analog — each chip holds a slice of every table and the
argmax/min reduction rides ICI collectives inserted by the SPMD
partitioner), while query micro-batches shard over "batch" (the
data-parallel analog — the per-core event-loop sharding of
app/Application.java:90-105 maps to batch shards). A single chip
overflows neither HBM nor step-rate for the reference's scale, so the
mesh exists for headroom and for multi-host DCN deployments where the
control plane replicates tables per host.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None, batch: int = 1) -> Mesh:
    """Mesh with axes (batch, rules); rules gets the remaining devices."""
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    n = len(devs)
    assert n % batch == 0, (n, batch)
    return Mesh(np.array(devs).reshape(batch, n // batch), ("batch", "rules"))


# PartitionSpecs per table key: 2-D matmul weights shard on their rule
# column axis, 1-D metadata shards on axis 0.
_HINT_SPECS = {
    "host_w": P(None, "rules"), "host_c": P("rules"),
    "host_valid": P("rules", None), "host_wild": P("rules"),
    "uri_w": P(None, "rules"), "uri_c": P("rules"),
    "uri_valid": P("rules"), "uri_wild": P("rules"),
    "uri_score": P("rules"), "port": P("rules"), "active": P("rules"),
}
_CIDR_SPECS = {
    "w": P(None, "rules"), "c": P("rules"), "family": P("rules"),
    "valid": P("rules"), "min_port": P("rules"), "max_port": P("rules"),
    "allow": P("rules"),
}
_HINT_Q_SPECS = {
    "host": P("batch", None), "has_host": P("batch"), "uri": P("batch", None),
    "has_uri": P("batch"), "port": P("batch"),
}


def shard_hint_table(table: dict, mesh: Mesh) -> dict:
    return {k: jax.device_put(v, NamedSharding(mesh, _HINT_SPECS[k]))
            for k, v in table.items()}


def shard_cidr_table(table: dict, mesh: Mesh) -> dict:
    return {k: jax.device_put(v, NamedSharding(mesh, _CIDR_SPECS[k]))
            for k, v in table.items()}


def shard_hint_queries(q: dict, mesh: Mesh) -> dict:
    return {k: jax.device_put(v, NamedSharding(mesh, _HINT_Q_SPECS[k]))
            for k, v in q.items()}


def shard_addr_queries(addr: np.ndarray, fam: np.ndarray, mesh: Mesh,
                       port: Optional[np.ndarray] = None):
    a = jax.device_put(addr, NamedSharding(mesh, P("batch", None)))
    f = jax.device_put(fam, NamedSharding(mesh, P("batch")))
    if port is None:
        return a, f, None
    return a, f, jax.device_put(port, NamedSharding(mesh, P("batch")))
