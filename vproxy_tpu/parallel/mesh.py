"""Device-mesh sharding for the classify engine.

The scaling story (SURVEY.md §5 "distributed communication backend"):
rule tables live in HBM sharded over the mesh's "rules" axis (the
tensor-parallel analog — each chip holds a slice of every table and the
argmax/min reduction rides ICI collectives inserted by the SPMD
partitioner), while query micro-batches shard over "batch" (the
data-parallel analog — the per-core event-loop sharding of
app/Application.java:90-105 maps to batch shards).

Multi-host: init_distributed() brings up jax.distributed (the analog of
the reference's cross-host fabric, RemoteSwitchIface.java — but over
the accelerator DCN, not VXLAN), after which jax.devices() is GLOBAL
and make_mesh(hosts=N) lays out a (host, batch, rules) mesh where

* tables are REPLICATED across the "host" axis (each host holds the
  full rule set — updates are control-plane broadcasts over DCN),
* the "rules" shards stay WITHIN a host, so the winner pmax/pmin
  reductions ride ICI only,
* query batches shard over (host, batch): each host classifies its own
  accepted connections; no per-query DCN traffic at all.

put()/to_local() abstract single- vs multi-process array creation so
the same engine code runs on one process (device_put) or many
(make_array_from_process_local_data, every process contributing its
local batch slice).
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout_s: Optional[float] = None) -> bool:
    """jax.distributed multi-host bring-up; reads VPROXY_TPU_DIST_COORD
    (host:port), VPROXY_TPU_DIST_NPROC, VPROXY_TPU_DIST_PROCID when the
    args are absent. Returns False (no-op) when not configured —
    single-host deployments never pay for it. Must run before the first
    device use (main.py boots it first thing).

    Bring-up is BOUNDED (VPROXY_TPU_DIST_TIMEOUT_S, default 120s): an
    unreachable coordinator, a missing peer, or two processes booted
    with the same VPROXY_TPU_DIST_PROCID would otherwise hang the
    barrier forever with no hint which knob is wrong. An unreachable
    coordinator is caught by a bounded pre-flight TCP probe and raises
    a RuntimeError naming the env vars to check BEFORE entering
    jaxlib's client (whose own deadline path is a LOG(FATAL) process
    abort — still bounded by initialization_timeout, just not
    catchable); other barrier failures surface through
    initialization_timeout."""
    coordinator = coordinator or os.environ.get("VPROXY_TPU_DIST_COORD")
    if num_processes is None:
        num_processes = int(os.environ.get("VPROXY_TPU_DIST_NPROC", "0")
                            or 0)
    if process_id is None:
        process_id = int(os.environ.get("VPROXY_TPU_DIST_PROCID", "-1")
                         or -1)
    if not coordinator or num_processes <= 1 or process_id < 0:
        return False
    if timeout_s is None:
        timeout_s = float(os.environ.get("VPROXY_TPU_DIST_TIMEOUT_S",
                                         "120"))
    if process_id > 0:
        _preflight_coordinator(coordinator, num_processes, process_id,
                               timeout_s)
    enable_cpu_collectives()
    try:
        jax.distributed.initialize(
            coordinator, num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=int(timeout_s))
    except Exception as e:
        raise RuntimeError(
            f"jax.distributed bring-up failed for process "
            f"{process_id}/{num_processes} against coordinator "
            f"{coordinator} within {timeout_s:.0f}s: {e!r}. Check "
            "VPROXY_TPU_DIST_COORD (is the coordinator host:port "
            "reachable, and running process id 0?), "
            "VPROXY_TPU_DIST_NPROC (are ALL processes booted?), and "
            "VPROXY_TPU_DIST_PROCID (ids must be unique in "
            f"[0, {num_processes})) — a duplicate or missing id leaves "
            "the bring-up barrier waiting forever; raise "
            "VPROXY_TPU_DIST_TIMEOUT_S for genuinely slow fleets."
        ) from e
    return True


def cpu_collectives_available() -> bool:
    """Can THIS jaxlib run multiprocess collectives on the CPU backend?
    Without a cross-process CPU collectives implementation (gloo/mpi)
    the CPU client fails any multiprocess computation with
    "Multiprocess computations aren't implemented on the CPU backend" —
    the capability probe tests gate on (tests/test_multihost.py) instead
    of failing in environments that cannot comply."""
    try:
        from jax._src.lib import xla_extension as _xe
        if not hasattr(_xe, "make_gloo_tcp_collectives"):
            return False
        # the config option wires gloo into the CPU client at creation;
        # a jax too old to register the option cannot enable it (the
        # option is holder-registered, not an attribute on jax.config)
        holders = getattr(jax.config, "_value_holders", {})
        return "jax_cpu_collectives_implementation" in holders
    except Exception:
        return False


def enable_cpu_collectives() -> None:
    """Select the gloo CPU collectives implementation (when this jaxlib
    ships it) BEFORE the backend initializes — multiprocess CPU fleets
    (and the 2-process tests) need it; accelerator backends ignore it.
    Must run before the first device use; init_distributed() calls it
    ahead of jax.distributed.initialize."""
    if not cpu_collectives_available():
        return
    try:
        holders = getattr(jax.config, "_value_holders", {})
        cur = holders["jax_cpu_collectives_implementation"].value
        if cur in (None, "", "none"):
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
    except Exception:
        pass  # backend already initialized: leave the config alone


def _preflight_coordinator(coordinator: str, num_processes: int,
                           process_id: int, timeout_s: float) -> None:
    """Bounded TCP probe of the coordinator before handing control to
    jaxlib: its deadline path aborts the process (LOG(FATAL)), so the
    by-far-most-common misconfiguration — coordinator address wrong or
    process 0 not up — must fail as a catchable error here instead."""
    import socket
    import time
    host, _, port = coordinator.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            socket.create_connection(
                (host, int(port)),
                timeout=max(0.5, min(5.0, deadline - time.monotonic()))
            ).close()
            return
        except OSError as e:
            last = e
            time.sleep(min(1.0, max(0.05, deadline - time.monotonic())))
    raise RuntimeError(
        f"jax.distributed coordinator {coordinator} unreachable after "
        f"{timeout_s:.0f}s (process {process_id}/{num_processes}): "
        f"{last!r}. Check VPROXY_TPU_DIST_COORD (must be the host:port "
        "where the VPROXY_TPU_DIST_PROCID=0 process runs, and that "
        "process must be up first), VPROXY_TPU_DIST_NPROC, and that "
        "every process has a unique VPROXY_TPU_DIST_PROCID in "
        f"[0, {num_processes}); raise VPROXY_TPU_DIST_TIMEOUT_S for "
        "genuinely slow fleets.")


def make_mesh(n_devices: Optional[int] = None, batch: int = 1,
              hosts: int = 1) -> Mesh:
    """Mesh with axes (batch, rules) — or (host, batch, rules) when
    hosts > 1; "rules" gets the remaining devices. With hosts equal to
    jax.process_count() the host axis follows process boundaries
    (jax.devices() orders all of process 0's devices first)."""
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    n = len(devs)
    assert n % (batch * hosts) == 0, (n, batch, hosts)
    if hosts > 1:
        return Mesh(np.array(devs).reshape(hosts, batch,
                                           n // (batch * hosts)),
                    ("host", "batch", "rules"))
    return Mesh(np.array(devs).reshape(batch, n // batch), ("batch", "rules"))


def batch_axes(mesh: Mesh) -> tuple:
    """Every mesh axis except "rules" carries query batches."""
    return tuple(a for a in mesh.axis_names if a != "rules")


def query_shards(mesh: Mesh) -> int:
    """Total batch-axis size (the pad multiple for query batches)."""
    n = 1
    for a in batch_axes(mesh):
        n *= mesh.shape[a]
    return n


def put(mesh: Mesh, spec: P, local: np.ndarray):
    """Create a global array from this process's local data: device_put
    single-process; make_array_from_process_local_data when the mesh
    spans processes (each host contributes its own batch slice; table
    arrays — replicated or rules-sharded-within-host — pass the full
    array since every local shard is derivable from it)."""
    sh = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sh, local)
    return jax.device_put(local, sh)


def to_local(arr) -> np.ndarray:
    """This process's contiguous slice of a batch-sharded output (the
    whole array on a single process). Assumes the leading dim is the
    batch axis and this process's shards are contiguous in it (true for
    (host, batch, rules) meshes where host follows process order). An
    output replicated over the in-host "rules" axis has one shard COPY
    per device — dedupe by index so each slice contributes once."""
    if jax.process_count() <= 1:
        return np.asarray(arr)
    seen = {}
    for s in arr.addressable_shards:
        start = s.index[0].start or 0
        if start not in seen:
            seen[start] = s.data
    return np.concatenate(
        [np.asarray(seen[k]) for k in sorted(seen)])


# PartitionSpecs per table key: 2-D matmul weights shard on their rule
# column axis, 1-D metadata shards on axis 0.
_HINT_SPECS = {
    "host_w": P(None, "rules"), "host_c": P("rules"),
    "host_valid": P("rules", None), "host_wild": P("rules"),
    "uri_w": P(None, "rules"), "uri_c": P("rules"),
    "uri_valid": P("rules"), "uri_wild": P("rules"),
    "uri_score": P("rules"), "port": P("rules"), "active": P("rules"),
}
_CIDR_SPECS = {
    "w": P(None, "rules"), "c": P("rules"), "family": P("rules"),
    "valid": P("rules"), "min_port": P("rules"), "max_port": P("rules"),
    "allow": P("rules"),
}
_HINT_Q_SPECS = {
    "host": P("batch", None), "has_host": P("batch"), "uri": P("batch", None),
    "has_uri": P("batch"), "port": P("batch"),
}


def shard_hint_table(table: dict, mesh: Mesh) -> dict:
    return {k: jax.device_put(v, NamedSharding(mesh, _HINT_SPECS[k]))
            for k, v in table.items()}


def shard_cidr_table(table: dict, mesh: Mesh) -> dict:
    return {k: jax.device_put(v, NamedSharding(mesh, _CIDR_SPECS[k]))
            for k, v in table.items()}


def shard_hint_queries(q: dict, mesh: Mesh) -> dict:
    return {k: jax.device_put(v, NamedSharding(mesh, _HINT_Q_SPECS[k]))
            for k, v in q.items()}  # dense experimental path: 2-axis mesh


def shard_addr_queries(addr: np.ndarray, fam: np.ndarray, mesh: Mesh,
                       port: Optional[np.ndarray] = None):
    ba = batch_axes(mesh)
    arrs = {"a": addr, "f": fam}
    specs = {"a": P(ba, None), "f": P(ba)}
    if port is not None:
        arrs["p"] = port
        specs["p"] = P(ba)
    out = put_many(mesh, specs, arrs)
    return out["a"], out["f"], out.get("p")


# ------------------------------------------------- hash-path (production)
#
# The cuckoo-hash tables (ops/hashmatch, "the 10M matches/s path") shard
# by SLICING THE RULE LIST: ShardedHashTable stacks S per-shard compiled
# tables on a leading axis that carries the "rules" PartitionSpec, and
# each device runs the unchanged single-shard kernel on its local slice
# under shard_map. The global winner is a two-phase collective: pmax of
# the match level, then pmin of the global rule index among the level
# winners — Upstream.java:187's strictly-greater-max/earliest-tie
# semantics as an ICI reduction. CIDR first-match reduces with one pmin.


def _leading_rules_spec(arrays: dict) -> dict:
    return {k: P("rules", *([None] * (v.ndim - 1)))
            for k, v in arrays.items()}


def shard_hash_table(stab, mesh: Mesh) -> dict:
    """Ship a ShardedHashTable's stacked arrays over the mesh (tables
    replicate across host/batch axes; multi-process hosts each pass the
    identical full array). Paced per key (ops.cuckoo.coop_yield): a
    standby install's upload slices multi-MB arrays per device under
    the GIL — unpaced, that window alone shows up in serving p99."""
    from ..ops.cuckoo import coop_yield
    specs = _leading_rules_spec(stab.arrays)
    out = {}
    for k, v in stab.arrays.items():
        coop_yield()
        out[k] = put(mesh, specs[k], v)
    return out


def release_host(stab) -> None:
    """Drop a ShardedHashTable's stacked HOST arrays after the device
    upload (the standby-swap memory-lean contract): each array is
    replaced by a zero-size stub that preserves ndim/dtype, which is
    all the jitted-fn spec builders ({k: v.ndim}) ever read. A 1M-rule
    generation would otherwise live in host RAM for as long as the
    matcher keeps its published snapshot."""
    stab.arrays = {k: np.empty((0,) * v.ndim, v.dtype)
                   for k, v in stab.arrays.items()}


def put_many(mesh: Mesh, specs: dict, arrs: dict) -> dict:
    """Batched device_put of a query/table dict: ONE call ships every
    array (the per-key call paid measurable per-transfer overhead on
    the dispatch path). Falls back to per-key put on multi-process
    meshes (make_array_from_process_local_data is per-array) or when
    the runtime rejects the batched form."""
    keys = list(arrs)
    if jax.process_count() > 1:
        return {k: put(mesh, specs[k], arrs[k]) for k in keys}
    try:
        out = jax.device_put(
            [arrs[k] for k in keys],
            [NamedSharding(mesh, specs[k]) for k in keys])
        return dict(zip(keys, out))
    except (TypeError, ValueError):
        return {k: put(mesh, specs[k], arrs[k]) for k in keys}


def shard_hint_queries_sharded(q: dict, mesh: Mesh) -> dict:
    """Stacked per-shard hint encodings: (rules, batch, ...) sharded."""
    ba = batch_axes(mesh)
    specs = {k: P("rules", ba, *([None] * (v.ndim - 2)))
             for k, v in q.items()}
    return put_many(mesh, specs, q)


def _shard_map(body, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)


def _donate_queries(mesh: Mesh, argnums: tuple) -> dict:
    """jit kwargs donating the per-dispatch QUERY buffers (tables are
    reused across dispatches and must never be donated). Donation lets
    XLA alias the uploaded probe arrays instead of copying them —
    real-accelerator meshes only: the XLA CPU runtime ignores donation
    with a per-compile warning, which is noise on the virtual test
    mesh."""
    devs = mesh.devices.reshape(-1)
    if len(devs) and devs[0].platform != "cpu":
        return {"donate_argnums": argnums}
    return {}


def make_sharded_hint_fn(mesh: Mesh, table_keys_ndim: dict,
                         query_keys_ndim: dict, kernel=None):
    """-> jitted fn(stacked_table, stacked_queries, shard_size) -> [B] i32
    global hint-rule index (-1 none) for the ENGINE's jax-sharded
    backends. `kernel` is the per-shard matcher — hashmatch (cuckoo,
    default) or fphash's hint_fp_match; both share the (idx, level)
    contract. shard_size is a traced scalar, so rule-count changes within
    the same caps reuse the compiled program; caps (shape) changes just
    retrace. Winner = pmax(match level) then pmin(global index) among
    level winners — Upstream.java:187 semantics as an ICI reduction."""
    import jax.numpy as jnp

    from ..ops.hashmatch import hint_hash_match
    hint_match = kernel or hint_hash_match

    BIG = 2 ** 30

    def body(ht, hq, shard_size):
        sid = jax.lax.axis_index("rules").astype(jnp.int32)
        ht0 = {k: v[0] for k, v in ht.items()}
        hq0 = {k: v[0] for k, v in hq.items()}
        hidx, hlvl = hint_match(ht0, hq0)
        lvl = jnp.where(hidx >= 0, hlvl, 0)
        best_lvl = jax.lax.pmax(lvl, "rules")
        gidx = jnp.where((lvl == best_lvl) & (hidx >= 0),
                         sid * shard_size + hidx, BIG)
        gmin = jax.lax.pmin(gidx, "rules")
        return jnp.where(best_lvl > 0, gmin, -1)

    # ndim values are the STACKED ndims (leading shard axis included)
    ba = batch_axes(mesh)
    in_specs = (
        {k: P("rules", *([None] * (nd - 1)))
         for k, nd in table_keys_ndim.items()},
        {k: P("rules", ba, *([None] * (nd - 2)))
         for k, nd in query_keys_ndim.items()},
        P(),
    )
    return jax.jit(_shard_map(body, mesh, in_specs, P(ba)),
                   **_donate_queries(mesh, (1,)))


def make_sharded_cidr_fn(mesh: Mesh, table_keys_ndim: dict,
                         with_port: bool, kernel=None):
    """-> jitted fn(stacked_table, a16, fam, [port,] shard_size) -> [B]
    i32 global first-match index (-1 none); first-match = one pmin over
    global indices (insert order is preserved across contiguous rule
    slices)."""
    import jax.numpy as jnp

    from ..ops.hashmatch import cidr_hash_match
    cidr_match = kernel or cidr_hash_match

    BIG = 2 ** 30

    if with_port:
        def body(t, a16, fam, port, shard_size):
            sid = jax.lax.axis_index("rules").astype(jnp.int32)
            t0 = {k: v[0] for k, v in t.items()}
            li = cidr_match(t0, a16, fam, port)
            g = jax.lax.pmin(jnp.where(li >= 0, sid * shard_size + li, BIG),
                             "rules")
            return jnp.where(g < BIG, g, -1)
        ba = batch_axes(mesh)
        q_specs = (P(ba, None), P(ba), P(ba), P())
    else:
        def body(t, a16, fam, shard_size):
            sid = jax.lax.axis_index("rules").astype(jnp.int32)
            t0 = {k: v[0] for k, v in t.items()}
            li = cidr_match(t0, a16, fam, None)
            g = jax.lax.pmin(jnp.where(li >= 0, sid * shard_size + li, BIG),
                             "rules")
            return jnp.where(g < BIG, g, -1)
        ba = batch_axes(mesh)
        q_specs = (P(ba, None), P(ba), P())

    in_specs = (
        {k: P("rules", *([None] * (nd - 1)))  # stacked ndims
         for k, nd in table_keys_ndim.items()},
    ) + q_specs
    return jax.jit(_shard_map(body, mesh, in_specs, P(ba)),
                   **_donate_queries(mesh, (1, 2, 3) if with_port
                                     else (1, 2)))


def make_sharded_classify(mesh: Mesh, hint_stab, route_stab, acl_stab,
                          example_hq: dict):
    """-> jitted fn(ht, rt, at, hq, a16, fam, port) -> [B, 3] i32 global
    (hint idx, route idx, acl idx), -1 for no match; runs the full hash
    classify SPMD over the (batch, rules) mesh. example_hq: one output
    of encode_hint_queries_sharded (shapes fix the query specs)."""
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    from ..ops.hashmatch import cidr_hash_match, hint_hash_match

    BIG = 2 ** 30
    h_size = hint_stab.shard_size
    r_size = route_stab.shard_size
    a_size = acl_stab.shard_size

    def body(ht, rt, at, hq, a16, fam, port):
        sid = jax.lax.axis_index("rules").astype(jnp.int32)
        ht0 = {k: v[0] for k, v in ht.items()}
        hq0 = {k: v[0] for k, v in hq.items()}
        hidx, hlvl = hint_hash_match(ht0, hq0)
        lvl = jnp.where(hidx >= 0, hlvl, 0)
        best_lvl = jax.lax.pmax(lvl, "rules")
        gidx = jnp.where((lvl == best_lvl) & (hidx >= 0),
                         sid * h_size + hidx, BIG)
        gmin = jax.lax.pmin(gidx, "rules")
        h_global = jnp.where(best_lvl > 0, gmin, -1)

        def cidr_global(t, port_, size):
            t0 = {k: v[0] for k, v in t.items()}
            li = cidr_hash_match(t0, a16, fam, port_)
            g = jax.lax.pmin(jnp.where(li >= 0, sid * size + li, BIG),
                             "rules")
            return jnp.where(g < BIG, g, -1)

        r_global = cidr_global(rt, None, r_size)
        a_global = cidr_global(at, port, a_size)
        return jnp.stack([h_global, r_global, a_global], axis=1)

    ba = batch_axes(mesh)
    in_specs = (
        _leading_rules_spec(hint_stab.arrays),
        _leading_rules_spec(route_stab.arrays),
        _leading_rules_spec(acl_stab.arrays),
        {k: P("rules", ba, *([None] * (v.ndim - 2)))
         for k, v in example_hq.items()},
        P(ba, None), P(ba), P(ba),
    )
    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=P(ba, None))
    return jax.jit(fn)
