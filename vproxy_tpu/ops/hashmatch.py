"""Hash-based classify kernels — the O(1)-per-query fast path.

The dense matchers (ops/matchers.py) reproduce the reference's linear
scans as matmuls: exact, but O(rules) FLOPs per query — a 100k-rule
table costs ~1 TFLOP per 4k batch, far past the 10M matches/s target.
These kernels replace the scan with cuckoo-hash probes + tiny gather
verification, so per-query work is O(labels + uri-lengths) regardless
of table size. Semantics stay bit-for-bit the reference's:

* hint match (Upstream.searchForGroup, Upstream.java:187-198; scoring
  Hint.matchLevel, Hint.java:92-160): a winning rule must have an
  exact/suffix/wildcard host match or an exact/prefix/wildcard uri
  match, so the candidate set is exactly
    - the host-table bucket for the query host (exact) and for each
      dot-suffix of it (suffix rules),
    - the uri-table bucket for each query-uri prefix whose length some
      rule uri has,
    - the (small) lists of host="*" / uri="*" rules.
  Each candidate is then scored with the full matchLevel formula from
  its gathered rule record — byte compares, no trust in hashes —
  and reduced with (max level, then min rule index).
* cidr first-match (RouteTable.lookup RouteTable.java:44,
  SecurityGroup.allow SecurityGroup.java:30-45): rules expand to the
  same <=3 (value,mask,family) patterns as the dense compiler; patterns
  group by (family, mask16) and each group gets a cuckoo table keyed on
  masked address bytes. Any rule matching a query is discoverable via
  its group's probe, so min-rule-index over all probe hits equals the
  ordered linear scan exactly (incl. ACL port-range buckets).

Query-side hashing is host-side numpy (rolling FNV-64: one pass gives
every dot-suffix / uri-prefix hash); the LPM kernel hashes masked
addresses on-device with FNV-32 (u32 wraparound matches numpy).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..rules.ir import AclRule, HintRule
from . import cuckoo as CK
from .tables import MAX_HOST, MAX_URI, V4, V6, _pad_cap

HOST_SHIFT = 10
URI_MAX_SCORE = 1023
DOT = ord(".")

# probe-count tiers for host dot-suffixes: static shapes, encoder picks
# the smallest tier covering the batch (jit caches one program per tier).
# Every padded probe is one wasted ~23ns row gather per query (measured
# r4), so the low tiers are fine-grained: typical 3-5-label domains land
# on 5/7 instead of 9
MAXP_TIERS = (5, 7, 9, 17, 33, 66)


def _pow2(n: int, lo: int = 2) -> int:
    c = lo
    while c < n:
        c <<= 1
    return c


# --------------------------------------------------------------- hint side


@dataclass
class HashHintTable:
    """Compiled hash-path hint table: device arrays + host-side meta the
    encoder needs (salts, caps, the rule-uri length set).

    `hw`/`uw` are the host/uri byte-compare windows — sized to the
    table's longest key (rounded up), not the global MAX_HOST/MAX_URI,
    because the query payload is h2d-bandwidth that bounds classify
    throughput: bytes beyond the longest rule key can never influence a
    match (exact needs equal lengths, suffix/prefix compare only rule
    bytes), so they are never shipped."""

    n: int
    r_cap: int
    arrays: dict  # numpy arrays; engine device_puts them
    host_cap: int
    host_salts: tuple
    uri_cap: int
    uri_salts: tuple
    lset: list  # distinct rule-uri lengths (normal rules)
    hw: int  # host window: max rule-host len + 1 boundary byte (padded)
    uw: int  # uri window: max rule-uri len (padded)
    caps: dict = field(default_factory=dict)  # all static caps for reuse


def _prune_list(rules, items, sig):
    seen, keep = set(), []
    for i in sorted(items):
        s = sig(rules[i])
        if s not in seen:
            seen.add(s)
            keep.append(i)
    return keep


def compile_hint_hash(rules: Sequence[HintRule],
                      caps: Optional[dict] = None) -> HashHintTable:
    caps = dict(caps or {})
    n = len(rules)
    r_cap = caps.get("r_cap") or _pad_cap(n, 256)
    if n > r_cap:
        r_cap = _pad_cap(n, 256)
    # past _PACK_I32_MAX rules the kernel's (level, index) reduction
    # switches from i32 packing to the two-pass form (see
    # hint_hash_match) — no capacity assert needed anymore

    host_buckets: dict[bytes, list[int]] = {}
    uri_buckets: dict[bytes, list[int]] = {}
    wh: list[int] = []
    wu: list[int] = []
    max_hl = max_ul = 0
    for i, r in enumerate(rules):
        if not (i & 31):
            CK.coop_yield()
        if r.is_empty():
            continue
        if r.host is not None:
            if len(r.host.encode()) > MAX_HOST:
                raise ValueError(f"host rule longer than {MAX_HOST}: {r.host!r}")
            max_hl = max(max_hl, len(r.host.encode()))
        if r.uri is not None:
            if len(r.uri.encode()) > MAX_URI:
                raise ValueError(f"uri rule longer than {MAX_URI}: {r.uri!r}")
            max_ul = max(max_ul, len(r.uri.encode()))
    # compare windows: +1 host byte for the suffix boundary dot
    hw = min(MAX_HOST + 1, max(caps.get("hw", 0), _pow2(max_hl + 1, 8)))
    uw = min(MAX_URI, max(caps.get("uw", 0), _pow2(max(max_ul, 1), 8)))

    r_active = np.zeros(r_cap, bool)
    r_port = np.zeros(r_cap, np.int32)
    r_host_kind = np.zeros(r_cap, np.int32)  # 0 none / 1 normal / 2 wild
    r_host_len = np.zeros(r_cap, np.int32)
    r_host = np.zeros((r_cap, hw), np.uint8)  # reversed bytes
    r_uri_kind = np.zeros(r_cap, np.int32)
    r_uri_len = np.zeros(r_cap, np.int32)
    r_uri = np.zeros((r_cap, uw), np.uint8)
    r_uri_score = np.zeros(r_cap, np.int32)

    for i, r in enumerate(rules):
        if not (i & 31):
            CK.coop_yield()
        if r.is_empty():
            continue
        r_active[i] = True
        r_port[i] = r.port
        if r.host is not None:
            hb = r.host.encode()[::-1]
            r_host_kind[i] = 2 if r.host == "*" else 1
            r_host_len[i] = len(hb)
            r_host[i, : len(hb)] = np.frombuffer(hb, np.uint8)
            host_buckets.setdefault(bytes(hb), []).append(i)
            if r.host == "*":
                wh.append(i)
        if r.uri is not None:
            ub = r.uri.encode()
            r_uri_kind[i] = 2 if r.uri == "*" else 1
            r_uri_len[i] = len(ub)
            r_uri[i, : len(ub)] = np.frombuffer(ub, np.uint8)
            r_uri_score[i] = min(len(ub) + 1, URI_MAX_SCORE)
            uri_buckets.setdefault(bytes(ub), []).append(i)
            if r.uri == "*":
                wu.append(i)

    # Bucket pruning (exactness-preserving): members of one bucket share
    # the keyed attribute, so a later member whose OTHER attributes equal
    # an earlier member's can never outscore it (same level, later index)
    # — keep only the earliest per residual signature. For uri buckets
    # the residual is just the port: a member whose host matches a query
    # surfaces via the (complete) host bucket with a >= level, so among
    # pure-uri contributions, earliest-per-port dominates. This is what
    # keeps candidate counts O(1) when thousands of rules share one uri.
    for bi, k in enumerate(host_buckets):
        if not (bi & 63):
            CK.coop_yield()
        host_buckets[k] = _prune_list(rules, host_buckets[k],
                                      lambda r: (r.uri, r.port))
    for bi, k in enumerate(uri_buckets):
        if not (bi & 63):
            CK.coop_yield()
        uri_buckets[k] = _prune_list(rules, uri_buckets[k], lambda r: r.port)
    # wh (host="*") members differ in uri, which the wildcard path must
    # itself score -> dedupe per (uri, port). wu (uri="*") members' host
    # relation is covered by the complete host buckets whenever it fires,
    # so the global list only represents the host-miss (0|1) case ->
    # earliest per port suffices even with thousands of wu rules.
    wh = _prune_list(rules, wh, lambda r: (r.uri, r.port))
    wu = _prune_list(rules, wu, lambda r: r.port)

    ht, hb_items = CK.build_cuckoo(host_buckets, hw,
                                   cap=caps.get("host_cap"), salt_base=1)
    ut, ub_items = CK.build_cuckoo(uri_buckets, uw,
                                   cap=caps.get("uri_cap"), salt_base=2)
    bh = max(caps.get("bh", 0), _pow2(int(ht.bucket_count.max(initial=1))))
    bu = max(caps.get("bu", 0), _pow2(int(ut.bucket_count.max(initial=1))))
    whc = max(caps.get("wh", 0), _pow2(len(wh), 2))
    wuc = max(caps.get("wu", 0), _pow2(len(wu), 2))
    hbc = max(caps.get("hb_items", 0), _pow2(max(len(hb_items), 1), 256))
    ubc = max(caps.get("ub_items", 0), _pow2(max(len(ub_items), 1), 256))

    lset = sorted({int(l) for l, k in zip(r_uri_len, r_uri_kind) if k == 1})
    lset_cap = max(caps.get("lset", 0), _pow2(max(len(lset), 1), 4))
    if len(lset) > lset_cap:
        lset_cap = _pow2(len(lset), 4)

    def pad_items(items, cap):
        out = np.full(cap, -1, np.int32)
        out[: len(items)] = items
        return out

    arrays = {
        "r_active": r_active, "r_port": r_port,
        "r_host_kind": r_host_kind, "r_host_len": r_host_len, "r_host": r_host,
        "r_uri_kind": r_uri_kind, "r_uri_len": r_uri_len, "r_uri": r_uri,
        "r_uri_score": r_uri_score,
        "hk_used": ht.used, "hk_len": ht.key_len, "hk_bytes": ht.key_bytes,
        "hk_bs": ht.bucket_start, "hk_bc": np.minimum(ht.bucket_count, bh),
        "hb_items": pad_items(hb_items, hbc),
        "uk_used": ut.used, "uk_len": ut.key_len, "uk_bytes": ut.key_bytes,
        "uk_bs": ut.bucket_start, "uk_bc": np.minimum(ut.bucket_count, bu),
        "ub_items": pad_items(ub_items, ubc),
        "wh_idx": pad_items(wh, whc), "wu_idx": pad_items(wu, wuc),
        # bucket caps as array shapes: [bh]/[bu] dummy arange carries the
        # static bucket width into the jitted kernel
        "bh_iota": np.arange(bh, dtype=np.int32),
        "bu_iota": np.arange(bu, dtype=np.int32),
    }
    return HashHintTable(
        n=n, r_cap=r_cap, arrays=arrays,
        host_cap=ht.cap, host_salts=(ht.salt1, ht.salt2),
        uri_cap=ut.cap, uri_salts=(ut.salt1, ut.salt2), lset=lset,
        hw=hw, uw=uw,
        caps={"r_cap": r_cap, "host_cap": ht.cap, "uri_cap": ut.cap,
              "bh": bh, "bu": bu, "wh": whc, "wu": wuc, "hw": hw, "uw": uw,
              "hb_items": hbc, "ub_items": ubc, "lset": lset_cap})


def _fill_query_windows(hints: Sequence, hw: int, uw: int, cap: int):
    """Shared query-byte-window fill for the vectorized encoders:
    -> (hostb [cap,hw] u8 reversed, hlen, has_host, urib [cap,uw] u8,
    ulen, has_uri, port). Rows past len(hints) stay zero (pad rows).
    The small-batch encoder fuses this walk with its per-hint hashing
    and intentionally does not share it."""
    q_hostb = np.zeros((cap, hw), np.uint8)
    q_hlen = np.zeros(cap, np.int32)
    q_has_host = np.zeros(cap, bool)
    q_urib = np.zeros((cap, uw), np.uint8)
    q_ulen = np.zeros(cap, np.int32)
    q_has_uri = np.zeros(cap, bool)
    q_port = np.zeros(cap, np.int32)
    for i, h in enumerate(hints):
        if h.host is not None:
            hb = h.host.encode()[::-1]
            q_hlen[i] = min(len(hb), 1 << 20)
            q_hostb[i, : min(len(hb), hw)] = np.frombuffer(hb[:hw],
                                                           np.uint8)
            q_has_host[i] = True
        if h.uri is not None:
            ub = h.uri.encode()
            q_ulen[i] = min(len(ub), 1 << 20)
            q_urib[i, : min(len(ub), uw)] = np.frombuffer(ub[:uw],
                                                          np.uint8)
            q_has_uri[i] = True
        q_port[i] = h.port
    return (q_hostb, q_hlen, q_has_host, q_urib, q_ulen, q_has_uri,
            q_port)


# the python-int FNV form lives in ops/cuckoo (single source for the
# bit-identity-critical constants); aliased for the hot loop below
_FNV64_MASK = CK._M64
_FNV64_PRIME_I = CK._FNV64_PRIME_I
_FNV64_OFFSET_I = CK._FNV64_OFFSET_I
# below this batch size the per-hint pure-python encoder wins: the
# vectorized rolling-FNV pass costs ~W sequential numpy calls whose
# per-call overhead dwarfs the math on accept-path-sized batches
# (measured 309us numpy vs ~60us python at b=8, 20k rules). The
# PR-6 crossover of 32 was measured against the 5-op dispatch chain;
# re-measured under the fused dispatch (PERF_NOTES round 12, both 20k
# and 200k tables) the python path's advantage ends at ~28 (b=24: 268
# vs 316us; b=28: 324 vs 328us; b=30: 328 vs 318us; b=32: 573 vs
# 346us) — the fused launch removed enough dispatch overhead that
# encode is a larger share of the batch, and the numpy pass amortizes
# sooner than the old 32 default assumed.
SMALL_ENCODE = int(os.environ.get("VPROXY_TPU_SMALL_ENCODE", "28"))


def _encode_hint_queries_small(hints: Sequence, tab: HashHintTable,
                               pad_to: int) -> dict:
    """Per-hint python encoder, bit-identical outputs to the vectorized
    path (same probe order: dot suffixes ascending, exact slot last;
    same shapes: MAXP tier + lset_cap widths), O(bytes) python ints
    instead of O(W) numpy dispatches."""
    b = len(hints)
    cap = max(b, pad_to)
    W = tab.hw
    q_hostb = np.zeros((cap, W), np.uint8)
    q_hlen = np.zeros(cap, np.int32)
    q_has_host = np.zeros(cap, bool)
    q_urib = np.zeros((cap, tab.uw), np.uint8)
    q_ulen = np.zeros(cap, np.int32)
    q_has_uri = np.zeros(cap, bool)
    q_port = np.zeros(cap, np.int32)

    s1, s2 = int(tab.host_salts[0]), int(tab.host_salts[1])
    us1, us2 = int(tab.uri_salts[0]), int(tab.uri_salts[1])
    hmask = tab.host_cap - 1
    umask = tab.uri_cap - 1
    probes: list[list] = []  # per hint: [(plen, slot1, slot2)]
    uprobes: list[list] = []  # per hint: [(lset_pos, plen, s1, s2)]
    need = 0
    for i, h in enumerate(hints):
        pr: list = []
        if h.host is not None:
            hb = h.host.encode()[::-1]
            hl = min(len(hb), 1 << 20)
            q_hlen[i] = hl
            win = hb[:W]
            q_hostb[i, : len(win)] = np.frombuffer(win, np.uint8)
            q_has_host[i] = True
            # one python pass: rolling FNV64 pair + dot probes
            h1 = _FNV64_OFFSET_I ^ s1
            h2 = _FNV64_OFFSET_I ^ s2
            lim = min(len(hb), W - 1)
            for p in range(lim):
                by = hb[p]
                if by == DOT and 1 <= p < hl:
                    pr.append((p, h1 & hmask, h2 & hmask))
                h1 = ((h1 ^ by) * _FNV64_PRIME_I) & _FNV64_MASK
                h2 = ((h2 ^ by) * _FNV64_PRIME_I) & _FNV64_MASK
            # boundary dot at position lim (a dot can be a probe
            # position without its byte being hashed into the prefix)
            if lim < len(hb) and lim < W and hb[lim] == DOT \
                    and 1 <= lim < hl:
                pr.append((lim, h1 & hmask, h2 & hmask))
            if hl <= W - 1:  # exact slot, last (vectorized order)
                pr.append((hl, h1 & hmask, h2 & hmask))
        probes.append(pr)
        need = max(need, len(pr))
        upr: list = []
        if h.uri is not None:
            ub = h.uri.encode()
            ul = min(len(ub), 1 << 20)
            q_ulen[i] = ul
            uwin = ub[: tab.uw]
            q_urib[i, : len(uwin)] = np.frombuffer(uwin, np.uint8)
            q_has_uri[i] = True
            u1 = _FNV64_OFFSET_I ^ us1
            u2 = _FNV64_OFFSET_I ^ us2
            pos = 0
            for li, l in enumerate(tab.lset):
                if l > ul:
                    break
                while pos < l:  # lset ascending: resume the roll
                    by = uwin[pos] if pos < len(uwin) else 0
                    u1 = ((u1 ^ by) * _FNV64_PRIME_I) & _FNV64_MASK
                    u2 = ((u2 ^ by) * _FNV64_PRIME_I) & _FNV64_MASK
                    pos += 1
                upr.append((li, l, u1 & umask, u2 & umask))
        uprobes.append(upr)
        q_port[i] = h.port

    maxp = next((t for t in MAXP_TIERS if t >= need), MAXP_TIERS[-1])
    hp_len = np.full((cap, maxp), -1, np.int32)
    hp_slot1 = np.full((cap, maxp), -1, np.int32)
    hp_slot2 = np.full((cap, maxp), -1, np.int32)
    for i, pr in enumerate(probes):
        for j, (plen, sl1, sl2) in enumerate(pr[:maxp]):
            hp_len[i, j] = plen
            hp_slot1[i, j] = sl1
            hp_slot2[i, j] = sl2
    lset_cap = tab.caps["lset"]
    up_len = np.full((cap, lset_cap), -1, np.int32)
    up_slot1 = np.full((cap, lset_cap), -1, np.int32)
    up_slot2 = np.full((cap, lset_cap), -1, np.int32)
    for i, upr in enumerate(uprobes):
        for (li, l, sl1, sl2) in upr:
            up_len[i, li] = l
            up_slot1[i, li] = sl1
            up_slot2[i, li] = sl2

    return {
        "hostb": q_hostb, "hlen": q_hlen, "has_host": q_has_host,
        "urib": q_urib, "ulen": q_ulen, "has_uri": q_has_uri,
        "port": q_port,
        "hp_len": hp_len, "hp_slot1": hp_slot1, "hp_slot2": hp_slot2,
        "up_len": up_len, "up_slot1": up_slot1, "up_slot2": up_slot2,
    }


def encode_hint_queries(hints: Sequence, tab: HashHintTable,
                        pad_to: int = 0) -> dict:
    """Hints -> device-ready query dict incl. precomputed probe slots.

    Host-side work is vectorized numpy: two rolling-FNV passes over the
    reversed host window and the uri window give every suffix/prefix
    hash; probe positions are the dots (host) and the table's rule-uri
    length set (uri). Batches up to SMALL_ENCODE take the per-hint
    python path instead (same outputs, ~5x cheaper at accept-path batch
    sizes). pad_to: emit arrays at this batch bucket, pad rows being
    invalid probes (never encode padding).
    """
    if len(hints) <= SMALL_ENCODE:
        return _encode_hint_queries_small(hints, tab,
                                          max(pad_to, len(hints)))
    b = len(hints)
    W = tab.hw  # reversed-host compare window (suffix boundary incl.)
    (q_hostb, q_hlen, q_has_host, q_urib, q_ulen, q_has_uri,
     q_port) = _fill_query_windows(hints, W, tab.uw, b)

    # --- host probes: exact (p = hlen) + every dot position p (suffix).
    # Valid probe lengths p <= hw-1 (no rule host is longer), so the
    # rolling window of hw-1 bytes covers every probe, incl. a boundary
    # dot at position hw-1 (max-length rule host + '.').
    h1 = CK.rolling_fnv64(q_hostb[:, : W - 1], tab.host_salts[0])
    h2 = CK.rolling_fnv64(q_hostb[:, : W - 1], tab.host_salts[1])
    pos = np.arange(W)[None, :]
    probe_ok = np.concatenate([
        (q_hostb == DOT) & (pos < q_hlen[:, None]) & (pos >= 1),
        (q_has_host & (q_hlen <= W - 1))[:, None],  # exact slot
    ], axis=1) & q_has_host[:, None]  # [B, W+1]
    probe_len = np.concatenate([
        np.broadcast_to(pos, (b, W)),
        q_hlen[:, None],
    ], axis=1).astype(np.int32)
    need = int(probe_ok.sum(axis=1).max(initial=0))
    maxp = next((t for t in MAXP_TIERS if t >= need), MAXP_TIERS[-1])

    # compact valid probes to the left (stable argsort on ~ok)
    order = np.argsort(~probe_ok, axis=1, kind="stable")[:, :maxp]
    pv = np.take_along_axis(probe_ok, order, 1)
    pl = np.where(pv, np.take_along_axis(probe_len, order, 1), 0)
    hp_len = np.where(pv, pl, -1).astype(np.int32)
    mask = np.uint64(tab.host_cap - 1)
    hp_slot1 = np.where(pv, (np.take_along_axis(h1, pl, 1) & mask).astype(np.int32), -1)
    hp_slot2 = np.where(pv, (np.take_along_axis(h2, pl, 1) & mask).astype(np.int32), -1)

    # --- uri probes at each rule-uri length <= query len
    lset_cap = tab.caps["lset"]
    lset = np.full(lset_cap, -1, np.int32)
    lset[: len(tab.lset)] = tab.lset
    u1 = CK.rolling_fnv64(q_urib, tab.uri_salts[0])
    u2 = CK.rolling_fnv64(q_urib, tab.uri_salts[1])
    lv = (lset[None, :] >= 0) & (lset[None, :] <= q_ulen[:, None]) & \
        q_has_uri[:, None]
    ll = np.where(lv, np.maximum(lset[None, :], 0), 0)
    umask = np.uint64(tab.uri_cap - 1)
    up_len = np.where(lv, ll, -1).astype(np.int32)
    up_slot1 = np.where(lv, (np.take_along_axis(u1, ll, 1) & umask).astype(np.int32), -1)
    up_slot2 = np.where(lv, (np.take_along_axis(u2, ll, 1) & umask).astype(np.int32), -1)

    return {
        "hostb": q_hostb, "hlen": q_hlen, "has_host": q_has_host,
        "urib": q_urib, "ulen": q_ulen, "has_uri": q_has_uri, "port": q_port,
        "hp_len": hp_len, "hp_slot1": hp_slot1, "hp_slot2": hp_slot2,
        "up_len": up_len, "up_slot1": up_slot1, "up_slot2": up_slot2,
    }


def _probe_buckets(slots, plen, used, klen, kbytes, bs, bc, qbytes, iota):
    """Byte-verified cuckoo probe -> candidate rule indices.

    slots/plen: [B, P] (slot -1 / len -1 = invalid); table arrays used
    [C], klen [C], kbytes [C, K], bs/bc [C]; qbytes [B, K'] query window
    (K' >= K); iota [BK]. -> [B, P, BK] candidate indices (-1 = none).
    """
    k = kbytes.shape[1]
    s = jnp.maximum(slots, 0)
    ok = (slots >= 0) & used[s] & (klen[s] == plen)
    kb = kbytes[s]  # [B, P, K]
    span = jnp.arange(k, dtype=jnp.int32)
    eq = (kb == qbytes[:, None, :k]) | (span[None, None, :] >= plen[:, :, None])
    ok = ok & jnp.all(eq, axis=-1)
    start, cnt = bs[s], bc[s]
    j = iota[None, None, :]
    return jnp.where(ok[:, :, None] & (j < cnt[:, :, None]),
                     start[:, :, None] + j, -1)


# largest r_cap whose (level, index) pair still packs into one i32
# (max level = (3 << HOST_SHIFT) + URI_MAX_SCORE = 4095)
_PACK_I32_MAX = (2**31 - 1) // 4096 - 1


def _reduce_best(level, c, r_cap: int):
    """(max level, min index among level-winners) -> (idx, level).
    Small tables keep the single-reduction i32 packing; past
    _PACK_I32_MAX (a million-rule single table — the fused path's
    scale tier) the packed product would overflow i32, so the same
    winner comes from two reductions. Static branch (r_cap is a trace
    constant): zero cost for the small case, identical winners in
    both."""
    if r_cap <= _PACK_I32_MAX:
        pack = jnp.where(level > 0, level * (r_cap + 1) + (r_cap - c), 0)
        best = jnp.max(pack, axis=1)
        best_level = best // (r_cap + 1)
        best_idx = r_cap - best % (r_cap + 1)
        return jnp.where(best > 0, best_idx, -1).astype(jnp.int32), \
            best_level.astype(jnp.int32)
    best_level = jnp.max(level, axis=1)
    cand = jnp.where((level == best_level[:, None]) & (level > 0), c,
                     r_cap)
    best_idx = jnp.min(cand, axis=1)
    return jnp.where(best_level > 0, best_idx, -1).astype(jnp.int32), \
        best_level.astype(jnp.int32)


def hint_hash_match(t: dict, q: dict):
    """-> (best rule idx [B] i32 or -1, best level [B] i32).

    Candidates from host/uri probes + wildcard lists, scored with the
    full Hint.matchLevel formula from gathered rule records.
    """
    r_cap = t["r_active"].shape[0]
    b = q["hostb"].shape[0]

    ch1 = _probe_buckets(q["hp_slot1"], q["hp_len"], t["hk_used"], t["hk_len"],
                         t["hk_bytes"], t["hk_bs"], t["hk_bc"], q["hostb"],
                         t["bh_iota"])
    ch2 = _probe_buckets(q["hp_slot2"], q["hp_len"], t["hk_used"], t["hk_len"],
                         t["hk_bytes"], t["hk_bs"], t["hk_bc"], q["hostb"],
                         t["bh_iota"])
    cu1 = _probe_buckets(q["up_slot1"], q["up_len"], t["uk_used"], t["uk_len"],
                         t["uk_bytes"], t["uk_bs"], t["uk_bc"], q["urib"],
                         t["bu_iota"])
    cu2 = _probe_buckets(q["up_slot2"], q["up_len"], t["uk_used"], t["uk_len"],
                         t["uk_bytes"], t["uk_bs"], t["uk_bc"], q["urib"],
                         t["bu_iota"])
    host_cand = jnp.where(ch1 >= 0, t["hb_items"][jnp.maximum(ch1, 0)], -1)
    host_cand2 = jnp.where(ch2 >= 0, t["hb_items"][jnp.maximum(ch2, 0)], -1)
    uri_cand = jnp.where(cu1 >= 0, t["ub_items"][jnp.maximum(cu1, 0)], -1)
    uri_cand2 = jnp.where(cu2 >= 0, t["ub_items"][jnp.maximum(cu2, 0)], -1)

    cand = jnp.concatenate([
        host_cand.reshape(b, -1), host_cand2.reshape(b, -1),
        uri_cand.reshape(b, -1), uri_cand2.reshape(b, -1),
        jnp.broadcast_to(t["wh_idx"][None], (b, t["wh_idx"].shape[0])),
        jnp.broadcast_to(t["wu_idx"][None], (b, t["wu_idx"].shape[0])),
    ], axis=1)  # [B, NC]

    c = jnp.maximum(cand, 0)
    valid = (cand >= 0) & t["r_active"][c]

    # port gate (Hint.java: ports both set and different -> no match)
    rp = t["r_port"][c]
    pg = (q["port"][:, None] == 0) | (rp == 0) | (q["port"][:, None] == rp)

    # host level: exact=3 / dot-suffix=2 / wildcard=1 (max of applicable)
    hw = t["r_host"].shape[1]
    hk, hl_ = t["r_host_kind"][c], t["r_host_len"][c]
    rb = t["r_host"][c]  # [B, NC, hw]
    span = jnp.arange(hw, dtype=jnp.int32)
    heq = jnp.all((rb == q["hostb"][:, None, :hw]) |
                  (span[None, None, :] >= hl_[:, :, None]), axis=-1)
    exact = heq & (hl_ == q["hlen"][:, None])
    boundary = jnp.take_along_axis(
        q["hostb"], jnp.clip(hl_, 0, hw - 1), axis=1)
    suffix = heq & (hl_ < q["hlen"][:, None]) & (boundary == DOT)
    host_level = jnp.maximum(
        jnp.maximum(jnp.where(exact, 3, 0), jnp.where(suffix, 2, 0)),
        jnp.where(hk == 2, 1, 0))
    host_level = jnp.where((hk > 0) & q["has_host"][:, None], host_level, 0)

    # uri level: exact/prefix -> min(len(rule.uri)+1, 1023), wildcard -> 1
    uw = t["r_uri"].shape[1]
    uk, ul = t["r_uri_kind"][c], t["r_uri_len"][c]
    ub = t["r_uri"][c]  # [B, NC, uw]
    uspan = jnp.arange(uw, dtype=jnp.int32)
    ueq = jnp.all((ub == q["urib"][:, None, :]) |
                  (uspan[None, None, :] >= ul[:, :, None]), axis=-1)
    prefix = ueq & (ul <= q["ulen"][:, None])
    uri_level = jnp.maximum(jnp.where(prefix, t["r_uri_score"][c], 0),
                            jnp.where(uk == 2, 1, 0))
    uri_level = jnp.where((uk > 0) & q["has_uri"][:, None], uri_level, 0)

    level = (host_level << HOST_SHIFT) + uri_level
    level = jnp.where(valid & pg, level, 0)
    return _reduce_best(level, c, r_cap)


# --------------------------------------------------------------- cidr side


def _expand_patterns(net) -> list:
    """Network -> [(key16, mask16, family)] reproducing Network.maskMatch
    (Network.java:183-278) — same cases as tables._expand_cidr."""
    ip, mask = net.ip, net.mask
    out = []

    def mk(key, m, fam):
        out.append((bytes(np.frombuffer(bytes(key), np.uint8) &
                          np.frombuffer(bytes(m), np.uint8)), bytes(m), fam))

    if len(ip) == 4:
        mk(b"\x00" * 12 + ip, b"\x00" * 12 + mask, V4)
        mk(b"\x00" * 12 + ip, b"\xff" * 12 + mask, V6)
        mk(b"\x00" * 10 + b"\xff\xff" + ip, b"\xff" * 12 + mask, V6)
    elif len(mask) == 4:
        mk(ip[:4] + b"\x00" * 12, mask + b"\x00" * 12, V6)
    else:
        mk(ip, mask, V6)
        hi_ok = all(b == 0 for b in ip[:10]) and ip[10:12] in (b"\x00\x00", b"\xff\xff")
        if hi_ok:
            mk(b"\x00" * 12 + ip[12:], b"\x00" * 12 + mask[12:], V4)
    return out


@dataclass
class HashCidrTable:
    n: int
    r_cap: int
    arrays: dict
    caps: dict = field(default_factory=dict)


def _fnv32_bytes(key: bytes, salt: int) -> int:
    return int(CK.fnv32_masked(np.frombuffer(key, np.uint8), salt))


def compile_cidr_hash(networks: Sequence, acl: Optional[Sequence[AclRule]] = None,
                      caps: Optional[dict] = None) -> HashCidrTable:
    caps = dict(caps or {})
    n = len(networks)
    r_cap = caps.get("r_cap") or _pad_cap(n, 256)
    if n > r_cap:
        r_cap = _pad_cap(n, 256)

    groups: dict[tuple, dict[bytes, list[int]]] = {}
    for i, net in enumerate(networks):
        if not (i & 31):
            CK.coop_yield()
        for key, mask, fam in _expand_patterns(net):
            groups.setdefault((fam, mask), {}).setdefault(key, []).append(i)

    g_live = sorted(groups.keys())
    g_cap = max(caps.get("g_cap", 0), _pow2(max(len(g_live), 1), 8))
    if len(g_live) > g_cap:
        g_cap = _pow2(len(g_live), 8)

    g_fam = np.full(g_cap, -1, np.int32)
    g_mask = np.zeros((g_cap, 16), np.uint8)
    g_off = np.zeros(g_cap, np.int32)
    g_capmask = np.zeros(g_cap, np.int32)
    g_salt1 = np.zeros(g_cap, np.uint32)
    g_salt2 = np.zeros(g_cap, np.uint32)

    tabs = []
    flat_items: list[int] = []
    off = 0
    bk = caps.get("bk", 1)
    for gi, (fam, mask) in enumerate(g_live):
        t, items = CK.build_cuckoo(groups[(fam, mask)], 16,
                                   hasher=_fnv32_bytes, salt_base=3 + gi)
        g_fam[gi] = fam
        g_mask[gi] = np.frombuffer(mask, np.uint8)
        g_off[gi] = off
        g_capmask[gi] = t.cap - 1
        g_salt1[gi] = t.salt1
        g_salt2[gi] = t.salt2
        t.bucket_start += len(flat_items)
        flat_items.extend(items.tolist())
        bk = max(bk, _pow2(int(t.bucket_count.max(initial=1))))
        tabs.append(t)
        off += t.cap

    ct = max(caps.get("ct", 0), _pow2(max(off, 1), 256))
    s_used = np.zeros(ct, bool)
    s_key = np.zeros((ct, 16), np.uint8)
    s_bs = np.zeros(ct, np.int32)
    s_bc = np.zeros(ct, np.int32)
    o = 0
    for t in tabs:
        s_used[o: o + t.cap] = t.used
        s_key[o: o + t.cap] = t.key_bytes
        s_bs[o: o + t.cap] = t.bucket_start
        s_bc[o: o + t.cap] = np.minimum(t.bucket_count, bk)
        o += t.cap

    cb = max(caps.get("cb", 0), _pow2(max(len(flat_items), 1), 256))
    cb_items = np.full(cb, -1, np.int32)
    cb_items[: len(flat_items)] = flat_items

    r_valid = np.zeros(r_cap, bool)
    r_valid[:n] = True
    min_port = np.zeros(r_cap, np.int32)
    max_port = np.full(r_cap, 65535, np.int32)
    allow = np.zeros(r_cap, bool)
    if acl is not None:
        for i, r in enumerate(acl):
            min_port[i], max_port[i], allow[i] = r.min_port, r.max_port, r.allow

    arrays = {
        "g_fam": g_fam, "g_mask": g_mask, "g_off": g_off,
        "g_capmask": g_capmask, "g_salt1": g_salt1, "g_salt2": g_salt2,
        "s_used": s_used, "s_key": s_key, "s_bs": s_bs, "s_bc": s_bc,
        "cb_items": cb_items, "r_valid": r_valid,
        "min_port": min_port, "max_port": max_port, "allow": allow,
        "bk_iota": np.arange(bk, dtype=np.int32),
    }
    return HashCidrTable(n=n, r_cap=r_cap, arrays=arrays,
                         caps={"r_cap": r_cap, "g_cap": g_cap, "ct": ct,
                               "cb": cb, "bk": bk})


def _fnv32_device(masked: jnp.ndarray, salt: jnp.ndarray) -> jnp.ndarray:
    """masked [B, G, 16] u8, salt [G] u32 -> [B, G] u32; bit-identical to
    cuckoo.fnv32_masked (u32 wraparound multiply)."""
    h = jnp.broadcast_to((CK.FNV32_OFFSET ^ salt)[None, :], masked.shape[:2])
    prime = jnp.uint32(CK.FNV32_PRIME)
    for p in range(16):
        h = (h ^ masked[:, :, p].astype(jnp.uint32)) * prime
    return h


def cidr_hash_match(t: dict, addr16: jnp.ndarray, fam: jnp.ndarray,
                    port: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """-> first-matching rule index [B] i32 (ordered-scan semantics), -1
    if none. addr16 [B,16] u8, fam [B] i32, port [B] i32 (ACL only)."""
    r_cap = t["r_valid"].shape[0]
    b = addr16.shape[0]
    masked = addr16[:, None, :] & t["g_mask"][None]  # [B, G, 16]
    gok = (t["g_fam"][None] >= 0) & (fam[:, None] == t["g_fam"][None])

    cands = []
    for salt in (t["g_salt1"], t["g_salt2"]):
        h = _fnv32_device(masked, salt)
        slot = t["g_off"][None] + (
            h.astype(jnp.int32) & t["g_capmask"][None])
        key = t["s_key"][slot]  # [B, G, 16]
        ok = gok & t["s_used"][slot] & jnp.all(key == masked, axis=-1)
        start, cnt = t["s_bs"][slot], t["s_bc"][slot]
        j = t["bk_iota"][None, None, :]
        cands.append(jnp.where(ok[:, :, None] & (j < cnt[:, :, None]),
                               start[:, :, None] + j, -1))
    slot_cand = jnp.concatenate(cands, axis=1).reshape(b, -1)
    cand = jnp.where(slot_cand >= 0,
                     t["cb_items"][jnp.maximum(slot_cand, 0)], -1)
    c = jnp.maximum(cand, 0)
    valid = (cand >= 0) & t["r_valid"][c]
    if port is not None:
        valid = valid & (t["min_port"][c] <= port[:, None]) & \
            (port[:, None] <= t["max_port"][c])
    first = jnp.min(jnp.where(valid, c, r_cap), axis=1).astype(jnp.int32)
    return jnp.where(first < r_cap, first, -1)


def classify_hash_all(hint_t: dict, route_t: dict, acl_t: dict,
                      hint_q: dict, addr16: jnp.ndarray, fam: jnp.ndarray,
                      port: jnp.ndarray) -> jnp.ndarray:
    """The fused flagship step: one dispatch classifies a micro-batch of
    LB/DNS hints + route LPM + ACL checks; one packed [B, 3] i32 result
    so the host pays a single d2h per step."""
    h_idx, _ = hint_hash_match(hint_t, hint_q)
    r_idx = cidr_hash_match(route_t, addr16, fam, None)
    a_idx = cidr_hash_match(acl_t, addr16, fam, port)
    return jnp.stack([h_idx, r_idx, a_idx], axis=1)


hint_hash_jit = jax.jit(hint_hash_match)
cidr_hash_jit = jax.jit(cidr_hash_match)
classify_hash_jit = jax.jit(classify_hash_all)


# ----------------------------------------------------- mesh-sharded path
#
# Rule-axis sharding for the hash path: the rule list is split into S
# contiguous slices, each compiled into its OWN cuckoo table (hash
# probing is slot-local, so sharding the compiled arrays directly would
# turn every probe into a cross-device gather). All shards share one
# unified `caps` dict, so the per-shard arrays have identical shapes and
# stack along a leading shard axis that carries the mesh's "rules"
# PartitionSpec. Each device runs the UNCHANGED single-shard kernel on
# its local slice inside shard_map; the global winner is a two-phase
# collective reduction (pmax best-level, then pmin global-index among
# level-winners — exactly Upstream.java:187's strictly-greater-max +
# earliest-index-tie semantics, distributed).


@dataclass
class ShardedHashTable:
    """S per-shard tables with unified shapes, stacked for the mesh."""

    shards: list  # per-shard HashHintTable | HashCidrTable
    arrays: dict  # stacked [S, ...] numpy arrays
    shard_size: int  # rules per shard (global idx = shard * size + local)
    n: int
    r_cap: int  # per-shard capacity
    # hint tables only (compile_hint_hash_sharded): the sorted union of
    # the shards' rule-uri length sets, precomputed so the single-pass
    # encoder does no per-dispatch set algebra; None for cidr/foreign
    # stabs (the encoder falls back to the legacy per-shard path)
    lset_u: Optional[list] = None


def _unify_caps(tabs_caps: list) -> dict:
    out: dict = {}
    for c in tabs_caps:
        for k, v in c.items():
            out[k] = max(out.get(k, 0), v)
    return out


class CapsExceeded(Exception):
    """A caps-reusing recompile outgrew the reused shapes — the caller's
    no-retrace update contract cannot hold; rebuild tables + fn."""


def _compile_sharded(items: Sequence, n_shards: int, compile_one,
                     caps: Optional[dict]) -> ShardedHashTable:
    """compile_one(slice, item_offset, caps) -> per-shard table; the
    offset is the slice's start index in `items`, so positional side
    tables (ACL windows) stay aligned with the slicing by construction.
    When caps is supplied (the runtime-update fast path), the result
    MUST fit: growth raises CapsExceeded instead of silently changing
    shapes and retracing the caller's jitted classify.

    Memory-lean: once the per-shard arrays are stacked, the per-shard
    copies are dropped (the shard objects stay — encoders read their
    salts/caps/lset, never the arrays). A 1M-rule table would otherwise
    sit in host RAM twice before it ever reaches the device."""
    reused = dict(caps) if caps else None
    per = max(1, -(-len(items) // n_shards))  # ceil; empty tail shards ok
    slices = [list(items[d * per: (d + 1) * per]) for d in range(n_shards)]
    caps = dict(caps or {})
    for _ in range(6):  # caps only grow; fixed point in a few rounds
        tabs = []
        for d, s in enumerate(slices):
            tabs.append(compile_one(s, d * per, caps))
            CK.coop_yield()  # standby-compile courtesy: explicit
            #                  preemption point between shard builds
        merged = _unify_caps([t.caps for t in tabs])
        if all(t.caps == merged for t in tabs):
            if reused is not None and merged != reused:
                raise CapsExceeded(
                    f"update outgrew reused caps: {reused} -> {merged}")
            arrays = {}
            for k in tabs[0].arrays:
                CK.coop_yield()  # stack chunks are multi-MB memcpys:
                #                  paced per key like the build loops
                arrays[k] = np.stack([t.arrays[k] for t in tabs])
            for t in tabs:
                t.arrays = {}
            return ShardedHashTable(shards=tabs, arrays=arrays,
                                    shard_size=per, n=len(items),
                                    r_cap=tabs[0].r_cap)
        caps = merged
    raise RuntimeError("sharded table caps did not converge")


def compile_hint_hash_sharded(rules: Sequence[HintRule], n_shards: int,
                              caps: Optional[dict] = None) -> ShardedHashTable:
    """Per-shard compiles under unified caps, plus the UNION uri-length
    cap ("lset_u") the single-pass sharded encoder sizes its probe axis
    by: a caps-stable width, so same-caps rule updates keep one query
    trace shape (the no-retrace contract) — an update whose uri-length
    union outgrows it raises CapsExceeded like any other caps growth
    (the engine transparently rebuilds + retraces once)."""
    reused_u = (caps or {}).get("lset_u")
    inner = dict(caps) if caps else None
    if inner is not None:
        inner.pop("lset_u", None)  # per-shard compiles don't know it
    stab = _compile_sharded(
        rules, n_shards,
        lambda s, off, caps: compile_hint_hash(s, caps=caps), inner)
    union = set()
    for t in stab.shards:
        union.update(t.lset)
    u_cap = _pow2(max(len(union), 1), 4)
    if reused_u:
        if u_cap > reused_u:
            raise CapsExceeded(
                f"uri-length union outgrew reused cap: {reused_u} -> "
                f"{u_cap}")
        u_cap = reused_u
    for t in stab.shards:
        t.caps["lset_u"] = u_cap
    stab.lset_u = sorted(union)
    return stab


def compile_cidr_hash_sharded(networks: Sequence, n_shards: int,
                              acl: Optional[Sequence[AclRule]] = None,
                              caps: Optional[dict] = None) -> ShardedHashTable:
    # each shard's ACL window follows its rule slice positionally (the
    # offset comes FROM the slicer, so they cannot drift apart)
    return _compile_sharded(
        networks, n_shards,
        lambda s, off, caps: compile_cidr_hash(
            s, acl=None if acl is None else acl[off: off + len(s)],
            caps=caps), caps)


def encode_hint_queries_sharded(hints: Sequence, stab: ShardedHashTable,
                                pad_to: Optional[int] = None) -> dict:
    """Per-shard probe encoding stacked on the leading shard axis.

    Probe slots/salts are shard-local, so the same hint batch encodes
    differently per shard — but only in the HASH VALUES: the unified
    caps guarantee every shard shares the compare windows and table
    capacities, and the probe POSITIONS (dots, uri lengths) depend only
    on query content. So this runs the byte walk and the rolling-FNV
    pass ONCE for all shards (rolling_fnv64_multi over the salt
    vector), instead of the S sequential re-encodes the original path
    paid — measured 8x of the whole dispatch's host cost at S=8.

    uri probes ride the UNION of the shards' rule-uri length sets: a
    probe at a length some shard lacks byte-verifies off (no key of
    that length exists there), so correctness is per-shard exact while
    the probe arrays stay shard-uniform.

    pad_to: encode the real hints only and zero/-1-fill the probe rows
    up to the batch bucket (a pad row has no probes and can never
    match). Each device still receives only its own slice (the stacked
    dims are sharded (rules, batch) on the mesh)."""
    shards = stab.shards
    t0 = shards[0]
    # compile_hint_hash_sharded guarantees unified shard shapes and
    # precomputes the uri-length union; a foreign-built stab (no
    # lset_u) pays the uniformity scan once per dispatch or drops to
    # the legacy per-shard encode
    if stab.lset_u is None and not all(
            t.hw == t0.hw and t.uw == t0.uw
            and t.host_cap == t0.host_cap and t.uri_cap == t0.uri_cap
            for t in shards):
        # non-unified shard shapes (foreign-built stab): legacy path
        if pad_to and pad_to > len(hints):
            from ..rules.ir import Hint
            hints = list(hints) + [Hint()] * (pad_to - len(hints))
        per = [encode_hint_queries(hints, t) for t in shards]
        return {k: np.stack([p[k] for p in per]) for k in per[0]}

    S = len(shards)
    b = len(hints)
    cap = max(b, pad_to or 0)
    W = t0.hw
    (q_hostb, q_hlen, q_has_host, q_urib, q_ulen, q_has_uri,
     q_port) = _fill_query_windows(hints, W, t0.uw, cap)

    def shared(a: np.ndarray) -> np.ndarray:
        # shard-invariant keys: a zero-stride broadcast view on the
        # shard axis (device_put materializes each device's slice)
        return np.broadcast_to(a, (S,) + a.shape)

    # --- host probes (positions shared; slots per shard salt)
    h1 = CK.rolling_fnv64_multi(
        q_hostb[:, : W - 1],
        [t.host_salts[0] for t in shards])  # [S, cap, W]
    h2 = CK.rolling_fnv64_multi(
        q_hostb[:, : W - 1], [t.host_salts[1] for t in shards])
    pos = np.arange(W)[None, :]
    probe_ok = np.concatenate([
        (q_hostb == DOT) & (pos < q_hlen[:, None]) & (pos >= 1),
        (q_has_host & (q_hlen <= W - 1))[:, None],  # exact slot
    ], axis=1) & q_has_host[:, None]  # [cap, W+1]
    probe_len = np.concatenate([
        np.broadcast_to(pos, (cap, W)), q_hlen[:, None],
    ], axis=1).astype(np.int32)
    need = int(probe_ok.sum(axis=1).max(initial=0))
    maxp = next((t for t in MAXP_TIERS if t >= need), MAXP_TIERS[-1])
    order = np.argsort(~probe_ok, axis=1, kind="stable")[:, :maxp]
    pv = np.take_along_axis(probe_ok, order, 1)
    pl = np.where(pv, np.take_along_axis(probe_len, order, 1), 0)
    hp_len = np.where(pv, pl, -1).astype(np.int32)  # [cap, P] shared
    mask = np.uint64(t0.host_cap - 1)
    pl_s = np.broadcast_to(pl, (S,) + pl.shape)
    hp_slot1 = np.where(pv[None],
                        (np.take_along_axis(h1, pl_s, 2) & mask)
                        .astype(np.int32), -1)
    hp_slot2 = np.where(pv[None],
                        (np.take_along_axis(h2, pl_s, 2) & mask)
                        .astype(np.int32), -1)

    # --- uri probes at the UNION of the shards' rule-uri length sets;
    # width = the caps-stable "lset_u" cap (compile_hint_hash_sharded)
    # so caps-reusing updates keep ONE query trace shape
    lset_u = stab.lset_u if stab.lset_u is not None else sorted(
        set().union(*[set(t.lset) for t in shards]))
    lw = t0.caps.get("lset_u") or _pow2(max(len(lset_u), 1), 4)
    lset = np.full(lw, -1, np.int32)
    lset[: len(lset_u)] = lset_u
    u1 = CK.rolling_fnv64_multi(q_urib,
                                [t.uri_salts[0] for t in shards])
    u2 = CK.rolling_fnv64_multi(q_urib,
                                [t.uri_salts[1] for t in shards])
    lv = (lset[None, :] >= 0) & (lset[None, :] <= q_ulen[:, None]) & \
        q_has_uri[:, None]  # [cap, lw]
    ll = np.where(lv, np.maximum(lset[None, :], 0), 0)
    umask = np.uint64(t0.uri_cap - 1)
    up_len = np.where(lv, ll, -1).astype(np.int32)  # shared
    ll_s = np.broadcast_to(ll, (S,) + ll.shape)
    up_slot1 = np.where(lv[None],
                        (np.take_along_axis(u1, ll_s, 2) & umask)
                        .astype(np.int32), -1)
    up_slot2 = np.where(lv[None],
                        (np.take_along_axis(u2, ll_s, 2) & umask)
                        .astype(np.int32), -1)

    return {
        "hostb": shared(q_hostb), "hlen": shared(q_hlen),
        "has_host": shared(q_has_host),
        "urib": shared(q_urib), "ulen": shared(q_ulen),
        "has_uri": shared(q_has_uri), "port": shared(q_port),
        "hp_len": shared(hp_len), "hp_slot1": hp_slot1,
        "hp_slot2": hp_slot2,
        "up_len": shared(up_len), "up_slot1": up_slot1,
        "up_slot2": up_slot2,
    }
