"""Host-side cuckoo hash tables for the classify() fast path.

The dense matmul matchers (ops/matchers.py) vectorize the reference's
linear scans (Upstream.java:187, RouteTable.java:44) — correct, but
O(rules) FLOPs per query. These tables give the O(1) path: each rule key
(reversed host, uri prefix, masked CIDR bytes) lives in exactly one of
two cuckoo slots, so a query resolves with 2 gather probes per candidate
position. Slots carry (bucket_start, bucket_count) into a rule-index
array so multiple rules sharing one key (same host, different uri/port;
same CIDR, different port range) stay distinguishable.

Hashes are salted FNV-1a. Collision quality only affects build success —
the device kernels byte-verify every probed key, so matching is exact
regardless of hash behavior. Build retries with fresh salts on a cuckoo
cycle and doubles capacity if salts alone cannot place all keys.

Query-side helpers compute rolling (prefix) hashes so one numpy pass
yields the hash of every dot-suffix of a host / every prefix of a uri —
the probe positions for suffix-rule and uri-prefix-rule matching.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

FNV64_OFFSET = np.uint64(14695981039346656037)
FNV64_PRIME = np.uint64(1099511628211)
FNV32_OFFSET = np.uint32(2166136261)
FNV32_PRIME = np.uint32(16777619)

_M64 = (1 << 64) - 1
_FNV64_OFFSET_I = int(FNV64_OFFSET)
_FNV64_PRIME_I = int(FNV64_PRIME)


class _Pacer(threading.local):
    """Per-thread build pacing. ratio=0 (every thread by default):
    coop_yield() is a bare GIL yield. The engine's background
    TableInstaller sets ratio=r around a standby compile: each yield
    then sleeps ~r x the work time since the previous yield, capping
    the installer's CPU/GIL duty at 1/(1+r) — measured, this is what
    keeps serving-thread p99 flat through an install on a shared
    interpreter (cooperative yields alone still cost dispatches the
    ~50% GIL share of a full-speed compile)."""

    ratio = 0.0
    last = 0.0


_PACER = _Pacer()


def set_build_pacing(ratio: float) -> None:
    """Set THIS thread's build pacing (0 = none). The installer calls
    this; foreground builds (matcher __init__) stay unpaced."""
    _PACER.ratio = max(0.0, ratio)
    _PACER.last = 0.0


def coop_yield() -> None:
    """Cooperative scheduling point for table-build hot loops (call
    every ~0.1-0.3ms of work): lets GIL waiters in immediately, and
    applies the thread's build pacing when one is set."""
    r = _PACER.ratio
    if not r:
        time.sleep(0)
        return
    now = time.perf_counter()
    last = _PACER.last
    if last:
        time.sleep(min(0.005, (now - last) * r))
    else:
        time.sleep(0)
    _PACER.last = time.perf_counter()


def fnv64(key: bytes, salt: int) -> np.uint64:
    """Bit-identical to the original np.uint64 form, computed on python
    ints (one masked multiply per byte instead of a numpy scalar
    round-trip — ~10x less build-time GIL hold, the table-compile cost
    AND contention driver for background standby installs)."""
    h = (_FNV64_OFFSET_I ^ int(salt)) & _M64
    for b in key:
        h = ((h ^ b) * _FNV64_PRIME_I) & _M64
    return np.uint64(h)


def rolling_fnv64(qbytes: np.ndarray, salt: int) -> np.ndarray:
    """uint8 [B, L] -> uint64 [B, L+1]; column p = hash of row prefix [:p].

    Vectorized across the batch: L sequential steps of [B] ops.
    """
    b, l = qbytes.shape
    out = np.empty((b, l + 1), dtype=np.uint64)
    h = np.full(b, FNV64_OFFSET ^ np.uint64(salt), dtype=np.uint64)
    out[:, 0] = h
    with np.errstate(over="ignore"):
        for p in range(l):
            h = (h ^ qbytes[:, p].astype(np.uint64)) * FNV64_PRIME
            out[:, p + 1] = h
    return out


def rolling_fnv64_multi(qbytes: np.ndarray, salts) -> np.ndarray:
    """uint8 [B, L], salts [S] -> uint64 [S, B, L+1]; out[s, :, p] =
    rolling_fnv64(qbytes, salts[s])[:, p]. One pass over the byte
    columns serves every salt — the sharded encoder's way to hash a
    query batch for S per-shard tables without S sequential passes."""
    b, l = qbytes.shape
    salts = np.asarray(salts, np.uint64)
    s = salts.shape[0]
    out = np.empty((s, b, l + 1), dtype=np.uint64)
    h = np.ascontiguousarray(
        np.broadcast_to(FNV64_OFFSET ^ salts[:, None], (s, b)))
    out[:, :, 0] = h
    with np.errstate(over="ignore"):
        qb = qbytes.astype(np.uint64)
        for p in range(l):
            h = (h ^ qb[None, :, p]) * FNV64_PRIME
            out[:, :, p + 1] = h
    return out


def fnv32_masked(key16: np.ndarray, salt: int) -> np.ndarray:
    """uint8 [..., 16] -> uint32 [...]; must match the device-side FNV-32
    in ops/hashmatch.py bit for bit (u32 wraparound multiply)."""
    h = np.full(key16.shape[:-1], FNV32_OFFSET ^ np.uint32(salt), np.uint32)
    with np.errstate(over="ignore"):
        for p in range(16):
            h = (h ^ key16[..., p].astype(np.uint32)) * FNV32_PRIME
    return h


def _pow2_at_least(n: int) -> int:
    c = 1
    while c < n:
        c <<= 1
    return c


@dataclass
class CuckooTable:
    """One built table. Keys byte-verified at probe time; `slot_of` maps
    key -> slot for build-side tests."""

    cap: int  # power of two
    salt1: int
    salt2: int
    used: np.ndarray  # [cap] bool
    key_len: np.ndarray  # [cap] int32
    key_bytes: np.ndarray  # [cap, key_slot] uint8 (zero-padded)
    bucket_start: np.ndarray  # [cap] int32
    bucket_count: np.ndarray  # [cap] int32
    slot_of: dict  # key bytes -> slot


class CuckooBuildError(Exception):
    pass


def _try_build(keys: list[bytes], cap: int, salt1: int, salt2: int,
               hasher) -> dict | None:
    """Place every key into one of its two slots; None on cycle.

    Cooperatively yields every few keys (~0.1ms of work): builds run
    on the engine's background installer while serving threads fight
    for the GIL — an unyielding build inflates dispatch p99 ~10x
    (measured); at this granularity it is invisible."""
    slot_key: list[bytes | None] = [None] * cap
    mask = cap - 1
    for ki, key in enumerate(keys):
        if not (ki & 3):
            coop_yield()
        cur = key
        # standard cuckoo insertion with bounded kicks
        h = int(hasher(cur, salt1)) & mask
        for kick in range(max(64, 8 * len(keys).bit_length() * 4)):
            if slot_key[h] is None:
                slot_key[h] = cur
                cur = None
                break
            slot_key[h], cur = cur, slot_key[h]
            h1 = int(hasher(cur, salt1)) & mask
            h2 = int(hasher(cur, salt2)) & mask
            h = h2 if h == h1 else h1
        if cur is not None:
            return None
    return {k: i for i, k in enumerate(slot_key) if k is not None}


def build_cuckoo(buckets: dict[bytes, list[int]], key_slot: int,
                 cap: int | None = None, hasher=fnv64,
                 bucket_items: np.ndarray | None = None,
                 salt_base: int = 0) -> tuple[CuckooTable, np.ndarray]:
    """buckets: key bytes -> sorted rule indices sharing that key.

    Returns (table, bucket_array): bucket_array is the concatenated
    int32 rule indices; slots point into it via (start, count).
    """
    keys = sorted(buckets.keys())
    n = len(keys)
    # a caller-supplied cap (shape reuse across rule updates) may be too
    # small for the new key count — enforce load factor <= 0.5 up front
    cap = max(cap or 4, 4, _pow2_at_least(2 * n))
    placement = None
    salt1 = salt2 = 0
    for attempt in range(64):
        salt1 = salt_base * 131 + attempt * 2 + 1
        salt2 = salt_base * 131 + attempt * 2 + 2
        placement = _try_build(keys, cap, salt1, salt2, hasher)
        if placement is not None:
            break
        if attempt and attempt % 8 == 0:
            cap <<= 1  # salts alone not enough: grow
    if placement is None:
        raise CuckooBuildError(f"cuckoo build failed for {n} keys")

    used = np.zeros(cap, bool)
    key_len = np.zeros(cap, np.int32)
    key_bytes = np.zeros((cap, key_slot), np.uint8)
    bstart = np.zeros(cap, np.int32)
    bcount = np.zeros(cap, np.int32)
    flat: list[int] = []
    for ki, k in enumerate(keys):
        if not (ki & 15):
            coop_yield()  # cooperative: see _try_build
        s = placement[k]
        used[s] = True
        key_len[s] = len(k)
        if len(k) > key_slot:
            raise CuckooBuildError(f"key longer than slot: {len(k)} > {key_slot}")
        key_bytes[s, : len(k)] = np.frombuffer(k, np.uint8)
        bstart[s] = len(flat)
        items = sorted(buckets[k])
        bcount[s] = len(items)
        flat.extend(items)
    bucket = np.asarray(flat, np.int32) if flat else np.zeros(0, np.int32)
    return CuckooTable(cap=cap, salt1=salt1, salt2=salt2, used=used,
                       key_len=key_len, key_bytes=key_bytes,
                       bucket_start=bstart, bucket_count=bcount,
                       slot_of=placement), bucket


def probe_slots(hashes1: np.ndarray, hashes2: np.ndarray, cap: int):
    """uint64 hash arrays -> int32 slot indices (cap is a power of two)."""
    mask = np.uint64(cap - 1)
    return ((hashes1 & mask).astype(np.int32),
            (hashes2 & mask).astype(np.int32))
