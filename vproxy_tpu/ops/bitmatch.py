"""Masked-equality matching as matmul (the MXU trick).

Every rule-match site in the framework reduces to: does a query byte
string equal a rule byte string under a per-rule byte mask?  We encode
bytes as bit-planes (values in {0,1}) and use

    popcount((q XOR r) AND m) = sum_k q_k*m_k + r_k*m_k - 2*q_k*r_k*m_k
                              = q . (m - 2*r*m) + sum(r*m)

so a [B, K] x [K, N] matmul + bias gives the per-(query, rule) mismatch
count; a pattern matches iff its count is zero.  With bf16 operands and
f32 accumulation this is exact (operands are in {-1, 0, 1} / {0, 1} and
sums stay far below 2^24), and it maps straight onto the TPU MXU instead
of the reference's per-connection Java scan (Upstream.java:187,
RouteTable.java:44, SecurityGroup.java:30).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def compile_patterns(values: np.ndarray, masks: np.ndarray):
    """Compile pattern bytes into matmul weights.

    values, masks: uint8 [N, L] (mask is 0x00/0xff per byte; partial-byte
    masks from CIDR prefixes are also supported bit-wise).
    Returns (W [L*8, N] float32, c [N] float32).
    """
    assert values.shape == masks.shape
    n, l = values.shape
    vb = np.unpackbits(values, axis=1).astype(np.float32)  # [N, L*8]
    mb = np.unpackbits(masks, axis=1).astype(np.float32)
    w = (mb - 2.0 * vb * mb).T  # [L*8, N]
    c = (vb * mb).sum(axis=1)  # [N]
    return np.ascontiguousarray(w), np.ascontiguousarray(c)


def unpack_bits(q: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., L] -> float [..., L*8] bit-planes (MSB first, matching
    np.unpackbits)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (q[..., None] >> shifts) & 1  # [..., L, 8]
    return bits.reshape(*q.shape[:-1], q.shape[-1] * 8).astype(jnp.float32)


def mismatch_counts(q_bits: jnp.ndarray, w: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """[B, K] x [K, N] + [N] -> [B, N] mismatch counts (exact)."""
    return jnp.dot(q_bits.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) + c
